"""Admission control: deadline budgets, priority lanes, SLO-driven
load shedding (ISSUE 15 / ROADMAP item 2).

The observability stack can *see* overload perfectly — burn rates
(obs/slo.py), per-request queue-delay stage attribution (obs/stages.py),
per-query tier attribution (obs/audit.py) — but until now nothing
*acted* on it: BENCH_r07 showed the gRPC surface past its open-loop
knee collapsing from p99 7.6 ms to 565 ms while achieved QPS fell below
offered, because every arrival was admitted into an unbounded queue.
This module is the actuator, in three parts:

1. **Per-request deadline budgets.** Every ingress mints an absolute
   deadline — from gRPC deadline metadata (``context.time_remaining``),
   the ``X-Nornic-Deadline-Ms`` HTTP header, or a default derived from
   the surface's SLO objective (threshold x
   ``NORNICDB_DEADLINE_SLO_FACTOR``, overridable with
   ``NORNICDB_DEADLINE_DEFAULT_MS``) — carried in a contextvar so it
   crosses the executor hop exactly like the trace context, and carried
   across the broker ring in the OP_VEC/OP_CALL slot header
   (search/broker.py). The MicroBatcher/BatchCoalescer consult it: a
   rider already past budget fails fast with a degrade-ledger record
   instead of occupying a device slot, and a rider whose remaining
   budget would expire inside the gather window triggers an immediate
   smaller dispatch (pow2 buckets absorb the size change — no new
   compile universe).

2. **Priority lanes.** Three bounded lanes — ``interactive`` (client
   reads) > ``replay`` (replica WAL replay, shadow-audit replays) >
   ``background`` (index rebuilds, decay/inference sweeps, bulk upsert
   convoys) — carried in a contextvar set by :func:`lane_scope` at the
   top of every background worker thread. Batch leaders seal batches in
   lane-priority order (with an aging promotion so background work can
   never starve outright), so a rebuild kicked mid-load cannot convoy
   interactive traffic through the shared dispatch machinery.

3. **SLO-driven shedding.** The controller tracks per-lane in-flight
   counts and a completion-rate EWMA per surface; when the estimated
   queue wait crosses ``NORNICDB_ADMIT_MAX_WAIT_MS`` (or the burn-rate
   engine breaches), admission first *degrades along the existing
   serving ladders* — the :func:`tier_gate` hook registered with
   obs/audit.py forces walk/quant/graph device tiers down to brute/host
   to shrink device pressure — then sheds lowest-priority work first
   with honest backpressure: HTTP 429 + ``Retry-After`` derived from
   the lane drain rate, gRPC ``RESOURCE_EXHAUSTED`` with
   ``grpc-retry-pushback-ms`` trailing metadata. Every shed is counted
   (``nornicdb_shed_total``), ledgered (one degrade-ledger record) and
   journaled (one ``shed`` event), trace-linked to the originating
   request.

Configuration is read ONCE at first use and cached (:func:`reload` for
tests) — the per-request functions here are registered hot paths
(lint/config.py HOT_PATHS) and must never read the environment.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import events as _events
from nornicdb_tpu.obs import metrics as _m
from nornicdb_tpu.obs import tenant as _tenant
from nornicdb_tpu.obs.metrics import REGISTRY
from nornicdb_tpu.obs.tracing import annotate, current_trace_id

# ---------------------------------------------------------------------------
# lanes
# ---------------------------------------------------------------------------

LANE_INTERACTIVE = "interactive"
LANE_REPLAY = "replay"
LANE_BACKGROUND = "background"
# priority order, best first — index IS the lane rank
LANES = (LANE_INTERACTIVE, LANE_REPLAY, LANE_BACKGROUND)
_LANE_RANK = {lane: i for i, lane in enumerate(LANES)}

# ring wire codes (search/broker.py slot header carries one byte)
LANE_CODES = {LANE_INTERACTIVE: 0, LANE_REPLAY: 1, LANE_BACKGROUND: 2}
LANE_FROM_CODE = {v: k for k, v in LANE_CODES.items()}

# the HTTP header carrying a client's deadline budget in milliseconds
DEADLINE_HEADER = "X-Nornic-Deadline-Ms"

_ctx_deadline: contextvars.ContextVar[Optional[float]] = \
    contextvars.ContextVar("nornic_deadline", default=None)
# True when the active deadline came from the CLIENT (gRPC deadline
# metadata, X-Nornic-Deadline-Ms, or a programmatic deadline_scope) as
# opposed to the server-minted surface default: only explicit budgets
# may EXTEND infrastructure timeouts (the broker's flat rider timeout)
# — a 30s server default must not double the dead-plane detection time
_ctx_deadline_explicit: contextvars.ContextVar[bool] = \
    contextvars.ContextVar("nornic_deadline_explicit", default=False)
_ctx_lane: contextvars.ContextVar[str] = \
    contextvars.ContextVar("nornic_lane", default=LANE_INTERACTIVE)
# set by record_shed inside an ingress scope: the scope's exit must
# not count a shed as served capacity in the drain-rate EWMA (a shed
# completes "instantly"; counting it would inflate the drain estimate
# and oscillate the shedding verdict)
_ctx_was_shed: contextvars.ContextVar[bool] = \
    contextvars.ContextVar("nornic_was_shed", default=False)

# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_SHED_C = REGISTRY.counter(
    "nornicdb_shed_total",
    "Queries rejected by admission control, by surface/lane/reason",
    labels=("surface", "lane", "reason"))
_MISS_C = REGISTRY.counter(
    "nornicdb_deadline_miss_total",
    "Requests failed fast past their deadline budget, by surface and "
    "the stage that caught the expiry",
    labels=("surface", "stage"))
_LANE_IN_G = REGISTRY.gauge(
    "nornicdb_lane_inflight",
    "Admitted requests currently in flight per priority lane",
    labels=("lane",))
_POSTURE_G = REGISTRY.gauge(
    "nornicdb_admission_posture",
    "Current admission posture (0 admit, 1 degrade, 2 shed, "
    "3 shed_hard)")

POSTURES = ("admit", "degrade", "shed", "shed_hard")


class ShedError(Exception):
    """Admission refused this request. Maps to HTTP 429 +
    ``Retry-After`` / gRPC ``RESOURCE_EXHAUSTED`` with
    ``grpc-retry-pushback-ms`` metadata — honest backpressure, never a
    silent queue."""

    status = 429

    def __init__(self, surface: str, lane: str, retry_after_s: float,
                 reason: str = "shed"):
        super().__init__(
            f"admission shed ({reason}): lane {lane} over capacity on "
            f"{surface}; retry after {retry_after_s:.1f}s")
        self.surface = surface
        self.lane = lane
        self.reason = reason
        self.retry_after_s = retry_after_s


class DeadlineExceeded(RuntimeError):
    """The request's deadline budget expired before (or while) it
    queued for dispatch — failed fast instead of occupying a device
    slot it can no longer use."""

    status = 504


# ---------------------------------------------------------------------------
# cached configuration (env read once; per-request paths read the dict)
# ---------------------------------------------------------------------------

_cfg_lock = threading.Lock()
_cfg: Optional[Dict[str, Any]] = None


def _load_cfg() -> Dict[str, Any]:
    from nornicdb_tpu.config import (env_bool, env_float, env_int,
                                     env_str)

    factor = env_float("DEADLINE_SLO_FACTOR", 120.0)
    default_ms = env_float("DEADLINE_DEFAULT_MS", 0.0)
    # per-surface default budgets derive from the SLO objectives: a
    # surface whose objective says "99% under 100ms" gets factor x
    # 100ms of budget before the scheduler treats the rider as
    # abandoned. NORNICDB_DEADLINE_DEFAULT_MS overrides every surface.
    defaults: Dict[str, float] = {}
    try:
        from nornicdb_tpu.obs.slo import _objectives_from_env

        for obj in _objectives_from_env():
            defaults[obj.name] = obj.threshold_s * factor
    except Exception:  # noqa: BLE001 — deadline defaults must not fail boot
        pass
    defaults.setdefault("http", 0.25 * factor)
    defaults.setdefault("grpc", 0.1 * factor)
    if default_ms > 0:
        defaults = {k: default_ms / 1e3 for k in defaults}
        defaults["*"] = default_ms / 1e3
    else:
        defaults["*"] = max(defaults.values())
    weights_spec = env_str("LANE_WEIGHTS", "")
    weights = {LANE_INTERACTIVE: 16.0, LANE_REPLAY: 4.0,
               LANE_BACKGROUND: 1.0}
    if weights_spec:
        try:
            parts = [float(x) for x in weights_spec.split(",")]
            for lane, w in zip(LANES, parts):
                weights[lane] = max(w, 0.1)
        except ValueError:
            pass
    return {
        "deadline_defaults_s": defaults,
        "lane_weights": weights,
        # aging promotion: a background/replay rider older than this
        # seals like interactive (no outright starvation)
        "lane_max_wait_s": env_float("LANE_MAX_WAIT_S", 2.0),
        "shed_enabled": env_str("ADMIT_SHED", "1").strip().lower()
        not in ("0", "false", "no", "off"),
        # estimated-wait bound for the interactive lane: the queueing
        # delay the scheduler refuses to let build up (the p99-at-load
        # bound the overload bench gates ≈ this + one dispatch)
        "max_wait_s": env_float("ADMIT_MAX_WAIT_MS", 50.0) / 1e3,
        # absolute in-flight cap per lane when no drain estimate exists
        "max_queue": env_int("ADMIT_MAX_QUEUE", 512),
        # burn-rate posture thresholds (fast window, obs/slo.py)
        "burn_degrade": env_float("ADMIT_BURN_DEGRADE", 6.0),
        "burn_shed": env_float("ADMIT_BURN_SHED", 14.4),
        # posture recompute cadence (the per-request check reads cache)
        "interval_s": env_float("ADMIT_INTERVAL_MS", 100.0) / 1e3,
        # fleet posture sharing (ISSUE 16): a peer-published posture
        # older than this is ignored (and may be overwritten in the
        # ring control block) — bounds how long a dead node's overload
        # signal can pin the fleet
        "fleet_posture_ttl_s": env_float("FLEET_POSTURE_TTL_S", 5.0),
        # cost-aware admission (ISSUE 20): at posture >= degrade a
        # query whose CALIBRATED predicted dispatch cost exceeds its
        # remaining deadline budget sheds up front (reason
        # ``admission_cost``) instead of occupying a device slot. The
        # gate only actuates on confident models (obs/device.py
        # abstains below its min-sample floor) — below confidence the
        # posture controller stays queue-wait-only, never a guess.
        "cost_gate_enabled": env_bool("ADMISSION_COST_GATE", True),
        # predicted_ms must exceed slack x remaining_ms to shed: > 1.0
        # sheds only clearly-doomed queries, < 1.0 sheds speculatively
        "cost_gate_slack": env_float("ADMISSION_COST_SLACK", 1.0),
    }


def cfg() -> Dict[str, Any]:
    global _cfg
    c = _cfg
    if c is None:
        with _cfg_lock:
            if _cfg is None:
                _cfg = _load_cfg()
            c = _cfg
    return c


def reload() -> None:
    """Drop the cached env-derived config (tests; admin flags)."""
    global _cfg
    with _cfg_lock:
        _cfg = None
    CONTROLLER.reset()


# ---------------------------------------------------------------------------
# deadline + lane context
# ---------------------------------------------------------------------------


def deadline() -> Optional[float]:
    """Absolute epoch deadline of the current request, or None."""
    return _ctx_deadline.get()


def deadline_explicit() -> bool:
    """True when the active deadline was supplied by the client (or a
    programmatic scope), not minted as the surface default."""
    return _ctx_deadline_explicit.get()


def remaining(now: Optional[float] = None) -> Optional[float]:
    dl = _ctx_deadline.get()
    if dl is None:
        return None
    return dl - (time.time() if now is None else now)


def lane() -> str:
    return _ctx_lane.get()


def lane_rank(lane_name: str, waited_s: float = 0.0) -> int:
    """Seal-order rank of a lane (lower seals first); a rider that has
    already waited past the aging bound promotes to interactive rank so
    low lanes cannot starve outright."""
    if waited_s >= cfg()["lane_max_wait_s"]:
        return 0
    return _LANE_RANK.get(lane_name, 0)


def default_deadline(surface: str, now: Optional[float] = None
                     ) -> float:
    d = cfg()["deadline_defaults_s"]
    budget = d.get(surface) or d["*"]
    return (time.time() if now is None else now) + budget


def mint_deadline(surface: str, budget_s: Optional[float] = None,
                  now: Optional[float] = None) -> Tuple[float, bool]:
    """(absolute deadline, explicit) for a fresh ingress request: the
    client's explicit budget when one came with the request (gRPC
    deadline, ``X-Nornic-Deadline-Ms``), else the surface default
    (``explicit`` False — a server-minted default must never EXTEND
    infrastructure timeouts downstream)."""
    now = time.time() if now is None else now
    if budget_s is not None and budget_s > 0:
        return now + budget_s, True
    return default_deadline(surface, now=now), False


def parse_deadline_header(value: Optional[str],
                          surface: str = "http") -> Tuple[float, bool]:
    """``X-Nornic-Deadline-Ms`` → (absolute deadline, explicit),
    falling back to the surface default on absent/garbage input — a
    malformed header degrades to the default budget, never to an
    error."""
    budget = None
    if value:
        try:
            ms = float(value)
            if 0 < ms <= 3.6e6:  # cap at one hour; junk stays default
                budget = ms / 1e3
        except ValueError:
            pass
    return mint_deadline(surface, budget)


class _Scope:
    __slots__ = ("_dl_tok", "_exp_tok", "_lane_tok", "_shed_tok",
                 "_surface", "_lane", "_t0")

    def __init__(self, surface: str, dl: Optional[float],
                 lane_name: Optional[str], explicit: bool):
        self._surface = surface
        self._lane = lane_name
        self._dl_tok = _ctx_deadline.set(dl)
        self._exp_tok = _ctx_deadline_explicit.set(
            explicit and dl is not None)
        self._lane_tok = (_ctx_lane.set(lane_name)
                          if lane_name is not None else None)
        self._shed_tok = _ctx_was_shed.set(False)
        self._t0 = time.time()
        CONTROLLER.note_enter(lane_name or _ctx_lane.get())

    def __enter__(self) -> "_Scope":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        served = exc_type is None and not _ctx_was_shed.get()
        CONTROLLER.note_exit(self._lane or _ctx_lane.get(),
                             self._surface, time.time() - self._t0,
                             served=served)
        _ctx_deadline.reset(self._dl_tok)
        _ctx_deadline_explicit.reset(self._exp_tok)
        _ctx_was_shed.reset(self._shed_tok)
        if self._lane_tok is not None:
            _ctx_lane.reset(self._lane_tok)


def request_scope(surface: str, dl: Optional[float],
                  lane_name: Optional[str] = None,
                  explicit: bool = False) -> _Scope:
    """Ingress scope: binds the deadline (and optionally the LANE —
    ingresses that resolved a lane for the shed verdict pass it here
    too, so the per-lane in-flight/drain accounting sees the same lane
    the verdict used) into the context, counts the request in the
    lane's in-flight gauge and feeds the completion-rate EWMA the
    shedding verdict divides by. ``explicit`` marks a CLIENT-supplied
    budget (may extend infrastructure timeouts downstream; a
    server-minted default may not). The constructor performs the enter
    so ``with request_scope(...)`` brackets exactly the handling
    interval."""
    return _Scope(surface, dl, lane_name, explicit)


class _LaneScope:
    __slots__ = ("_lane", "_tok")

    def __init__(self, lane_name: str):
        self._lane = lane_name
        self._tok = None

    def __enter__(self) -> "_LaneScope":
        self._tok = _ctx_lane.set(self._lane)
        return self

    def __exit__(self, *exc) -> None:
        if self._tok is not None:
            _ctx_lane.reset(self._tok)
            self._tok = None


class _DeadlineScope:
    __slots__ = ("_dl", "_tok", "_exp_tok")

    def __init__(self, dl: Optional[float]):
        self._dl = dl
        self._tok = None
        self._exp_tok = None

    def __enter__(self) -> "_DeadlineScope":
        self._tok = _ctx_deadline.set(self._dl)
        # a programmatic scope IS an explicit budget
        self._exp_tok = _ctx_deadline_explicit.set(self._dl is not None)
        return self

    def __exit__(self, *exc) -> None:
        if self._tok is not None:
            _ctx_deadline.reset(self._tok)
            self._tok = None
        if self._exp_tok is not None:
            _ctx_deadline_explicit.reset(self._exp_tok)
            self._exp_tok = None


def deadline_scope(dl: Optional[float]) -> _DeadlineScope:
    """Bind an absolute deadline into the context without the ingress
    accounting — the broker binds a ring-carried deadline around a
    plane-side dispatch with this (the worker's ingress scope already
    counted the request). A programmatic scope counts as an EXPLICIT
    budget (it may extend infrastructure timeouts)."""
    return _DeadlineScope(dl)


def select_batch(pending: Sequence[Any], max_batch: int,
                 now: float) -> Tuple[List[Any], List[Any]]:
    """Choose up to ``max_batch`` items from ``pending`` (objects with
    ``.lane`` and ``.t_enq``) — the ONE seal policy shared by the
    MicroBatcher and BatchCoalescer (ISSUE 15):

    - FIFO within a lane; a single-lane backlog is a plain slice;
    - lanes seal in priority order (interactive > replay >
      background), with items older than the aging bound promoted to
      interactive rank so low lanes cannot starve outright;
    - when lanes compete for one batch, each present lane is
      guaranteed its WEIGHTED minimum share of the batch
      (``NORNICDB_LANE_WEIGHTS``, floor 1 slot) before the remainder
      fills in priority order — the weighted-queue contract, not just
      strict priority.

    Returns ``(batch, rest)``; ``rest`` preserves arrival order."""
    if len(pending) <= max_batch:
        return list(pending), []
    c = cfg()
    ranked: Dict[int, List[Any]] = {}
    for it in pending:
        ranked.setdefault(lane_rank(it.lane, now - it.t_enq),
                          []).append(it)
    if len(ranked) == 1:
        only = next(iter(ranked.values()))
        taken = set(map(id, only[:max_batch]))
        return (only[:max_batch],
                [it for it in pending if id(it) not in taken])
    weights = c["lane_weights"]
    present = sorted(ranked)
    total_w = sum(weights.get(LANES[min(r, len(LANES) - 1)], 1.0)
                  for r in present)
    batch: List[Any] = []
    # weighted minimum share first: every present lane lands at least
    # floor(max_batch * w / total_w) (>= 1) of its items
    for r in present:
        w = weights.get(LANES[min(r, len(LANES) - 1)], 1.0)
        share = max(1, int(max_batch * w / total_w))
        take = ranked[r][:share]
        del ranked[r][: len(take)]
        batch.extend(take)
    # remainder by priority order
    for r in present:
        if len(batch) >= max_batch:
            break
        take = ranked[r][: max_batch - len(batch)]
        batch.extend(take)
    batch = batch[:max_batch]
    taken = set(map(id, batch))
    return batch, [it for it in pending if id(it) not in taken]


def lane_scope(lane_name: str) -> _LaneScope:
    """Tag everything inside (one thread's work) with a priority lane —
    wrapped around every background maintenance worker body (index
    rebuilds, decay/inference sweeps, replica replay, shadow-audit
    replays) so any coalescer ride from that thread seals BEHIND
    interactive traffic."""
    return _LaneScope(lane_name)


# ---------------------------------------------------------------------------
# shed / deadline-miss recording (exactly-once ledger + journal)
# ---------------------------------------------------------------------------


def record_shed(surface: str, lane_name: str, reason: str,
                retry_after_s: float = 0.0) -> None:
    """One shed, recorded exactly once everywhere it must appear:
    ``nornicdb_shed_total``, one ``shed`` serve in the tier mix, ONE
    degrade-ledger record and ONE ``shed`` event-journal record — both
    trace-linked. Deliberately NOT via :func:`obs.audit.record_degrade`
    (which would journal a second, ``degrade``-kind event for the same
    query)."""
    if not _m.enabled():
        return
    try:
        _ctx_was_shed.set(True)
    except Exception:  # noqa: BLE001 — accounting only
        pass
    _SHED_C.labels(surface, lane_name, reason).inc()
    _audit.record_served(surface, _audit.TIER_SHED)
    tid = current_trace_id()
    rec: Dict[str, Any] = {
        "ts": round(time.time(), 6),
        "surface": surface,
        "from_tier": lane_name,
        "to_tier": _audit.TIER_SHED,
        "reason": reason,
        "index": "",
    }
    if tid:
        rec["trace_id"] = tid
    if retry_after_s:
        rec["retry_after_s"] = round(retry_after_s, 3)
    tenant = _tenant.current_tenant()
    if tenant:
        rec["tenant"] = tenant
    _tenant.record_shed(surface, reason)
    _audit.LEDGER.record(rec)
    _events.record_event("shed", surface=surface, reason=reason,
                         trace_id=tid,
                         detail={"lane": lane_name,
                                 "retry_after_s": round(retry_after_s,
                                                        3)})
    annotate(shed=reason)


def record_deadline_miss(surface: str, stage: str,
                         lane_name: Optional[str] = None) -> None:
    """A request failed fast past its budget: counted per stage that
    caught it (``ingress`` / ``queued`` / ``ring``) and recorded as a
    shed with reason ``deadline``."""
    if not _m.enabled():
        return
    _MISS_C.labels(surface, stage).inc()
    record_shed(surface, lane_name or _ctx_lane.get(), "deadline")


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------


class AdmissionController:
    """Per-lane in-flight accounting, completion-rate EWMAs, and the
    cached admission posture the per-request :meth:`check` reads.

    Everything on the request path is a couple of lock-striped integer
    updates plus one float compare against the cached posture; the
    posture itself recomputes at most once per ``interval_s`` (burn
    rates + thresholds), triggered lazily from whichever request
    crosses the cadence."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {ln: 0 for ln in LANES}
        # completion EWMA: done/sec per lane (the drain rate Retry-After
        # derives from)
        self._done: Dict[str, int] = {ln: 0 for ln in LANES}
        self._drain: Dict[str, float] = {ln: 0.0 for ln in LANES}
        # per-lane OBSERVED queue-wait EWMA (seconds), time-decayed.
        # This is measured wait — batcher coalesce_wait, the executor
        # hop, the broker ring post->dispatch interval — not a
        # Little's-law estimate: residence-time estimates conflate
        # service time with queueing (a closed-loop fleet of slow
        # requests would read as overload) and rate estimates over
        # bursty low traffic divide by idle time. Measured wait is
        # ~zero in both healthy shapes and explodes within tens of ms
        # at the open-loop knee.
        self._wait: Dict[str, float] = {ln: 0.0 for ln in LANES}
        self._wait_t: Dict[str, float] = {ln: 0.0 for ln in LANES}
        self._drain_t = time.time()
        self.posture = "admit"
        self.posture_since = time.time()
        self._next_eval = 0.0
        self.sheds = 0
        self._burn_fast = 0.0
        self._eff_max_wait = 0.05
        # fleet posture sharing (ISSUE 16): the publisher pushes the
        # LOCAL posture out (ring control block, metrics gauge); each
        # source returns a peer-observed (level, age_s). The effective
        # posture is max(local, freshest-remote) — hooks survive
        # reset() because they encode topology, not load state.
        self.posture_local = "admit"
        self.posture_source = "local"
        self._posture_publisher: Optional[Any] = None
        self._posture_sources: List[Any] = []

    def reset(self) -> None:
        with self._lock:
            self._inflight = {ln: 0 for ln in LANES}
            self._done = {ln: 0 for ln in LANES}
            self._drain = {ln: 0.0 for ln in LANES}
            self._wait = {ln: 0.0 for ln in LANES}
            self._wait_t = {ln: 0.0 for ln in LANES}
            self._drain_t = time.time()
            self.posture = "admit"
            self.posture_since = time.time()
            self._next_eval = 0.0
            self.sheds = 0
            self._burn_fast = 0.0
            self._eff_max_wait = cfg()["max_wait_s"]
            self.posture_local = "admit"
            self.posture_source = "local"
            # publisher/sources deliberately survive: topology wiring

    # -- fleet posture sharing (ISSUE 16) ------------------------------

    def set_posture_publisher(self, fn: Optional[Any]) -> None:
        """``fn(level:int)`` is called with the LOCAL posture level on
        every posture evaluation (never the fleet-merged one — a node
        must not echo a peer's overload back at the fleet)."""
        with self._lock:
            self._posture_publisher = fn

    def add_posture_source(self, fn: Any) -> None:
        """Register ``fn() -> (level:int, age_s:float) | None`` —
        a peer-observed posture (the broker-ring control word, the
        fleet aggregator's remote gauge sweep). Idempotent per
        callable identity."""
        with self._lock:
            if fn not in self._posture_sources:
                self._posture_sources.append(fn)

    def remove_posture_source(self, fn: Any) -> None:
        with self._lock:
            try:
                self._posture_sources.remove(fn)
            except ValueError:
                pass

    def clear_posture_publisher(self, fn: Any = None) -> None:
        """Drop the publisher — only if it is ``fn`` when one is given
        (a stopping ring endpoint must not unhook a replacement)."""
        with self._lock:
            if fn is None or self._posture_publisher == fn:
                self._posture_publisher = None

    def _merge_fleet_posture(self, local_level: int,
                             ttl_s: float) -> Tuple[int, str]:
        """(effective level, source tag): the max of the local verdict
        and every FRESH peer-published level. Failing sources
        contribute nothing — posture must never fail a request."""
        with self._lock:
            pub = self._posture_publisher
            sources = list(self._posture_sources)
        if pub is not None:
            try:
                pub(local_level)
            except Exception:  # noqa: BLE001 — publish is best-effort
                pass
        eff, src = local_level, "local"
        for fn in sources:
            try:
                res = fn()
            except Exception:  # noqa: BLE001 — a dead peer feed is not overload
                continue
            if not res:
                continue
            level, age = res
            if age <= ttl_s and int(level) > eff:
                eff, src = int(level), "fleet"
        return eff, src

    # -- accounting ----------------------------------------------------

    def note_enter(self, lane_name: str) -> None:
        with self._lock:
            self._inflight[lane_name] = \
                self._inflight.get(lane_name, 0) + 1

    def note_exit(self, lane_name: str, surface: str,
                  seconds: float, served: bool = True) -> None:
        with self._lock:
            n = self._inflight.get(lane_name, 0)
            self._inflight[lane_name] = n - 1 if n > 0 else 0
            if served:
                self._done[lane_name] = self._done.get(lane_name, 0) + 1

    def note_wait(self, lane_name: str, seconds: float,
                  now: Optional[float] = None) -> None:
        """One measured queue-wait observation (a batcher rider's
        coalesce wait, the gRPC executor hop, the broker ring
        post->dispatch interval). Folds into the lane's time-decayed
        EWMA — the signal the shedding verdict gates on."""
        if seconds <= 0.0:
            return
        now = time.time() if now is None else now
        with self._lock:
            v = self._decayed_wait_locked(lane_name, now)
            self._wait[lane_name] = (seconds if v <= 0.0
                                     else v * 0.8 + seconds * 0.2)
            self._wait_t[lane_name] = now

    def _decayed_wait_locked(self, lane_name: str, now: float) -> float:
        v = self._wait.get(lane_name, 0.0)
        if v <= 0.0:
            return 0.0
        dt = now - self._wait_t.get(lane_name, now)
        if dt <= 0.0:
            return v
        # halve per second of silence: a past burst cannot poison
        # admission once the queue has actually drained
        return v * (0.5 ** dt)

    def observed_wait(self, lane_name: str,
                      now: Optional[float] = None) -> float:
        now = time.time() if now is None else now
        with self._lock:
            return self._decayed_wait_locked(lane_name, now)

    def inflight(self, lane_name: str) -> int:
        with self._lock:
            return self._inflight.get(lane_name, 0)

    def drain_rate(self, lane_name: str) -> float:
        """Completions/sec EWMA for one lane (0.0 until warm)."""
        with self._lock:
            return self._drain.get(lane_name, 0.0)


    # -- posture -------------------------------------------------------

    def _roll_drain_locked(self, now: float) -> None:
        # caller holds the lock (refresh): fold the completion
        # counters into the EWMAs over the elapsed window. The window
        # clamps to 5s so an idle gap attributes its completions to
        # recent time instead of diluting them to ~zero.
        dt = now - self._drain_t
        if dt <= 0:
            return
        dt_eff = min(dt, 5.0)
        alpha = min(1.0, dt_eff / 2.0)  # ~2s time constant
        for ln in LANES:
            inst = self._done.get(ln, 0) / dt_eff
            prev = self._drain.get(ln, 0.0)
            self._drain[ln] = (prev * (1.0 - alpha) + inst * alpha
                               if prev > 0.0 else inst)
            self._done[ln] = 0
        self._drain_t = now

    def _burn_rate(self) -> float:
        """Worst fast-window burn rate across SLO objectives (0.0 when
        the engine has no complete data)."""
        try:
            from nornicdb_tpu.obs.slo import get_engine

            status = get_engine().status()
        except Exception:  # noqa: BLE001 — posture must not fail
            return 0.0
        worst = 0.0
        for doc in status.get("objectives", {}).values():
            wins = doc.get("windows") or []
            if not wins:
                continue
            fast = wins[0]
            br = fast.get("burn_rate")
            if br is not None and fast.get("total", 0) >= 30:
                worst = max(worst, float(br))
        return worst

    def refresh(self, now: Optional[float] = None,
                force: bool = False) -> str:
        """Recompute the posture if the evaluation cadence elapsed."""
        now = time.time() if now is None else now
        c = cfg()
        with self._lock:
            if not force and now < self._next_eval:
                return self.posture
            self._next_eval = now + c["interval_s"]
            self._roll_drain_locked(now)
            inflight = dict(self._inflight)
            drain = dict(self._drain)
        burn = self._burn_rate()
        it_in = inflight.get(LANE_INTERACTIVE, 0)
        est_wait = self.observed_wait(LANE_INTERACTIVE, now=now)
        # MEASURED QUEUE PRESSURE is the posture trigger (it reacts in
        # ms and is zero on an idle or merely-slow node); an SLO
        # burn-rate breach TIGHTENS the wait bound — a node already
        # torching its error budget gets less slack before it
        # degrades/sheds — but never flips the posture on its own (a
        # breach with no queue means the latency is in compute, and
        # shedding would not help it). The absolute in-flight cap is
        # the backstop for pathologies no wait observation survives.
        max_wait = c["max_wait_s"]
        if burn >= c["burn_shed"]:
            max_wait *= 0.5
        elif burn >= c["burn_degrade"]:
            max_wait *= 0.75
        posture = "admit"
        if est_wait > max_wait * 0.5 or it_in > c["max_queue"] // 2:
            posture = "degrade"
        if est_wait > max_wait or it_in > c["max_queue"]:
            posture = "shed"
        if est_wait > max_wait * 4 or it_in > c["max_queue"] * 2:
            posture = "shed_hard"
        # fleet merge (ISSUE 16): publish the local verdict, then let a
        # FRESH peer-published posture tighten (never loosen) it — the
        # whole fleet sheds together instead of funneling the load one
        # worker at a time into the overloaded one
        local_posture = posture
        eff_level, src = self._merge_fleet_posture(
            POSTURES.index(posture), c["fleet_posture_ttl_s"])
        posture = POSTURES[min(eff_level, len(POSTURES) - 1)]
        with self._lock:
            self._eff_max_wait = max_wait
            self._burn_fast = burn
            self.posture_local = local_posture
            self.posture_source = src
            if posture != self.posture:
                prev, self.posture = self.posture, posture
                self.posture_since = time.time()
            else:
                prev = None
        if prev is not None:
            _POSTURE_G.set(float(POSTURES.index(local_posture)))
            _events.record_event(
                "posture", reason=posture,
                detail={"from": prev, "source": src,
                        "burn_fast": round(burn, 2),
                        "interactive_inflight": it_in,
                        "est_wait_ms": (round(est_wait * 1e3, 1)
                                        if est_wait != float("inf")
                                        else None)})
        return posture

    def retry_after_s(self, lane_name: str) -> float:
        """Honest pushback interval from the lane's drain rate: the
        time the current backlog takes to drain, clamped to [1, 30]s."""
        with self._lock:
            inflight = self._inflight.get(lane_name, 0)
            drain = self._drain.get(lane_name, 0.0)
        if drain <= 0.0:
            return 2.0
        return min(30.0, max(1.0, inflight / drain))

    # -- the per-request verdict ---------------------------------------

    def check(self, surface: str, lane_name: Optional[str] = None,
              now: Optional[float] = None) -> None:
        """Admit or raise :class:`ShedError`. Cheap: reads the cached
        posture (recomputing at most once per interval across all
        callers) and compares the lane against it."""
        c = cfg()
        if not c["shed_enabled"]:
            return
        ln = lane_name if lane_name is not None else _ctx_lane.get()
        posture = self.refresh(now=now)
        if posture == "admit":
            return
        rank = _LANE_RANK.get(ln, 0)
        if posture == "degrade":
            shed = rank >= 2                            # background only
        elif posture == "shed":
            # replay+background shed outright; interactive sheds the
            # EXCESS — only while the live observed queue wait still
            # sits past the bound, so the admitted stream stays at
            # capacity (goodput ~= knee) with bounded p99
            shed = rank >= 1 or \
                self.observed_wait(LANE_INTERACTIVE) > self._eff_max_wait
        else:                                           # shed_hard
            shed = True
        if not shed:
            return
        with self._lock:
            self.sheds += 1
        ra = self.retry_after_s(ln)
        record_shed(surface, ln, "shed", retry_after_s=ra)
        raise ShedError(surface, ln, ra)

    def cost_check(self, surface: str, kind: str, bucket: int = 1,
                   lane_name: Optional[str] = None,
                   now: Optional[float] = None) -> Optional[float]:
        """Cost-aware admission (ISSUE 20): while posture >= degrade,
        shed a query whose CALIBRATED predicted dispatch milliseconds
        exceed its remaining deadline budget — up front, before it
        occupies a queue or device slot (reason ``admission_cost``,
        exactly-once ledger+journal via record_shed). Confidence-gated:
        obs/device.py abstains below its min-sample floor, and this
        gate then does nothing (queue-wait-only, never a guess).
        Returns the predicted ms when a confident model admitted the
        query, else None. Per-request hot path: cached config + one
        model-dict read, no env access."""
        c = cfg()
        if not c["shed_enabled"] or not c["cost_gate_enabled"]:
            return None
        t = time.time() if now is None else now
        rem = remaining(now=t)
        if rem is None:
            return None
        if self.refresh(now=t) == "admit":
            return None
        from nornicdb_tpu.obs import device as _device

        pred_ms = _device.predict_ms(kind, bucket)
        if pred_ms is None:
            return None
        if pred_ms <= max(rem, 0.0) * 1e3 * c["cost_gate_slack"]:
            return pred_ms
        ln = lane_name if lane_name is not None else _ctx_lane.get()
        with self._lock:
            self.sheds += 1
        ra = self.retry_after_s(ln)
        record_shed(surface, ln, "admission_cost", retry_after_s=ra)
        raise ShedError(surface, ln, ra, reason="admission_cost")

    # -- tier forcing (degrade-first actuation) ------------------------

    def tier_gate(self, tier: str) -> bool:
        """False while the posture is ``degrade`` or worse and ``tier``
        is an expensive device rung — registered with obs/audit.py so
        every existing ladder gate steps walk/quant/graph tiers down to
        brute/host (reason ``admission``), shrinking device pressure
        before any query is rejected."""
        if self.posture == "admit":
            return True
        if tier in (_audit.TIER_HOST, _audit.TIER_CACHED,
                    _audit.TIER_SHED):
            return True
        return tier.endswith("brute_f32")

    # -- the /admin/scheduler payload ----------------------------------

    def summary(self) -> Dict[str, Any]:
        c = cfg()
        now = time.time()
        with self._lock:
            inflight = dict(self._inflight)
            drain = dict(self._drain)
            waits = {ln: self._decayed_wait_locked(ln, now)
                     for ln in LANES}
        lanes: Dict[str, Any] = {}
        for ln in LANES:
            lanes[ln] = {
                "inflight": inflight.get(ln, 0),
                "drain_qps": round(drain.get(ln, 0.0), 1),
                "wait_ms": round(waits.get(ln, 0.0) * 1e3, 2),
                "weight": c["lane_weights"][ln],
            }
        misses = {}
        for (surface, stage), child in _MISS_C.children().items():
            if child.value:
                misses[f"{surface}:{stage}"] = child.value
        sheds = {}
        for key, child in _SHED_C.children().items():
            if child.value:
                sheds[":".join(key)] = child.value
        return {
            "posture": self.posture,
            "posture_local": self.posture_local,
            "posture_source": self.posture_source,
            "posture_since": round(self.posture_since, 3),
            "burn_fast": round(self._burn_fast, 3),
            "shed_enabled": c["shed_enabled"],
            "fleet": {
                "publisher": self._posture_publisher is not None,
                "sources": len(self._posture_sources),
                "ttl_s": c["fleet_posture_ttl_s"],
            },
            "lanes": lanes,
            "deadline": {
                "defaults_ms": {k: round(v * 1e3, 1)
                                for k, v in
                                c["deadline_defaults_s"].items()},
                "misses": misses,
            },
            "shed": {"total": sum(sheds.values()), "by": sheds},
            "limits": {
                "max_wait_ms": round(c["max_wait_s"] * 1e3, 1),
                "max_queue": c["max_queue"],
                "burn_degrade": c["burn_degrade"],
                "burn_shed": c["burn_shed"],
            },
        }


CONTROLLER = AdmissionController()


def check(surface: str, lane_name: Optional[str] = None) -> None:
    CONTROLLER.check(surface, lane_name)


def scheduler_summary() -> Dict[str, Any]:
    return CONTROLLER.summary()


def retry_after_s(lane_name: str = LANE_INTERACTIVE) -> float:
    return CONTROLLER.retry_after_s(lane_name)


def _collect() -> None:
    # scrape-time lane gauges (PR 5 collector discipline). The posture
    # gauge carries the LOCAL posture — it is the cross-node
    # propagation carrier (obs/fleet.py sweeps it off peer state
    # dumps), so publishing the fleet-merged value would echo a peer's
    # overload back at the fleet forever.
    with CONTROLLER._lock:
        for ln in LANES:
            _LANE_IN_G.labels(ln).set(
                float(CONTROLLER._inflight.get(ln, 0)))
        _POSTURE_G.set(float(POSTURES.index(CONTROLLER.posture_local)))


REGISTRY.add_collector(_collect)

# degrade-first actuation: the ladder gates in cagra/device_quant/
# hybrid_fused/device_graph consult obs.audit.tier_allowed +
# admission_allows; registering here makes the admission posture a
# first-class rung-forcing input beside the parity quarantine
_audit.set_admission_gate(CONTROLLER.tier_gate)

# the noisy-neighbor detector (obs/tenant.py) arms only while the
# posture is >= degrade — it reads the level through this provider so
# the tenant layer never imports the actuator
_tenant.set_posture_provider(
    lambda: POSTURES.index(CONTROLLER.posture))
