"""Loader for the ``native/`` build scripts.

Imports by file path so ``native/`` never lands on ``sys.path`` (it
would shadow any top-level module named ``build``). Routing every
native-library load through the build script matters: its content-hash
stamp check is what guarantees a stale ``.so`` that no longer matches
its ``.cpp`` is rebuilt rather than silently loaded (ADVICE r4).
"""

from __future__ import annotations

import importlib.util
import os


def load_build_module(script_name: str):
    """Import ``native/<script_name>`` and return the module (exposing
    ``build()``)."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo_root, "native", script_name)
    spec = importlib.util.spec_from_file_location(
        "nornicdb_tpu_native_" + script_name[:-3], path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod
