"""APOC function/procedure library (core subset).

Reference: apoc/ (23k LoC, ~40 categories, apoc.go:78 Initialize /
:222 registerAllFunctions). Round-1 surface: coll, map, text, math,
convert/json, date helpers, meta, merge, plus apoc.algo.pageRank and
apoc.path procedures. The long tail grows by registering into the same
table.
"""

from __future__ import annotations

import json
import math
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from nornicdb_tpu.errors import CypherRuntimeError

APOC_FUNCS: Dict[str, Callable[..., Any]] = {}


def register(name: str, fn: Callable[..., Any]) -> None:
    APOC_FUNCS[name.lower()] = fn


def lookup_apoc(name: str) -> Optional[Callable[..., Any]]:
    return APOC_FUNCS.get(name.lower())


# storage-backed APOC functions: impls take (ctx, *args) where ctx is the
# executor's query context (ctx.storage, ctx.ex). The reference gives its
# whole apoc registry storage access via apoc.GetStorage (apoc/apoc.go:110);
# here only the functions that need it are context-aware.
APOC_CTX_FUNCS: Dict[str, Callable[..., Any]] = {}


def register_ctx(name: str, fn: Callable[..., Any]) -> None:
    APOC_CTX_FUNCS[name.lower()] = fn


def lookup_apoc_ctx(name: str) -> Optional[Callable[..., Any]]:
    return APOC_CTX_FUNCS.get(name.lower())


def _flatten(lst, out):
    for x in lst:
        if isinstance(x, list):
            _flatten(x, out)
        else:
            out.append(x)
    return out


def _install():
    # -- apoc.coll -------------------------------------------------------
    register("apoc.coll.sum", lambda l: float(sum(l)) if l else 0.0)
    register("apoc.coll.avg", lambda l: (sum(l) / len(l)) if l else None)
    register("apoc.coll.min", lambda l: min(l) if l else None)
    register("apoc.coll.max", lambda l: max(l) if l else None)
    register("apoc.coll.contains", lambda l, v: v in (l or []))
    register("apoc.coll.reverse", lambda l: list(reversed(l or [])))
    register("apoc.coll.sort", lambda l: sorted(l or []))
    register("apoc.coll.sortNodes", lambda l, prop: sorted(
        l or [], key=lambda n: (n.properties.get(prop) is None, n.properties.get(prop))))
    register("apoc.coll.toSet", lambda l: list(dict.fromkeys(l or [])))
    register("apoc.coll.flatten", lambda l: _flatten(l or [], []))
    register("apoc.coll.indexOf", lambda l, v: (l or []).index(v) if v in (l or []) else -1)
    register("apoc.coll.pairs", lambda l: [
        [l[i], l[i + 1] if i + 1 < len(l) else None] for i in range(len(l or []))])
    register("apoc.coll.zip", lambda a, b: [[x, y] for x, y in zip(a or [], b or [])])
    register("apoc.coll.union", lambda a, b: list(dict.fromkeys((a or []) + (b or []))))
    register("apoc.coll.intersection", lambda a, b: [x for x in dict.fromkeys(a or []) if x in (b or [])])
    register("apoc.coll.subtract", lambda a, b: [x for x in dict.fromkeys(a or []) if x not in (b or [])])
    register("apoc.coll.shuffle", lambda l: __import__("random").sample(l or [], len(l or [])))
    register("apoc.coll.frequencies", lambda l: [
        {"item": k, "count": v}
        for k, v in __import__("collections").Counter(l or []).items()])

    # -- apoc.map --------------------------------------------------------
    register("apoc.map.fromPairs", lambda pairs: {p[0]: p[1] for p in (pairs or [])})
    register("apoc.map.fromLists", lambda ks, vs: dict(zip(ks or [], vs or [])))
    register("apoc.map.merge", lambda a, b: {**(a or {}), **(b or {})})
    register("apoc.map.setKey", lambda m, k, v: {**(m or {}), k: v})
    register("apoc.map.removeKey", lambda m, k: {
        kk: vv for kk, vv in (m or {}).items() if kk != k})
    register("apoc.map.keys", lambda m: sorted((m or {}).keys()))
    register("apoc.map.values", lambda m, keys=None: (
        [m.get(k) for k in keys] if keys else list((m or {}).values())))

    # -- apoc.text -------------------------------------------------------
    register("apoc.text.join", lambda l, d: d.join(str(x) for x in (l or [])))
    register("apoc.text.split", lambda s, regex: __import__("re").split(regex, s or ""))
    register("apoc.text.replace", lambda s, regex, repl: __import__("re").sub(regex, repl, s or ""))
    register("apoc.text.capitalize", lambda s: (s or "").capitalize())
    register("apoc.text.decapitalize", lambda s: (s[:1].lower() + s[1:]) if s else s)
    register("apoc.text.upperCamelCase", lambda s: "".join(
        w.capitalize() for w in __import__("re").split(r"[\s_-]+", s or "")))
    register("apoc.text.camelCase", lambda s: (lambda parts: (
        parts[0].lower() + "".join(w.capitalize() for w in parts[1:]) if parts else ""))(
        __import__("re").split(r"[\s_-]+", s or "")))
    register("apoc.text.random", lambda length, valid="A-Za-z0-9": "".join(
        __import__("random").choices("ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789", k=int(length))))
    register("apoc.text.lpad", lambda s, count, delim=" ": str(s).rjust(int(count), delim))
    register("apoc.text.rpad", lambda s, count, delim=" ": str(s).ljust(int(count), delim))
    register("apoc.text.indexOf", lambda s, sub: (s or "").find(sub))
    register("apoc.text.distance", _levenshtein)
    register("apoc.text.clean", lambda s: "".join(
        c for c in (s or "").lower() if c.isalnum()))

    # -- apoc.math / number ---------------------------------------------
    register("apoc.math.round", lambda x, prec=0: round(x, int(prec)))
    register("apoc.math.maxLong", lambda: 2**63 - 1)
    register("apoc.math.minLong", lambda: -(2**63))
    register("apoc.math.sigmoid", lambda x: 1.0 / (1.0 + math.exp(-x)))
    register("apoc.math.tanh", lambda x: math.tanh(x))
    register("apoc.number.format", lambda x, pattern=None: f"{x:,}")

    # -- apoc.convert / json ---------------------------------------------
    register("apoc.convert.toJson", lambda v: json.dumps(_jsonable(v)))
    register("apoc.convert.fromJsonMap", lambda s: json.loads(s))
    register("apoc.convert.fromJsonList", lambda s: json.loads(s))
    register("apoc.convert.toList", lambda v: list(v) if v is not None else [])
    register("apoc.convert.toString", lambda v: None if v is None else str(v))
    register("apoc.convert.toInteger", lambda v: int(v) if v is not None else None)
    register("apoc.convert.toFloat", lambda v: float(v) if v is not None else None)
    register("apoc.convert.toBoolean", lambda v: bool(v))
    register("apoc.json.path", lambda s, path="$": json.loads(s))

    # -- apoc.date -------------------------------------------------------
    register("apoc.date.currentTimestamp", lambda: int(time.time() * 1000))
    register("apoc.date.format", _date_format)
    register("apoc.date.parse", _date_parse)

    # -- apoc.label / meta ----------------------------------------------
    register("apoc.label.exists", lambda node, label: (
        label in node.labels if hasattr(node, "labels") else False))
    register("apoc.meta.type", _meta_type)

    # -- apoc.scoring ----------------------------------------------------
    register("apoc.scoring.existence", lambda score, exists: float(score) if exists else 0.0)
    register("apoc.scoring.pareto", lambda minimumThreshold, eightyPercentValue, maximumValue, score: (
        0.0 if score < minimumThreshold else
        maximumValue * (1 - math.exp(-score * math.log(5.0) / eightyPercentValue))))


def _levenshtein(a: str, b: str) -> int:
    a, b = a or "", b or ""
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


def _jsonable(v):
    from nornicdb_tpu.storage.types import Edge, Node

    if isinstance(v, Node):
        return {"id": v.id, "labels": v.labels, "properties": v.properties}
    if isinstance(v, Edge):
        return {"id": v.id, "type": v.type, "start": v.start_node,
                "end": v.end_node, "properties": v.properties}
    if isinstance(v, list):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


_JAVA_TO_STRFTIME = [
    ("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
    ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
]


def _convert_java_format(fmt: str) -> str:
    for j, p in _JAVA_TO_STRFTIME:
        fmt = fmt.replace(j, p)
    return fmt


def _date_format(epoch, unit="ms", fmt="yyyy-MM-dd HH:mm:ss"):
    from datetime import datetime, timezone

    secs = epoch / 1000.0 if unit == "ms" else float(epoch)
    return datetime.fromtimestamp(secs, tz=timezone.utc).strftime(
        _convert_java_format(fmt)
    )


def _date_parse(text, unit="ms", fmt="yyyy-MM-dd HH:mm:ss"):
    from datetime import datetime, timezone

    dt = datetime.strptime(text, _convert_java_format(fmt)).replace(
        tzinfo=timezone.utc
    )
    v = dt.timestamp()
    return int(v * 1000) if unit == "ms" else int(v)


def _meta_type(v):
    from nornicdb_tpu.storage.types import Edge, Node

    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "FLOAT"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "LIST"
    if isinstance(v, dict):
        return "MAP"
    if isinstance(v, Node):
        return "NODE"
    if isinstance(v, Edge):
        return "RELATIONSHIP"
    return type(v).__name__.upper()


_install()

# extended categories (periodic/trigger/path/export/create/merge/util —
# apoc_ext.py) register into the same table on import, as does the
# value-level bulk tail (bitwise/number/math/stats/scoring/temporal/
# text/util/json/diff/convert/xml/hashing/agg — apoc_bulk.py)
from nornicdb_tpu.query import apoc_ext as _apoc_ext  # noqa: E402,F401
from nornicdb_tpu.query import apoc_bulk as _apoc_bulk  # noqa: E402,F401
from nornicdb_tpu.query import apoc_graph as _apoc_graph  # noqa: E402,F401
from nornicdb_tpu.query import apoc_algo as _apoc_algo  # noqa: E402,F401
from nornicdb_tpu.query import apoc_admin as _apoc_admin  # noqa: E402,F401
from nornicdb_tpu.query import apoc_io as _apoc_io  # noqa: E402,F401

# -- APOC procedures (CALL apoc.*) ---------------------------------------


def run_apoc_procedure(executor, name: str, args: List[Any], ctx) -> Iterator[Dict[str, Any]]:
    name = name.lower()
    from nornicdb_tpu.query.apoc_ext import run_ext_procedure

    ext = run_ext_procedure(executor, name, args, ctx)
    if ext is not None:
        yield from ext
        return
    if name == "apoc.algo.pagerank":
        # args: [nodes] or nothing — run over whole graph
        from nornicdb_tpu.ops.graph import pagerank_engine

        # the executor's device graph plane caches the edge snapshot +
        # its device transfer per catalog version (only valid when this
        # query runs against the executor's own storage view)
        plane = (getattr(executor, "device_graph", None)
                 if ctx.storage is getattr(executor, "storage", None)
                 else None)
        scores = pagerank_engine(ctx.storage, plane=plane)
        for node_id, score in scores:
            try:
                node = ctx.storage.get_node(node_id)
            except KeyError:
                continue
            yield {"node": node, "score": float(score)}
        return
    if name == "apoc.help":
        prefix = (args[0] if args else "").lower()
        for fname in sorted(APOC_FUNCS):
            if prefix in fname:
                yield {"name": fname, "text": fname}
        return
    if name == "apoc.meta.stats":
        labels: Dict[str, int] = {}
        for n in ctx.storage.all_nodes():
            for l in n.labels:
                labels[l] = labels.get(l, 0) + 1
        rel_types: Dict[str, int] = {}
        for e in ctx.storage.all_edges():
            rel_types[e.type] = rel_types.get(e.type, 0) + 1
        yield {
            "nodeCount": ctx.storage.count_nodes(),
            "relCount": ctx.storage.count_edges(),
            "labels": labels,
            "relTypes": rel_types,
        }
        return
    cfn = lookup_apoc_ctx(name)
    if cfn is not None:
        out = cfn(ctx, *args)
        # procedure form: map results yield their fields as columns
        if isinstance(out, dict):
            yield out
        elif isinstance(out, list) and all(
                isinstance(x, dict) for x in out):
            yield from out  # empty list = zero rows, stable columns
        else:
            yield {"value": out}
        return
    fn = lookup_apoc(name)
    if fn is not None:
        yield {"value": fn(*args)}
        return
    raise CypherRuntimeError(f"unknown APOC procedure {name}")
