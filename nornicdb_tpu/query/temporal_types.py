"""Cypher temporal, duration, and spatial value types.

Reference: pkg/cypher/duration.go + the temporal builtins in
functions_eval_functions.go (date/datetime/localdatetime/time/localtime
construction, component access, truncate, arithmetic) and spatial
point()/distance(). Semantics follow the openCypher/Neo4j temporal
model: value types compare within kind, support component properties
(d.year, t.hour, dur.days, p.x), add/subtract durations, and stringify
to ISO-8601.
"""

from __future__ import annotations

import datetime as _dt
import math
import re
from typing import Any, Dict, Optional, Tuple

from nornicdb_tpu.errors import CypherRuntimeError

_AVG_DAYS_PER_MONTH = 30.436875
_AVG_SECONDS_PER_DAY = 86400.0

_NANOS = 1_000_000_000


class CypherDuration:
    """Neo4j duration: months / days / seconds / nanoseconds held
    separately (calendar-aware, like duration.go)."""

    __slots__ = ("months", "days", "seconds", "nanos")

    def __init__(self, months: int = 0, days: int = 0, seconds: int = 0,
                 nanos: int = 0):
        # normalize nanos into seconds but keep months/days/seconds apart
        extra, nanos = divmod(nanos, _NANOS)
        self.months = int(months)
        self.days = int(days)
        self.seconds = int(seconds) + int(extra)
        self.nanos = int(nanos)

    # -- component access (dur.years, dur.minutes, ...) ------------------

    def component(self, name: str):
        n = name.lower()
        if n == "years":
            return self.months // 12
        if n == "quarters":
            return self.months // 3
        if n == "months":
            return self.months
        if n == "monthsofyear":
            return self.months % 12
        if n == "weeks":
            return self.days // 7
        if n == "days":
            return self.days
        if n == "daysofweek":
            return self.days % 7
        if n == "hours":
            return self.seconds // 3600
        if n == "minutes":
            return self.seconds // 60
        if n == "minutesofhour":
            return (self.seconds // 60) % 60
        if n == "seconds":
            return self.seconds
        if n == "secondsofminute":
            return self.seconds % 60
        if n == "milliseconds":
            return self.seconds * 1000 + self.nanos // 1_000_000
        if n == "millisecondsofsecond":
            return self.nanos // 1_000_000
        if n == "microseconds":
            return self.seconds * 1_000_000 + self.nanos // 1000
        if n == "nanoseconds":
            return self.seconds * _NANOS + self.nanos
        if n == "nanosecondsofsecond":
            return self.nanos
        return None

    # -- arithmetic ------------------------------------------------------

    def __add__(self, other):
        if isinstance(other, CypherDuration):
            return CypherDuration(self.months + other.months,
                                  self.days + other.days,
                                  self.seconds + other.seconds,
                                  self.nanos + other.nanos)
        return NotImplemented

    def __sub__(self, other):
        if isinstance(other, CypherDuration):
            return CypherDuration(self.months - other.months,
                                  self.days - other.days,
                                  self.seconds - other.seconds,
                                  self.nanos - other.nanos)
        return NotImplemented

    def __neg__(self):
        return CypherDuration(-self.months, -self.days, -self.seconds,
                              -self.nanos)

    def __mul__(self, k):
        if isinstance(k, bool) or not isinstance(k, (int, float)):
            return NotImplemented
        total_n = (self.seconds * _NANOS + self.nanos) * k
        return CypherDuration(
            months=round(self.months * k), days=round(self.days * k),
            seconds=int(total_n // _NANOS), nanos=int(total_n % _NANOS),
        )

    __rmul__ = __mul__

    def __truediv__(self, k):
        if isinstance(k, bool) or not isinstance(k, (int, float)) or k == 0:
            return NotImplemented
        return self.__mul__(1.0 / k)

    def _approx_seconds(self) -> float:
        return ((self.months * _AVG_DAYS_PER_MONTH + self.days)
                * _AVG_SECONDS_PER_DAY
                + self.seconds + self.nanos / _NANOS)

    def __eq__(self, other):
        return (isinstance(other, CypherDuration)
                and (self.months, self.days, self.seconds, self.nanos)
                == (other.months, other.days, other.seconds, other.nanos))

    def __lt__(self, other):
        if not isinstance(other, CypherDuration):
            return NotImplemented
        return self._approx_seconds() < other._approx_seconds()

    def __hash__(self):
        return hash(("dur", self.months, self.days, self.seconds, self.nanos))

    def __str__(self):
        if not any((self.months, self.days, self.seconds, self.nanos)):
            return "PT0S"
        out = "P"
        if self.months:
            y, m = divmod(self.months, 12)
            if y:
                out += f"{y}Y"
            if m:
                out += f"{m}M"
        if self.days:
            out += f"{self.days}D"
        if self.seconds or self.nanos:
            out += "T"
            secs = self.seconds
            h, secs = divmod(secs, 3600)
            m, s = divmod(secs, 60)
            if h:
                out += f"{h}H"
            if m:
                out += f"{m}M"
            if s or self.nanos:
                if self.nanos:
                    frac = f"{self.nanos / _NANOS:.9f}".rstrip("0")[1:]
                    out += f"{s}{frac}S"
                else:
                    out += f"{s}S"
        return out

    __repr__ = __str__


_DUR_RE = re.compile(
    r"^P(?:(?P<y>-?\d+(?:\.\d+)?)Y)?(?:(?P<mo>-?\d+(?:\.\d+)?)M)?"
    r"(?:(?P<w>-?\d+(?:\.\d+)?)W)?(?:(?P<d>-?\d+(?:\.\d+)?)D)?"
    r"(?:T(?:(?P<h>-?\d+(?:\.\d+)?)H)?(?:(?P<mi>-?\d+(?:\.\d+)?)M)?"
    r"(?:(?P<s>-?\d+(?:\.\d+)?)S)?)?$"
)


def parse_duration(value: Any) -> CypherDuration:
    if isinstance(value, CypherDuration):
        return value
    if isinstance(value, dict):
        months = (int(value.get("years", 0)) * 12
                  + int(value.get("quarters", 0)) * 3
                  + int(value.get("months", 0)))
        days = int(value.get("weeks", 0)) * 7 + int(value.get("days", 0))
        seconds = (int(value.get("hours", 0)) * 3600
                   + int(value.get("minutes", 0)) * 60
                   + int(value.get("seconds", 0)))
        nanos = (int(value.get("milliseconds", 0)) * 1_000_000
                 + int(value.get("microseconds", 0)) * 1000
                 + int(value.get("nanoseconds", 0)))
        return CypherDuration(months, days, seconds, nanos)
    if isinstance(value, str):
        if value.startswith("-"):
            # leading sign negates the whole duration (Neo4j accepts -P1D)
            return -parse_duration(value[1:])
        if value.startswith("+"):
            return parse_duration(value[1:])
        m = _DUR_RE.match(value)
        if not m or value == "P":
            raise CypherRuntimeError(f"invalid duration {value!r}")
        g = {k: float(v) if v else 0.0 for k, v in m.groupdict().items()}
        months = g["y"] * 12 + g["mo"]
        days = g["w"] * 7 + g["d"]
        seconds = g["h"] * 3600 + g["mi"] * 60 + g["s"]
        # fractional months/days cascade downward (Neo4j semantics)
        mi, mf = divmod(months, 1)
        days += mf * _AVG_DAYS_PER_MONTH
        di, df = divmod(days, 1)
        seconds += df * _AVG_SECONDS_PER_DAY
        si, sf = divmod(seconds, 1)
        return CypherDuration(int(mi), int(di), int(si), round(sf * _NANOS))
    raise CypherRuntimeError(
        f"duration() expects a string or map, got {type(value).__name__}"
    )


class _TemporalBase:
    """Shared component access + comparison plumbing."""

    _dt: Any  # datetime.date / datetime.time / datetime.datetime

    def component(self, name: str):
        n = name.lower()
        d = self._dt
        has_date = hasattr(d, "year") and not isinstance(d, _dt.time)
        has_time = isinstance(d, (_dt.time, _dt.datetime))
        if has_date:
            if n == "year":
                return d.year
            if n == "quarter":
                return (d.month - 1) // 3 + 1
            if n == "month":
                return d.month
            if n == "week":
                return d.isocalendar()[1]
            if n == "weekyear":
                return d.isocalendar()[0]
            if n == "day":
                return d.day
            if n in ("ordinalday", "dayofyear"):
                return d.timetuple().tm_yday
            if n == "dayofweek":
                return d.isoweekday()
            if n == "dayofquarter":
                q_start = _dt.date(d.year, 3 * ((d.month - 1) // 3) + 1, 1)
                return (_dt.date(d.year, d.month, d.day) - q_start).days + 1
        if has_time:
            if n == "hour":
                return d.hour
            if n == "minute":
                return d.minute
            if n == "second":
                return d.second
            if n == "millisecond":
                return d.microsecond // 1000
            if n == "microsecond":
                return d.microsecond
            if n == "nanosecond":
                return d.microsecond * 1000
        if isinstance(d, _dt.datetime):
            if n == "epochmillis":
                return int(self._epoch_seconds() * 1000)
            if n == "epochseconds":
                return int(self._epoch_seconds())
            if n in ("timezone", "offset"):
                off = d.utcoffset()
                if off is None:
                    return None
                total = int(off.total_seconds())
                sign = "+" if total >= 0 else "-"
                total = abs(total)
                return f"{sign}{total // 3600:02d}:{(total % 3600) // 60:02d}"
        return None

    def _epoch_seconds(self) -> float:
        d = self._dt
        if d.tzinfo is None:
            d = d.replace(tzinfo=_dt.timezone.utc)
        return d.timestamp()

    def _key(self) -> Tuple:
        raise NotImplementedError

    def __eq__(self, other):
        return type(other) is type(self) and self._key() == other._key()

    def __lt__(self, other):
        if type(other) is not type(self):
            return NotImplemented
        return self._key() < other._key()

    def __le__(self, other):
        if type(other) is not type(self):
            return NotImplemented
        return self._key() <= other._key()

    def __gt__(self, other):
        eq = self.__le__(other)
        return NotImplemented if eq is NotImplemented else not eq

    def __ge__(self, other):
        eq = self.__lt__(other)
        return NotImplemented if eq is NotImplemented else not eq

    def __hash__(self):
        return hash((type(self).__name__, self._key()))

    def __repr__(self):
        return str(self)


class CypherDate(_TemporalBase):
    __slots__ = ("_dt",)

    def __init__(self, d: _dt.date):
        self._dt = d

    def _key(self):
        return (self._dt.year, self._dt.month, self._dt.day)

    def __str__(self):
        return self._dt.isoformat()

    def __add__(self, other):
        if isinstance(other, CypherDuration):
            return CypherDate(_shift_date(self._dt, other))
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, CypherDuration):
            return CypherDate(_shift_date(self._dt, -other))
        return NotImplemented


class CypherLocalTime(_TemporalBase):
    __slots__ = ("_dt",)

    def __init__(self, t: _dt.time):
        self._dt = t.replace(tzinfo=None)

    def _key(self):
        t = self._dt
        return (t.hour, t.minute, t.second, t.microsecond)

    def __str__(self):
        return self._dt.isoformat()

    def __add__(self, other):
        if isinstance(other, CypherDuration):
            return CypherLocalTime(_shift_time(self._dt, other))
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, CypherDuration):
            return CypherLocalTime(_shift_time(self._dt, -other))
        return NotImplemented


class CypherTime(_TemporalBase):
    __slots__ = ("_dt",)

    def __init__(self, t: _dt.time):
        if t.tzinfo is None:
            t = t.replace(tzinfo=_dt.timezone.utc)
        self._dt = t

    def _key(self):
        t = self._dt
        off = t.utcoffset() or _dt.timedelta(0)
        base = (t.hour * 3600 + t.minute * 60 + t.second
                - int(off.total_seconds()))
        return (base, t.microsecond)

    def __str__(self):
        return self._dt.isoformat()

    def __add__(self, other):
        if isinstance(other, CypherDuration):
            tz = self._dt.tzinfo
            return CypherTime(_shift_time(self._dt.replace(tzinfo=None),
                                          other).replace(tzinfo=tz))
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, CypherDuration):
            return self.__add__(-other)
        return NotImplemented


class CypherLocalDateTime(_TemporalBase):
    __slots__ = ("_dt",)

    def __init__(self, d: _dt.datetime):
        self._dt = d.replace(tzinfo=None)

    def _key(self):
        d = self._dt
        return (d.year, d.month, d.day, d.hour, d.minute, d.second,
                d.microsecond)

    def __str__(self):
        return self._dt.isoformat()

    def __add__(self, other):
        if isinstance(other, CypherDuration):
            return CypherLocalDateTime(_shift_datetime(self._dt, other))
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, CypherDuration):
            return CypherLocalDateTime(_shift_datetime(self._dt, -other))
        return NotImplemented


class CypherDateTime(_TemporalBase):
    __slots__ = ("_dt",)

    def __init__(self, d: _dt.datetime):
        if d.tzinfo is None:
            d = d.replace(tzinfo=_dt.timezone.utc)
        self._dt = d

    def _key(self):
        return (self._epoch_seconds(), self._dt.microsecond % 1000)

    def __str__(self):
        return self._dt.isoformat()

    def __add__(self, other):
        if isinstance(other, CypherDuration):
            tz = self._dt.tzinfo
            naive = _shift_datetime(self._dt.replace(tzinfo=None), other)
            return CypherDateTime(naive.replace(tzinfo=tz))
        return NotImplemented

    __radd__ = __add__

    def __sub__(self, other):
        if isinstance(other, CypherDuration):
            return self.__add__(-other)
        return NotImplemented


def _shift_date(d: _dt.date, dur: CypherDuration) -> _dt.date:
    if dur.months:
        total = d.year * 12 + (d.month - 1) + dur.months
        y, m = divmod(total, 12)
        day = min(d.day, _days_in_month(y, m + 1))
        d = _dt.date(y, m + 1, day)
    if dur.days or dur.seconds or dur.nanos:
        d = d + _dt.timedelta(days=dur.days,
                              seconds=dur.seconds + dur.nanos / _NANOS)
        if isinstance(d, _dt.datetime):
            d = d.date()
    return d


def _shift_time(t: _dt.time, dur: CypherDuration) -> _dt.time:
    total_us = (t.hour * 3600 + t.minute * 60 + t.second) * 1_000_000 + t.microsecond
    total_us += dur.seconds * 1_000_000 + dur.nanos // 1000
    total_us %= 24 * 3600 * 1_000_000
    s, us = divmod(total_us, 1_000_000)
    h, s2 = divmod(s, 3600)
    m, s3 = divmod(s2, 60)
    return _dt.time(int(h), int(m), int(s3), int(us))


def _shift_datetime(d: _dt.datetime, dur: CypherDuration) -> _dt.datetime:
    if dur.months:
        total = d.year * 12 + (d.month - 1) + dur.months
        y, m = divmod(total, 12)
        day = min(d.day, _days_in_month(y, m + 1))
        d = d.replace(year=y, month=m + 1, day=day)
    return d + _dt.timedelta(days=dur.days,
                             seconds=dur.seconds,
                             microseconds=dur.nanos // 1000)


def _days_in_month(y: int, m: int) -> int:
    if m == 12:
        return 31
    return (_dt.date(y, m + 1, 1) - _dt.timedelta(days=1)).day


# -- constructors ---------------------------------------------------------


_TZ_RE = re.compile(r"(Z|[+-]\d{2}:?\d{2})$")


def _parse_tz(name_or_offset: Any) -> _dt.tzinfo:
    if isinstance(name_or_offset, str):
        s = name_or_offset
        if s in ("Z", "z", "UTC", "utc"):
            return _dt.timezone.utc
        m = re.match(r"^([+-])(\d{2}):?(\d{2})?$", s)
        if m:
            sign = 1 if m.group(1) == "+" else -1
            mins = int(m.group(2)) * 60 + int(m.group(3) or 0)
            return _dt.timezone(sign * _dt.timedelta(minutes=mins))
        try:
            import zoneinfo

            return zoneinfo.ZoneInfo(s)
        except Exception:
            raise CypherRuntimeError(f"unknown timezone {s!r}")
    raise CypherRuntimeError("timezone must be a string")


def make_date(value: Any = None) -> Optional[CypherDate]:
    if value is None:
        return CypherDate(_dt.datetime.now(_dt.timezone.utc).date())
    if isinstance(value, CypherDate):
        return value
    if isinstance(value, (CypherDateTime, CypherLocalDateTime)):
        return CypherDate(value._dt.date())
    if isinstance(value, str):
        try:
            return CypherDate(_dt.date.fromisoformat(_normalize_date_str(value)))
        except ValueError:
            raise CypherRuntimeError(f"invalid date {value!r}")
    if isinstance(value, dict):
        try:
            return CypherDate(_dt.date(int(value.get("year", 0)),
                                       int(value.get("month", 1)),
                                       int(value.get("day", 1))))
        except ValueError as e:
            raise CypherRuntimeError(f"invalid date components: {e}")
    raise CypherRuntimeError("date() expects a string or map")


def _normalize_date_str(s: str) -> str:
    # Neo4j accepts 20260101 and 2026-01-01
    if re.fullmatch(r"\d{8}", s):
        return f"{s[:4]}-{s[4:6]}-{s[6:]}"
    return s


def _normalize_iso(s: str) -> str:
    """Rewrite ISO-8601 variants pre-3.11 ``fromisoformat`` rejects.

    Python 3.10's C parser wants exactly 3 or 6 fractional digits, a
    ``+HH:MM`` offset with the colon, and no ``Z`` suffix. Neo4j (and
    wire payloads) emit ``Z``, nanosecond fractions, and colon-less
    offsets — normalize those to the strict form before retrying.
    """
    s = s.strip()
    if s and s[-1] in "zZ":
        s = s[:-1] + "+00:00"
    tz = ""
    m = re.search(r"([+-]\d{2}):?(\d{2})$", s)
    if m:
        tz = f"{m.group(1)}:{m.group(2)}"
        s = s[: m.start()]
    fm = re.search(r"\.(\d+)$", s)
    if fm:
        s = s[: fm.start()] + "." + (fm.group(1) + "000000")[:6]
    return s + tz


def _iso_time(s: str) -> _dt.time:
    try:
        return _dt.time.fromisoformat(s)
    except ValueError:
        return _dt.time.fromisoformat(_normalize_iso(s))


def _iso_datetime(s: str) -> _dt.datetime:
    try:
        return _dt.datetime.fromisoformat(s)
    except ValueError:
        return _dt.datetime.fromisoformat(_normalize_iso(s))


def make_localtime(value: Any = None) -> Optional[CypherLocalTime]:
    if value is None:
        return CypherLocalTime(_dt.datetime.now().time())
    if isinstance(value, CypherLocalTime):
        return value
    if isinstance(value, CypherTime):
        return CypherLocalTime(value._dt.replace(tzinfo=None))
    if isinstance(value, (CypherDateTime, CypherLocalDateTime)):
        return CypherLocalTime(value._dt.time())
    if isinstance(value, str):
        try:
            return CypherLocalTime(_iso_time(value))
        except ValueError:
            raise CypherRuntimeError(f"invalid localtime {value!r}")
    if isinstance(value, dict):
        return CypherLocalTime(_time_from_map(value))
    raise CypherRuntimeError("localtime() expects a string or map")


def _time_from_map(m: Dict[str, Any]) -> _dt.time:
    us = (int(m.get("millisecond", 0)) * 1000
          + int(m.get("microsecond", 0))
          + int(m.get("nanosecond", 0)) // 1000)
    try:
        return _dt.time(int(m.get("hour", 0)), int(m.get("minute", 0)),
                        int(m.get("second", 0)), us)
    except ValueError as e:
        raise CypherRuntimeError(f"invalid time components: {e}")


def make_time(value: Any = None) -> Optional[CypherTime]:
    if value is None:
        return CypherTime(_dt.datetime.now(_dt.timezone.utc).timetz())
    if isinstance(value, CypherTime):
        return value
    if isinstance(value, CypherLocalTime):
        return CypherTime(value._dt.replace(tzinfo=_dt.timezone.utc))
    if isinstance(value, CypherDateTime):
        return CypherTime(value._dt.timetz())
    if isinstance(value, CypherLocalDateTime):
        return CypherTime(value._dt.time().replace(tzinfo=_dt.timezone.utc))
    if isinstance(value, str):
        try:
            return CypherTime(_iso_time(value.replace("Z", "+00:00")))
        except ValueError:
            raise CypherRuntimeError(f"invalid time {value!r}")
    if isinstance(value, dict):
        t = _time_from_map(value)
        tz = value.get("timezone")
        return CypherTime(t.replace(
            tzinfo=_parse_tz(tz) if tz else _dt.timezone.utc))
    raise CypherRuntimeError("time() expects a string or map")


def make_localdatetime(value: Any = None) -> Optional[CypherLocalDateTime]:
    if value is None:
        return CypherLocalDateTime(_dt.datetime.now())
    if isinstance(value, CypherLocalDateTime):
        return value
    if isinstance(value, CypherDateTime):
        return CypherLocalDateTime(value._dt.replace(tzinfo=None))
    if isinstance(value, CypherDate):
        return CypherLocalDateTime(
            _dt.datetime.combine(value._dt, _dt.time()))
    if isinstance(value, str):
        try:
            return CypherLocalDateTime(_iso_datetime(value))
        except ValueError:
            raise CypherRuntimeError(f"invalid localdatetime {value!r}")
    if isinstance(value, dict):
        return CypherLocalDateTime(_datetime_from_map(value))
    raise CypherRuntimeError("localdatetime() expects a string or map")


def _datetime_from_map(m: Dict[str, Any]) -> _dt.datetime:
    us = (int(m.get("millisecond", 0)) * 1000
          + int(m.get("microsecond", 0))
          + int(m.get("nanosecond", 0)) // 1000)
    try:
        return _dt.datetime(int(m.get("year", 0)), int(m.get("month", 1)),
                            int(m.get("day", 1)), int(m.get("hour", 0)),
                            int(m.get("minute", 0)), int(m.get("second", 0)),
                            us)
    except ValueError as e:
        raise CypherRuntimeError(f"invalid datetime components: {e}")


def make_datetime(value: Any = None) -> Optional[CypherDateTime]:
    if value is None:
        return CypherDateTime(_dt.datetime.now(_dt.timezone.utc))
    if isinstance(value, CypherDateTime):
        return value
    if isinstance(value, CypherLocalDateTime):
        return CypherDateTime(value._dt.replace(tzinfo=_dt.timezone.utc))
    if isinstance(value, CypherDate):
        return CypherDateTime(_dt.datetime.combine(
            value._dt, _dt.time(), tzinfo=_dt.timezone.utc))
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        # epoch millis convenience (Neo4j: datetime({epochMillis: v}))
        return CypherDateTime(_dt.datetime.fromtimestamp(
            value / 1000.0, tz=_dt.timezone.utc))
    if isinstance(value, str):
        try:
            return CypherDateTime(
                _iso_datetime(value.replace("Z", "+00:00")))
        except ValueError:
            raise CypherRuntimeError(f"invalid datetime {value!r}")
    if isinstance(value, dict):
        if "epochmillis" in {k.lower() for k in value}:
            millis = next(v for k, v in value.items()
                          if k.lower() == "epochmillis")
            return CypherDateTime(_dt.datetime.fromtimestamp(
                millis / 1000.0, tz=_dt.timezone.utc))
        if "epochseconds" in {k.lower() for k in value}:
            secs = next(v for k, v in value.items()
                        if k.lower() == "epochseconds")
            return CypherDateTime(_dt.datetime.fromtimestamp(
                secs, tz=_dt.timezone.utc))
        d = _datetime_from_map(value)
        tz = value.get("timezone")
        return CypherDateTime(d.replace(
            tzinfo=_parse_tz(tz) if tz else _dt.timezone.utc))
    raise CypherRuntimeError("datetime() expects a string, map, or millis")


# -- truncate -------------------------------------------------------------

_TRUNC_ORDER = ["year", "quarter", "month", "week", "day", "hour", "minute",
                "second", "millisecond", "microsecond"]


def truncate(unit: str, value: Any, kind: str):
    """date.truncate / datetime.truncate / localdatetime.truncate."""
    unit = unit.lower()
    if unit not in _TRUNC_ORDER:
        raise CypherRuntimeError(f"unknown truncation unit {unit!r}")
    if isinstance(value, CypherDate):
        src = _dt.datetime.combine(value._dt, _dt.time())
        tz = None
    elif isinstance(value, (CypherDateTime, CypherLocalDateTime)):
        src = value._dt
        tz = getattr(src, "tzinfo", None)
    elif isinstance(value, (CypherTime, CypherLocalTime)):
        t = value._dt
        src = _dt.datetime(1970, 1, 1, t.hour, t.minute, t.second,
                           t.microsecond)
        tz = getattr(t, "tzinfo", None)
    else:
        raise CypherRuntimeError("truncate expects a temporal value")
    d = src
    if unit == "year":
        d = d.replace(month=1, day=1, hour=0, minute=0, second=0, microsecond=0)
    elif unit == "quarter":
        q_month = 3 * ((d.month - 1) // 3) + 1
        d = d.replace(month=q_month, day=1, hour=0, minute=0, second=0,
                      microsecond=0)
    elif unit == "month":
        d = d.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    elif unit == "week":
        d = (d - _dt.timedelta(days=d.isoweekday() - 1)).replace(
            hour=0, minute=0, second=0, microsecond=0)
    elif unit == "day":
        d = d.replace(hour=0, minute=0, second=0, microsecond=0)
    elif unit == "hour":
        d = d.replace(minute=0, second=0, microsecond=0)
    elif unit == "minute":
        d = d.replace(second=0, microsecond=0)
    elif unit == "second":
        d = d.replace(microsecond=0)
    elif unit == "millisecond":
        d = d.replace(microsecond=(d.microsecond // 1000) * 1000)
    if kind == "date":
        return CypherDate(d.date())
    if kind == "datetime":
        return CypherDateTime(d if d.tzinfo else d.replace(
            tzinfo=_dt.timezone.utc))
    if kind == "time":
        return CypherTime(_dt.time(d.hour, d.minute, d.second,
                                   d.microsecond,
                                   tzinfo=tz or _dt.timezone.utc))
    if kind == "localtime":
        return CypherLocalTime(_dt.time(d.hour, d.minute, d.second,
                                        d.microsecond))
    return CypherLocalDateTime(d.replace(tzinfo=None))


# -- duration.between family ---------------------------------------------


def _as_datetime(v: Any) -> _dt.datetime:
    if isinstance(v, CypherDate):
        return _dt.datetime.combine(v._dt, _dt.time())
    if isinstance(v, (CypherDateTime, CypherLocalDateTime)):
        return v._dt.replace(tzinfo=None)
    if isinstance(v, (CypherTime, CypherLocalTime)):
        t = v._dt
        return _dt.datetime(1970, 1, 1, t.hour, t.minute, t.second,
                            t.microsecond)
    raise CypherRuntimeError("expected a temporal value")


def duration_between(a: Any, b: Any) -> CypherDuration:
    """Calendar-aware difference (duration.between)."""
    da, db = _as_datetime(a), _as_datetime(b)
    sign = 1
    if db < da:
        da, db = db, da
        sign = -1
    months = (db.year - da.year) * 12 + (db.month - da.month)
    anchor = _shift_datetime(da, CypherDuration(months=months))
    if anchor > db:
        months -= 1
        anchor = _shift_datetime(da, CypherDuration(months=months))
    delta = db - anchor
    days = delta.days
    seconds = delta.seconds
    nanos = delta.microseconds * 1000
    d = CypherDuration(months, days, seconds, nanos)
    return -d if sign < 0 else d


def duration_in_months(a: Any, b: Any) -> CypherDuration:
    d = duration_between(a, b)
    return CypherDuration(months=d.months)


def duration_in_days(a: Any, b: Any) -> CypherDuration:
    da, db = _as_datetime(a), _as_datetime(b)
    delta = db - da
    total_s = delta.days * 86400 + delta.seconds
    return CypherDuration(days=int(total_s / 86400))  # truncate toward zero


def duration_in_seconds(a: Any, b: Any) -> CypherDuration:
    da, db = _as_datetime(a), _as_datetime(b)
    delta = db - da
    # exact integer microseconds; timedelta's days carries the sign while
    # seconds/microseconds are positive floor remainders — summing keeps
    # the exact (possibly negative) instant
    total_us = ((delta.days * 86400 + delta.seconds) * 1_000_000
                + delta.microseconds)
    return CypherDuration(seconds=total_us // 1_000_000,
                          nanos=(total_us % 1_000_000) * 1000)


# -- spatial --------------------------------------------------------------


class CypherPoint:
    """2D/3D point, cartesian or WGS-84 (reference: spatial functions)."""

    __slots__ = ("x", "y", "z", "crs")

    def __init__(self, x: float, y: float, z: Optional[float] = None,
                 crs: str = "cartesian"):
        self.x = float(x)
        self.y = float(y)
        self.z = None if z is None else float(z)
        self.crs = crs

    @property
    def longitude(self):
        return self.x if self.crs.startswith("wgs-84") else None

    @property
    def latitude(self):
        return self.y if self.crs.startswith("wgs-84") else None

    def component(self, name: str):
        n = name.lower()
        if n == "x":
            return self.x
        if n == "y":
            return self.y
        if n == "z":
            return self.z
        if n == "crs":
            return self.crs
        if n == "srid":
            return {"cartesian": 7203, "cartesian-3d": 9157,
                    "wgs-84": 4326, "wgs-84-3d": 4979}.get(self.crs)
        if n == "longitude":
            return self.longitude
        if n == "latitude":
            return self.latitude
        if n == "height":
            return self.z if self.crs == "wgs-84-3d" else None
        return None

    def __eq__(self, other):
        return (isinstance(other, CypherPoint)
                and (self.x, self.y, self.z, self.crs)
                == (other.x, other.y, other.z, other.crs))

    def __hash__(self):
        return hash(("point", self.x, self.y, self.z, self.crs))

    def __str__(self):
        if self.z is not None:
            return f"point({{x: {self.x}, y: {self.y}, z: {self.z}, crs: '{self.crs}'}})"
        return f"point({{x: {self.x}, y: {self.y}, crs: '{self.crs}'}})"

    __repr__ = __str__


def make_point(m: Any) -> Optional[CypherPoint]:
    if m is None:
        return None
    if isinstance(m, CypherPoint):
        return m
    if not isinstance(m, dict):
        raise CypherRuntimeError("point() expects a map")
    low = {k.lower(): v for k, v in m.items()}
    if "latitude" in low and "longitude" in low:
        z = low.get("height")
        crs = "wgs-84-3d" if z is not None else "wgs-84"
        return CypherPoint(low["longitude"], low["latitude"], z, crs)
    if "x" in low and "y" in low:
        z = low.get("z")
        crs = low.get("crs") or ("cartesian-3d" if z is not None else "cartesian")
        return CypherPoint(low["x"], low["y"], z, crs)
    raise CypherRuntimeError("point() requires x/y or latitude/longitude")


_EARTH_RADIUS_M = 6_378_140.0


def point_distance(a: Any, b: Any) -> Optional[float]:
    if a is None or b is None:
        return None
    if not isinstance(a, CypherPoint) or not isinstance(b, CypherPoint):
        raise CypherRuntimeError("distance() expects two points")
    if a.crs != b.crs:
        return None  # Neo4j: distance across CRS is null
    if a.crs.startswith("wgs-84"):
        # haversine on the sphere (+ altitude delta for 3d)
        la1, lo1 = math.radians(a.latitude), math.radians(a.longitude)
        la2, lo2 = math.radians(b.latitude), math.radians(b.longitude)
        h = (math.sin((la2 - la1) / 2) ** 2
             + math.cos(la1) * math.cos(la2) * math.sin((lo2 - lo1) / 2) ** 2)
        ground = 2 * _EARTH_RADIUS_M * math.asin(math.sqrt(h))
        if a.crs == "wgs-84-3d":
            dz = (a.z or 0.0) - (b.z or 0.0)
            return math.sqrt(ground * ground + dz * dz)
        return ground
    dz = ((a.z or 0.0) - (b.z or 0.0)) if (a.z is not None or b.z is not None) else 0.0
    return math.sqrt((a.x - b.x) ** 2 + (a.y - b.y) ** 2 + dz * dz)


TEMPORAL_TYPES = (CypherDate, CypherTime, CypherLocalTime, CypherDateTime,
                  CypherLocalDateTime)


# -- storage / wire codec -------------------------------------------------
#
# Temporal, duration, and point values stored as node/edge properties must
# survive msgpack (WAL, native KV) and JSON (cluster transport) encoding.
# They serialize as tagged maps and decode back to value objects, so a
# restart or a replica apply reconstructs the same typed value
# (reference: Neo4j persists temporals natively in its record format).

_TAG = "__nornic_value__"

_KIND_MAKERS = {
    "date": lambda s: make_date(s),
    "datetime": lambda s: make_datetime(s),
    "localdatetime": lambda s: make_localdatetime(s),
    "time": lambda s: make_time(s),
    "localtime": lambda s: make_localtime(s),
}


def encode_value(v: Any):
    """msgpack `default=` / json `default=` hook for typed values."""
    if isinstance(v, CypherDate):
        return {_TAG: "date", "v": str(v)}
    if isinstance(v, CypherDateTime):
        return {_TAG: "datetime", "v": str(v)}
    if isinstance(v, CypherLocalDateTime):
        return {_TAG: "localdatetime", "v": str(v)}
    if isinstance(v, CypherTime):
        return {_TAG: "time", "v": str(v)}
    if isinstance(v, CypherLocalTime):
        return {_TAG: "localtime", "v": str(v)}
    if isinstance(v, CypherDuration):
        return {_TAG: "duration", "m": v.months, "d": v.days,
                "s": v.seconds, "n": v.nanos}
    if isinstance(v, CypherPoint):
        return {_TAG: "point", "x": v.x, "y": v.y, "z": v.z, "crs": v.crs}
    raise TypeError(f"can not serialize {type(v).__name__}")


def decode_map(m: Dict[str, Any]):
    """msgpack `object_hook`: revive a tagged map, else return it as-is.

    `__nornic_value__` is a reserved property-map key. Decoding is
    strict-schema: a map carrying the tag but not matching the codec's
    exact shape is returned unchanged (never crashes replay), so an
    unlucky user map can only collide by reproducing the full schema.
    """
    kind = m.get(_TAG) if isinstance(m, dict) else None
    if kind is None:
        return m
    try:
        if kind == "duration" and set(m) == {_TAG, "m", "d", "s", "n"}:
            return CypherDuration(m["m"], m["d"], m["s"], m["n"])
        if kind == "point" and set(m) == {_TAG, "x", "y", "z", "crs"}:
            return CypherPoint(m["x"], m["y"], m.get("z"),
                               m.get("crs", "cartesian"))
        maker = _KIND_MAKERS.get(kind)
        if maker is not None and set(m) == {_TAG, "v"} and isinstance(
            m["v"], str
        ):
            return maker(m["v"])
    except (KeyError, TypeError, ValueError, CypherRuntimeError):
        return m
    return m


def decode_tree(obj: Any):
    """Recursively revive tagged maps in a parsed-JSON tree (cluster
    transport path, where no object_hook ran)."""
    if isinstance(obj, dict):
        decoded = {k: decode_tree(v) for k, v in obj.items()}
        return decode_map(decoded)
    if isinstance(obj, list):
        return [decode_tree(x) for x in obj]
    return obj
