"""Strict diagnostic parser mode.

Reference: pkg/cypher/antlr/ — the reference runs a second, full
OpenCypher ANTLR parser for strict validation with line/column
diagnostics (73-4,753x slower than the nornic fast path;
docs/architecture/cypher-parser-modes.md), selected by
NORNICDB_PARSER. The TPU build's diagnostic mode runs a genuine second
parser: a grammar-complete recursive-descent implementation
(strict_grammar.py) enforcing the clause-order/shape rules the fast
parser skips on the hot path, then layers semantic validation on the
fast parser's AST — undefined variables, aggregates in WHERE, unknown
functions/procedures — all with line/col diagnostics.

Executor wiring: ``CypherExecutor(parser_mode="strict")`` (or the
NORNICDB_TPU_PARSER env var) validates every query before execution and
raises with diagnostics; parity with the fast path is covered by
tests/test_strict_parser.py (same accept/reject on the corpus).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Set

from nornicdb_tpu.errors import CypherSyntaxError
from nornicdb_tpu.query import ast as A


@dataclass
class Diagnostic:
    severity: str  # 'error' | 'warning'
    message: str
    line: int = 1
    column: int = 1

    def __str__(self):
        return f"{self.severity} at {self.line}:{self.column}: {self.message}"


def _line_col(text: str, pos: int) -> tuple:
    upto = text[:pos]
    line = upto.count("\n") + 1
    col = pos - (upto.rfind("\n") + 1) + 1
    return line, col


_AGG = {"count", "sum", "avg", "min", "max", "collect", "stdev", "stdevp",
        "percentilecont", "percentiledisc"}


def _is_agg(name: str) -> bool:
    return name in _AGG or name.startswith("apoc.agg.")


def validate(query: str) -> List[Diagnostic]:
    """Full-strictness validation; empty list = clean.

    Two passes, mirroring the reference's ANTLR mode:
    1. grammar: the independent strict parser (strict_grammar.py) —
       clause order, UNION mixing, pagination types, pattern shape —
       the class of syntax errors the fast parser tolerates;
    2. semantics: undefined variables, aggregates in WHERE, unknown
       functions, over the fast parser's AST."""
    from nornicdb_tpu.query.parser import parse
    from nornicdb_tpu.query.strict_grammar import StrictParser, \
        StrictSyntaxError

    diags: List[Diagnostic] = []
    try:
        StrictParser(query).parse()
    except StrictSyntaxError as e:
        diags.append(Diagnostic("error", e.bare_message, e.line, e.column))
        return diags
    except CypherSyntaxError as e:
        diags.append(Diagnostic("error", str(e)))
        return diags
    try:
        uq = parse(query)
    except CypherSyntaxError as e:
        msg = str(e)
        line, col = 1, 1
        # fast-parser errors embed the byte offset ("... at 17")
        import re

        m = re.search(r" at (\d+)$", msg)
        if m:
            line, col = _line_col(query, int(m.group(1)))
        diags.append(Diagnostic("error", msg, line, col))
        return diags
    for part in uq.parts:
        diags.extend(_validate_query(part))
    return diags


def _validate_query(q: A.Query) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    bound: Set[str] = set()

    def bind_path(path: A.PatternPath) -> None:
        for n in path.nodes:
            if n.var:
                bound.add(n.var)
        for r in path.rels:
            if r.var:
                bound.add(r.var)
        if path.path_var:
            bound.add(path.path_var)

    def check_expr(e: Optional[A.Expr], where: str,
                   local: Optional[Set[str]] = None,
                   allow_agg: bool = False) -> None:
        if e is None:
            return
        scope = bound | (local or set())
        if isinstance(e, A.Var):
            # "*" marks an open scope (CALL ... YIELD * / WITH * after a
            # procedure): yielded columns are unknowable statically
            if e.name not in scope and "*" not in scope:
                diags.append(Diagnostic(
                    "error", f"variable `{e.name}` not defined ({where})"))
            return
        if isinstance(e, A.FuncCall):
            if _is_agg(e.name) and not allow_agg:
                diags.append(Diagnostic(
                    "error",
                    f"aggregate {e.name}() is not allowed in {where}"))
            elif not _is_agg(e.name) and not _known_function(e.name):
                diags.append(Diagnostic(
                    "warning", f"unknown function {e.name}()"))
            for a in e.args:
                check_expr(a, where, local, allow_agg=False)
            return
        if isinstance(e, (A.ListComp,)):
            check_expr(e.source, where, local)
            inner = (local or set()) | {e.var}
            check_expr(e.where, where, inner)
            check_expr(e.projection, where, inner)
            return
        if isinstance(e, A.ListPredicate):
            check_expr(e.source, where, local)
            check_expr(e.where, where, (local or set()) | {e.var})
            return
        if isinstance(e, A.Reduce):
            check_expr(e.init, where, local)
            check_expr(e.source, where, local)
            check_expr(e.expr, where, (local or set()) | {e.acc, e.var})
            return
        if isinstance(e, (A.PatternPredicate, A.Exists)):
            return  # patterns bind their own scope
        import dataclasses

        if dataclasses.is_dataclass(e) and not isinstance(e, type):
            for f in dataclasses.fields(e):
                v = getattr(e, f.name)
                if isinstance(v, A.Expr):
                    check_expr(v, where, local, allow_agg=allow_agg)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, A.Expr):
                            check_expr(x, where, local, allow_agg=allow_agg)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, A.Expr):
                                    check_expr(y, where, local,
                                               allow_agg=allow_agg)

    for clause in q.clauses:
        if isinstance(clause, A.MatchClause):
            for p in clause.paths:
                bind_path(p)
            check_expr(clause.where, "WHERE")
        elif isinstance(clause, (A.CreateClause,)):
            for p in clause.paths:
                for pr in p.rels:
                    if not pr.types:
                        diags.append(Diagnostic(
                            "error",
                            "CREATE requires a relationship type"))
                    if pr.min_hops != 1 or pr.max_hops != 1:
                        diags.append(Diagnostic(
                            "error",
                            "CREATE cannot use variable-length patterns"))
                bind_path(p)
        elif isinstance(clause, A.MergeClause):
            bind_path(clause.path)
        elif isinstance(clause, A.UnwindClause):
            check_expr(clause.expr, "UNWIND")
            bound.add(clause.var)
        elif isinstance(clause, (A.WithClause, A.ReturnClause)):
            for item in clause.items:
                check_expr(item.expr, "projection", allow_agg=True)
            for expr, _desc in clause.order_by:
                pass  # ORDER BY may reference aliases; skip
            if isinstance(clause, A.WithClause):
                new_scope = set()
                if clause.star:
                    new_scope |= bound
                elif "*" in bound:
                    new_scope.add("*")  # open scope survives projection
                for item in clause.items:
                    if item.alias:
                        new_scope.add(item.alias)
                    elif isinstance(item.expr, A.Var):
                        new_scope.add(item.expr.name)
                bound.clear()
                bound.update(new_scope)
                check_expr(clause.where, "WHERE")
        elif isinstance(clause, A.SetClause):
            for item in clause.items:
                check_expr(item.target, "SET")
                check_expr(item.value, "SET")
        elif isinstance(clause, A.DeleteClause):
            for e in clause.exprs:
                check_expr(e, "DELETE")
        elif isinstance(clause, A.CallClause):
            for a in clause.args:
                check_expr(a, "CALL arguments")
            for name, alias in clause.yield_items:
                bound.add(alias or name)
            if clause.yield_star:
                bound.add("*")
    return diags


def _known_function(name: str) -> bool:
    from nornicdb_tpu.query.apoc import lookup_apoc, lookup_apoc_ctx
    from nornicdb_tpu.query.functions import lookup

    if (lookup(name) is not None or lookup_apoc(name) is not None
            or lookup_apoc_ctx(name) is not None):
        return True
    if name.startswith("apoc.agg."):
        from nornicdb_tpu.query.apoc_bulk import AGG_FINALIZERS

        return name in AGG_FINALIZERS
    return name in ("exists", "shortestpath", "allshortestpaths",
                    "degree", "indegree", "outdegree", "__pattern_count__")


def assert_valid(query: str) -> None:
    """Raise CypherSyntaxError listing every error diagnostic."""
    errors = [d for d in validate(query) if d.severity == "error"]
    if errors:
        raise CypherSyntaxError("; ".join(str(d) for d in errors))
