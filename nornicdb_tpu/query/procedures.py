"""CALL procedure implementations.

Reference: pkg/cypher/call.go:613 executeCall dispatch + the db.*/dbms.*
surface (call_vector.go:19 db.index.vector.queryNodes, call_fulltext.go,
executor_show.go).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List

from nornicdb_tpu.errors import CypherRuntimeError


def _coerce_instant(v: Any):
    """Any temporal-ish value -> comparable instant (epoch seconds).
    Bare numbers are epoch MILLIS, matching the datetime() builtin's
    convention (temporal_types.make_datetime; Neo4j
    datetime({epochMillis: v})) so mixed string/numeric temporal
    properties compare on one scale."""
    from nornicdb_tpu.query import temporal_types as T

    if v is None:
        return None
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v) / 1000.0
    if isinstance(v, str):
        return T.make_datetime(v)._epoch_seconds()
    if isinstance(v, (T.CypherDateTime, T.CypherLocalDateTime)):
        return v._epoch_seconds()
    if isinstance(v, T.CypherDate):
        return T.make_datetime(v)._epoch_seconds()
    raise CypherRuntimeError(f"not a datetime: {v!r}")


def _find_wal(storage):
    """Unwrap the engine chain to the WAL, if one is present."""
    eng = storage
    for _ in range(8):
        wal = getattr(eng, "wal", None)
        if wal is not None:
            return wal
        eng = getattr(eng, "inner", None)
        if eng is None:
            return None
    return None


def run_procedure(
    executor, name: str, args: List[Any], ctx
) -> Iterator[Dict[str, Any]]:
    name = name.lower()
    storage = ctx.storage

    if name == "db.labels":
        seen = {}
        for n in storage.all_nodes():
            for l in n.labels:
                seen[l] = None
        for l in sorted(seen):
            yield {"label": l}
        return

    if name == "db.relationshiptypes":
        seen = {}
        for e in storage.all_edges():
            seen[e.type] = None
        for t in sorted(seen):
            yield {"relationshipType": t}
        return

    if name == "db.propertykeys":
        seen = {}
        for n in storage.all_nodes():
            for k in n.properties:
                seen[k] = None
        for e in storage.all_edges():
            for k in e.properties:
                seen[k] = None
        for k in sorted(seen):
            yield {"propertyKey": k}
        return

    if name == "db.schema.visualization":
        labels = {}
        for n in storage.all_nodes():
            for l in n.labels:
                labels[l] = None
        yield {"nodes": sorted(labels), "relationships": []}
        return

    if name in ("dbms.components",):
        from nornicdb_tpu import __version__

        yield {
            "name": "nornicdb-tpu",
            "versions": [__version__],
            "edition": "tpu",
        }
        return

    if name == "db.index.vector.querynodes":
        # (indexName, k, queryVector) — reference call_vector.go:19
        if len(args) < 3:
            raise CypherRuntimeError(
                "db.index.vector.queryNodes(indexName, k, vector)"
            )
        _index_name, k, vector = args[0], int(args[1]), args[2]
        svc = executor._search
        if svc is None:
            raise CypherRuntimeError("no search service wired")
        for node_id, score in svc.vector_search_candidates(vector, k):
            try:
                node = storage.get_node(node_id)
            except KeyError:
                continue
            yield {"node": node, "score": float(score)}
        return

    if name == "db.index.fulltext.querynodes":
        if len(args) < 2:
            raise CypherRuntimeError(
                "db.index.fulltext.queryNodes(indexName, query[, k])"
            )
        _index_name, query = args[0], args[1]
        k = int(args[2]) if len(args) > 2 else 10
        svc = executor._search
        if svc is None:
            raise CypherRuntimeError("no search service wired")
        for node_id, score in svc.bm25.search(query, k):
            try:
                node = storage.get_node(node_id)
            except KeyError:
                continue
            yield {"node": node, "score": float(score)}
        return

    if name == "db.temporal.asof":
        # (label, keyProp, keyValue, validFromProp, validToProp, asOf) —
        # most recent node whose [validFrom, validTo) covers asOf
        # (reference: call_temporal.go:98 callDbTemporalAsOf)
        if len(args) < 6:
            raise CypherRuntimeError(
                "db.temporal.asOf(label, keyProp, keyValue, validFromProp, "
                "validToProp, asOf)")
        label, key_prop, key_value, from_prop, to_prop, as_of = args[:6]
        as_of_v = _coerce_instant(as_of)
        if as_of_v is None:
            raise CypherRuntimeError("asOf must be a valid datetime")
        best = None
        best_from = None
        for n in storage.get_nodes_by_label(str(label)):
            if n.properties.get(key_prop) != key_value:
                continue
            vf = _coerce_instant(n.properties.get(from_prop))
            vt = n.properties.get(to_prop)
            vt_v = _coerce_instant(vt) if vt is not None else None
            if vf is None or vf > as_of_v:
                continue
            if vt_v is not None and vt_v <= as_of_v:
                continue
            if best_from is None or vf > best_from:
                best, best_from = n, vf
        if best is not None:
            yield {"node": best}
        return

    if name == "db.temporal.assertnooverlap":
        # (label, keyProp, validFromProp, validToProp, keyValue,
        #  newValidFrom, newValidTo) — reference: call_temporal.go:29
        if len(args) < 7:
            raise CypherRuntimeError(
                "db.temporal.assertNoOverlap requires 7 parameters")
        label, key_prop, from_prop, to_prop, key_value, nf, nt = args[:7]
        new_from = _coerce_instant(nf)
        if new_from is None:
            raise CypherRuntimeError("newValidFrom must be a valid datetime")
        new_to = _coerce_instant(nt) if nt is not None else None
        for n in storage.get_nodes_by_label(str(label)):
            if n.properties.get(key_prop) != key_value:
                continue
            vf = _coerce_instant(n.properties.get(from_prop))
            vt = n.properties.get(to_prop)
            vt_v = _coerce_instant(vt) if vt is not None else None
            if vf is None:
                continue
            # [vf, vt) overlaps [new_from, new_to)?
            starts_before_existing_ends = vt_v is None or new_from < vt_v
            existing_starts_before_new_ends = new_to is None or vf < new_to
            if starts_before_existing_ends and existing_starts_before_new_ends:
                raise CypherRuntimeError(
                    f"temporal overlap with node {n.id} "
                    f"[{n.properties.get(from_prop)}, "
                    f"{n.properties.get(to_prop)})")
        yield {"ok": True}
        return

    if name == "db.txlog.entries":
        # (fromSeq[, toSeq]) — reference: call_txlog.go:17; yields the
        # WAL's seq-tagged mutation history
        wal = _find_wal(executor.storage)
        if wal is None:
            raise CypherRuntimeError(
                "db.txlog.entries requires a WAL-backed engine")
        from_seq = int(args[0]) if args else 0
        to_seq = int(args[1]) if len(args) > 1 else None
        # drain the whole engine chain first: with async_writes the
        # AsyncEngine overlay holds committed mutations until flushed
        try:
            executor.storage.flush()
        except Exception:
            pass
        wal.flush()  # segment writes are buffered; readers open the file
        for rec in wal.iter_records(from_seq=max(0, from_seq - 1)):
            seq = rec.get("seq", 0)
            if seq < from_seq or (to_seq is not None and seq > to_seq):
                continue
            yield {"sequence": seq, "operation": rec.get("op", ""),
                   "data": rec.get("data", {})}
        return

    if name in ("db.awaitindex", "db.awaitindexes", "db.resampleindex",
                "db.resampleoutdatedindexes"):
        # indexes here are synchronous (label/type maps maintained on
        # write; columnar snapshots built lazily) — nothing to wait for
        # (reference: call_index_mgmt.go)
        yield {"ok": True}
        return

    if name.startswith("db.stats."):
        stats = getattr(executor, "_db_stats", None)
        if name == "db.stats.collect":
            executor._db_stats = {"collecting": True, "queries": 0}
            yield {"section": "QUERIES", "success": True,
                   "message": "collection started"}
            return
        if name == "db.stats.stop":
            if stats is not None:
                stats["collecting"] = False
            yield {"section": "QUERIES", "success": True,
                   "message": "collection stopped"}
            return
        if name == "db.stats.clear":
            executor._db_stats = None
            yield {"section": "QUERIES", "success": True,
                   "message": "cleared"}
            return
        if name == "db.stats.retrieve":
            yield {"section": "QUERIES",
                   "data": dict(stats or {}, **{
                       "cache": executor.query_cache.stats()})}
            return

    if name == "gds.version":
        yield {"version": "2.x-compat (nornicdb-tpu)"}
        return

    if name.startswith("gds.graph.") or name == "gds.fastrp.stream":
        # graph catalog + FastRP (reference: pkg/cypher/fastrp.go:8-26)
        from nornicdb_tpu.ops.fastrp import GdsGraphCatalog

        catalog = getattr(executor, "gds_catalog", None)
        if catalog is None:
            catalog = GdsGraphCatalog()
            executor.gds_catalog = catalog
        if name == "gds.graph.project":
            if len(args) < 3:
                raise CypherRuntimeError(
                    "gds.graph.project(name, nodeProjection, relProjection)")
            g = catalog.project(storage, str(args[0]),
                                args[1] if args[1] != "*" else None,
                                args[2] if args[2] != "*" else None)
            yield {
                "graphName": g["name"], "nodeCount": g["nodeCount"],
                "relationshipCount": g["relationshipCount"],
                "nodeProjection": g["nodeProjection"],
                "relationshipProjection": g["relationshipProjection"],
            }
            return
        if name == "gds.graph.list":
            for g in catalog.list():
                yield {"graphName": g["name"], "nodeCount": g["nodeCount"],
                       "relationshipCount": g["relationshipCount"]}
            return
        if name == "gds.graph.drop":
            g = catalog.drop(str(args[0]) if args else "")
            if g is None:
                raise CypherRuntimeError(f"graph {args[0]!r} not found")
            yield {"graphName": g["name"]}
            return
        if name == "gds.fastrp.stream":
            if not args:
                raise CypherRuntimeError(
                    "gds.fastRP.stream(graphName, config)")
            cfg = args[1] if len(args) > 1 else {}
            cfg = cfg or {}
            try:
                ids, emb = catalog.fastrp(
                    str(args[0]),
                    dim=int(cfg.get("embeddingDimension", 64)),
                    iteration_weights=cfg.get("iterationWeights",
                                              (0.0, 1.0, 1.0)),
                    normalization_strength=float(
                        cfg.get("normalizationStrength", 0.0)),
                    seed=int(cfg.get("randomSeed", 42)),
                )
            except KeyError as e:
                raise CypherRuntimeError(str(e))
            for nid, vec in zip(ids, emb):
                yield {"nodeId": nid, "embedding": [float(x) for x in vec]}
            return

    if name.startswith("gds.linkprediction."):
        # Neo4j GDS link-prediction procedures (reference:
        # pkg/cypher/linkprediction.go:1-559 — always available, result
        # format {node1, node2, score}; hybrid predict adds
        # topology_score/semantic_score).
        from nornicdb_tpu.linkpredict import hybrid_predict, predict_links

        method_map = {
            "gds.linkprediction.adamicadar.stream": "adamic_adar",
            "gds.linkprediction.commonneighbors.stream": "common_neighbors",
            "gds.linkprediction.jaccard.stream": "jaccard",
            "gds.linkprediction.preferentialattachment.stream":
                "preferential_attachment",
            "gds.linkprediction.resourceallocation.stream":
                "resource_allocation",
        }
        cfg = args[0] if args else {}
        if not isinstance(cfg, dict):
            raise CypherRuntimeError(
                "gds.linkPrediction.*.stream expects a configuration map "
                "{sourceNode, topK}"
            )
        source = cfg.get("sourceNode") or cfg.get("sourcenode")
        if source is None:
            raise CypherRuntimeError("configuration requires sourceNode")
        source_id = source.id if hasattr(source, "id") else str(source)
        top_k = int(cfg.get("topK", cfg.get("topk", 10)))
        if name in method_map:
            for nid, score in predict_links(
                storage, source_id, method=method_map[name], limit=top_k
            ):
                yield {"node1": source_id, "node2": nid, "score": float(score)}
            return
        if name == "gds.linkprediction.predict.stream":
            weight = float(cfg.get("topologyWeight",
                                   cfg.get("topologyweight", 0.5)))
            for nid, score, topo, sem in hybrid_predict(
                storage, executor._search, source_id,
                topology_weight=weight, limit=top_k,
            ):
                yield {
                    "node1": source_id, "node2": nid, "score": float(score),
                    "topology_score": float(topo),
                    "semantic_score": float(sem),
                }
            return
        raise CypherRuntimeError(f"unknown procedure {name}")

    if name.startswith("apoc."):
        from nornicdb_tpu.query.apoc import run_apoc_procedure

        yield from run_apoc_procedure(executor, name, args, ctx)
        return

    raise CypherRuntimeError(f"unknown procedure {name}")
