"""CALL procedure implementations.

Reference: pkg/cypher/call.go:613 executeCall dispatch + the db.*/dbms.*
surface (call_vector.go:19 db.index.vector.queryNodes, call_fulltext.go,
executor_show.go).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List

from nornicdb_tpu.errors import CypherRuntimeError


def run_procedure(
    executor, name: str, args: List[Any], ctx
) -> Iterator[Dict[str, Any]]:
    name = name.lower()
    storage = ctx.storage

    if name == "db.labels":
        seen = {}
        for n in storage.all_nodes():
            for l in n.labels:
                seen[l] = None
        for l in sorted(seen):
            yield {"label": l}
        return

    if name == "db.relationshiptypes":
        seen = {}
        for e in storage.all_edges():
            seen[e.type] = None
        for t in sorted(seen):
            yield {"relationshipType": t}
        return

    if name == "db.propertykeys":
        seen = {}
        for n in storage.all_nodes():
            for k in n.properties:
                seen[k] = None
        for e in storage.all_edges():
            for k in e.properties:
                seen[k] = None
        for k in sorted(seen):
            yield {"propertyKey": k}
        return

    if name == "db.schema.visualization":
        labels = {}
        for n in storage.all_nodes():
            for l in n.labels:
                labels[l] = None
        yield {"nodes": sorted(labels), "relationships": []}
        return

    if name in ("dbms.components",):
        from nornicdb_tpu import __version__

        yield {
            "name": "nornicdb-tpu",
            "versions": [__version__],
            "edition": "tpu",
        }
        return

    if name == "db.index.vector.querynodes":
        # (indexName, k, queryVector) — reference call_vector.go:19
        if len(args) < 3:
            raise CypherRuntimeError(
                "db.index.vector.queryNodes(indexName, k, vector)"
            )
        _index_name, k, vector = args[0], int(args[1]), args[2]
        svc = executor._search
        if svc is None:
            raise CypherRuntimeError("no search service wired")
        for node_id, score in svc.vector_search_candidates(vector, k):
            try:
                node = storage.get_node(node_id)
            except KeyError:
                continue
            yield {"node": node, "score": float(score)}
        return

    if name == "db.index.fulltext.querynodes":
        if len(args) < 2:
            raise CypherRuntimeError(
                "db.index.fulltext.queryNodes(indexName, query[, k])"
            )
        _index_name, query = args[0], args[1]
        k = int(args[2]) if len(args) > 2 else 10
        svc = executor._search
        if svc is None:
            raise CypherRuntimeError("no search service wired")
        for node_id, score in svc.bm25.search(query, k):
            try:
                node = storage.get_node(node_id)
            except KeyError:
                continue
            yield {"node": node, "score": float(score)}
        return

    if name.startswith("apoc."):
        from nornicdb_tpu.query.apoc import run_apoc_procedure

        yield from run_apoc_procedure(executor, name, args, ctx)
        return

    raise CypherRuntimeError(f"unknown procedure {name}")
