"""CALL procedure implementations.

Reference: pkg/cypher/call.go:613 executeCall dispatch + the db.*/dbms.*
surface (call_vector.go:19 db.index.vector.queryNodes, call_fulltext.go,
executor_show.go).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List

from nornicdb_tpu.errors import CypherRuntimeError


def run_procedure(
    executor, name: str, args: List[Any], ctx
) -> Iterator[Dict[str, Any]]:
    name = name.lower()
    storage = ctx.storage

    if name == "db.labels":
        seen = {}
        for n in storage.all_nodes():
            for l in n.labels:
                seen[l] = None
        for l in sorted(seen):
            yield {"label": l}
        return

    if name == "db.relationshiptypes":
        seen = {}
        for e in storage.all_edges():
            seen[e.type] = None
        for t in sorted(seen):
            yield {"relationshipType": t}
        return

    if name == "db.propertykeys":
        seen = {}
        for n in storage.all_nodes():
            for k in n.properties:
                seen[k] = None
        for e in storage.all_edges():
            for k in e.properties:
                seen[k] = None
        for k in sorted(seen):
            yield {"propertyKey": k}
        return

    if name == "db.schema.visualization":
        labels = {}
        for n in storage.all_nodes():
            for l in n.labels:
                labels[l] = None
        yield {"nodes": sorted(labels), "relationships": []}
        return

    if name in ("dbms.components",):
        from nornicdb_tpu import __version__

        yield {
            "name": "nornicdb-tpu",
            "versions": [__version__],
            "edition": "tpu",
        }
        return

    if name == "db.index.vector.querynodes":
        # (indexName, k, queryVector) — reference call_vector.go:19
        if len(args) < 3:
            raise CypherRuntimeError(
                "db.index.vector.queryNodes(indexName, k, vector)"
            )
        _index_name, k, vector = args[0], int(args[1]), args[2]
        svc = executor._search
        if svc is None:
            raise CypherRuntimeError("no search service wired")
        for node_id, score in svc.vector_search_candidates(vector, k):
            try:
                node = storage.get_node(node_id)
            except KeyError:
                continue
            yield {"node": node, "score": float(score)}
        return

    if name == "db.index.fulltext.querynodes":
        if len(args) < 2:
            raise CypherRuntimeError(
                "db.index.fulltext.queryNodes(indexName, query[, k])"
            )
        _index_name, query = args[0], args[1]
        k = int(args[2]) if len(args) > 2 else 10
        svc = executor._search
        if svc is None:
            raise CypherRuntimeError("no search service wired")
        for node_id, score in svc.bm25.search(query, k):
            try:
                node = storage.get_node(node_id)
            except KeyError:
                continue
            yield {"node": node, "score": float(score)}
        return

    if name == "gds.version":
        yield {"version": "2.x-compat (nornicdb-tpu)"}
        return

    if name.startswith("gds.graph.") or name == "gds.fastrp.stream":
        # graph catalog + FastRP (reference: pkg/cypher/fastrp.go:8-26)
        from nornicdb_tpu.ops.fastrp import GdsGraphCatalog

        catalog = getattr(executor, "gds_catalog", None)
        if catalog is None:
            catalog = GdsGraphCatalog()
            executor.gds_catalog = catalog
        if name == "gds.graph.project":
            if len(args) < 3:
                raise CypherRuntimeError(
                    "gds.graph.project(name, nodeProjection, relProjection)")
            g = catalog.project(storage, str(args[0]),
                                args[1] if args[1] != "*" else None,
                                args[2] if args[2] != "*" else None)
            yield {
                "graphName": g["name"], "nodeCount": g["nodeCount"],
                "relationshipCount": g["relationshipCount"],
                "nodeProjection": g["nodeProjection"],
                "relationshipProjection": g["relationshipProjection"],
            }
            return
        if name == "gds.graph.list":
            for g in catalog.list():
                yield {"graphName": g["name"], "nodeCount": g["nodeCount"],
                       "relationshipCount": g["relationshipCount"]}
            return
        if name == "gds.graph.drop":
            g = catalog.drop(str(args[0]) if args else "")
            if g is None:
                raise CypherRuntimeError(f"graph {args[0]!r} not found")
            yield {"graphName": g["name"]}
            return
        if name == "gds.fastrp.stream":
            if not args:
                raise CypherRuntimeError(
                    "gds.fastRP.stream(graphName, config)")
            cfg = args[1] if len(args) > 1 else {}
            cfg = cfg or {}
            try:
                ids, emb = catalog.fastrp(
                    str(args[0]),
                    dim=int(cfg.get("embeddingDimension", 64)),
                    iteration_weights=cfg.get("iterationWeights",
                                              (0.0, 1.0, 1.0)),
                    normalization_strength=float(
                        cfg.get("normalizationStrength", 0.0)),
                    seed=int(cfg.get("randomSeed", 42)),
                )
            except KeyError as e:
                raise CypherRuntimeError(str(e))
            for nid, vec in zip(ids, emb):
                yield {"nodeId": nid, "embedding": [float(x) for x in vec]}
            return

    if name.startswith("gds.linkprediction."):
        # Neo4j GDS link-prediction procedures (reference:
        # pkg/cypher/linkprediction.go:1-559 — always available, result
        # format {node1, node2, score}; hybrid predict adds
        # topology_score/semantic_score).
        from nornicdb_tpu.linkpredict import hybrid_predict, predict_links

        method_map = {
            "gds.linkprediction.adamicadar.stream": "adamic_adar",
            "gds.linkprediction.commonneighbors.stream": "common_neighbors",
            "gds.linkprediction.jaccard.stream": "jaccard",
            "gds.linkprediction.preferentialattachment.stream":
                "preferential_attachment",
            "gds.linkprediction.resourceallocation.stream":
                "resource_allocation",
        }
        cfg = args[0] if args else {}
        if not isinstance(cfg, dict):
            raise CypherRuntimeError(
                "gds.linkPrediction.*.stream expects a configuration map "
                "{sourceNode, topK}"
            )
        source = cfg.get("sourceNode") or cfg.get("sourcenode")
        if source is None:
            raise CypherRuntimeError("configuration requires sourceNode")
        source_id = source.id if hasattr(source, "id") else str(source)
        top_k = int(cfg.get("topK", cfg.get("topk", 10)))
        if name in method_map:
            for nid, score in predict_links(
                storage, source_id, method=method_map[name], limit=top_k
            ):
                yield {"node1": source_id, "node2": nid, "score": float(score)}
            return
        if name == "gds.linkprediction.predict.stream":
            weight = float(cfg.get("topologyWeight",
                                   cfg.get("topologyweight", 0.5)))
            for nid, score, topo, sem in hybrid_predict(
                storage, executor._search, source_id,
                topology_weight=weight, limit=top_k,
            ):
                yield {
                    "node1": source_id, "node2": nid, "score": float(score),
                    "topology_score": float(topo),
                    "semantic_score": float(sem),
                }
            return
        raise CypherRuntimeError(f"unknown procedure {name}")

    if name.startswith("apoc."):
        from nornicdb_tpu.query.apoc import run_apoc_procedure

        yield from run_apoc_procedure(executor, name, args, ctx)
        return

    raise CypherRuntimeError(f"unknown procedure {name}")
