"""Kalman filter Cypher functions with JSON-string state.

Reference: pkg/cypher/kalman_functions.go (952 LoC). The database stays
stateless: ``kalman.init()`` returns a JSON state string the user stores
in a node property; each ``process()`` call takes it and returns
``{value, state}`` with the updated state. Three filter families:

- ``kalman.*``          — scalar filter with velocity-projected predict
- ``kalman.velocity.*`` — 2-state (position, velocity) filter
- ``kalman.adaptive.*`` — auto-switches basic/velocity on trend strength

The JSON field names match the reference wire format (x/lx/p/k/e/q/r/vs/n
for basic; pos/vel/p/qp/qv/r/dt/n for velocity) so states written by one
implementation are readable by the other.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, Optional


def _default_basic() -> Dict[str, Any]:
    return {"x": 0.0, "lx": 0.0, "p": 30.0, "k": 0.0, "e": 1.0,
            "q": 0.0001, "r": 88.0, "vs": 10.0, "n": 0}


def _default_velocity() -> Dict[str, Any]:
    return {"pos": 0.0, "vel": 0.0, "p": [100.0, 0.0, 0.0, 10.0],
            "qp": 0.1, "qv": 0.01, "r": 1.0, "dt": 1.0, "n": 0}


def _default_adaptive() -> Dict[str, Any]:
    return {"basic": _default_basic(), "velocity": _default_velocity(),
            "mode": "basic", "ss": 0, "tt": 0.1, "st": 0.02, "hy": 10,
            "n": 0, "lf": 0.0, "ts": 0.0}


def _load(state_json: Any) -> Optional[Dict[str, Any]]:
    if not isinstance(state_json, str):
        return None
    try:
        s = json.loads(state_json)
    except (ValueError, TypeError):
        return None
    return s if isinstance(s, dict) else None


def _f(v: Any, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def kalman_init(config: Any = None) -> str:
    s = _default_basic()
    if isinstance(config, dict):
        if "processNoise" in config:
            s["q"] = _f(config["processNoise"]) * 0.001
        if "measurementNoise" in config:
            s["r"] = _f(config["measurementNoise"])
        if "initialCovariance" in config:
            s["p"] = _f(config["initialCovariance"])
        if "varianceScale" in config:
            s["vs"] = _f(config["varianceScale"])
    return json.dumps(s)


def kalman_process(measurement: Any, state_json: Any,
                   target: Any = 0.0) -> Dict[str, Any]:
    s = _load(state_json)
    m = _f(measurement)
    if s is None:
        return {"value": m, "state": state_json, "error": "invalid state"}
    tgt = _f(target)
    # project ahead using implied velocity, then standard scalar update
    velocity = _f(s.get("x")) - _f(s.get("lx"))
    x = _f(s.get("x")) + velocity
    s["lx"] = x
    if tgt != 0.0 and s["lx"] != 0.0:
        s["e"] = abs(1.0 - (tgt / s["lx"]))
    else:
        s["e"] = 1.0
    p = _f(s.get("p")) + _f(s.get("q")) * s["e"]
    k = p / (p + _f(s.get("r"), 1.0))
    x = x + k * (m - x)
    s["x"] = x
    s["k"] = k
    s["p"] = (1.0 - k) * p
    s["n"] = int(s.get("n", 0)) + 1
    return {"value": x, "state": json.dumps(s)}


def kalman_predict(state_json: Any, steps: Any) -> float:
    s = _load(state_json)
    if s is None:
        return 0.0
    velocity = _f(s.get("x")) - _f(s.get("lx"))
    return _f(s.get("x")) + _f(steps) * velocity


def kalman_state(state_json: Any) -> float:
    s = _load(state_json)
    return 0.0 if s is None else _f(s.get("x"))


def kalman_rate(state_json: Any) -> float:
    s = _load(state_json)
    return 0.0 if s is None else _f(s.get("x")) - _f(s.get("lx"))


def kalman_reset(state_json: Any) -> str:
    s = _load(state_json)
    fresh = _default_basic()
    if s is not None:  # keep configured noise parameters
        for key in ("q", "r", "vs"):
            if key in s:
                fresh[key] = _f(s[key], fresh[key])
    return json.dumps(fresh)


def kalman_velocity_init(initial_pos: Any = None,
                         initial_vel: Any = None) -> str:
    s = _default_velocity()
    if initial_pos is not None:
        s["pos"] = _f(initial_pos)
    if initial_vel is not None:
        s["vel"] = _f(initial_vel)
    return json.dumps(s)


def kalman_velocity_process(measurement: Any,
                            state_json: Any) -> Dict[str, Any]:
    s = _load(state_json)
    m = _f(measurement)
    if s is None:
        return {"value": m, "velocity": 0.0, "state": state_json,
                "error": "invalid state"}
    dt = _f(s.get("dt"), 1.0)
    if dt <= 0:
        dt = 1.0
    pos, vel = _f(s.get("pos")), _f(s.get("vel"))
    pm = s.get("p") or [100.0, 0.0, 0.0, 10.0]
    p00, p01, p10, p11 = (_f(pm[i]) for i in range(4))
    qp, qv, r = _f(s.get("qp"), 0.1), _f(s.get("qv"), 0.01), _f(s.get("r"), 1.0)
    # predict: constant-velocity transition F = [[1, dt], [0, 1]]
    pred_pos = pos + vel * dt
    pred_p00 = p00 + dt * p10 + dt * p01 + dt * dt * p11 + qp
    pred_p01 = p01 + dt * p11
    pred_p10 = p10 + dt * p11
    pred_p11 = p11 + qv
    # update against the position measurement (H = [1, 0])
    innov = m - pred_pos
    sj = pred_p00 + r
    k0 = pred_p00 / sj
    k1 = pred_p10 / sj
    s["pos"] = pred_pos + k0 * innov
    s["vel"] = vel + k1 * innov
    s["p"] = [(1 - k0) * pred_p00, (1 - k0) * pred_p01,
              pred_p10 - k1 * pred_p00, pred_p11 - k1 * pred_p01]
    s["n"] = int(s.get("n", 0)) + 1
    return {"value": s["pos"], "velocity": s["vel"], "state": json.dumps(s)}


def kalman_velocity_predict(state_json: Any, steps: Any) -> float:
    s = _load(state_json)
    if s is None:
        return 0.0
    dt = _f(s.get("dt"), 1.0)
    if dt <= 0:
        dt = 1.0
    return _f(s.get("pos")) + _f(s.get("vel")) * _f(steps) * dt


def kalman_adaptive_init(config: Any = None) -> str:
    s = _default_adaptive()
    if isinstance(config, dict):
        if "trendThreshold" in config:
            s["tt"] = _f(config["trendThreshold"])
        if "stabilityThreshold" in config:
            s["st"] = _f(config["stabilityThreshold"])
        if "hysteresis" in config:
            s["hy"] = int(_f(config["hysteresis"]))
        if config.get("initialMode") == "velocity":
            s["mode"] = "velocity"
    return json.dumps(s)


def kalman_adaptive_process(measurement: Any,
                            state_json: Any) -> Dict[str, Any]:
    s = _load(state_json)
    m = _f(measurement)
    if s is None:
        return {"value": m, "mode": "error", "state": state_json,
                "error": "invalid state"}
    mode = s.get("mode", "basic")
    if mode == "velocity":
        res = kalman_velocity_process(m, json.dumps(s.get("velocity") or
                                                    _default_velocity()))
        filtered = _f(res["value"])
        s["velocity"] = json.loads(res["state"])
        s["ts"] = _f(s["velocity"].get("vel"))
    else:
        res = kalman_process(m, json.dumps(s.get("basic") or
                                           _default_basic()))
        filtered = _f(res["value"])
        s["basic"] = json.loads(res["state"])
        s["ts"] = _f(s["basic"].get("x")) - _f(s["basic"].get("lx"))
    s["n"] = int(s.get("n", 0)) + 1
    s["ss"] = int(s.get("ss", 0)) + 1
    if s["ss"] >= int(s.get("hy", 10)):
        trend = abs(_f(s.get("ts")))
        if mode == "basic" and trend > _f(s.get("tt"), 0.1):
            s["mode"] = "velocity"
            s["ss"] = 0
            s["velocity"] = s.get("velocity") or _default_velocity()
            s["velocity"]["pos"] = _f(s["basic"].get("x"))
            s["velocity"]["vel"] = _f(s.get("ts"))
        elif mode == "velocity" and trend < _f(s.get("st"), 0.02):
            s["mode"] = "basic"
            s["ss"] = 0
            s["basic"] = s.get("basic") or _default_basic()
            s["basic"]["x"] = _f(s["velocity"].get("pos"))
            s["basic"]["lx"] = (_f(s["velocity"].get("pos"))
                                - _f(s["velocity"].get("vel")))
    s["lf"] = filtered
    return {"value": filtered, "mode": s.get("mode", "basic"),
            "state": json.dumps(s)}


def register_all(register) -> None:
    register("kalman.init", kalman_init)
    register("kalman.process", kalman_process)
    register("kalman.predict", kalman_predict)
    register("kalman.state", kalman_state)
    register("kalman.rate", kalman_rate)
    register("kalman.reset", kalman_reset)
    register("kalman.velocity.init", kalman_velocity_init)
    register("kalman.velocity.process", kalman_velocity_process)
    register("kalman.velocity.predict", kalman_velocity_predict)
    register("kalman.adaptive.init", kalman_adaptive_init)
    register("kalman.adaptive.process", kalman_adaptive_process)
