"""APOC long tail: periodic, triggers, path expansion, export/import,
create/merge, util/hashing, and additional function categories.

Reference: apoc/ (~40 categories, apoc/apoc.go:222 registerAllFunctions);
apoc.periodic.iterate/commit (apoc/periodic/), triggers (apoc/trigger/),
path expansion (apoc/path/), export/import/load (apoc/export/,
apoc/load/), create/merge (apoc/create/, apoc/merge/). Functions register
into the shared APOC table (query/apoc.py); procedures dispatch through
``run_ext_procedure``.
"""

from __future__ import annotations

import csv
import hashlib
import io
import json
import os
import time
from collections import Counter
from typing import Any, Dict, Iterator, List, Optional

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.query.apoc import APOC_FUNCS, _jsonable, register
from nornicdb_tpu.storage.types import Direction, Edge, Node


# -- additional function categories ---------------------------------------


def _install_functions():
    import math
    import re as _re

    # apoc.coll long tail
    register("apoc.coll.partition", lambda l, size: [
        (l or [])[i:i + int(size)] for i in range(0, len(l or []), int(size))])
    register("apoc.coll.split", lambda l, v: _coll_split(l or [], v))
    register("apoc.coll.occurrences", lambda l, v: (l or []).count(v))
    register("apoc.coll.removeAll", lambda l, items: [
        x for x in (l or []) if x not in (items or [])])
    register("apoc.coll.insert", lambda l, idx, v: (
        (l or [])[: int(idx)] + [v] + (l or [])[int(idx):]))
    register("apoc.coll.set", lambda l, idx, v: [
        v if i == int(idx) else x for i, x in enumerate(l or [])])
    register("apoc.coll.remove", lambda l, idx, length=1: (
        (l or [])[: int(idx)] + (l or [])[int(idx) + int(length):]))
    register("apoc.coll.duplicates", lambda l: [
        k for k, c in Counter(l or []).items() if c > 1])
    register("apoc.coll.different", lambda l: len(set(l or [])) == len(l or []))
    register("apoc.coll.dropDuplicateNeighbors", lambda l: [
        x for i, x in enumerate(l or []) if i == 0 or x != l[i - 1]])
    register("apoc.coll.fill", lambda v, n: [v] * int(n))
    register("apoc.coll.sumLongs", lambda l: int(sum(l or [])))
    # isBiasCorrected defaults true in APOC => sample stdev (divide n-1)
    register("apoc.coll.stdev", lambda l, bias_corrected=True: _stdev(
        l or [], biased=not bias_corrected))
    register("apoc.coll.sortMaps", lambda l, key: sorted(
        l or [], key=lambda m: (m.get(key) is None, m.get(key)), reverse=True))
    register("apoc.coll.randomItem", lambda l: (
        __import__("random").choice(l) if l else None))
    register("apoc.coll.containsAll", lambda l, items: all(
        x in (l or []) for x in (items or [])))
    register("apoc.coll.containsAny", lambda l, items: any(
        x in (l or []) for x in (items or [])))
    register("apoc.coll.unionAll", lambda a, b: (a or []) + (b or []))
    register("apoc.coll.min", lambda l: min(l) if l else None)

    # apoc.map long tail
    register("apoc.map.clean", lambda m, keys, values: {
        k: v for k, v in (m or {}).items()
        if k not in (keys or []) and v not in (values or [])})
    register("apoc.map.flatten", lambda m, delim=".": _map_flatten(m or {}, delim))
    register("apoc.map.groupBy", lambda l, key: {
        str(m.get(key)): m for m in (l or []) if m.get(key) is not None})
    register("apoc.map.groupByMulti", lambda l, key: _group_by_multi(l or [], key))
    register("apoc.map.mget", lambda m, keys: [(m or {}).get(k) for k in (keys or [])])
    register("apoc.map.submap", lambda m, keys: {
        k: (m or {}).get(k) for k in (keys or [])})
    register("apoc.map.sortedProperties", lambda m: [
        [k, (m or {})[k]] for k in sorted(m or {})])
    register("apoc.map.values", lambda m, keys=None: (
        [(m or {}).get(k) for k in keys] if keys else list((m or {}).values())))
    register("apoc.map.fromValues", lambda l: {
        l[i]: l[i + 1] for i in range(0, len(l or []) - 1, 2)})
    register("apoc.map.setEntry", lambda m, k, v: {**(m or {}), k: v})
    register("apoc.map.merge", lambda a, b: {**(a or {}), **(b or {})})

    # apoc.text long tail
    register("apoc.text.format", lambda fmt, params: (
        fmt % tuple(params or []) if "%" in (fmt or "") else fmt))
    register("apoc.text.regexGroups", lambda s, regex: [
        [m.group(0)] + list(m.groups())
        for m in _re.finditer(regex, s or "")])
    register("apoc.text.regreplace", lambda s, regex, repl: _re.sub(
        regex, repl, s or ""))
    register("apoc.text.slug", lambda s, delim="-": _re.sub(
        r"[\W_]+", delim, (s or "").strip()).strip(delim))
    register("apoc.text.hammingDistance", lambda a, b: (
        abs(len(a or "") - len(b or ""))
        + sum(x != y for x, y in zip(a or "", b or ""))))
    register("apoc.text.jaroWinklerDistance", _jaro_winkler)
    register("apoc.text.sorensenDiceSimilarity", _sorensen_dice)
    register("apoc.text.fuzzyMatch", lambda a, b: _fuzzy_match(a, b))
    register("apoc.text.code", lambda cp: chr(int(cp)))
    register("apoc.text.charAt", lambda s, i: (
        ord(s[int(i)]) if s and 0 <= int(i) < len(s) else None))
    register("apoc.text.repeat", lambda s, n: (s or "") * int(n))
    register("apoc.text.snakeCase", lambda s: _re.sub(
        r"[\s_-]+", "_", _re.sub(r"(?<=[a-z0-9])([A-Z])", r"_\1", s or "")).lower())
    register("apoc.text.toUpperCase", lambda s: (s or "").upper())
    register("apoc.text.swapCase", lambda s: (s or "").swapcase())
    register("apoc.text.byteCount", lambda s, charset="UTF-8": len(
        (s or "").encode(charset)))

    # apoc.number
    register("apoc.number.parseInt", lambda s, radix=10: (
        int(s, int(radix)) if s else None))
    register("apoc.number.parseFloat", lambda s: float(s) if s else None)

    # apoc.date long tail
    register("apoc.date.add", lambda epoch, unit, value, value_unit: (
        int(epoch) + int(value) * _unit_ms(value_unit) // _unit_ms_div(unit)))
    register("apoc.date.convert", lambda v, frm, to: (
        int(int(v) * _unit_ms(frm) / _unit_ms(to))))
    register("apoc.date.field", _date_field)
    register("apoc.date.toISO8601", lambda ms, unit="ms": __import__(
        "datetime").datetime.fromtimestamp(
        int(ms) * _unit_ms(unit) / 1000.0,
        tz=__import__("datetime").timezone.utc).isoformat())
    register("apoc.date.fromISO8601", lambda s: int(__import__(
        "datetime").datetime.fromisoformat(
        s.replace("Z", "+00:00")).timestamp() * 1000))
    register("apoc.temporal.format", _temporal_format)

    # apoc.util / hashing
    register("apoc.util.md5", lambda vals: _digest("md5", vals))
    register("apoc.util.sha1", lambda vals: _digest("sha1", vals))
    register("apoc.util.sha256", lambda vals: _digest("sha256", vals))
    register("apoc.util.sha512", lambda vals: _digest("sha512", vals))
    register("apoc.hashing.fingerprint", lambda v, excl=None: _digest(
        "md5", [_stable_json(v, excl or [])]))
    register("apoc.version", lambda: "5.x-compat (nornicdb-tpu)")

    # apoc.node / any (degree is a procedure — it needs storage context)
    register("apoc.node.labels", lambda n: list(n.labels)
             if isinstance(n, Node) else None)
    register("apoc.rel.type", lambda r: r.type if isinstance(r, Edge) else None)
    register("apoc.any.properties", lambda x: (
        dict(x.properties) if isinstance(x, (Node, Edge)) else
        (dict(x) if isinstance(x, dict) else None)))
    register("apoc.any.property", lambda x, k: (
        x.properties.get(k) if isinstance(x, (Node, Edge)) else
        (x.get(k) if isinstance(x, dict) else None)))
    register("apoc.create.uuid", lambda: str(__import__("uuid").uuid4()))
    register("apoc.create.uuidBase64", lambda: __import__(
        "base64").urlsafe_b64encode(
        __import__("uuid").uuid4().bytes).decode().rstrip("="))
    register("apoc.label.exists", lambda node, label: (
        label in node.labels if isinstance(node, Node) else False))


def _coll_split(l: List, v) -> List[List]:
    out, cur = [], []
    for x in l:
        if x == v:
            if cur:
                out.append(cur)
            cur = []
        else:
            cur.append(x)
    if cur:
        out.append(cur)
    return out


def _stdev(l: List[float], biased: bool) -> Optional[float]:
    if len(l) < 2:
        return 0.0 if l else None
    mean = sum(l) / len(l)
    var = sum((x - mean) ** 2 for x in l) / (len(l) if biased else len(l) - 1)
    return var ** 0.5


def _map_flatten(m: Dict, delim: str, prefix: str = "") -> Dict:
    out = {}
    for k, v in m.items():
        key = f"{prefix}{delim}{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_map_flatten(v, delim, key))
        else:
            out[key] = v
    return out


def _group_by_multi(l: List[Dict], key: str) -> Dict[str, List[Dict]]:
    out: Dict[str, List[Dict]] = {}
    for m in l:
        v = m.get(key)
        if v is not None:
            out.setdefault(str(v), []).append(m)
    return out


def _jaro_winkler(a: str, b: str) -> float:
    a, b = a or "", b or ""
    if a == b:
        return 1.0
    if not a or not b:
        return 0.0
    window = max(len(a), len(b)) // 2 - 1
    match_a = [False] * len(a)
    match_b = [False] * len(b)
    matches = 0
    for i, ca in enumerate(a):
        lo, hi = max(0, i - window), min(len(b), i + window + 1)
        for j in range(lo, hi):
            if not match_b[j] and b[j] == ca:
                match_a[i] = match_b[j] = True
                matches += 1
                break
    if matches == 0:
        return 0.0
    t = 0
    k = 0
    for i in range(len(a)):
        if match_a[i]:
            while not match_b[k]:
                k += 1
            if a[i] != b[k]:
                t += 1
            k += 1
    t //= 2
    jaro = (matches / len(a) + matches / len(b)
            + (matches - t) / matches) / 3
    prefix = 0
    for ca, cb in zip(a, b):
        if ca != cb or prefix == 4:
            break
        prefix += 1
    return jaro + prefix * 0.1 * (1 - jaro)


def _sorensen_dice(a: str, b: str) -> float:
    a, b = (a or "").lower(), (b or "").lower()
    if a == b:
        return 1.0
    bi_a = Counter(a[i:i + 2] for i in range(len(a) - 1))
    bi_b = Counter(b[i:i + 2] for i in range(len(b) - 1))
    inter = sum((bi_a & bi_b).values())
    total = sum(bi_a.values()) + sum(bi_b.values())
    return 2.0 * inter / total if total else 0.0


def _fuzzy_match(a: str, b: str) -> bool:
    from nornicdb_tpu.query.apoc import _levenshtein

    a, b = (a or "").lower(), (b or "").lower()
    shorter = min(len(a), len(b))
    if shorter < 3:
        return a == b
    allowed = 1 if shorter < 5 else 2
    return _levenshtein(a, b) <= allowed


_UNIT_MS = {
    "ms": 1, "millis": 1, "milliseconds": 1,
    "s": 1000, "seconds": 1000, "sec": 1000,
    "m": 60_000, "minutes": 60_000, "minute": 60_000,
    "h": 3_600_000, "hours": 3_600_000, "hour": 3_600_000,
    "d": 86_400_000, "days": 86_400_000, "day": 86_400_000,
}


def _unit_ms(unit: str) -> int:
    u = _UNIT_MS.get((unit or "ms").lower())
    if u is None:
        raise CypherRuntimeError(f"unknown time unit {unit!r}")
    return u


def _unit_ms_div(unit: str) -> int:
    return _unit_ms(unit)


def _date_field(epoch_ms, unit: str = "d", tz: str = "UTC"):
    import datetime as _dt

    d = _dt.datetime.fromtimestamp(int(epoch_ms) / 1000.0, tz=_dt.timezone.utc)
    u = (unit or "d").lower()
    return {
        "years": d.year, "year": d.year,
        "months": d.month, "month": d.month,
        "days": d.day, "day": d.day, "d": d.day,
        "hours": d.hour, "hour": d.hour, "h": d.hour,
        "minutes": d.minute, "minute": d.minute, "m": d.minute,
        "seconds": d.second, "second": d.second, "s": d.second,
    }.get(u)


def _temporal_format(value, fmt: str) -> str:
    from nornicdb_tpu.query.apoc import _convert_java_format

    return _nonstr(value).strftime(_convert_java_format(fmt))


def _nonstr(v):
    from nornicdb_tpu.query import temporal_types as T

    if isinstance(v, (T.CypherDate, T.CypherDateTime, T.CypherLocalDateTime,
                      T.CypherTime, T.CypherLocalTime)):
        return v._dt
    raise CypherRuntimeError("expected a temporal value")


def _digest(algo: str, vals) -> str:
    h = hashlib.new(algo)
    if not isinstance(vals, list):
        vals = [vals]
    for v in vals:
        h.update(str(v).encode("utf-8"))
    return h.hexdigest()


def _stable_json(v, exclude: List[str]) -> str:
    j = _jsonable(v)
    if isinstance(j, dict):
        j = {k: x for k, x in sorted(j.items()) if k not in exclude}
    return json.dumps(j, sort_keys=True, default=str)


# -- trigger registry -----------------------------------------------------


class TriggerRegistry:
    """apoc.trigger.* — statements fired after any updating query
    (reference: apoc/trigger; subset: 'after' phase, no txData params)."""

    def __init__(self):
        self.triggers: Dict[str, Dict[str, Any]] = {}

    def add(self, name: str, statement: str, selector: Optional[Dict] = None):
        self.triggers[name] = {
            "name": name, "statement": statement,
            "selector": selector or {}, "paused": False,
        }
        return self.triggers[name]

    def remove(self, name: str) -> Optional[Dict]:
        return self.triggers.pop(name, None)

    def remove_all(self) -> int:
        n = len(self.triggers)
        self.triggers.clear()
        return n

    def set_paused(self, name: str, paused: bool) -> Optional[Dict]:
        t = self.triggers.get(name)
        if t:
            t["paused"] = paused
        return t

    def fire(self, executor) -> None:
        for t in list(self.triggers.values()):
            if t["paused"]:
                continue
            try:
                executor._execute_for_trigger(t["statement"])
            except Exception:
                pass  # trigger failure must not fail the outer query


# -- path expansion -------------------------------------------------------


def _parse_rel_filter(spec: Optional[str]):
    """'KNOWS>|<WORKS_AT|LIKES' -> [(type, direction)]."""
    if not spec:
        return None
    out = []
    for part in str(spec).split("|"):
        part = part.strip()
        if not part:
            continue
        if part.endswith(">"):
            out.append((part[:-1], "out"))
        elif part.startswith("<"):
            out.append((part[1:], "in"))
        else:
            out.append((part, "both"))
    return out


def _parse_label_filter(spec: Optional[str]):
    """'+Person|-Banned' -> (allow, deny, terminate, end)."""
    allow, deny, term, end = set(), set(), set(), set()
    if spec:
        for part in str(spec).split("|"):
            part = part.strip()
            if not part:
                continue
            if part.startswith("+"):
                allow.add(part[1:])
            elif part.startswith("-"):
                deny.add(part[1:])
            elif part.startswith("/"):
                term.add(part[1:])
            elif part.startswith(">"):
                end.add(part[1:])
            else:
                allow.add(part)
    return allow, deny, term, end


def _expand_paths(storage, start: Node, rel_filter, label_filter,
                  min_level: int, max_level: int, bfs: bool = True,
                  uniqueness: str = "RELATIONSHIP_PATH"):
    """BFS path expansion (reference: apoc/path/path.go expandConfig)."""
    from nornicdb_tpu.query.functions import PathValue

    allow, deny, term, end = label_filter
    results = []
    queue = [(start, [], [start], set())]
    while queue:
        node, rels, nodes, used = queue.pop(0 if bfs else -1)
        depth = len(rels)
        if depth >= min_level:
            ok = True
            if allow and not (set(node.labels) & allow) and node.id != start.id:
                ok = False
            if end and not (set(node.labels) & end):
                ok = False
            if ok:
                results.append(PathValue(list(nodes), list(rels)))
        if depth >= max_level >= 0:
            continue
        if term and (set(node.labels) & term) and node.id != start.id:
            continue
        for e in storage.get_node_edges(node.id, Direction.BOTH):
            if e.id in used:
                continue
            if e.start_node == node.id:
                other_id, direction = e.end_node, "out"
            else:
                other_id, direction = e.start_node, "in"
            if rel_filter is not None:
                match = False
                for t, d in rel_filter:
                    if (not t or t == e.type) and d in (direction, "both"):
                        match = True
                        break
                if not match:
                    continue
            try:
                other = storage.get_node(other_id)
            except KeyError:
                continue
            if deny and (set(other.labels) & deny):
                continue
            queue.append((other, rels + [e], nodes + [other], used | {e.id}))
    return results


# -- procedures -----------------------------------------------------------


def _bfs_subgraph(storage, start: Node, rel_filter, label_filter,
                  max_level: int):
    """NODE_GLOBAL-uniqueness BFS (reference: apoc/path subgraph
    procedures) — each node visited once via its first (tree) path, so
    dense graphs stay linear instead of enumerating factorially many
    relationship-unique walks."""
    from collections import deque

    from nornicdb_tpu.query.functions import PathValue

    allow, deny, term, end = label_filter

    def rel_ok(e: Edge, direction: str) -> bool:
        return rel_filter is None or any(
            (not t or t == e.type) and d in (direction, "both")
            for t, d in rel_filter
        )

    visited = {start.id}
    tree_paths = [PathValue([start], [])]
    queue = deque([(start, [], [start])])
    while queue:
        node, rels, nodes = queue.popleft()
        depth = len(rels)
        if depth >= max_level >= 0:
            continue
        if term and (set(node.labels) & term) and node.id != start.id:
            continue
        for e in storage.get_node_edges(node.id, Direction.BOTH):
            if e.start_node == node.id:
                other_id, direction = e.end_node, "out"
            else:
                other_id, direction = e.start_node, "in"
            if not rel_ok(e, direction):
                continue
            try:
                other = storage.get_node(other_id)
            except KeyError:
                continue
            if deny and (set(other.labels) & deny):
                continue
            if allow and not (set(other.labels) & allow):
                continue
            if other.id in visited:
                continue
            visited.add(other.id)
            p = PathValue(nodes + [other], rels + [e])
            tree_paths.append(p)
            queue.append((other, rels + [e], nodes + [other]))
    # relationships = ALL matching edges between subgraph nodes, including
    # frontier-to-frontier edges never expanded by the tree walk (real
    # APOC subgraphAll semantics)
    all_rels: Dict[str, Edge] = {}
    for nid in visited:
        for e in storage.get_node_edges(nid, Direction.BOTH):
            direction = "out" if e.start_node == nid else "in"
            if not rel_ok(e, direction):
                continue
            if e.start_node in visited and e.end_node in visited:
                all_rels[e.id] = e
    return tree_paths, all_rels


def run_ext_procedure(executor, name: str, args: List[Any],
                      ctx) -> Optional[Iterator[Dict[str, Any]]]:
    """Dispatch for the extended APOC procedures; returns None when the
    name is not handled here."""
    storage = ctx.storage

    if name == "apoc.periodic.iterate":
        return _periodic_iterate(executor, args, ctx)
    if name == "apoc.periodic.commit":
        return _periodic_commit(executor, args, ctx)
    if name in ("apoc.cypher.run", "apoc.cypher.dorit", "apoc.cypher.doit"):
        return _cypher_run(executor, args, ctx)
    if name in ("apoc.when", "apoc.do.when"):
        return _do_when(executor, args, ctx)

    if name.startswith("apoc.trigger."):
        return _trigger_proc(executor, name, args)

    if name == "apoc.path.expand":
        start, rel_spec, label_spec, min_l, max_l = (list(args) + [None] * 5)[:5]
        paths = _expand_paths(
            storage, _as_node(storage, start),
            _parse_rel_filter(rel_spec), _parse_label_filter(label_spec),
            int(min_l) if min_l is not None else 1,
            int(max_l) if max_l is not None else 5,
        )
        return iter([{"path": p} for p in paths])
    if name == "apoc.path.expandconfig":
        # config-map form (reference apoc/path expandConfig)
        start, cfg = (list(args) + [{}])[:2]
        cfg = cfg or {}
        paths = _expand_paths(
            storage, _as_node(storage, start),
            _parse_rel_filter(cfg.get("relationshipFilter")),
            _parse_label_filter(cfg.get("labelFilter")),
            int(cfg.get("minLevel", 1)),
            int(cfg.get("maxLevel", 5)),
            bfs=bool(cfg.get("bfs", True)),
            uniqueness=str(cfg.get("uniqueness", "RELATIONSHIP_PATH")),
        )
        limit = cfg.get("limit")
        if limit is not None:
            paths = paths[: int(limit)]
        return iter([{"path": p} for p in paths])
    if name in ("apoc.path.shortestpath", "apoc.path.allshortestpaths"):
        from nornicdb_tpu.query.functions import PathValue

        a, b = (list(args) + [None, None])[:2]
        a = _as_node(storage, a)
        b = _as_node(storage, b)
        # BFS with parent tracking; allshortestpaths collects every
        # parent at the shortest depth
        # undirected BFS (shortestPath semantics ignore direction)
        prev: Dict[str, List[tuple]] = {a.id: []}
        frontier = [a.id]
        depth_of = {a.id: 0}
        found_depth = None
        while frontier and found_depth is None:
            nxt = []
            for nid in frontier:
                for e in storage.get_node_edges(nid, direction="both"):
                    other = (e.end_node if e.start_node == nid
                             else e.start_node)
                    if other not in depth_of:
                        depth_of[other] = depth_of[nid] + 1
                        prev[other] = [(nid, e)]
                        nxt.append(other)
                    elif depth_of[other] == depth_of[nid] + 1:
                        prev[other].append((nid, e))
                    if other == b.id:
                        found_depth = depth_of[other]
            frontier = nxt
        if b.id not in prev and a.id != b.id:
            return iter([])

        def build(nid) -> List[List[tuple]]:
            if nid == a.id:
                return [[]]
            out = []
            for pnode, e in prev[nid]:
                for tail in build(pnode):
                    out.append(tail + [(pnode, e)])
            return out

        combos = build(b.id)
        if name == "apoc.path.shortestpath":
            combos = combos[:1]
        results = []
        for combo in combos:
            nodes = [a]
            rels = []
            for pnode, e in combo:
                rels.append(e)
                other = e.end_node if e.start_node == pnode else e.start_node
                nodes.append(storage.get_node(other))
            results.append({"path": PathValue(nodes, rels)})
        return iter(results)
    if name in ("apoc.path.subgraphnodes", "apoc.path.subgraphall",
                "apoc.path.spanningtree"):
        start, cfg = (list(args) + [{}])[:2]
        cfg = cfg or {}
        tree_paths, all_rels = _bfs_subgraph(
            storage, _as_node(storage, start),
            _parse_rel_filter(cfg.get("relationshipFilter")),
            _parse_label_filter(cfg.get("labelFilter")),
            int(cfg.get("maxLevel", -1)),
        )
        if name == "apoc.path.subgraphnodes":
            return iter([{"node": p.nodes[-1]} for p in tree_paths])
        if name == "apoc.path.spanningtree":
            return iter([{"path": p} for p in tree_paths])
        return iter([{"nodes": [p.nodes[-1] for p in tree_paths],
                      "relationships": list(all_rels.values())}])

    if name == "apoc.create.node":
        labels, props = (list(args) + [{}])[:2]
        node = _create_node(storage, ctx, labels or [], props or {})
        return iter([{"node": node}])
    if name == "apoc.create.nodes":
        labels, props_list = (list(args) + [[]])[:2]
        return iter([
            {"node": _create_node(storage, ctx, labels or [], p or {})}
            for p in (props_list or [])
        ])
    if name == "apoc.create.relationship":
        frm, rel_type, props, to = args
        import uuid as _uuid

        edge = Edge(id=str(_uuid.uuid4()), type=rel_type,
                    start_node=_as_id(frm), end_node=_as_id(to),
                    properties=props or {})
        storage.create_edge(edge)
        ctx.stats.relationships_created += 1
        return iter([{"rel": storage.get_edge(edge.id)}])
    if name == "apoc.create.setproperty":
        target, key, value = args
        node = storage.get_node(_as_id(target))
        node.properties[key] = value
        storage.update_node(node)
        ctx.stats.properties_set += 1
        return iter([{"node": storage.get_node(node.id)}])

    if name == "apoc.merge.node":
        labels, ident, on_create = (list(args) + [{}, {}])[:3]
        return iter([_merge_node(storage, ctx, labels or [], ident or {},
                                 on_create or {})])
    if name == "apoc.merge.relationship":
        frm, rel_type, ident, on_create, to = (list(args) + [{}])[:5]
        return iter([_merge_rel(storage, ctx, frm, rel_type, ident or {},
                                on_create or {}, to)])

    if name in ("apoc.export.json.all", "apoc.export.csv.all"):
        fmt = "json" if ".json." in name else "csv"
        file_path = args[0] if args else None
        return iter([_export_all(storage, file_path, fmt)])
    if name == "apoc.import.json":
        return iter([_import_json(storage, ctx, args[0])])
    if name == "apoc.load.json":
        return _load_json(args[0])
    if name == "apoc.load.csv":
        return _load_csv(args[0])

    if name == "apoc.util.sleep":
        time.sleep(min(float(args[0]) / 1000.0, 10.0))
        return iter([])
    if name == "apoc.util.validate":
        predicate, message = args[0], args[1] if len(args) > 1 else "failed"
        if predicate:
            raise CypherRuntimeError(str(message))
        return iter([])
    if name == "apoc.node.degree":
        node, spec = (list(args) + [None])[:2]
        rf = _parse_rel_filter(spec)
        n = _as_node(storage, node)
        deg = 0
        for e in storage.get_node_edges(n.id, Direction.BOTH):
            direction = "out" if e.start_node == n.id else "in"
            if rf is None or any(
                (not t or t == e.type) and d in (direction, "both")
                for t, d in rf
            ):
                deg += 1
        return iter([{"value": deg}])

    return None


def _as_node(storage, v) -> Node:
    if isinstance(v, Node):
        return v
    return storage.get_node(str(v))


def _as_id(v) -> str:
    return v.id if isinstance(v, Node) else str(v)


def _create_node(storage, ctx, labels: List[str], props: Dict) -> Node:
    import uuid as _uuid

    node = Node(id=str(_uuid.uuid4()), labels=list(labels),
                properties=dict(props))
    storage.create_node(node)
    ctx.stats.nodes_created += 1
    ctx.stats.labels_added += len(labels)
    ctx.stats.properties_set += len(props)
    return storage.get_node(node.id)


def _merge_node(storage, ctx, labels, ident, on_create):
    label = labels[0] if labels else None
    candidates = (storage.get_nodes_by_label(label) if label
                  else list(storage.all_nodes()))
    for n in candidates:
        if all(l in n.labels for l in labels) and all(
            n.properties.get(k) == v for k, v in ident.items()
        ):
            return {"node": n}
    node = _create_node(storage, ctx, labels, {**ident, **on_create})
    return {"node": node}


def _merge_rel(storage, ctx, frm, rel_type, ident, on_create, to):
    import uuid as _uuid

    frm_id, to_id = _as_id(frm), _as_id(to)
    for e in storage.get_node_edges(frm_id, Direction.OUTGOING):
        if (e.type == rel_type and e.end_node == to_id and all(
            e.properties.get(k) == v for k, v in ident.items()
        )):
            return {"rel": e}
    edge = Edge(id=str(_uuid.uuid4()), type=rel_type, start_node=frm_id,
                end_node=to_id, properties={**ident, **on_create})
    storage.create_edge(edge)
    ctx.stats.relationships_created += 1
    return {"rel": storage.get_edge(edge.id)}


def _export_all(storage, file_path: Optional[str], fmt: str) -> Dict:
    t0 = time.time()
    n_nodes = n_rels = 0
    if fmt == "json":
        buf = io.StringIO()
        # "kind" is the record discriminator; "type" stays the edge type
        for n in storage.all_nodes():
            buf.write(json.dumps(
                {"kind": "node", **_jsonable(n)}, default=str) + "\n")
            n_nodes += 1
        for e in storage.all_edges():
            buf.write(json.dumps(
                {"kind": "relationship", **_jsonable(e)}, default=str) + "\n")
            n_rels += 1
        data = buf.getvalue()
    else:
        buf = io.StringIO()
        w = csv.writer(buf)
        w.writerow(["_id", "_labels", "_type", "_start", "_end", "properties"])
        for n in storage.all_nodes():
            w.writerow([n.id, ";".join(n.labels), "", "", "",
                        json.dumps(n.properties, default=str)])
            n_nodes += 1
        for e in storage.all_edges():
            w.writerow([e.id, "", e.type, e.start_node, e.end_node,
                        json.dumps(e.properties, default=str)])
            n_rels += 1
        data = buf.getvalue()
    if file_path:
        with open(file_path, "w") as f:
            f.write(data)
    return {
        "file": file_path or "(memory)", "format": fmt,
        "nodes": n_nodes, "relationships": n_rels,
        "time": int((time.time() - t0) * 1000),
        "data": None if file_path else data,
    }


def _import_json(storage, ctx, file_path: str) -> Dict:
    n_nodes = n_rels = 0
    with open(file_path) as f:
        records = [json.loads(line) for line in f if line.strip()]
    # nodes first so relationships resolve
    for rec in records:
        if rec.get("kind") == "node":
            node = Node(id=rec["id"], labels=rec.get("labels", []),
                        properties=rec.get("properties", {}))
            if not storage.has_node(node.id):
                storage.create_node(node)
                n_nodes += 1
                ctx.stats.nodes_created += 1
    for rec in records:
        if rec.get("kind") == "relationship":
            edge = Edge(id=rec["id"], type=rec.get("type", "RELATED"),
                        start_node=rec.get("start") or rec.get("start_node"),
                        end_node=rec.get("end") or rec.get("end_node"),
                        properties=rec.get("properties", {}))
            if not storage.has_edge(edge.id):
                storage.create_edge(edge)
                n_rels += 1
                ctx.stats.relationships_created += 1
    return {"file": file_path, "nodes": n_nodes, "relationships": n_rels}


def _load_json(path: str) -> Iterator[Dict]:
    """File-path loading only (zero-egress environment: no URLs)."""
    if str(path).startswith(("http://", "https://")):
        raise CypherRuntimeError(
            "apoc.load.json: remote URLs are disabled (no egress); "
            "use a file path"
        )
    with open(path) as f:
        text = f.read().strip()
    if text.startswith("["):
        for item in json.loads(text):
            yield {"value": item}
    else:
        for line in text.splitlines():
            if line.strip():
                yield {"value": json.loads(line)}


def _load_csv(path: str) -> Iterator[Dict]:
    if str(path).startswith(("http://", "https://")):
        raise CypherRuntimeError(
            "apoc.load.csv: remote URLs are disabled (no egress); "
            "use a file path"
        )
    with open(path, newline="") as f:
        reader = csv.DictReader(f)
        for i, row in enumerate(reader):
            yield {"lineNo": i, "map": dict(row),
                   "list": list(row.values())}


def _periodic_iterate(executor, args, ctx) -> Iterator[Dict]:
    """CALL apoc.periodic.iterate(outer, action, {batchSize, params})
    (reference: apoc/periodic — batched write execution)."""
    if len(args) < 2:
        raise CypherRuntimeError(
            "apoc.periodic.iterate(cypherIterate, cypherAction, config)")
    outer_q, action_q = args[0], args[1]
    cfg = args[2] if len(args) > 2 else {}
    batch_size = int((cfg or {}).get("batchSize", 1000))
    params = (cfg or {}).get("params", {})
    t0 = time.time()
    outer = executor._execute_for_trigger(outer_q, params)
    records = outer.records()
    total = len(records)
    batches = failed_ops = committed = 0
    errors: Dict[str, int] = {}
    for i in range(0, total, batch_size):
        batch = records[i:i + batch_size]
        batches += 1
        for rec in batch:
            # outer row columns become variables in the action (APOC
            # semantics): prepend `WITH $col AS col, ...`
            cols = [k for k in rec if k.isidentifier()]
            action = action_q
            if cols:
                action = ("WITH " + ", ".join(f"${k} AS {k}" for k in cols)
                          + " " + action_q)
            try:
                executor._execute_for_trigger(action, {**params, **rec})
                committed += 1
            except Exception as exc:
                failed_ops += 1
                key = str(exc)[:120]
                errors[key] = errors.get(key, 0) + 1
    yield {
        "batches": batches, "total": total,
        "timeTaken": int((time.time() - t0) * 1000),
        "committedOperations": committed,
        "failedOperations": failed_ops,
        "failedBatches": 0 if not failed_ops else batches,
        "retries": 0,
        "errorMessages": errors,
        "operations": {"total": total, "committed": committed,
                       "failed": failed_ops, "errors": errors},
    }


def _periodic_commit(executor, args, ctx) -> Iterator[Dict]:
    """Run a LIMIT-ed statement until it stops updating."""
    if not args:
        raise CypherRuntimeError("apoc.periodic.commit(statement, params)")
    statement = args[0]
    params = args[1] if len(args) > 1 else {}
    if "limit" not in statement.lower():
        raise CypherRuntimeError("apoc.periodic.commit requires a LIMIT")
    executions = updates = 0
    for _ in range(10_000):  # runaway guard
        r = executor._execute_for_trigger(statement, params)
        executions += 1
        delta = (r.stats.nodes_created + r.stats.nodes_deleted
                 + r.stats.relationships_created
                 + r.stats.relationships_deleted + r.stats.properties_set
                 + r.stats.labels_added + r.stats.labels_removed)
        updates += delta
        if delta == 0:
            break
    yield {"updates": updates, "executions": executions,
           "batchSize": -1, "failedBatches": 0}


def _cypher_run(executor, args, ctx) -> Iterator[Dict]:
    statement = args[0]
    params = args[1] if len(args) > 1 else {}
    r = executor._execute_for_trigger(statement, params or {})
    for rec in r.records():
        # APOC contract: each row is wrapped as the `value` map
        yield {"value": rec}


def _do_when(executor, args, ctx) -> Iterator[Dict]:
    if len(args) < 3:
        raise CypherRuntimeError(
            "apoc.do.when(condition, ifQuery, elseQuery, params)")
    cond, if_q, else_q = args[0], args[1], args[2]
    params = args[3] if len(args) > 3 else {}
    q = if_q if cond else else_q
    if not q:
        return
    r = executor._execute_for_trigger(q, params or {})
    for rec in r.records():
        yield {"value": rec}


def _trigger_proc(executor, name: str, args) -> Iterator[Dict]:
    reg = executor.triggers
    if name == "apoc.trigger.add":
        t = reg.add(args[0], args[1], args[2] if len(args) > 2 else None)
        return iter([{"name": t["name"], "query": t["statement"],
                      "selector": t["selector"], "paused": False,
                      "installed": True}])
    if name == "apoc.trigger.remove":
        t = reg.remove(args[0])
        return iter([{"name": args[0], "installed": False,
                      "removed": t is not None}])
    if name == "apoc.trigger.removeall":
        n = reg.remove_all()
        return iter([{"removed": n}])
    if name == "apoc.trigger.list":
        return iter([
            {"name": t["name"], "query": t["statement"],
             "paused": t["paused"]}
            for t in reg.triggers.values()
        ])
    if name == "apoc.trigger.pause":
        reg.set_paused(args[0], True)
        return iter([{"name": args[0], "paused": True}])
    if name == "apoc.trigger.resume":
        reg.set_paused(args[0], False)
        return iter([{"name": args[0], "paused": False}])
    return None  # unknown trigger name: fall through to the ctx table
    # (apoc_io registers show/install/before/onCreate/... there)


_install_functions()
