"""Grammar-complete strict Cypher parser (diagnostic mode).

Reference: pkg/cypher/antlr/CypherParser.g4 — the reference's strict
mode runs a second, grammar-complete OpenCypher parser (ANTLR) whose
job is rejecting malformed queries with precise diagnostics, at 73-
4,753x the fast path's cost (docs/architecture/cypher-parser-modes.md).

This is the TPU build's second parser: an independent recursive-descent
implementation of the full clause grammar over the shared tokenizer.
It builds no AST — its output is acceptance plus diagnostics — and it
enforces the grammar rules the fast parser deliberately skips on the
hot path:

- clause ORDER (openCypher SinglePartQuery/MultiPartQuery): reading
  clauses cannot follow updating clauses within a query part, nothing
  follows RETURN except UNION, WHERE attaches only to MATCH/WITH and
  at most once;
- UNION / UNION ALL cannot be mixed in one statement;
- SKIP/LIMIT take non-negative integer literals or parameters;
- MERGE takes exactly one path; ON can only introduce CREATE/MATCH SET;
- CREATE relationships need a type and exactly one hop;
- label/type positions must hold identifiers (the fast parser will
  swallow a stray token as a label name);
- one statement per parse (a second `;`-separated statement is
  diagnosed, not silently concatenated).

``parse(query)`` raises StrictSyntaxError (line/col attached) on the
first violation; ``check(query)`` returns a list of Diagnostics.
tests/test_strict_grammar.py diffs a few-hundred-case accept/reject
corpus against the fast parser.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from nornicdb_tpu.errors import CypherSyntaxError
from nornicdb_tpu.query.tokens import (
    EOF,
    IDENT,
    NUMBER,
    OP,
    PARAM,
    PUNCT,
    STRING,
    Token,
    TokenStream,
    tokenize,
)


class StrictSyntaxError(CypherSyntaxError):
    def __init__(self, message: str, line: int = 1, column: int = 1):
        super().__init__(f"{message} (line {line}, column {column})")
        self.bare_message = message
        self.line = line
        self.column = column


_UPDATING = {"CREATE", "MERGE", "SET", "REMOVE", "DELETE", "DETACH"}
_READING = {"MATCH", "OPTIONAL", "UNWIND", "CALL"}

_RESERVED_NOT_NAMES = {
    "WHERE", "RETURN", "WITH", "MATCH", "CREATE", "MERGE", "DELETE",
    "DETACH", "REMOVE", "SET", "UNWIND", "UNION", "ORDER", "SKIP",
    "LIMIT", "CALL", "YIELD", "ON", "WHEN", "THEN", "ELSE", "END",
}


class StrictParser:
    def __init__(self, text: str):
        self.text = text
        try:
            self.ts = TokenStream(tokenize(text))
        except CypherSyntaxError as e:
            raise self._wrap_tokenize_error(e)

    # -- diagnostics ------------------------------------------------------

    def _line_col(self, pos: int):
        upto = self.text[:pos]
        return upto.count("\n") + 1, pos - (upto.rfind("\n") + 1) + 1

    def _err(self, message: str, tok: Optional[Token] = None):
        tok = tok or self.ts.peek()
        pos = getattr(tok, "pos", len(self.text))
        line, col = self._line_col(min(pos, len(self.text)))
        raise StrictSyntaxError(message, line, col)

    def _wrap_tokenize_error(self, e: CypherSyntaxError):
        import re

        m = re.search(r" at (\d+)$", str(e))
        pos = int(m.group(1)) if m else 0
        line, col = self._line_col(min(pos, len(self.text)))
        return StrictSyntaxError(str(e), line, col)

    # -- token helpers ----------------------------------------------------

    def _name(self, what: str) -> str:
        t = self.ts.peek()
        if t.kind != IDENT:
            self._err(f"expected {what}, got {t.value!r}", t)
        if t.upper() in _RESERVED_NOT_NAMES:
            self._err(f"reserved word {t.value!r} cannot be a {what}", t)
        return self.ts.next().value

    def _expect(self, value: str, kind: Optional[str] = None):
        t = self.ts.peek()
        ok = (t.value == value if kind is None
              else (t.kind == kind and t.value == value))
        if t.kind == IDENT and kind is None and t.upper() == value.upper():
            ok = True
        if not ok:
            self._err(f"expected {value!r}, got {t.value!r}", t)
        return self.ts.next()

    # -- statement --------------------------------------------------------

    def parse(self) -> None:
        self._query_part_sequence()
        union_kind = None  # 'ALL' | 'DISTINCT'
        while self.ts.peek_kw("UNION"):
            self.ts.next()
            this = "ALL" if self.ts.accept_kw("ALL") else "DISTINCT"
            if union_kind is not None and union_kind != this:
                self._err("cannot mix UNION and UNION ALL")
            union_kind = this
            self._query_part_sequence()
        if self.ts.accept(";", PUNCT):
            if not self.ts.at_end():
                self._err("only one statement per query "
                          "(text after ';')")
        if not self.ts.at_end():
            t = self.ts.peek()
            self._err(f"unexpected token {t.value!r} after query", t)

    # -- single query (clause order automaton) ----------------------------

    def _query_part_sequence(self) -> None:
        """MultiPartQuery := ((Reading* Updating*) WITH)* SinglePart.
        State machine per part: reading -> updating; WITH resets;
        RETURN terminates."""
        state = "reading"
        saw_clause = False
        returned = False
        last_where_host = False  # current clause can host a WHERE
        while not self.ts.at_end():
            t = self.ts.peek()
            if t.kind == PUNCT and t.value == ";":
                break
            if t.kind != IDENT:
                self._err(f"expected a clause, got {t.value!r}", t)
            kw = t.upper()
            if kw == "UNION":
                break
            if returned:
                self._err(f"{kw} cannot follow RETURN", t)
            if kw in ("MATCH", "OPTIONAL"):
                if state == "updating":
                    self._err(
                        f"{kw + ' MATCH' if kw == 'OPTIONAL' else kw} "
                        "cannot follow an updating clause — "
                        "introduce a WITH first", t)
                self.ts.next()
                if kw == "OPTIONAL":
                    self._expect("MATCH")
                self._patterns(allow_where_anchor=True)
                last_where_host = self._maybe_where()
            elif kw == "UNWIND":
                if state == "updating":
                    self._err("UNWIND cannot follow an updating clause "
                              "— introduce a WITH first", t)
                self.ts.next()
                self._expression()
                self._expect("AS")
                self._name("variable")
                last_where_host = False
            elif kw == "CALL":
                if state == "updating":
                    self._err("CALL cannot follow an updating clause "
                              "— introduce a WITH first", t)
                self.ts.next()
                self._call()
                last_where_host = False
            elif kw == "CREATE":
                state = "updating"
                self.ts.next()
                self._patterns(creating=True)
                last_where_host = False
            elif kw == "MERGE":
                state = "updating"
                self.ts.next()
                self._merge()
                last_where_host = False
            elif kw == "SET":
                state = "updating"
                self.ts.next()
                self._set_items()
                last_where_host = False
            elif kw == "REMOVE":
                state = "updating"
                self.ts.next()
                self._remove_items()
                last_where_host = False
            elif kw in ("DELETE", "DETACH"):
                state = "updating"
                self.ts.next()
                if kw == "DETACH":
                    self._expect("DELETE")
                self._expression()
                while self.ts.accept(",", PUNCT):
                    self._expression()
                last_where_host = False
            elif kw == "WITH":
                self.ts.next()
                self._projection(is_return=False)
                state = "reading"
                last_where_host = False  # WITH's WHERE parsed inline
            elif kw == "RETURN":
                self.ts.next()
                self._projection(is_return=True)
                returned = True
                last_where_host = False
            elif kw == "WHERE":
                self._err(
                    "WHERE must directly follow MATCH or WITH"
                    if not last_where_host
                    else "only one WHERE per MATCH/WITH", t)
            elif kw in ("ORDER", "SKIP", "LIMIT"):
                self._err(f"{kw} is only allowed after RETURN or WITH "
                          "projections", t)
            else:
                self._err(f"unknown clause {t.value!r}", t)
            saw_clause = True
        if not saw_clause:
            self._err("empty query")

    def _maybe_where(self) -> bool:
        if self.ts.accept_kw("WHERE"):
            self._expression()
            return True  # a second WHERE is now an error
        return True

    # -- clauses ----------------------------------------------------------

    def _projection(self, is_return: bool) -> None:
        self.ts.accept_kw("DISTINCT")
        t = self.ts.peek()
        if t.kind == OP and t.value == "*":
            self.ts.next()
        else:
            self._projection_item()
            while self.ts.accept(",", PUNCT):
                self._projection_item()
        if self.ts.accept_kw("ORDER"):
            self._expect("BY")
            self._expression()
            self._order_direction()
            while self.ts.accept(",", PUNCT):
                self._expression()
                self._order_direction()
        if self.ts.accept_kw("SKIP"):
            self._pagination_value("SKIP")
        if self.ts.accept_kw("LIMIT"):
            self._pagination_value("LIMIT")
        if not is_return and self.ts.accept_kw("WHERE"):
            self._expression()
            if self.ts.peek_kw("WHERE"):
                self._err("only one WHERE per MATCH/WITH")

    def _order_direction(self) -> None:
        if not (self.ts.accept_kw("DESC") or self.ts.accept_kw("DESCENDING")):
            self.ts.accept_kw("ASC") or self.ts.accept_kw("ASCENDING")

    def _projection_item(self) -> None:
        t = self.ts.peek()
        if t.kind == EOF or (t.kind == IDENT
                             and t.upper() in _RESERVED_NOT_NAMES
                             and t.upper() not in ("END",)):
            self._err("expected a projection expression", t)
        self._expression()
        if self.ts.accept_kw("AS"):
            self._name("alias")

    def _pagination_value(self, what: str) -> None:
        t = self.ts.peek()
        if t.kind == PARAM:
            self.ts.next()
            return
        neg = False
        if t.kind == OP and t.value == "-":
            neg = True
            self.ts.next()
            t = self.ts.peek()
        if t.kind != NUMBER:
            self._err(f"{what} expects a non-negative integer", t)
        if neg:
            self._err(f"{what} cannot be negative", t)
        if ("." in t.value or "e" in t.value.lower()) \
                and not t.value.startswith("0x"):
            self._err(f"{what} expects an integer, got {t.value!r}", t)
        self.ts.next()

    def _call(self) -> None:
        self._name("procedure name")
        while self.ts.accept(".", PUNCT):
            self._name("procedure name")
        if self.ts.accept("(", PUNCT):
            if not (self.ts.peek().kind == PUNCT
                    and self.ts.peek().value == ")"):
                self._expression()
                while self.ts.accept(",", PUNCT):
                    self._expression()
            self._expect(")")
        if self.ts.accept_kw("YIELD"):
            t = self.ts.peek()
            if t.kind == OP and t.value == "*":
                self.ts.next()
            else:
                self._name("yield item")
                if self.ts.accept_kw("AS"):
                    self._name("alias")
                while self.ts.accept(",", PUNCT):
                    self._name("yield item")
                    if self.ts.accept_kw("AS"):
                        self._name("alias")
            if self.ts.accept_kw("WHERE"):
                self._expression()

    def _merge(self) -> None:
        self._path()
        if self.ts.peek().kind == PUNCT and self.ts.peek().value == ",":
            self._err("MERGE takes exactly one pattern path")
        while self.ts.peek_kw("ON"):
            self.ts.next()
            t = self.ts.peek()
            if self.ts.accept_kw("CREATE") or self.ts.accept_kw("MATCH"):
                self._expect("SET")
                self._set_items()
            else:
                self._err("ON must introduce CREATE SET or MATCH SET", t)

    def _set_items(self) -> None:
        self._set_item()
        while self.ts.accept(",", PUNCT):
            self._set_item()

    def _set_item(self) -> None:
        var_tok = self.ts.peek()
        self._name("variable")
        if self.ts.accept(":", PUNCT):
            # SET n:Label[:Label...]
            self._name("label")
            while self.ts.accept(":", PUNCT):
                self._name("label")
            return
        path = False
        while self.ts.accept(".", PUNCT):
            self._name("property name")
            path = True
        t = self.ts.peek()
        if t.kind == OP and t.value in ("=", "+="):
            if t.value == "+=" and path:
                self._err("+= applies to maps on a variable, not a "
                          "property", t)
            self.ts.next()
            self._expression()
            return
        if not path:
            self._err("SET expects `var.prop = expr`, `var += map` or "
                      "`var:Label`", var_tok)
        self._err("SET expects `=` or `+=`", t)

    def _remove_items(self) -> None:
        def one():
            self._name("variable")
            if self.ts.accept(":", PUNCT):
                self._name("label")
                while self.ts.accept(":", PUNCT):
                    self._name("label")
                return
            if not self.ts.accept(".", PUNCT):
                self._err("REMOVE expects `var.prop` or `var:Label`")
            self._name("property name")
            while self.ts.accept(".", PUNCT):
                self._name("property name")

        one()
        while self.ts.accept(",", PUNCT):
            one()

    # -- patterns ---------------------------------------------------------

    def _patterns(self, creating: bool = False,
                  allow_where_anchor: bool = False) -> None:
        self._path(creating=creating)
        while self.ts.accept(",", PUNCT):
            self._path(creating=creating)

    def _path(self, creating: bool = False) -> None:
        # named path: p = (...)
        if (self.ts.peek().kind == IDENT
                and self.ts.peek(1).kind == OP
                and self.ts.peek(1).value == "="
                and self.ts.peek().upper() not in _RESERVED_NOT_NAMES):
            self._name("path variable")
            self.ts.next()  # '='
        # shortestPath( path ) / allShortestPaths( path )
        if (self.ts.peek().kind == IDENT
                and self.ts.peek().upper() in ("SHORTESTPATH",
                                               "ALLSHORTESTPATHS")
                and self.ts.peek(1).kind == PUNCT
                and self.ts.peek(1).value == "("):
            self.ts.next()
            self._expect("(")
            self._path(creating=creating)
            self._expect(")")
            return
        self._node()
        while self._at_rel_start():
            self._rel(creating=creating)
            self._node()

    def _at_rel_start(self) -> bool:
        t = self.ts.peek()
        return t.kind == OP and t.value in ("-", "<-", "<", "->")

    def _node(self) -> None:
        self._expect("(", PUNCT)
        t = self.ts.peek()
        if t.kind == IDENT and t.upper() not in _RESERVED_NOT_NAMES:
            self.ts.next()
        elif t.kind == IDENT and t.upper() in _RESERVED_NOT_NAMES:
            self._err(f"reserved word {t.value!r} cannot name a node", t)
        while self.ts.accept(":", PUNCT):
            self._name("label")
        if self.ts.peek().kind == PUNCT and self.ts.peek().value == "{":
            self._map_literal()
        elif self.ts.peek().kind == PARAM:
            self.ts.next()  # node properties from a parameter
        self._expect(")", PUNCT)

    def _rel(self, creating: bool = False) -> None:
        t = self.ts.next()  # '-', '<-', '<'
        incoming = False
        if t.value == "<-":
            incoming = True
        elif t.value == "<":
            self._expect("-", OP)
            incoming = True
        elif t.value == "->":
            self._err("relationship must open with '-' or '<-'", t)
        typed = False
        var_length = False
        if self.ts.accept("[", PUNCT):
            if (self.ts.peek().kind == IDENT
                    and self.ts.peek().upper() not in _RESERVED_NOT_NAMES):
                self.ts.next()
            if self.ts.accept(":", PUNCT):
                typed = True
                self._name("relationship type")
                while self.ts.accept("|", PUNCT):
                    self.ts.accept(":", PUNCT)  # legacy |:TYPE
                    self._name("relationship type")
            if self.ts.peek().kind == OP and self.ts.peek().value == "*":
                var_length = True
                self.ts.next()
                if self.ts.peek().kind == NUMBER:
                    self._hop_bound()
                    if self.ts.accept("..", OP):
                        if self.ts.peek().kind == NUMBER:
                            self._hop_bound()
                elif self.ts.accept("..", OP):
                    if self.ts.peek().kind == NUMBER:
                        self._hop_bound()
            if self.ts.peek().kind == PUNCT and self.ts.peek().value == "{":
                self._map_literal()
            self._expect("]", PUNCT)
        if incoming:
            self._expect("-", OP)
            if self.ts.peek().kind == OP and self.ts.peek().value == ">":
                self._err("a relationship cannot point both ways")
        else:
            nxt = self.ts.peek()
            if nxt.kind == OP and nxt.value in ("->", "-"):
                self.ts.next()
            else:
                self._err("expected '->' or '-' to close the "
                          "relationship", nxt)
        if creating:
            if not typed:
                self._err("CREATE requires a relationship type")
            if var_length:
                self._err("CREATE cannot use variable-length "
                          "relationships")

    def _hop_bound(self) -> None:
        t = self.ts.peek()
        if "." in t.value or t.value.lower().find("e") > 0:
            self._err("hop bounds must be integers", t)
        self.ts.next()

    def _map_literal(self) -> None:
        self._expect("{", PUNCT)
        if self.ts.accept("}", PUNCT):
            return
        while True:
            key = self.ts.peek()
            if key.kind not in (IDENT, STRING):
                self._err("map keys must be identifiers or strings", key)
            self.ts.next()
            self._expect(":", PUNCT)
            self._expression()
            if not self.ts.accept(",", PUNCT):
                break
        self._expect("}", PUNCT)

    # -- expressions (full precedence ladder) -----------------------------

    def _expression(self) -> None:
        self._or_expr()

    def _or_expr(self) -> None:
        self._xor_expr()
        while self.ts.accept_kw("OR"):
            self._xor_expr()

    def _xor_expr(self) -> None:
        self._and_expr()
        while self.ts.accept_kw("XOR"):
            self._and_expr()

    def _and_expr(self) -> None:
        self._not_expr()
        while self.ts.accept_kw("AND"):
            self._not_expr()

    def _not_expr(self) -> None:
        while self.ts.accept_kw("NOT"):
            pass
        self._comparison()

    def _comparison(self) -> None:
        self._string_list_null()
        while True:
            t = self.ts.peek()
            if t.kind == OP and t.value in ("=", "<>", "<", "<=", ">",
                                            ">=", "=~"):
                self.ts.next()
                self._string_list_null()
                continue
            if t.kind == IDENT and t.upper() == "IN":
                self.ts.next()
                self._string_list_null()
                continue
            if t.kind == IDENT and t.upper() in ("STARTS", "ENDS"):
                self.ts.next()
                self._expect("WITH")
                self._string_list_null()
                continue
            if t.kind == IDENT and t.upper() == "CONTAINS":
                self.ts.next()
                self._string_list_null()
                continue
            break

    def _string_list_null(self) -> None:
        self._add_sub()
        while True:
            t = self.ts.peek()
            if t.kind == IDENT and t.upper() == "IS":
                self.ts.next()
                self.ts.accept_kw("NOT")
                if not self.ts.accept_kw("NULL"):
                    self._err("IS must be followed by [NOT] NULL")
                continue
            break

    def _add_sub(self) -> None:
        self._mul_div()
        while True:
            t = self.ts.peek()
            if t.kind == OP and t.value in ("+", "-"):
                self.ts.next()
                self._mul_div()
            else:
                break

    def _mul_div(self) -> None:
        self._power()
        while True:
            t = self.ts.peek()
            if t.kind == OP and t.value in ("*", "/", "%"):
                self.ts.next()
                self._power()
            else:
                break

    def _power(self) -> None:
        self._unary()
        while self.ts.peek().kind == OP and self.ts.peek().value == "^":
            self.ts.next()
            self._unary()

    def _unary(self) -> None:
        while (self.ts.peek().kind == OP
               and self.ts.peek().value in ("+", "-")):
            self.ts.next()
        self._postfix()

    def _postfix(self) -> None:
        self._atom()
        while True:
            t = self.ts.peek()
            if t.kind == PUNCT and t.value == ".":
                self.ts.next()
                self._name("property name")
            elif t.kind == PUNCT and t.value == "[":
                self.ts.next()
                if not (self.ts.peek().kind == OP
                        and self.ts.peek().value == ".."):
                    self._expression()
                if self.ts.accept("..", OP):
                    if not (self.ts.peek().kind == PUNCT
                            and self.ts.peek().value == "]"):
                        self._expression()
                self._expect("]", PUNCT)
            elif t.kind == PUNCT and t.value == ":":
                # label predicate n:Label
                self.ts.next()
                self._name("label")
                while self.ts.accept(":", PUNCT):
                    self._name("label")
            else:
                break

    def _atom(self) -> None:
        t = self.ts.peek()
        if t.kind in (STRING, NUMBER, PARAM):
            self.ts.next()
            return
        if t.kind == PUNCT and t.value == "(":
            if self._looks_like_pattern():
                self._path()
                return
            self.ts.next()
            self._expression()
            self._expect(")", PUNCT)
            return
        if t.kind == PUNCT and t.value == "[":
            self._list_or_comprehension()
            return
        if t.kind == PUNCT and t.value == "{":
            self._map_literal()
            return
        if t.kind == IDENT:
            kw = t.upper()
            if kw in ("TRUE", "FALSE", "NULL"):
                self.ts.next()
                return
            if kw == "CASE":
                self._case()
                return
            if kw == "EXISTS" and self.ts.peek(1).kind == PUNCT \
                    and self.ts.peek(1).value == "(":
                self.ts.next()
                self._expect("(")
                if self._looks_like_pattern():
                    self._path()
                else:
                    self._expression()
                self._expect(")")
                return
            if (kw in ("ALL", "ANY", "NONE", "SINGLE")
                    and self.ts.peek(1).kind == PUNCT
                    and self.ts.peek(1).value == "("
                    and self.ts.peek(2).kind == IDENT
                    and self.ts.peek(3).kind == IDENT
                    and self.ts.peek(3).upper() == "IN"):
                self.ts.next()
                self._expect("(")
                self._name("variable")
                self._expect("IN")
                self._expression()
                if not self.ts.accept_kw("WHERE"):
                    self._err(f"{kw.lower()}() requires a WHERE predicate")
                self._expression()
                self._expect(")")
                return
            if kw == "REDUCE" and self.ts.peek(1).kind == PUNCT \
                    and self.ts.peek(1).value == "(":
                self.ts.next()
                self._expect("(")
                self._name("accumulator")
                self._expect("=", OP)
                self._expression()
                self._expect(",")
                self._name("variable")
                self._expect("IN")
                self._expression()
                self._expect("|", PUNCT)
                self._expression()
                self._expect(")")
                return
            if (kw in ("EXTRACT", "FILTER")
                    and self.ts.peek(1).kind == PUNCT
                    and self.ts.peek(1).value == "("
                    and self.ts.peek(2).kind == IDENT
                    and self.ts.peek(3).kind == IDENT
                    and self.ts.peek(3).upper() == "IN"):
                self.ts.next()
                self._expect("(")
                self._name("variable")
                self._expect("IN")
                self._expression()
                if kw == "FILTER":
                    if not self.ts.accept_kw("WHERE"):
                        self._err("filter() requires WHERE")
                    self._expression()
                else:
                    self._expect("|", PUNCT)
                    self._expression()
                self._expect(")")
                return
            if kw == "COUNT" and self.ts.peek(1).kind == PUNCT \
                    and self.ts.peek(1).value == "{":
                self.ts.next()
                self._expect("{")
                self._path()
                self._expect("}")
                return
            if kw in ("SHORTESTPATH", "ALLSHORTESTPATHS") \
                    and self.ts.peek(1).kind == PUNCT \
                    and self.ts.peek(1).value == "(":
                self.ts.next()
                self._expect("(")
                self._path()
                self._expect(")")
                return
            if self._is_func_call():
                self.ts.next()
                while self.ts.accept(".", PUNCT):
                    self._name("function name")
                self._expect("(")
                self.ts.accept_kw("DISTINCT")
                if self.ts.peek().kind == OP \
                        and self.ts.peek().value == "*":
                    self.ts.next()
                elif not (self.ts.peek().kind == PUNCT
                          and self.ts.peek().value == ")"):
                    self._expression()
                    while self.ts.accept(",", PUNCT):
                        self._expression()
                self._expect(")")
                return
            if kw in _RESERVED_NOT_NAMES:
                self._err(
                    f"expected an expression, got keyword {t.value!r}", t)
            self.ts.next()  # plain variable
            return
        self._err(f"expected an expression, got {t.value!r}", t)

    def _list_or_comprehension(self) -> None:
        self._expect("[", PUNCT)
        if self.ts.accept("]", PUNCT):
            return
        if (self.ts.peek().kind == IDENT
                and self.ts.peek(1).kind == IDENT
                and self.ts.peek(1).upper() == "IN"):
            self._name("variable")
            self.ts.next()  # IN
            self._expression()
            if self.ts.accept_kw("WHERE"):
                self._expression()
            if self.ts.accept("|", PUNCT):
                self._expression()
            self._expect("]", PUNCT)
            return
        self._expression()
        while self.ts.accept(",", PUNCT):
            self._expression()
        self._expect("]", PUNCT)

    def _case(self) -> None:
        self._expect("CASE")
        if not self.ts.peek_kw("WHEN"):
            self._expression()
        saw = False
        while self.ts.accept_kw("WHEN"):
            saw = True
            self._expression()
            self._expect("THEN")
            self._expression()
        if not saw:
            self._err("CASE requires at least one WHEN")
        if self.ts.accept_kw("ELSE"):
            self._expression()
        self._expect("END")

    # -- lookahead helpers ------------------------------------------------

    def _is_func_call(self) -> bool:
        j = 0
        if self.ts.peek(j).kind != IDENT:
            return False
        j += 1
        while self.ts.peek(j).kind == PUNCT and self.ts.peek(j).value == ".":
            if self.ts.peek(j + 1).kind != IDENT:
                return False
            j += 2
        return self.ts.peek(j).kind == PUNCT and self.ts.peek(j).value == "("

    def _looks_like_pattern(self) -> bool:
        ts = self.ts
        if not (ts.peek().kind == PUNCT and ts.peek().value == "("):
            return False
        j = 1
        if ts.peek(j).kind == IDENT:
            j += 1
        while ts.peek(j).kind == PUNCT and ts.peek(j).value == ":":
            if ts.peek(j + 1).kind != IDENT:
                return False
            j += 2
        if ts.peek(j).kind == PUNCT and ts.peek(j).value == "{":
            depth = 0
            while True:
                t = ts.peek(j)
                if t.kind == EOF:
                    return False
                if t.kind == PUNCT and t.value == "{":
                    depth += 1
                elif t.kind == PUNCT and t.value == "}":
                    depth -= 1
                    if depth == 0:
                        j += 1
                        break
                j += 1
        if not (ts.peek(j).kind == PUNCT and ts.peek(j).value == ")"):
            return False
        nxt = ts.peek(j + 1)
        if nxt.kind != OP:
            return False
        if nxt.value in ("<-", "<"):
            return True
        if nxt.value == "-":
            after = ts.peek(j + 2)
            return (after.kind == OP and after.value in ("-", "->")) or (
                after.kind == PUNCT and after.value == "[")
        return False


def parse(query: str) -> None:
    """Accept or raise StrictSyntaxError with line/col diagnostics."""
    StrictParser(query).parse()


def accepts(query: str) -> bool:
    try:
        parse(query)
        return True
    except CypherSyntaxError:
        return False
