"""Cypher tokenizer.

The reference's default parser is string/regex-based clause extraction with
no full parse tree on the hot path (pkg/cypher/parser.go:24,
keyword_scan.go). Here a single lightweight tokenizer feeds both the
clause splitter and the Pratt expression parser — still cheap (one linear
scan), but structurally sound for nesting/quoting.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from nornicdb_tpu.errors import CypherSyntaxError

# token kinds
IDENT = "IDENT"
STRING = "STRING"
NUMBER = "NUMBER"
PARAM = "PARAM"
OP = "OP"
PUNCT = "PUNCT"
EOF = "EOF"

_PUNCT = set("()[]{},:;.|")
_OP_CHARS = set("=<>+-*/%^!")
_TWO_CHAR_OPS = {"<>", "<=", ">=", "=~", "->", "<-", "..", "+="}


@dataclass
class Token:
    kind: str
    value: str
    pos: int

    def upper(self) -> str:
        return self.value.upper()


def tokenize(text: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c.isspace():
            i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "/":  # line comment
            while i < n and text[i] != "\n":
                i += 1
            continue
        if c == "/" and i + 1 < n and text[i + 1] == "*":  # block comment
            j = text.find("*/", i + 2)
            i = n if j < 0 else j + 2
            continue
        if c in "'\"":
            quote = c
            j = i + 1
            buf = []
            while j < n:
                if text[j] == "\\" and j + 1 < n:
                    esc = text[j + 1]
                    buf.append(
                        {"n": "\n", "t": "\t", "r": "\r", "\\": "\\",
                         "'": "'", '"': '"'}.get(esc, esc)
                    )
                    j += 2
                    continue
                if text[j] == quote:
                    break
                buf.append(text[j])
                j += 1
            if j >= n:
                raise CypherSyntaxError(f"unterminated string at {i}")
            toks.append(Token(STRING, "".join(buf), i))
            i = j + 1
            continue
        if c == "`":  # escaped identifier
            j = text.find("`", i + 1)
            if j < 0:
                raise CypherSyntaxError(f"unterminated backtick at {i}")
            toks.append(Token(IDENT, text[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (
            c == "." and i + 1 < n and text[i + 1].isdigit()
        ):
            j = i
            seen_dot = False
            while j < n and (
                text[j].isdigit()
                or (text[j] == "." and not seen_dot and j + 1 < n and text[j + 1].isdigit())
                or text[j] in "eE"
                or (text[j] in "+-" and j > i and text[j - 1] in "eE")
                or (text[j] == "x" and j == i + 1 and text[i] == "0")
                or (text[i : i + 2] == "0x" and text[j] in "abcdefABCDEF")
            ):
                if text[j] == ".":
                    seen_dot = True
                j += 1
            toks.append(Token(NUMBER, text[i:j], i))
            i = j
            continue
        if c == "$":
            j = i + 1
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            toks.append(Token(PARAM, text[i + 1 : j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            toks.append(Token(IDENT, text[i:j], i))
            i = j
            continue
        two = text[i : i + 2]
        if two in _TWO_CHAR_OPS:
            # ".." only counts as an op in range context; "." handled below
            toks.append(Token(OP, two, i))
            i += 2
            continue
        if c in _PUNCT:
            toks.append(Token(PUNCT, c, i))
            i += 1
            continue
        if c in _OP_CHARS:
            toks.append(Token(OP, c, i))
            i += 1
            continue
        raise CypherSyntaxError(f"unexpected character {c!r} at {i}")
    toks.append(Token(EOF, "", n))
    return toks


class TokenStream:
    def __init__(self, tokens: List[Token]):
        self.toks = tokens
        self.i = 0

    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.peek()
        if t.kind != EOF:
            self.i += 1
        return t

    def at_end(self) -> bool:
        return self.peek().kind == EOF

    def accept(self, value: str, kind: Optional[str] = None) -> Optional[Token]:
        t = self.peek()
        if t.kind == EOF:
            return None
        if kind is not None and t.kind != kind:
            return None
        if t.kind == IDENT:
            if t.upper() != value.upper():
                return None
        elif t.value != value:
            return None
        return self.next()

    def expect(self, value: str, kind: Optional[str] = None) -> Token:
        t = self.accept(value, kind)
        if t is None:
            got = self.peek()
            raise CypherSyntaxError(
                f"expected {value!r}, got {got.value!r} at {got.pos}"
            )
        return t

    def accept_kw(self, *words: str) -> bool:
        """Accept a multi-word keyword sequence (case-insensitive)."""
        save = self.i
        for w in words:
            t = self.peek()
            if t.kind != IDENT or t.upper() != w:
                self.i = save
                return False
            self.next()
        return True

    def peek_kw(self, *words: str) -> bool:
        save = self.i
        ok = self.accept_kw(*words)
        self.i = save
        return ok
