"""Cypher query layer.

Reference: pkg/cypher (78k LoC) — StorageExecutor.Execute routing
(executor.go:517-700), the nornic string-routing parser (parser.go:24),
streaming fast paths (optimized_executors.go), ~200 builtin functions,
CALL procedures, EXPLAIN/PROFILE. The TPU design keeps parsing/routing on
CPU and vectorizes aggregation shapes over columnar snapshots dispatched
to XLA (fastpaths.py).
"""

from nornicdb_tpu.query.executor import CypherExecutor, CypherResult  # noqa: F401
