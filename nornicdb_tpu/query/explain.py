"""EXPLAIN / PROFILE: plan-tree construction and db-hit accounting.

Reference: pkg/cypher/explain.go:95,110 (executeExplain/executeProfile) and
explain.go:149 (buildExecutionPlan) — a plan tree derived from the parsed
query with estimated-row counts from storage statistics; PROFILE executes
the query through a db-hit-counting storage proxy and reports actuals.

The plan is returned both as rows (operator table, the way `EXPLAIN`
renders in a shell) and as a nested dict on `CypherResult.plan` for
drivers that want the tree.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from nornicdb_tpu.query import ast as A
from nornicdb_tpu.storage.types import Engine


@dataclass
class PlanNode:
    operator: str
    details: str = ""
    estimated_rows: int = 0
    db_hits: int = 0
    actual_rows: int = 0
    children: List["PlanNode"] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "operator": self.operator,
            "details": self.details,
            "estimated_rows": self.estimated_rows,
            "db_hits": self.db_hits,
            "actual_rows": self.actual_rows,
            "children": [c.to_dict() for c in self.children],
        }

    def flatten(self, depth: int = 0) -> List[Tuple[int, "PlanNode"]]:
        out = [(depth, self)]
        for c in self.children:
            out.extend(c.flatten(depth + 1))
        return out


class CountingEngine:
    """Delegating storage proxy that counts db hits for PROFILE
    (reference: explain.go db-hit accounting on the operator tree)."""

    _READS = {
        "get_node", "get_edge", "get_nodes_by_label", "get_edges_by_type",
        "all_nodes", "all_edges", "get_node_edges", "neighbors", "degree",
        "batch_get_nodes", "has_node", "has_edge", "count_nodes",
        "count_edges", "count_nodes_by_label", "count_nodes_with_prefix",
        "count_edges_with_prefix",
    }
    _WRITES = {
        "create_node", "update_node", "delete_node", "create_edge",
        "update_edge", "delete_edge", "delete_by_prefix",
    }

    def __init__(self, inner: Engine):
        self._inner = inner
        self.hits = 0

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if name in self._READS or name in self._WRITES:
            def counted(*args, **kwargs):
                self.hits += 1
                out = attr(*args, **kwargs)
                # iterables of rows cost ~1 hit per row fetched
                if name in ("get_nodes_by_label", "get_edges_by_type",
                            "batch_get_nodes", "neighbors", "get_node_edges"):
                    try:
                        self.hits += len(out)
                    except TypeError:
                        pass
                return out
            return counted
        return attr


def _label_estimate(storage: Engine, labels: List[str], total: int) -> int:
    if not labels:
        return total
    counter = getattr(storage, "count_nodes_by_label", None)
    if counter is not None:
        try:
            return counter(labels[0])
        except Exception:
            pass
    # never materialize the label's node list just for an estimate
    return max(1, total // 10)


def _pattern_plan(storage: Engine, path: A.PatternPath, optional: bool) -> PlanNode:
    total = storage.count_nodes()
    first = path.nodes[0]
    if first.labels:
        est = _label_estimate(storage, first.labels, total)
        leaf = PlanNode(
            operator="NodeByLabelScan",
            details=f"({first.var or ''}:{':'.join(first.labels)})",
            estimated_rows=est,
        )
    else:
        leaf = PlanNode(
            operator="AllNodesScan",
            details=f"({first.var or ''})",
            estimated_rows=total,
        )
    if first.props is not None:
        leaf = PlanNode(
            operator="Filter",
            details="property predicate",
            estimated_rows=max(1, leaf.estimated_rows // 4),
            children=[leaf],
        )
    node = leaf
    avg_degree = (
        (storage.count_edges() / max(1, total)) if total else 0.0
    )
    for src, rel, dst in zip(path.nodes, path.rels, path.nodes[1:]):
        var_len = rel.min_hops != 1 or rel.max_hops != 1
        op = "VarLengthExpand" if var_len else "Expand(All)"
        arrow = {"out": "-->", "in": "<--", "both": "--"}[rel.direction]
        t = ":" + "|".join(rel.types) if rel.types else ""
        est = max(1, int(node.estimated_rows * max(avg_degree, 1.0)))
        node = PlanNode(
            operator="OptionalExpand" if optional and not var_len else op,
            details=f"({src.var or ''}){arrow}[{rel.var or ''}{t}]"
                    f"({dst.var or ''})",
            estimated_rows=est,
            children=[node],
        )
        if dst.labels or dst.props is not None:
            node = PlanNode(
                operator="Filter",
                details=f"(:{':'.join(dst.labels)})" if dst.labels else
                        "property predicate",
                estimated_rows=max(1, node.estimated_rows // 2),
                children=[node],
            )
    return node


def build_plan(storage: Engine, uq: A.UnionQuery) -> PlanNode:
    """Build the operator tree for a parsed query
    (reference: buildExecutionPlan, explain.go:149)."""
    parts = [_build_query_plan(storage, part) for part in uq.parts]
    if len(parts) == 1:
        root = parts[0]
    else:
        root = PlanNode(
            operator="Union",
            estimated_rows=sum(p.estimated_rows for p in parts),
            children=parts,
        )
    return PlanNode(operator="ProduceResults",
                    estimated_rows=root.estimated_rows, children=[root])


def _build_query_plan(storage: Engine, q: A.Query) -> PlanNode:
    node: Optional[PlanNode] = None

    def attach(new: PlanNode) -> PlanNode:
        if node is not None:
            new.children.insert(0, node)
        return new

    for clause in q.clauses:
        if isinstance(clause, A.MatchClause):
            pats = [_pattern_plan(storage, p, clause.optional)
                    for p in clause.paths]
            sub = pats[0]
            for extra in pats[1:]:
                sub = PlanNode(
                    operator="CartesianProduct",
                    estimated_rows=max(1, sub.estimated_rows *
                                       extra.estimated_rows),
                    children=[sub, extra],
                )
            if node is not None:
                sub = PlanNode(operator="Apply",
                               estimated_rows=sub.estimated_rows,
                               children=[node, sub])
            node = sub
            if clause.where is not None:
                node = PlanNode(operator="Filter", details="WHERE",
                                estimated_rows=max(1, node.estimated_rows // 4),
                                children=[node])
        elif isinstance(clause, A.UnwindClause):
            node = attach(PlanNode(
                operator="Unwind", details=clause.var,
                estimated_rows=max(10, node.estimated_rows if node else 10)))
        elif isinstance(clause, A.CreateClause):
            n_nodes = sum(len(p.nodes) for p in clause.paths)
            n_rels = sum(len(p.rels) for p in clause.paths)
            node = attach(PlanNode(
                operator="Create",
                details=f"{n_nodes} nodes, {n_rels} rels",
                estimated_rows=node.estimated_rows if node else 1))
        elif isinstance(clause, A.MergeClause):
            node = attach(PlanNode(
                operator="Merge",
                estimated_rows=node.estimated_rows if node else 1))
        elif isinstance(clause, A.SetClause):
            node = attach(PlanNode(
                operator="SetProperties",
                estimated_rows=node.estimated_rows if node else 1))
        elif isinstance(clause, A.RemoveClause):
            node = attach(PlanNode(
                operator="RemoveProperties",
                estimated_rows=node.estimated_rows if node else 1))
        elif isinstance(clause, A.DeleteClause):
            node = attach(PlanNode(
                operator="Delete", details="DETACH" if clause.detach else "",
                estimated_rows=node.estimated_rows if node else 1))
        elif isinstance(clause, (A.WithClause, A.ReturnClause)):
            est = node.estimated_rows if node else 1
            has_agg = any(_is_aggregating(i.expr) for i in clause.items)
            op = "EagerAggregation" if has_agg else "Projection"
            details = ", ".join(i.alias or i.text for i in clause.items)
            if clause.star:
                details = "*" + (", " + details if details else "")
            node = attach(PlanNode(
                operator=op, details=details,
                estimated_rows=max(1, est // 10) if has_agg else est))
            if clause.distinct and not has_agg:
                node = PlanNode(operator="Distinct",
                                estimated_rows=node.estimated_rows,
                                children=[node])
            if clause.order_by:
                node = PlanNode(operator="Sort",
                                estimated_rows=node.estimated_rows,
                                children=[node])
            if clause.skip is not None:
                node = PlanNode(operator="Skip",
                                estimated_rows=node.estimated_rows,
                                children=[node])
            if clause.limit is not None:
                lim = clause.limit
                est_l = (lim.value if isinstance(lim, A.Literal) and
                         isinstance(lim.value, int) else node.estimated_rows)
                node = PlanNode(operator="Limit", details=str(est_l),
                                estimated_rows=min(node.estimated_rows, est_l),
                                children=[node])
            if isinstance(clause, A.WithClause) and clause.where is not None:
                node = PlanNode(operator="Filter", details="WHERE",
                                estimated_rows=max(1, node.estimated_rows // 4),
                                children=[node])
        elif isinstance(clause, A.CallClause):
            node = attach(PlanNode(
                operator="ProcedureCall", details=clause.proc,
                estimated_rows=node.estimated_rows if node else 1))
    return node or PlanNode(operator="EmptyResult")


def _is_aggregating(e: A.Expr) -> bool:
    # single source of truth with actual execution (executor._contains_agg)
    from nornicdb_tpu.query.executor import _contains_agg

    return _contains_agg(e)


def plan_rows(plan: PlanNode) -> Tuple[List[str], List[List[Any]]]:
    """Render the plan tree as the tabular EXPLAIN output. (PROFILE
    returns the query's records; its plan rides on CypherResult.plan.)"""
    cols = ["Operator", "Details", "EstimatedRows"]
    rows: List[List[Any]] = []
    for depth, n in plan.flatten():
        op = ("+" * depth) + n.operator if depth else n.operator
        rows.append([op, n.details, n.estimated_rows])
    return cols, rows
