"""Cypher builtin scalar functions.

Reference: pkg/cypher/functions_eval_functions.go (2,211 LoC) + registry
pkg/cypher/fn/registry.go, builtins_core.go (~200 builtins). This module
covers the high-traffic core; APOC-namespaced functions register through
the same table (nornicdb_tpu.query.apoc).
"""

from __future__ import annotations

import math
import random
import time
import uuid as _uuid
from datetime import datetime, timezone
from typing import Any, Callable, Dict, List, Optional

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.storage.types import Edge, Node


class PathValue:
    """A matched path: alternating nodes and relationships."""

    def __init__(self, nodes: List[Node], rels: List[Edge]):
        self.nodes = nodes
        self.rels = rels

    def __eq__(self, other):
        return (
            isinstance(other, PathValue)
            and [n.id for n in self.nodes] == [n.id for n in other.nodes]
            and [r.id for r in self.rels] == [r.id for r in other.rels]
        )

    def __len__(self):
        return len(self.rels)


FunctionImpl = Callable[..., Any]
REGISTRY: Dict[str, FunctionImpl] = {}


def register(name: str, fn: FunctionImpl) -> None:
    REGISTRY[name.lower()] = fn


def lookup(name: str) -> Optional[FunctionImpl]:
    return REGISTRY.get(name.lower())


def _num(x: Any) -> float:
    if isinstance(x, bool) or not isinstance(x, (int, float)):
        raise CypherRuntimeError(f"expected number, got {type(x).__name__}")
    return x


# -- entity functions ----------------------------------------------------


def _id(x):
    if isinstance(x, (Node, Edge)):
        return x.id
    return None


def _labels(n):
    if isinstance(n, Node):
        return list(n.labels)
    if n is None:
        return None
    raise CypherRuntimeError("labels() expects a node")


def _type(r):
    if isinstance(r, Edge):
        return r.type
    if r is None:
        return None
    raise CypherRuntimeError("type() expects a relationship")


def _properties(x):
    if isinstance(x, (Node, Edge)):
        return dict(x.properties)
    if isinstance(x, dict):
        return dict(x)
    if x is None:
        return None
    raise CypherRuntimeError("properties() expects a node/relationship/map")


def _start_node(r):
    return r.start_node if isinstance(r, Edge) else None


def _end_node(r):
    return r.end_node if isinstance(r, Edge) else None


def _keys(x):
    if isinstance(x, (Node, Edge)):
        return sorted(x.properties.keys())
    if isinstance(x, dict):
        return sorted(x.keys())
    if x is None:
        return None
    raise CypherRuntimeError("keys() expects a node/relationship/map")


# -- list / size ---------------------------------------------------------


def _size(x):
    if x is None:
        return None
    if isinstance(x, (list, str, dict)):
        return len(x)
    raise CypherRuntimeError("size() expects a list/string/map")


def _length(x):
    if x is None:
        return None
    if isinstance(x, PathValue):
        return len(x)
    if isinstance(x, (list, str)):
        return len(x)
    raise CypherRuntimeError("length() expects a path")


def _range(start, end, step=1):
    start, end, step = int(start), int(end), int(step)
    if step == 0:
        raise CypherRuntimeError("range() step must not be zero")
    out = []
    i = start
    if step > 0:
        while i <= end:
            out.append(i)
            i += step
    else:
        while i >= end:
            out.append(i)
            i += step
    return out


def _coalesce(*args):
    for a in args:
        if a is not None:
            return a
    return None


def _head(lst):
    if lst is None:
        return None
    return lst[0] if lst else None


def _last(lst):
    if lst is None:
        return None
    return lst[-1] if lst else None


def _tail(lst):
    if lst is None:
        return None
    return list(lst[1:])


def _reverse(x):
    if x is None:
        return None
    if isinstance(x, str):
        return x[::-1]
    return list(reversed(x))


def _nodes(p):
    if isinstance(p, PathValue):
        return list(p.nodes)
    return None


def _relationships(p):
    if isinstance(p, PathValue):
        return list(p.rels)
    return None


# -- string --------------------------------------------------------------


def _to_string(x):
    if x is None:
        return None
    if isinstance(x, bool):
        return "true" if x else "false"
    if isinstance(x, float) and x.is_integer():
        return f"{x:.1f}"
    return str(x)


def _substring(s, start, length=None):
    if s is None:
        return None
    start = int(start)
    if length is None:
        return s[start:]
    return s[start : start + int(length)]


def _split(s, delim):
    if s is None:
        return None
    return s.split(delim)


def _replace(s, search, repl):
    if s is None:
        return None
    return s.replace(search, repl)


def _left(s, n):
    return None if s is None else s[: int(n)]


def _right(s, n):
    return None if s is None else (s[-int(n):] if int(n) > 0 else "")


# -- numeric -------------------------------------------------------------


def _to_integer(x):
    if x is None:
        return None
    try:
        if isinstance(x, str):
            return int(float(x)) if x.strip() else None
        if isinstance(x, bool):
            return 1 if x else 0
        return int(x)
    except (ValueError, TypeError):
        return None


def _to_float(x):
    if x is None:
        return None
    try:
        if isinstance(x, bool):
            return 1.0 if x else 0.0
        return float(x)
    except (ValueError, TypeError):
        return None


def _round(x, precision=0):
    if x is None:
        return None
    p = int(precision)
    # Cypher rounds half away from zero
    scaled = _num(x) * (10 ** p)
    r = math.floor(abs(scaled) + 0.5) * (1 if scaled >= 0 else -1)
    out = r / (10 ** p)
    return out if p > 0 else float(out)


def _install_core():
    register("id", _id)
    register("elementId", _id)
    register("labels", _labels)
    register("type", _type)
    register("properties", _properties)
    register("startNode", _start_node)
    register("endNode", _end_node)
    register("keys", _keys)
    register("size", _size)
    register("length", _length)
    register("range", _range)
    register("coalesce", _coalesce)
    register("head", _head)
    register("last", _last)
    register("tail", _tail)
    register("reverse", _reverse)
    register("nodes", _nodes)
    register("relationships", _relationships)

    register("toString", _to_string)
    register("toUpper", lambda s: None if s is None else s.upper())
    register("toLower", lambda s: None if s is None else s.lower())
    register("trim", lambda s: None if s is None else s.strip())
    register("ltrim", lambda s: None if s is None else s.lstrip())
    register("rtrim", lambda s: None if s is None else s.rstrip())
    register("substring", _substring)
    register("split", _split)
    register("replace", _replace)
    register("left", _left)
    register("right", _right)

    register("abs", lambda x: None if x is None else abs(_num(x)))
    register("ceil", lambda x: None if x is None else float(math.ceil(_num(x))))
    register("floor", lambda x: None if x is None else float(math.floor(_num(x))))
    register("round", _round)
    register("sqrt", lambda x: None if x is None else math.sqrt(_num(x)))
    register("sign", lambda x: None if x is None else (0 if x == 0 else (1 if x > 0 else -1)))
    register("exp", lambda x: None if x is None else math.exp(_num(x)))
    register("log", lambda x: None if x is None else math.log(_num(x)))
    register("log10", lambda x: None if x is None else math.log10(_num(x)))
    register("sin", lambda x: None if x is None else math.sin(_num(x)))
    register("cos", lambda x: None if x is None else math.cos(_num(x)))
    register("tan", lambda x: None if x is None else math.tan(_num(x)))
    register("atan", lambda x: None if x is None else math.atan(_num(x)))
    register("atan2", lambda y, x: math.atan2(_num(y), _num(x)))
    register("acos", lambda x: None if x is None else math.acos(_num(x)))
    register("asin", lambda x: None if x is None else math.asin(_num(x)))
    register("pi", lambda: math.pi)
    register("e", lambda: math.e)
    register("rand", lambda: random.random())
    register("toInteger", _to_integer)
    register("toFloat", _to_float)
    register("toBoolean", lambda x: None if x is None else (
        x if isinstance(x, bool) else
        (x.lower() == "true" if isinstance(x, str) and x.lower() in ("true", "false") else None)))

    register("timestamp", lambda: int(time.time() * 1000))
    register("randomUUID", lambda: str(_uuid.uuid4()))
    register("date", lambda s=None: (
        datetime.now(timezone.utc).strftime("%Y-%m-%d") if s is None else str(s)))
    register("datetime", lambda s=None: (
        datetime.now(timezone.utc).isoformat() if s is None
        else str(s)))


_install_core()
