"""Cypher builtin scalar functions.

Reference: pkg/cypher/functions_eval_functions.go (2,211 LoC) + registry
pkg/cypher/fn/registry.go, builtins_core.go (~200 builtins). This module
covers the high-traffic core; APOC-namespaced functions register through
the same table (nornicdb_tpu.query.apoc).
"""

from __future__ import annotations

import math
import random
import time
import uuid as _uuid
from datetime import datetime, timezone
from typing import Any, Callable, Dict, List, Optional

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.storage.types import Edge, Node


class PathValue:
    """A matched path: alternating nodes and relationships."""

    def __init__(self, nodes: List[Node], rels: List[Edge]):
        self.nodes = nodes
        self.rels = rels

    def __eq__(self, other):
        return (
            isinstance(other, PathValue)
            and [n.id for n in self.nodes] == [n.id for n in other.nodes]
            and [r.id for r in self.rels] == [r.id for r in other.rels]
        )

    def __len__(self):
        return len(self.rels)


FunctionImpl = Callable[..., Any]
REGISTRY: Dict[str, FunctionImpl] = {}


def register(name: str, fn: FunctionImpl) -> None:
    REGISTRY[name.lower()] = fn


def lookup(name: str) -> Optional[FunctionImpl]:
    return REGISTRY.get(name.lower())


def _num(x: Any) -> float:
    if isinstance(x, bool) or not isinstance(x, (int, float)):
        raise CypherRuntimeError(f"expected number, got {type(x).__name__}")
    return x


# -- entity functions ----------------------------------------------------


def _id(x):
    if isinstance(x, (Node, Edge)):
        return x.id
    return None


def _labels(n):
    if isinstance(n, Node):
        return list(n.labels)
    if n is None:
        return None
    raise CypherRuntimeError("labels() expects a node")


def _type(r):
    if isinstance(r, Edge):
        return r.type
    if r is None:
        return None
    raise CypherRuntimeError("type() expects a relationship")


def _properties(x):
    if isinstance(x, (Node, Edge)):
        return dict(x.properties)
    if isinstance(x, dict):
        return dict(x)
    if x is None:
        return None
    raise CypherRuntimeError("properties() expects a node/relationship/map")


def _start_node(r):
    return r.start_node if isinstance(r, Edge) else None


def _end_node(r):
    return r.end_node if isinstance(r, Edge) else None


def _keys(x):
    if isinstance(x, (Node, Edge)):
        return sorted(x.properties.keys())
    if isinstance(x, dict):
        return sorted(x.keys())
    if x is None:
        return None
    raise CypherRuntimeError("keys() expects a node/relationship/map")


# -- list / size ---------------------------------------------------------


def _size(x):
    if x is None:
        return None
    if isinstance(x, (list, str, dict)):
        return len(x)
    raise CypherRuntimeError("size() expects a list/string/map")


def _length(x):
    if x is None:
        return None
    if isinstance(x, PathValue):
        return len(x)
    if isinstance(x, (list, str)):
        return len(x)
    raise CypherRuntimeError("length() expects a path")


def _range(start, end, step=1):
    start, end, step = int(start), int(end), int(step)
    if step == 0:
        raise CypherRuntimeError("range() step must not be zero")
    out = []
    i = start
    if step > 0:
        while i <= end:
            out.append(i)
            i += step
    else:
        while i >= end:
            out.append(i)
            i += step
    return out


def _coalesce(*args):
    for a in args:
        if a is not None:
            return a
    return None


def _head(lst):
    if lst is None:
        return None
    return lst[0] if lst else None


def _last(lst):
    if lst is None:
        return None
    return lst[-1] if lst else None


def _tail(lst):
    if lst is None:
        return None
    return list(lst[1:])


def _reverse(x):
    if x is None:
        return None
    if isinstance(x, str):
        return x[::-1]
    return list(reversed(x))


def _nodes(p):
    if isinstance(p, PathValue):
        return list(p.nodes)
    return None


def _relationships(p):
    if isinstance(p, PathValue):
        return list(p.rels)
    return None


# -- string --------------------------------------------------------------


def _to_string(x):
    if x is None:
        return None
    if isinstance(x, bool):
        return "true" if x else "false"
    if isinstance(x, float) and x.is_integer():
        return f"{x:.1f}"
    return str(x)


def _substring(s, start, length=None):
    if s is None:
        return None
    start = int(start)
    if length is None:
        return s[start:]
    return s[start : start + int(length)]


def _split(s, delim):
    if s is None:
        return None
    return s.split(delim)


def _replace(s, search, repl):
    if s is None:
        return None
    return s.replace(search, repl)


def _left(s, n):
    return None if s is None else s[: int(n)]


def _right(s, n):
    return None if s is None else (s[-int(n):] if int(n) > 0 else "")


# -- numeric -------------------------------------------------------------


def _to_integer(x):
    if x is None:
        return None
    try:
        if isinstance(x, str):
            return int(float(x)) if x.strip() else None
        if isinstance(x, bool):
            return 1 if x else 0
        return int(x)
    except (ValueError, TypeError):
        return None


def _to_float(x):
    if x is None:
        return None
    try:
        if isinstance(x, bool):
            return 1.0 if x else 0.0
        return float(x)
    except (ValueError, TypeError):
        return None


def _to_boolean(x):
    if x is None or isinstance(x, bool):
        return x
    if isinstance(x, str) and x.lower() in ("true", "false"):
        return x.lower() == "true"
    return None


def _round(x, precision=0):
    if x is None:
        return None
    p = int(precision)
    # Cypher rounds half away from zero
    scaled = _num(x) * (10 ** p)
    r = math.floor(abs(scaled) + 0.5) * (1 if scaled >= 0 else -1)
    out = r / (10 ** p)
    return out if p > 0 else float(out)


def _install_core():
    register("id", _id)
    register("elementId", _id)
    register("labels", _labels)
    register("type", _type)
    register("properties", _properties)
    register("startNode", _start_node)
    register("endNode", _end_node)
    register("keys", _keys)
    register("size", _size)
    register("length", _length)
    register("range", _range)
    register("coalesce", _coalesce)
    register("head", _head)
    register("last", _last)
    register("tail", _tail)
    register("reverse", _reverse)
    register("nodes", _nodes)
    register("relationships", _relationships)

    register("toString", _to_string)
    register("toUpper", lambda s: None if s is None else s.upper())
    register("toLower", lambda s: None if s is None else s.lower())
    register("trim", lambda s: None if s is None else s.strip())
    register("ltrim", lambda s: None if s is None else s.lstrip())
    register("rtrim", lambda s: None if s is None else s.rstrip())
    register("substring", _substring)
    register("split", _split)
    register("replace", _replace)
    register("left", _left)
    register("right", _right)

    register("abs", lambda x: None if x is None else abs(_num(x)))
    register("ceil", lambda x: None if x is None else float(math.ceil(_num(x))))
    register("floor", lambda x: None if x is None else float(math.floor(_num(x))))
    register("round", _round)
    register("sqrt", lambda x: None if x is None else math.sqrt(_num(x)))
    register("sign", lambda x: None if x is None else (0 if x == 0 else (1 if x > 0 else -1)))
    register("exp", lambda x: None if x is None else math.exp(_num(x)))
    register("log", lambda x: None if x is None else math.log(_num(x)))
    register("log10", lambda x: None if x is None else math.log10(_num(x)))
    register("sin", lambda x: None if x is None else math.sin(_num(x)))
    register("cos", lambda x: None if x is None else math.cos(_num(x)))
    register("tan", lambda x: None if x is None else math.tan(_num(x)))
    register("atan", lambda x: None if x is None else math.atan(_num(x)))
    register("atan2", lambda y, x: math.atan2(_num(y), _num(x)))
    register("acos", lambda x: None if x is None else math.acos(_num(x)))
    register("asin", lambda x: None if x is None else math.asin(_num(x)))
    register("pi", lambda: math.pi)
    register("e", lambda: math.e)
    register("rand", lambda: random.random())
    register("toInteger", _to_integer)
    register("toFloat", _to_float)
    register("toBoolean", _to_boolean)

    register("timestamp", lambda: int(time.time() * 1000))
    register("randomUUID", lambda: str(_uuid.uuid4()))


def _install_temporal_spatial():
    """Temporal, duration, and spatial builtins (reference:
    pkg/cypher/duration.go + temporal functions in
    functions_eval_functions.go; spatial point/distance)."""
    from nornicdb_tpu.query import temporal_types as T

    def _nullable_ctor(maker):
        # fn() -> now; fn(null) -> null (Cypher distinguishes the two)
        def fn(*args):
            if args and args[0] is None:
                return None
            return maker(args[0]) if args else maker()
        return fn

    register("date", _nullable_ctor(T.make_date))
    register("datetime", _nullable_ctor(T.make_datetime))
    register("localdatetime", _nullable_ctor(T.make_localdatetime))
    register("time", _nullable_ctor(T.make_time))
    register("localtime", _nullable_ctor(T.make_localtime))
    def _duration(*args):
        if not args:
            raise CypherRuntimeError(
                "duration() requires a string or map argument"
            )
        return None if args[0] is None else T.parse_duration(args[0])

    register("duration", _duration)

    register("date.truncate",
             lambda unit, v=None: T.truncate(unit, v if v is not None
                                             else T.make_date(), "date"))
    register("datetime.truncate",
             lambda unit, v=None: T.truncate(unit, v if v is not None
                                             else T.make_datetime(),
                                             "datetime"))
    register("localdatetime.truncate",
             lambda unit, v=None: T.truncate(unit, v if v is not None
                                             else T.make_localdatetime(),
                                             "localdatetime"))
    # transaction/statement/realtime clocks (same instant in this engine)
    for fn_name, maker in [("date", T.make_date),
                           ("datetime", T.make_datetime),
                           ("localdatetime", T.make_localdatetime),
                           ("time", T.make_time),
                           ("localtime", T.make_localtime)]:
        for clock in ("transaction", "statement", "realtime"):
            register(f"{fn_name}.{clock}", (lambda mk: lambda: mk())(maker))
    register("datetime.fromepoch",
             lambda secs, nanos=0: T.make_datetime(
                 float(secs) * 1000.0 + float(nanos) / 1e6))
    register("datetime.fromepochmillis",
             lambda ms: T.make_datetime(float(ms)))

    register("duration.between", T.duration_between)
    register("duration.inmonths", T.duration_in_months)
    register("duration.indays", T.duration_in_days)
    register("duration.inseconds", T.duration_in_seconds)

    register("point", T.make_point)
    register("distance", T.point_distance)
    register("point.distance", T.point_distance)
    register("point.withinbbox", _point_within_bbox)


def _point_within_bbox(p, lower, upper):
    from nornicdb_tpu.query.temporal_types import CypherPoint

    if p is None or lower is None or upper is None:
        return None
    for v in (p, lower, upper):
        if not isinstance(v, CypherPoint):
            raise CypherRuntimeError("point.withinBBox() expects points")
    return (lower.x <= p.x <= upper.x) and (lower.y <= p.y <= upper.y)


def _install_extended():
    """Breadth beyond the core (reference builtins_core.go ~200 entries):
    *OrNull conversions, list conversions/operations, extra string and
    math functions, isEmpty/valueType, char_length."""
    # conversions with explicit null-on-failure contract
    register("tointegerornull", _to_integer)
    register("tofloatornull", _to_float)
    register("tobooleanornull", _to_boolean)
    register("tostringornull", lambda x: (
        _to_string(x) if isinstance(x, (bool, int, float, str)) else None))

    def _list_conv(conv):
        def fn(lst):
            if lst is None:
                return None
            if not isinstance(lst, list):
                raise CypherRuntimeError("expected a list")
            return [conv(x) for x in lst]
        return fn

    register("tointegerlist", _list_conv(_to_integer))
    register("tofloatlist", _list_conv(_to_float))
    register("tostringlist", _list_conv(
        lambda x: _to_string(x) if isinstance(x, (bool, int, float, str))
        else None))
    register("tobooleanlist", _list_conv(_to_boolean))

    register("isempty", lambda x: None if x is None else (
        len(x) == 0 if isinstance(x, (list, str, dict)) else
        _raise(CypherRuntimeError("isEmpty() expects list/string/map"))))
    register("char_length", lambda s: None if s is None else len(s))
    register("character_length", lambda s: None if s is None else len(s))
    register("normalize", lambda s, form="NFC": (
        None if s is None else __import__("unicodedata").normalize(form, s)))
    register("btrim", lambda s, chars=None: (
        None if s is None else s.strip(chars)))

    register("degrees", lambda x: None if x is None else math.degrees(_num(x)))
    register("radians", lambda x: None if x is None else math.radians(_num(x)))
    register("cot", lambda x: None if x is None else (
        float("inf") if math.tan(_num(x)) == 0 else 1.0 / math.tan(_num(x))))
    register("haversin", lambda x: None if x is None else
             math.sin(_num(x) / 2) ** 2)
    register("isnan", lambda x: None if x is None else (
        isinstance(x, float) and math.isnan(x)))

    def _value_type(x):
        if x is None:
            return "NULL"
        if isinstance(x, bool):
            return "BOOLEAN"
        if isinstance(x, int):
            return "INTEGER"
        if isinstance(x, float):
            return "FLOAT"
        if isinstance(x, str):
            return "STRING"
        if isinstance(x, list):
            return "LIST<ANY>"
        if isinstance(x, dict):
            return "MAP"
        if isinstance(x, Node):
            return "NODE"
        if isinstance(x, Edge):
            return "RELATIONSHIP"
        if isinstance(x, PathValue):
            return "PATH"
        from nornicdb_tpu.query import temporal_types as T

        if isinstance(x, T.CypherDate):
            return "DATE"
        if isinstance(x, T.CypherDateTime):
            return "ZONED DATETIME"
        if isinstance(x, T.CypherLocalDateTime):
            return "LOCAL DATETIME"
        if isinstance(x, T.CypherTime):
            return "ZONED TIME"
        if isinstance(x, T.CypherLocalTime):
            return "LOCAL TIME"
        if isinstance(x, T.CypherDuration):
            return "DURATION"
        if isinstance(x, T.CypherPoint):
            return "POINT"
        return type(x).__name__.upper()

    register("valuetype", _value_type)


def _raise(exc):
    raise exc


def _install_reference_tail() -> None:
    """Long-tail builtins for reference parity (functions_eval_math.go,
    functions_eval_functions.go, kalman_functions.go): hyperbolic math,
    string padding, legacy aliases, component-accessor function forms,
    spatial geometry, vector similarity, Kalman filters."""
    from nornicdb_tpu.query import temporal_types as T

    # hyperbolic / aliases
    register("sinh", lambda x: None if x is None else math.sinh(_num(x)))
    register("cosh", lambda x: None if x is None else math.cosh(_num(x)))
    register("tanh", lambda x: None if x is None else math.tanh(_num(x)))
    register("coth", lambda x: None if x is None else (
        float("inf") if math.tanh(_num(x)) == 0
        else 1.0 / math.tanh(_num(x))))
    def _power(x, y):
        if x is None or y is None:
            return None
        xv, yv = _num(x), _num(y)
        if (isinstance(xv, int) and isinstance(yv, int) and yv >= 0):
            return xv ** yv
        try:
            return math.pow(xv, yv)  # NaN/domain cases below
        except (ValueError, ZeroDivisionError):
            if xv == 0 and yv < 0:
                return float("inf")  # 0 ^ negative (IEEE pow)
            return float("nan")  # e.g. (-2) ^ 0.5

    register("power", _power)
    register("toint", REGISTRY["tointeger"])
    register("lower", REGISTRY["tolower"])
    register("upper", REGISTRY["toupper"])

    # string padding / search
    def _lpad(s, width, pad=" "):
        if s is None or width is None:
            return None
        s = str(s)
        pad = " " if pad is None else (str(pad) or " ")
        w = int(width)
        if len(s) >= w:
            return s
        fill = (pad * w)[: w - len(s)]
        return fill + s

    def _rpad(s, width, pad=" "):
        if s is None or width is None:
            return None
        s = str(s)
        pad = " " if pad is None else (str(pad) or " ")
        w = int(width)
        if len(s) >= w:
            return s
        return s + (pad * w)[: w - len(s)]

    register("lpad", _lpad)
    register("rpad", _rpad)

    def _index_of(coll, item):
        if coll is None:
            return None
        if isinstance(coll, str):
            return coll.find("" if item is None else str(item))
        if isinstance(coll, list):
            for i, x in enumerate(coll):
                if x == item and isinstance(x, bool) == isinstance(item, bool):
                    return i
            return -1
        raise CypherRuntimeError("indexOf() expects list or string")

    register("indexof", _index_of)
    register("nullif", lambda a, b: (
        None if a == b and isinstance(a, bool) == isinstance(b, bool)
        else a))

    def _format(template, *args):
        if template is None:
            return None
        t = str(template).replace("%v", "%s")
        try:
            return t % tuple(args)
        except (TypeError, ValueError):
            try:
                return t % tuple(str(a) for a in args)
            except (TypeError, ValueError):
                return t

    register("format", _format)

    def _slice(lst, start, end=None):
        if lst is None or start is None:
            return None
        if not isinstance(lst, list):
            raise CypherRuntimeError("slice() expects a list")
        n = len(lst)
        s = int(start)
        e = n if end is None else int(end)
        if s < 0:
            s += n
        if e < 0:
            e += n
        s = max(s, 0)
        e = min(e, n)
        return lst[s:e] if s < e else []

    register("slice", _slice)

    def _has_labels(node, labels):
        if not isinstance(node, Node):
            return False
        want = labels if isinstance(labels, list) else [labels]
        return all(lb in node.labels for lb in want)

    register("haslabels", _has_labels)

    # component-accessor function forms: date.year(d), datetime.hour(x)…
    def _component(name):
        def get(v):
            if v is None:
                return None
            comp = getattr(v, "component", None)
            if comp is None:
                v2 = T.make_datetime(v)
                return v2.component(name)
            return comp(name)
        return get

    for comp in ("year", "quarter", "month", "week", "weekyear", "day",
                 "dayofweek", "dayofyear", "ordinalday"):
        register(f"date.{comp}", _component(comp))
    for comp in ("year", "month", "day", "hour", "minute", "second"):
        register(f"datetime.{comp}", _component(comp))
    register("time.truncate",
             lambda unit, v=None: T.truncate(unit, v if v is not None
                                             else T.make_time(), "time"))
    register("localtime.truncate",
             lambda unit, v=None: T.truncate(unit, v if v is not None
                                             else T.make_localtime(),
                                             "localtime"))

    # point component accessors
    def _point_comp(name):
        def get(p):
            if p is None:
                return None
            if not isinstance(p, T.CypherPoint):
                raise CypherRuntimeError(f"point.{name}() expects a point")
            return p.component(name)
        return get

    for comp in ("x", "y", "z", "crs", "srid", "latitude", "longitude",
                 "height"):
        register(f"point.{comp}", _point_comp(comp))

    def _within_distance(p, center, dist):
        if p is None or center is None or dist is None:
            return None
        d = T.point_distance(p, center)
        if d is None:  # cross-CRS distance is null
            return None
        return d <= _num(dist)

    register("point.withindistance", _within_distance)
    register("withinbbox", REGISTRY["point.withinbbox"])

    # geometry constructors + predicates (reference returns plain maps,
    # functions_eval_math.go:1090-1230)
    def _geom_points(pts, kind):
        if not isinstance(pts, list) or len(pts) < (2 if kind ==
                                                    "linestring" else 3):
            raise CypherRuntimeError(
                f"{kind}() expects a list of at least "
                f"{2 if kind == 'linestring' else 3} points")
        out = []
        for p in pts:
            q = T.make_point(p) if not isinstance(p, T.CypherPoint) else p
            if q is None:
                raise CypherRuntimeError(f"{kind}(): bad point {p!r}")
            out.append(q)
        return out

    register("linestring", lambda pts: {
        "type": "linestring", "points": _geom_points(pts, "linestring")})
    register("polygon", lambda pts: {
        "type": "polygon", "points": _geom_points(pts, "polygon")})

    def _poly_pts(geom):
        if isinstance(geom, dict) and isinstance(geom.get("points"), list):
            return [p for p in geom["points"] if isinstance(p, T.CypherPoint)]
        return None

    def _point_in_polygon(poly, p):
        """Ray casting on the x/y plane."""
        pts = _poly_pts(poly)
        q = p if isinstance(p, T.CypherPoint) else (
            T.make_point(p) if isinstance(p, dict) else None)
        if not pts or q is None:
            return False
        inside = False
        j = len(pts) - 1
        for i in range(len(pts)):
            xi, yi = pts[i].x, pts[i].y
            xj, yj = pts[j].x, pts[j].y
            if (yi > q.y) != (yj > q.y) and (
                q.x < (xj - xi) * (q.y - yi) / (yj - yi) + xi
            ):
                inside = not inside
            j = i
        return inside

    register("point.contains", _point_in_polygon)
    register("point.intersects",
             lambda p, poly: _point_in_polygon(poly, p))

    # vector similarity (reference pkg/math/vector/similarity.go)
    def _fvec(v):
        if not isinstance(v, list) or not v:
            return None
        try:
            return [float(x) for x in v]
        except (TypeError, ValueError):
            return None

    def _cos_sim(a, b):
        va, vb = _fvec(a), _fvec(b)
        if va is None or vb is None or len(va) != len(vb):
            return None
        dot = sum(x * y for x, y in zip(va, vb))
        na = math.sqrt(sum(x * x for x in va))
        nb = math.sqrt(sum(y * y for y in vb))
        if na == 0 or nb == 0:
            return 0.0
        return dot / (na * nb)

    def _euc_sim(a, b):
        va, vb = _fvec(a), _fvec(b)
        if va is None or vb is None or len(va) != len(vb):
            return None
        return 1.0 / (1.0 + math.sqrt(
            sum((x - y) ** 2 for x, y in zip(va, vb))))

    register("vector.similarity.cosine", _cos_sim)
    register("vector.similarity.euclidean", _euc_sim)

    from nornicdb_tpu.query import kalman_fns

    kalman_fns.register_all(register)


_install_core()
_install_temporal_spatial()
_install_extended()
_install_reference_tail()
