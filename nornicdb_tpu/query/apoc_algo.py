"""APOC graph algorithms: community detection, path analytics, classic
algo (dijkstra/astar/centralities).

Reference: apoc/community/community.go (1,081 LoC), apoc/paths,
apoc/algo. All ctx-registered (they read the whole graph through
ctx.storage). Community results follow the reference's shape: a list of
{node, communityId}. The reference maps InfoMap -> LabelPropagation and
WalkTrap -> FastGreedy (community.go:803,1056); the same aliases apply
here.
"""

from __future__ import annotations

import heapq
import math
from typing import Any, Dict, List, Optional, Set, Tuple

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.query.apoc import register_ctx
from nornicdb_tpu.storage.types import Direction, Edge, Node

_MAX_NODES = 200_000  # whole-graph algorithm safety cap


def _graph(ctx) -> Tuple[List[Node], List[Edge]]:
    nodes = list(ctx.storage.all_nodes())
    if len(nodes) > _MAX_NODES:
        raise CypherRuntimeError(
            f"graph too large for in-memory algorithm ({len(nodes)} nodes)")
    return nodes, list(ctx.storage.all_edges())


def _adj(nodes: List[Node], rels: List[Edge],
         directed: bool = False) -> Dict[str, Set[str]]:
    ids = {n.id for n in nodes}
    adj: Dict[str, Set[str]] = {n.id: set() for n in nodes}
    for e in rels:
        if e.start_node in ids and e.end_node in ids:
            adj[e.start_node].add(e.end_node)
            if not directed:
                adj[e.end_node].add(e.start_node)
    return adj


def _weight(e: Edge, prop: Optional[str]) -> float:
    if prop:
        v = e.properties.get(prop)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return 1.0


def _result(nodes: List[Node], comm: Dict[str, int]) -> List[Dict[str, Any]]:
    # densify community ids in first-seen order (reference remaps too)
    remap: Dict[int, int] = {}
    out = []
    for n in nodes:
        c = comm.get(n.id, -1)
        if c not in remap:
            remap[c] = len(remap)
        out.append({"node": n, "communityId": remap[c]})
    return out


# -- components ----------------------------------------------------------


def _union_find_components(nodes: List[Node],
                           rels: List[Edge]) -> Dict[str, int]:
    parent: Dict[str, str] = {n.id: n.id for n in nodes}

    def find(x: str) -> str:
        root = x
        while parent[root] != root:
            root = parent[root]
        while parent[x] != root:
            parent[x], x = root, parent[x]
        return root

    for e in rels:
        if e.start_node in parent and e.end_node in parent:
            ra, rb = find(e.start_node), find(e.end_node)
            if ra != rb:
                parent[ra] = rb
    roots: Dict[str, int] = {}
    comm: Dict[str, int] = {}
    for n in nodes:
        r = find(n.id)
        if r not in roots:
            roots[r] = len(roots)
        comm[n.id] = roots[r]
    return comm


def _scc(nodes: List[Node], rels: List[Edge]) -> Dict[str, int]:
    """Tarjan's strongly connected components, iterative."""
    adj = _adj(nodes, rels, directed=True)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    comm: Dict[str, int] = {}
    counter = [0]
    n_comms = [0]

    for start in (n.id for n in nodes):
        if start in index:
            continue
        work = [(start, iter(sorted(adj[start])))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comm[w] = n_comms[0]
                    if w == v:
                        break
                n_comms[0] += 1
    return comm


def _label_propagation(nodes: List[Node], rels: List[Edge],
                       max_iter: int = 10) -> Dict[str, int]:
    adj = _adj(nodes, rels)
    comm = {n.id: i for i, n in enumerate(nodes)}
    for _ in range(max(int(max_iter), 1)):
        changed = False
        for n in nodes:
            if not adj[n.id]:
                continue
            counts: Dict[int, int] = {}
            for m in adj[n.id]:
                counts[comm[m]] = counts.get(comm[m], 0) + 1
            # deterministic tie-break: highest count, then lowest id
            best = min(counts.items(), key=lambda kv: (-kv[1], kv[0]))[0]
            if best != comm[n.id]:
                comm[n.id] = best
                changed = True
        if not changed:
            break
    return comm


def _modularity(nodes: List[Node], rels: List[Edge],
                comm: Dict[str, int], weight_prop=None) -> float:
    ids = {n.id for n in nodes}
    m2 = 0.0
    deg: Dict[str, float] = {n.id: 0.0 for n in nodes}
    for e in rels:
        if e.start_node in ids and e.end_node in ids:
            w = _weight(e, weight_prop)
            m2 += 2 * w
            deg[e.start_node] += w
            deg[e.end_node] += w
    if m2 == 0:
        return 0.0
    q = 0.0
    for e in rels:
        if e.start_node in ids and e.end_node in ids:
            if comm.get(e.start_node) == comm.get(e.end_node):
                q += 2 * _weight(e, weight_prop)
    for cid in set(comm.values()):
        tot = sum(deg[nid] for nid, c in comm.items() if c == cid)
        q -= tot * tot / m2
    return q / m2


def _greedy_modularity(nodes: List[Node], rels: List[Edge],
                       max_iter: int = 10) -> Dict[str, int]:
    """One-level greedy modularity optimization (the Louvain local-move
    phase; also serves FastGreedy, as in the reference)."""
    adj_w: Dict[str, Dict[str, float]] = {n.id: {} for n in nodes}
    ids = {n.id for n in nodes}
    m2 = 0.0
    deg: Dict[str, float] = {n.id: 0.0 for n in nodes}
    for e in rels:
        if e.start_node in ids and e.end_node in ids:
            w = _weight(e, "weight")
            adj_w[e.start_node][e.end_node] = \
                adj_w[e.start_node].get(e.end_node, 0.0) + w
            adj_w[e.end_node][e.start_node] = \
                adj_w[e.end_node].get(e.start_node, 0.0) + w
            deg[e.start_node] += w
            deg[e.end_node] += w
            m2 += 2 * w
    comm = {n.id: i for i, n in enumerate(nodes)}
    if m2 == 0:
        return comm
    comm_deg: Dict[int, float] = {}
    for nid, c in comm.items():
        comm_deg[c] = comm_deg.get(c, 0.0) + deg[nid]
    for _ in range(max(int(max_iter), 1)):
        moved = False
        for n in nodes:
            nid = n.id
            cur = comm[nid]
            # weights to neighboring communities
            to_comm: Dict[int, float] = {}
            for m, w in adj_w[nid].items():
                to_comm[comm[m]] = to_comm.get(comm[m], 0.0) + w
            comm_deg[cur] -= deg[nid]
            best, best_gain = cur, 0.0
            for c, w_in in sorted(to_comm.items()):
                gain = w_in / m2 - comm_deg.get(c, 0.0) * deg[nid] / (
                    m2 * m2) * 2
                base = to_comm.get(cur, 0.0) / m2 - comm_deg.get(
                    cur, 0.0) * deg[nid] / (m2 * m2) * 2
                if gain - base > best_gain + 1e-12:
                    best, best_gain = c, gain - base
            comm[nid] = best
            comm_deg[best] = comm_deg.get(best, 0.0) + deg[nid]
            if best != cur:
                moved = True
        if not moved:
            break
    return comm


def _triangles_per_node(nodes: List[Node],
                        rels: List[Edge]) -> Dict[str, int]:
    adj = _adj(nodes, rels)
    tri = {n.id: 0 for n in nodes}
    for n in nodes:
        neigh = sorted(adj[n.id])
        for i in range(len(neigh)):
            for j in range(i + 1, len(neigh)):
                if neigh[j] in adj[neigh[i]]:
                    tri[n.id] += 1
    return tri


def _core_numbers(nodes: List[Node], rels: List[Edge]) -> Dict[str, int]:
    adj = {k: set(v) for k, v in _adj(nodes, rels).items()}
    deg = {nid: len(v) for nid, v in adj.items()}
    core: Dict[str, int] = {}
    remaining = set(deg)
    k = 0
    while remaining:
        k_nodes = sorted(nid for nid in remaining if deg[nid] <= k)
        if not k_nodes:
            k += 1
            continue
        while k_nodes:
            nid = k_nodes.pop()
            core[nid] = k
            remaining.discard(nid)
            for m in adj[nid]:
                if m in remaining:
                    deg[m] -= 1
                    if deg[m] <= k:
                        k_nodes.append(m)
            adj[nid] = set()
    return core


def _install_community() -> None:
    c = "apoc.community."

    def _cc(ctx):
        nodes, rels = _graph(ctx)
        return _result(nodes, _union_find_components(nodes, rels))

    register_ctx(c + "connectedComponents", _cc)
    register_ctx(c + "weaklyConnectedComponents", _cc)

    def _scc_fn(ctx):
        nodes, rels = _graph(ctx)
        return _result(nodes, _scc(nodes, rels))

    register_ctx(c + "stronglyConnectedComponents", _scc_fn)
    register_ctx(c + "numComponents", lambda ctx: len(set(
        _union_find_components(*_graph(ctx)).values())))

    def _lp(ctx, max_iter=10):
        nodes, rels = _graph(ctx)
        return _result(nodes, _label_propagation(nodes, rels, max_iter))

    register_ctx(c + "labelPropagation", _lp)
    register_ctx(c + "infomap", _lp)  # reference community.go:803

    def _louvain(ctx, max_iter=10):
        nodes, rels = _graph(ctx)
        return _result(nodes, _greedy_modularity(nodes, rels, max_iter))

    register_ctx(c + "louvain", _louvain)
    register_ctx(c + "fastGreedy", _louvain)
    register_ctx(c + "walktrap", _louvain)  # reference community.go:1056
    register_ctx(c + "spinglass", lambda ctx, spins=25, gamma=1.0: _louvain(
        ctx))

    def _mod(ctx, community_map=None):
        nodes, rels = _graph(ctx)
        if community_map is None:
            comm = _greedy_modularity(nodes, rels)
        else:
            comm = {str(k): int(v) for k, v in community_map.items()}
        return _modularity(nodes, rels, comm)

    register_ctx(c + "modularity", _mod)

    def _tri(ctx):
        nodes, rels = _graph(ctx)
        t = _triangles_per_node(nodes, rels)
        return [{"node": n, "triangles": t[n.id]} for n in nodes]

    register_ctx(c + "triangleCount", _tri)
    register_ctx(c + "totalTriangles", lambda ctx: sum(
        _triangles_per_node(*_graph(ctx)).values()) // 3)

    def _clustering(ctx):
        nodes, rels = _graph(ctx)
        adj = _adj(nodes, rels)
        tri = _triangles_per_node(nodes, rels)
        out = []
        for n in nodes:
            d = len(adj[n.id])
            coeff = (2.0 * tri[n.id] / (d * (d - 1))) if d >= 2 else 0.0
            out.append({"node": n, "coefficient": coeff})
        return out

    register_ctx(c + "clusteringCoefficient", _clustering)
    register_ctx(c + "averageClusteringCoefficient", lambda ctx: (
        (sum(d["coefficient"] for d in _clustering(ctx)) / len(cs))
        if (cs := _clustering(ctx)) else 0.0))

    def _density(ctx):
        nodes, rels = _graph(ctx)
        n = len(nodes)
        if n < 2:
            return 0.0
        ids = {x.id for x in nodes}
        m = sum(1 for e in rels
                if e.start_node in ids and e.end_node in ids)
        return 2.0 * m / (n * (n - 1))

    register_ctx(c + "density", _density)

    def _conductance(ctx, community_nodes):
        nodes, rels = _graph(ctx)
        inside = {x.id for x in (community_nodes or [])
                  if isinstance(x, Node)}
        cut = vol_in = vol_out = 0
        for e in rels:
            s_in = e.start_node in inside
            t_in = e.end_node in inside
            if s_in != t_in:
                cut += 1
            if s_in:
                vol_in += 1
            if t_in:
                vol_in += 1
            if not s_in:
                vol_out += 1
            if not t_in:
                vol_out += 1
        denom = min(vol_in, vol_out)
        return cut / denom if denom else 0.0

    register_ctx(c + "conductance", _conductance)

    def _kcore(ctx, k=2):
        nodes, rels = _graph(ctx)
        core = _core_numbers(nodes, rels)
        return [n for n in nodes if core.get(n.id, 0) >= int(k)]

    register_ctx(c + "kcore", _kcore)

    def _corenumber(ctx):
        nodes, rels = _graph(ctx)
        core = _core_numbers(nodes, rels)
        return [{"node": n, "coreNumber": core.get(n.id, 0)}
                for n in nodes]

    register_ctx(c + "coreNumber", _corenumber)


# -- paths ---------------------------------------------------------------


def _neighbors_dir(ctx, nid: str, directed: bool) -> List[Tuple[str, Edge]]:
    direction = Direction.OUTGOING if directed else Direction.BOTH
    out = []
    for e in ctx.storage.get_node_edges(nid, direction=direction):
        other = e.end_node if e.start_node == nid else e.start_node
        out.append((other, e))
    return out


def _bfs_dist(ctx, a: Node, b: Node, directed=True) -> Optional[int]:
    p = _bfs_path(ctx, a, b, directed)
    return None if p is None else len(p) - 1


def _bfs_path(ctx, a: Node, b: Node, directed=True) -> Optional[List[str]]:
    """Exact shortest path (node-id list) by BFS with parent tracking."""
    if a.id == b.id:
        return [a.id]
    prev = {a.id: None}
    frontier = [a.id]
    while frontier:
        nxt = []
        for nid in frontier:
            for other, _e in _neighbors_dir(ctx, nid, directed):
                if other in prev:
                    continue
                prev[other] = nid
                if other == b.id:
                    path = [other]
                    while path[-1] != a.id:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                nxt.append(other)
        frontier = nxt
    return None


def _all_simple_paths(ctx, a: Node, b: Node, max_len=6,
                      limit=1000) -> List[List[str]]:
    paths: List[List[str]] = []
    stack: List[Tuple[str, List[str]]] = [(a.id, [a.id])]
    while stack and len(paths) < int(limit):
        cur, path = stack.pop()
        if len(path) > int(max_len) + 1:
            continue
        for other, _e in _neighbors_dir(ctx, cur, directed=True):
            if other == b.id:
                paths.append(path + [other])
            elif other not in path and len(path) <= int(max_len):
                stack.append((other, path + [other]))
    return paths


def _install_paths() -> None:
    p = "apoc.paths."

    register_ctx(p + "distance", lambda ctx, a, b: _bfs_dist(ctx, a, b))
    register_ctx(p + "exists", lambda ctx, a, b: _bfs_dist(
        ctx, a, b) is not None)
    register_ctx(p + "count", lambda ctx, a, b, max_len=6: len(
        _all_simple_paths(ctx, a, b, max_len)))
    register_ctx(p + "all", lambda ctx, a, b, max_len=6: _all_simple_paths(
        ctx, a, b, max_len))
    register_ctx(p + "simple", lambda ctx, a, b, max_len=6:
                 _all_simple_paths(ctx, a, b, max_len))
    register_ctx(p + "shortest", lambda ctx, a, b: _bfs_path(ctx, a, b))
    register_ctx(p + "longest", lambda ctx, a, b, max_len=8: max(
        _all_simple_paths(ctx, a, b, max_len), key=len, default=None))
    register_ctx(p + "kShortest", lambda ctx, a, b, k=3, max_len=10: sorted(
        _all_simple_paths(ctx, a, b, max_len), key=len)[: int(k)])
    register_ctx(p + "withinLength", lambda ctx, a, b, max_len: [
        q for q in _all_simple_paths(ctx, a, b, max_len)])
    register_ctx(p + "withLength", lambda ctx, a, b, length: [
        q for q in _all_simple_paths(ctx, a, b, int(length))
        if len(q) - 1 == int(length)])
    register_ctx(p + "common", lambda ctx, a, b: sorted(
        {other for other, _ in _neighbors_dir(ctx, a.id, False)}
        & {other for other, _ in _neighbors_dir(ctx, b.id, False)}))
    register_ctx(p + "disjoint", lambda ctx, paths_a, paths_b: not (
        {n for q in (paths_a or []) for n in q}
        & {n for q in (paths_b or []) for n in q}))
    register_ctx(p + "edgeDisjoint", lambda ctx, a, b: _edge_disjoint(
        ctx, a, b))
    register_ctx(p + "unique", lambda ctx, paths: [
        list(q) for q in dict.fromkeys(tuple(q) for q in (paths or []))])
    register_ctx(p + "reverse", lambda ctx, path: list(
        reversed(path or [])))
    register_ctx(p + "slice", lambda ctx, path, start, length: list(
        (path or [])[int(start): int(start) + int(length)]))
    register_ctx(p + "merge", lambda ctx, a, b: (
        list(a or []) + list(b or [])[1:]
        if (a and b and a[-1] == b[0]) else list(a or []) + list(b or [])))
    register_ctx(p + "elementary", lambda ctx, path: len(
        set(path or [])) == len(path or []))

    def _cycles(ctx, start, max_len=8):
        start = start if isinstance(start, Node) else None
        if start is None:
            raise CypherRuntimeError("apoc.paths.cycles expects a node")
        cycles = []
        stack = [(start.id, [start.id])]
        while stack:
            cur, path = stack.pop()
            if len(path) > int(max_len):
                continue
            for other, _e in _neighbors_dir(ctx, cur, directed=True):
                if other == start.id and len(path) > 1:
                    cycles.append(path + [other])
                elif other not in path:
                    stack.append((other, path + [other]))
        return cycles

    register_ctx(p + "cycles", _cycles)

    def _eulerian(ctx):
        """Connected + every node has even degree (undirected check)."""
        nodes, rels = _graph(ctx)
        if not nodes:
            return False
        comp = _union_find_components(
            [n for n in nodes
             if ctx.storage.get_node_edges(n.id)], rels)
        if len(set(comp.values())) > 1:
            return False
        for n in nodes:
            if len(ctx.storage.get_node_edges(n.id)) % 2:
                return False
        return True

    register_ctx(p + "eulerian", _eulerian)

    def _hamiltonian(ctx, max_nodes=12):
        """Exact search, exponential: refuses graphs beyond max_nodes."""
        nodes, rels = _graph(ctx)
        if len(nodes) > int(max_nodes):
            raise CypherRuntimeError(
                "hamiltonian path search is exponential; graph exceeds "
                f"{max_nodes} nodes")
        if not nodes:
            return False
        adj = _adj(nodes, rels)
        n_total = len(nodes)
        for start in nodes:
            stack = [(start.id, {start.id})]
            path_stack = [[start.id]]
            while stack:
                cur, seen = stack.pop()
                path = path_stack.pop()
                if len(seen) == n_total:
                    return True
                for m in sorted(adj[cur]):
                    if m not in seen:
                        stack.append((m, seen | {m}))
                        path_stack.append(path + [m])
        return False

    register_ctx(p + "hamiltonian", _hamiltonian)


def _edge_disjoint(ctx, a: Node, b: Node) -> int:
    """Max edge-disjoint paths between a and b (greedy BFS removal)."""
    used: Set[str] = set()
    count = 0
    while True:
        prev: Dict[str, Tuple[str, str]] = {}
        visited = {a.id}
        frontier = [a.id]
        found = False
        while frontier and not found:
            nxt = []
            for nid in frontier:
                for other, e in _neighbors_dir(ctx, nid, directed=True):
                    if e.id in used or other in visited:
                        continue
                    prev[other] = (nid, e.id)
                    if other == b.id:
                        found = True
                        break
                    visited.add(other)
                    nxt.append(other)
                if found:
                    break
            frontier = nxt
        if not found:
            return count
        cur = b.id
        while cur != a.id:
            pnode, eid = prev[cur]
            used.add(eid)
            cur = pnode
        count += 1


# -- classic algo --------------------------------------------------------


def _install_algo() -> None:
    al = "apoc.algo."

    def _dijkstra(ctx, a, b, weight_prop="weight", default_weight=1.0):
        if not isinstance(a, Node) or not isinstance(b, Node):
            raise CypherRuntimeError("dijkstra expects two nodes")
        dist: Dict[str, float] = {a.id: 0.0}
        prev: Dict[str, str] = {}
        pq: List[Tuple[float, str]] = [(0.0, a.id)]
        done: Set[str] = set()
        while pq:
            d, nid = heapq.heappop(pq)
            if nid in done:
                continue
            done.add(nid)
            if nid == b.id:
                break
            for other, e in _neighbors_dir(ctx, nid, directed=True):
                w = e.properties.get(weight_prop, default_weight)
                w = float(w) if isinstance(w, (int, float)) and \
                    not isinstance(w, bool) else float(default_weight)
                nd = d + w
                if nd < dist.get(other, math.inf):
                    dist[other] = nd
                    prev[other] = nid
                    heapq.heappush(pq, (nd, other))
        if b.id not in dist or b.id not in done:
            return None
        path = [b.id]
        while path[-1] != a.id:
            path.append(prev[path[-1]])
        return {"weight": dist[b.id], "path": list(reversed(path))}

    register_ctx(al + "dijkstra", _dijkstra)

    def _astar(ctx, a, b, weight_prop="weight", lat_prop="latitude",
               lon_prop="longitude"):
        """A* with geographic haversine heuristic; falls back to
        dijkstra when coordinates are absent."""
        if not isinstance(a, Node) or not isinstance(b, Node):
            raise CypherRuntimeError("astar expects two nodes")

        def coords(n: Node):
            la, lo = n.properties.get(lat_prop), n.properties.get(lon_prop)
            if isinstance(la, (int, float)) and isinstance(lo, (int, float)):
                return float(la), float(lo)
            return None

        target = coords(b)

        def h(nid: str) -> float:
            """Haversine meters (reference semantics: edge weights are
            distances in meters when coordinates are present — the same
            unit as the heuristic, keeping A* admissible)."""
            if target is None:
                return 0.0
            from nornicdb_tpu.errors import NotFoundError
            try:
                n = ctx.storage.get_node(nid)
            except NotFoundError:
                return 0.0
            c = coords(n)
            if c is None:
                return 0.0
            la1, lo1 = c
            la2, lo2 = target
            p1, p2 = math.radians(la1), math.radians(la2)
            dp = math.radians(la2 - la1)
            dl = math.radians(lo2 - lo1)
            hv = (math.sin(dp / 2) ** 2
                  + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2)
            return 2 * 6_371_000.0 * math.asin(math.sqrt(hv))

        dist: Dict[str, float] = {a.id: 0.0}
        prev: Dict[str, str] = {}
        pq: List[Tuple[float, str]] = [(h(a.id), a.id)]
        done: Set[str] = set()
        while pq:
            _f, nid = heapq.heappop(pq)
            if nid in done:
                continue
            done.add(nid)
            if nid == b.id:
                break
            for other, e in _neighbors_dir(ctx, nid, directed=True):
                w = e.properties.get(weight_prop, 1.0)
                w = float(w) if isinstance(w, (int, float)) and \
                    not isinstance(w, bool) else 1.0
                nd = dist[nid] + w
                if nd < dist.get(other, math.inf):
                    dist[other] = nd
                    prev[other] = nid
                    heapq.heappush(pq, (nd + h(other), other))
        if b.id not in done:
            return None
        path = [b.id]
        while path[-1] != a.id:
            path.append(prev[path[-1]])
        return {"weight": dist[b.id], "path": list(reversed(path))}

    register_ctx(al + "astar", _astar)

    def _degree_centrality(ctx):
        nodes, rels = _graph(ctx)
        n = max(len(nodes) - 1, 1)
        deg: Dict[str, int] = {x.id: 0 for x in nodes}
        for e in rels:
            if e.start_node in deg:
                deg[e.start_node] += 1
            if e.end_node in deg:
                deg[e.end_node] += 1
        return [{"node": x, "centrality": deg[x.id] / n} for x in nodes]

    register_ctx(al + "degreeCentrality", _degree_centrality)

    def _closeness(ctx):
        nodes, rels = _graph(ctx)
        adj = _adj(nodes, rels)
        out = []
        for x in nodes:
            # BFS from x
            dist = {x.id: 0}
            frontier = [x.id]
            d = 0
            while frontier:
                d += 1
                nxt = []
                for nid in frontier:
                    for m in adj[nid]:
                        if m not in dist:
                            dist[m] = d
                            nxt.append(m)
                frontier = nxt
            total = sum(dist.values())
            reach = len(dist) - 1
            c = (reach / total) * (reach / max(len(nodes) - 1, 1)) \
                if total else 0.0
            out.append({"node": x, "centrality": c})
        return out

    register_ctx(al + "closenessCentrality", _closeness)

    def _betweenness(ctx):
        """Brandes' algorithm (unweighted)."""
        nodes, rels = _graph(ctx)
        adj = _adj(nodes, rels)
        cb: Dict[str, float] = {x.id: 0.0 for x in nodes}
        for s in nodes:
            stack: List[str] = []
            pred: Dict[str, List[str]] = {x.id: [] for x in nodes}
            sigma = {x.id: 0.0 for x in nodes}
            sigma[s.id] = 1.0
            dist = {x.id: -1 for x in nodes}
            dist[s.id] = 0
            queue = [s.id]
            while queue:
                v = queue.pop(0)
                stack.append(v)
                for w in sorted(adj[v]):
                    if dist[w] < 0:
                        dist[w] = dist[v] + 1
                        queue.append(w)
                    if dist[w] == dist[v] + 1:
                        sigma[w] += sigma[v]
                        pred[w].append(v)
            delta = {x.id: 0.0 for x in nodes}
            while stack:
                w = stack.pop()
                for v in pred[w]:
                    delta[v] += sigma[v] / sigma[w] * (1 + delta[w])
                if w != s.id:
                    cb[w] += delta[w]
            # undirected: each pair counted twice; halve at the end
        return [{"node": x, "centrality": cb[x.id] / 2.0} for x in nodes]

    register_ctx(al + "betweennessCentrality", _betweenness)

    def _pagerank_power(ctx, iterations=20, damping=0.85):
        """Plain power iteration. The device data-plane PageRank
        (ops/graph.py, gds.pageRank procedures) serves large graphs;
        this is the small-graph convenience surface."""
        nodes, rels = _graph(ctx)
        pos = {n.id: i for i, n in enumerate(nodes)}
        n = len(nodes)
        if n == 0:
            return []
        out_deg = [0] * n
        edges = []
        for e in rels:
            if e.start_node in pos and e.end_node in pos:
                edges.append((pos[e.start_node], pos[e.end_node]))
                out_deg[pos[e.start_node]] += 1
        rank = [1.0 / n] * n
        d = float(damping)
        for _ in range(int(iterations)):
            nxt = [(1 - d) / n] * n
            for s, t in edges:
                if out_deg[s]:
                    nxt[t] += d * rank[s] / out_deg[s]
            sink = sum(rank[i] for i in range(n) if not out_deg[i])
            for i in range(n):
                nxt[i] += d * sink / n
            rank = nxt
        return [{"node": x, "score": rank[pos[x.id]]} for x in nodes]

    register_ctx(al + "pagerank", _pagerank_power)

    def _cover(ctx, node_list):
        """Relationships fully inside the given node set."""
        ids = {x.id for x in (node_list or []) if isinstance(x, Node)}
        return [e for e in ctx.storage.all_edges()
                if e.start_node in ids and e.end_node in ids]

    register_ctx(al + "cover", _cover)

    def _all_pairs(ctx, max_nodes=200):
        nodes, rels = _graph(ctx)
        if len(nodes) > int(max_nodes):
            raise CypherRuntimeError(
                f"allPairs is O(n^2); graph exceeds {max_nodes} nodes")
        adj = _adj(nodes, rels)
        out = []
        for a in nodes:
            dist = {a.id: 0}
            frontier = [a.id]
            d = 0
            while frontier:
                d += 1
                nxt = []
                for nid in frontier:
                    for m in adj[nid]:
                        if m not in dist:
                            dist[m] = d
                            nxt.append(m)
                frontier = nxt
            for b in nodes:
                if b.id != a.id and b.id in dist:
                    out.append({"source": a.id, "target": b.id,
                                "distance": dist[b.id]})
        return out

    register_ctx(al + "allPairs", _all_pairs)

    def _community(ctx, max_iter=10):
        nodes, rels = _graph(ctx)
        return _result(nodes, _label_propagation(nodes, rels, max_iter))

    register_ctx(al + "community", _community)


def install() -> None:
    _install_community()
    _install_paths()
    _install_algo()


install()
