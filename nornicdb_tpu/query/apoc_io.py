"""APOC IO + orchestration long tail: cypher subqueries, export/import,
load, virtual graphs, triggers, periodic jobs, and per-category
leftovers (map, path, node/rel write forms, search index mgmt, hashing).

Reference: apoc/cypher, apoc/export, apoc/import, apoc/load, apoc/graph,
apoc/trigger, apoc/periodic. External-system loaders (kafka, jdbc, s3,
elasticsearch, ...) mirror the reference's observable behavior: they are
acknowledged placeholders returning empty results (apoc/load/load.go:425
"Placeholder - would consume from Kafka"). The simplified xxhash/cityhash
formulas reproduce the reference's actual outputs
(apoc/hashing/hashing.go:302-360: cityHash64 == fnv1a64; byte-loop
xxhash variants).
"""

from __future__ import annotations

import csv as _csv
import io as _io
import json as _json
import os
import threading
import time as _time
from typing import Any, Dict, Iterator, List, Optional

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.query.apoc import register, register_ctx
from nornicdb_tpu.storage.types import Edge, Node

_U32 = 0xFFFFFFFF
_U64 = (1 << 64) - 1


# -- apoc.cypher ----------------------------------------------------------


def _sub(ctx, statement: str, params: Optional[Dict] = None):
    return ctx.ex._execute_for_trigger(str(statement), params or {})


def _install_cypher() -> None:
    cy = "apoc.cypher."

    register_ctx(cy + "run", lambda ctx, stmt, params=None: [
        rec for rec in _sub(ctx, stmt, params).records()])
    register_ctx(cy + "doIt", lambda ctx, stmt, params=None: [
        rec for rec in _sub(ctx, stmt, params).records()])
    register_ctx(cy + "runFirstColumn",
                 lambda ctx, stmt, params=None, first_only=False: (
                     (vals[0] if vals else None) if first_only
                     else vals)
                 if (vals := _first_col(ctx, stmt, params)) is not None
                 else None)
    register_ctx(cy + "runFirstColumnMany",
                 lambda ctx, stmt, params=None: _first_col(
                     ctx, stmt, params))
    register_ctx(cy + "runFirstColumnSingle",
                 lambda ctx, stmt, params=None: (
                     vals[0] if (vals := _first_col(ctx, stmt, params))
                     else None))

    def _run_many(ctx, statements, params=None):
        out = []
        for i, stmt in enumerate(_split_statements(statements)):
            r = _sub(ctx, stmt, params)
            out.append({"index": i, "rows": [list(row) for row in r.rows],
                        "columns": list(r.columns)})
        return out

    register_ctx(cy + "runMany", _run_many)

    def _run_file(ctx, path):
        with open(str(path), "r", encoding="utf-8") as f:
            return _run_many(ctx, f.read())

    register_ctx(cy + "runFile", _run_file)

    register_ctx(cy + "toJson", lambda ctx, stmt, params=None: _json.dumps(
        [_jsonable(rec) for rec in _sub(ctx, stmt, params).records()]))
    register_ctx(cy + "toList", lambda ctx, stmt, params=None: [
        list(row) for row in _sub(ctx, stmt, params).rows])
    register_ctx(cy + "toMap", lambda ctx, stmt, params=None: (
        recs[0] if (recs := _sub(ctx, stmt, params).records()) else {}))

    def _explain(ctx, stmt):
        r = ctx.ex.execute(f"EXPLAIN {stmt}")
        return r.plan

    register_ctx(cy + "explain", _explain)

    def _profile(ctx, stmt, params=None):
        r = ctx.ex.execute(f"PROFILE {stmt}", params or {})
        return r.plan

    register_ctx(cy + "profile", _profile)

    def _parse(ctx, stmt):
        from nornicdb_tpu.query.parser import parse

        uq = parse(str(stmt))
        return {"parts": len(uq.parts),
                "clauses": [type(c).__name__ for p in uq.parts
                            for c in p.clauses]}

    register_ctx(cy + "parse", _parse)

    def _validate(ctx, stmt):
        from nornicdb_tpu.query.strict import validate

        return [{"severity": d.severity, "message": d.message,
                 "line": d.line, "column": d.column}
                for d in validate(str(stmt))]

    register_ctx(cy + "validate", _validate)

    # parallel forms execute sequentially here: correctness first; the
    # data plane parallelism lives in the columnar/vectorized engine
    register_ctx(cy + "parallel", lambda ctx, stmt, params_list=None,
                 key="value": [
                     {"value": rec} for p in (params_list or [{}])
                     for rec in _sub(ctx, stmt, p if isinstance(p, dict)
                                     else {key: p}).records()])
    register_ctx(cy + "mapParallel", lambda ctx, stmt, items=None: [
        rec for item in (items or [])
        for rec in _sub(ctx, stmt, {"_": item}).records()])


def _first_col(ctx, stmt, params) -> List[Any]:
    r = _sub(ctx, stmt, params)
    return [row[0] for row in r.rows] if r.columns else []


def _split_statements(text: Any) -> List[str]:
    if isinstance(text, list):
        return [str(s) for s in text if str(s).strip()]
    return [s.strip() for s in str(text).split(";") if s.strip()]


def _jsonable(v: Any) -> Any:
    if isinstance(v, Node):
        return {"id": v.id, "labels": list(v.labels),
                "properties": _jsonable(v.properties)}
    if isinstance(v, Edge):
        return {"id": v.id, "type": v.type, "start": v.start_node,
                "end": v.end_node, "properties": _jsonable(v.properties)}
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# -- apoc.export / apoc.import / apoc.load --------------------------------


def _all_graph(ctx):
    return list(ctx.storage.all_nodes()), list(ctx.storage.all_edges())


def _nodes_csv(nodes: List[Node]) -> str:
    keys = sorted({k for n in nodes for k in n.properties})
    buf = _io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["_id", "_labels"] + keys)
    for n in nodes:
        w.writerow([n.id, ";".join(n.labels)]
                   + [_csv_val(n.properties.get(k)) for k in keys])
    return buf.getvalue()


def _rels_csv(rels: List[Edge]) -> str:
    keys = sorted({k for e in rels for k in e.properties})
    buf = _io.StringIO()
    w = _csv.writer(buf)
    w.writerow(["_id", "_type", "_start", "_end"] + keys)
    for e in rels:
        w.writerow([e.id, e.type, e.start_node, e.end_node]
                   + [_csv_val(e.properties.get(k)) for k in keys])
    return buf.getvalue()


def _csv_val(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, (list, dict)):
        return _json.dumps(v)
    return str(v)


def _graph_json(nodes: List[Node], rels: List[Edge]) -> str:
    rows = [_json.dumps({"type": "node", **_jsonable(n)}) for n in nodes]
    for e in rels:
        d = _jsonable(e)
        d["relType"] = d.pop("type")  # row kind key takes "type"
        d["type"] = "relationship"
        rows.append(_json.dumps(d))
    return "\n".join(rows)


def _graph_cypher(nodes: List[Node], rels: List[Edge]) -> str:
    lines = []
    for n in nodes:
        labels = "".join(f":`{l}`" for l in n.labels)
        props = {**n.properties, "_import_id": n.id}
        lines.append(f"CREATE ({labels} {_cy_map(props)});")
    for e in rels:
        lines.append(
            f"MATCH (a {{_import_id: {_json.dumps(e.start_node)}}}), "
            f"(b {{_import_id: {_json.dumps(e.end_node)}}}) "
            f"CREATE (a)-[:`{e.type}` {_cy_map(e.properties)}]->(b);")
    return "\n".join(lines)


def _cy_map(props: Dict[str, Any]) -> str:
    if not props:
        return "{}"
    parts = [f"`{k}`: {_json.dumps(v)}" for k, v in sorted(props.items())]
    return "{" + ", ".join(parts) + "}"


def _graph_graphml(nodes: List[Node], rels: List[Edge]) -> str:
    from xml.sax.saxutils import escape, quoteattr

    out = ['<?xml version="1.0" encoding="UTF-8"?>',
           '<graphml xmlns="http://graphml.graphdrawing.org/xmlns">',
           '<graph id="G" edgedefault="directed">']
    for n in nodes:
        out.append(f"<node id={quoteattr(n.id)} "
                   f"labels={quoteattr(':'.join(n.labels))}>")
        for k, v in sorted(n.properties.items()):
            out.append(f"<data key={quoteattr(k)}>"
                       f"{escape(_csv_val(v))}</data>")
        out.append("</node>")
    for e in rels:
        out.append(f"<edge id={quoteattr(e.id)} "
                   f"source={quoteattr(e.start_node)} "
                   f"target={quoteattr(e.end_node)} "
                   f"label={quoteattr(e.type)}>")
        for k, v in sorted(e.properties.items()):
            out.append(f"<data key={quoteattr(k)}>"
                       f"{escape(_csv_val(v))}</data>")
        out.append("</edge>")
    out.append("</graph></graphml>")
    return "\n".join(out)


def _install_export() -> None:
    ex = "apoc.export."

    def _pick(ctx, nodes=None, rels=None):
        if nodes is None and rels is None:
            return _all_graph(ctx)
        return ([x for x in (nodes or []) if isinstance(x, Node)],
                [e for e in (rels or []) if isinstance(e, Edge)])

    register_ctx(ex + "csv", lambda ctx, nodes=None, rels=None: (
        lambda g: {"nodes": _nodes_csv(g[0]), "relationships":
                   _rels_csv(g[1])})(_pick(ctx, nodes, rels)))
    def _csv_all(ctx):
        nodes, rels = _all_graph(ctx)
        return {"nodes": _nodes_csv(nodes), "relationships":
                _rels_csv(rels)}

    register_ctx(ex + "csvAll", _csv_all)
    register_ctx(ex + "csvData", lambda ctx, nodes, rels: {
        "nodes": _nodes_csv([x for x in (nodes or [])
                             if isinstance(x, Node)]),
        "relationships": _rels_csv([e for e in (rels or [])
                                    if isinstance(e, Edge)])})
    register_ctx(ex + "json", lambda ctx, nodes=None, rels=None:
                 _graph_json(*_pick(ctx, nodes, rels)))
    register_ctx(ex + "jsonAll", lambda ctx: _graph_json(*_all_graph(ctx)))
    register_ctx(ex + "jsonData", lambda ctx, nodes, rels: _graph_json(
        [x for x in (nodes or []) if isinstance(x, Node)],
        [e for e in (rels or []) if isinstance(e, Edge)]))
    register_ctx(ex + "cypher", lambda ctx, nodes=None, rels=None:
                 _graph_cypher(*_pick(ctx, nodes, rels)))
    register_ctx(ex + "cypherAll", lambda ctx: _graph_cypher(
        *_all_graph(ctx)))
    register_ctx(ex + "cypherData", lambda ctx, nodes, rels: _graph_cypher(
        [x for x in (nodes or []) if isinstance(x, Node)],
        [e for e in (rels or []) if isinstance(e, Edge)]))
    register_ctx(ex + "graphml", lambda ctx, nodes=None, rels=None:
                 _graph_graphml(*_pick(ctx, nodes, rels)))
    register_ctx(ex + "graphmlAll", lambda ctx: _graph_graphml(
        *_all_graph(ctx)))
    register_ctx(ex + "graphmlData", lambda ctx, nodes, rels:
                 _graph_graphml(
                     [x for x in (nodes or []) if isinstance(x, Node)],
                     [e for e in (rels or []) if isinstance(e, Edge)]))
    register_ctx(ex + "toString", lambda ctx, fmt="json": (
        _graph_json(*_all_graph(ctx)) if fmt == "json"
        else _graph_cypher(*_all_graph(ctx)) if fmt == "cypher"
        else _graph_graphml(*_all_graph(ctx)) if fmt == "graphml"
        else _nodes_csv(_all_graph(ctx)[0])))

    def _to_file(ctx, path, fmt="json"):
        content = {
            "json": lambda: _graph_json(*_all_graph(ctx)),
            "cypher": lambda: _graph_cypher(*_all_graph(ctx)),
            "graphml": lambda: _graph_graphml(*_all_graph(ctx)),
        }.get(str(fmt))
        if content is None:
            raise CypherRuntimeError(f"unknown export format {fmt!r}")
        text = content()
        with open(str(path), "w", encoding="utf-8") as f:
            f.write(text)
        return {"file": str(path), "bytes": len(text.encode())}

    register_ctx(ex + "toFile", _to_file)


def _install_import_load() -> None:
    im = "apoc.import."

    def _import_json_rows(ctx, rows):
        id_map: Dict[str, str] = {}
        nodes = rels = 0
        pending_rels = []
        from nornicdb_tpu.query.apoc_admin import _fresh_edge, _fresh_node

        for row in rows:
            if not isinstance(row, dict):
                continue
            kind = row.get("type")
            if kind == "node":
                node = _fresh_node(ctx, row.get("labels") or [],
                                   row.get("properties") or {})
                if row.get("id") is not None:
                    id_map[str(row["id"])] = node.id
                nodes += 1
            elif kind == "relationship":
                pending_rels.append(row)
        for row in pending_rels:
            start = id_map.get(str(row.get("start")), str(row.get("start")))
            end = id_map.get(str(row.get("end")), str(row.get("end")))
            _fresh_edge(ctx, row.get("relType") or row.get("label")
                        or "RELATED",
                        start, end, row.get("properties") or {})
            rels += 1
        return {"nodes": nodes, "relationships": rels}

    def _import_json(ctx, text):
        rows = []
        for line in str(text).splitlines():
            line = line.strip()
            if line:
                rows.append(_json.loads(line))
        return _import_json_rows(ctx, rows)

    register_ctx(im + "json", _import_json)
    register_ctx(im + "jsonData", lambda ctx, rows: _import_json_rows(
        ctx, rows or []))

    def _import_csv(ctx, nodes_csv, rels_csv=None):
        from nornicdb_tpu.query.apoc_admin import _fresh_edge, _fresh_node

        id_map: Dict[str, str] = {}
        n_nodes = n_rels = 0
        for rec in _csv.DictReader(_io.StringIO(str(nodes_csv))):
            labels = [l for l in (rec.pop("_labels", "") or "").split(";")
                      if l]
            ext_id = rec.pop("_id", None)
            node = _fresh_node(ctx, labels,
                               {k: v for k, v in rec.items() if v != ""})
            if ext_id:
                id_map[ext_id] = node.id
            n_nodes += 1
        if rels_csv:
            for rec in _csv.DictReader(_io.StringIO(str(rels_csv))):
                etype = rec.pop("_type", "RELATED")
                start = id_map.get(rec.pop("_start", ""), "")
                end = id_map.get(rec.pop("_end", ""), "")
                rec.pop("_id", None)
                if start and end:
                    _fresh_edge(ctx, etype, start, end,
                                {k: v for k, v in rec.items() if v != ""})
                    n_rels += 1
        return {"nodes": n_nodes, "relationships": n_rels}

    register_ctx(im + "csv", _import_csv)
    register_ctx(im + "csvData", _import_csv)

    def _import_cypher(ctx, script):
        n = 0
        for stmt in _split_statements(script):
            _sub(ctx, stmt)
            n += 1
        return {"statements": n}

    register_ctx(im + "cypher", _import_cypher)
    register_ctx(im + "cypherData", _import_cypher)

    def _import_graphml(ctx, text):
        import xml.etree.ElementTree as ET

        from nornicdb_tpu.query.apoc_admin import _fresh_edge, _fresh_node

        ns = {"g": "http://graphml.graphdrawing.org/xmlns"}
        root = ET.fromstring(str(text))
        id_map: Dict[str, str] = {}
        n_nodes = n_rels = 0
        for el in root.iter("{http://graphml.graphdrawing.org/xmlns}node"):
            props = {d.get("key"): d.text or ""
                     for d in el.findall("g:data", ns)}
            labels = [l for l in (el.get("labels") or "").split(":") if l]
            node = _fresh_node(ctx, labels, props)
            id_map[el.get("id") or node.id] = node.id
            n_nodes += 1
        for el in root.iter("{http://graphml.graphdrawing.org/xmlns}edge"):
            props = {d.get("key"): d.text or ""
                     for d in el.findall("g:data", ns)}
            start = id_map.get(el.get("source") or "")
            end = id_map.get(el.get("target") or "")
            if start and end:
                _fresh_edge(ctx, el.get("label") or "RELATED", start, end,
                            props)
                n_rels += 1
        return {"nodes": n_nodes, "relationships": n_rels}

    register_ctx(im + "graphml", _import_graphml)
    register_ctx(im + "graphmlData", _import_graphml)

    def _import_file(ctx, path):
        text = open(str(path), "r", encoding="utf-8").read()
        p = str(path).lower()
        if p.endswith(".json") or p.endswith(".jsonl"):
            return _import_json(ctx, text)
        if p.endswith(".graphml") or p.endswith(".xml"):
            return _import_graphml(ctx, text)
        if p.endswith(".cypher") or p.endswith(".cql"):
            return _import_cypher(ctx, text)
        if p.endswith(".csv"):
            return _import_csv(ctx, text)
        raise CypherRuntimeError(f"unknown import format for {path!r}")

    register_ctx(im + "file", _import_file)
    register_ctx(im + "stream", lambda ctx, rows: _import_json_rows(
        ctx, rows or []))
    register_ctx(im + "batch", lambda ctx, batches: [
        _import_json_rows(ctx, b or []) for b in (batches or [])])

    register(im + "parseCsvLine", lambda line, sep=",": next(
        _csv.reader(_io.StringIO(str(line)), delimiter=str(sep)), []))
    register(im + "parseJsonLine", lambda line: _json.loads(str(line)))

    def _convert_type(value, typ):
        t = str(typ).lower()
        if value is None or value == "":
            return None
        if t in ("int", "integer", "long"):
            return int(float(value))
        if t in ("float", "double"):
            return float(value)
        if t in ("bool", "boolean"):
            return str(value).lower() in ("true", "1", "yes")
        if t == "string":
            return str(value)
        raise CypherRuntimeError(f"unknown type {typ!r}")

    register(im + "convertType", _convert_type)
    register(im + "transform", lambda rows, mapping: [
        {mapping.get(k, k): v for k, v in (row or {}).items()}
        for row in (rows or [])])
    register(im + "filter", lambda rows, key, value: [
        row for row in (rows or []) if (row or {}).get(key) == value])
    register(im + "merge", lambda a, b: list(a or []) + list(b or []))

    def _validate_schema(rows, schema):
        errors = []
        for i, row in enumerate(rows or []):
            for key, typ in (schema or {}).items():
                if key not in (row or {}):
                    errors.append(f"row {i}: missing {key!r}")
                    continue
                try:
                    _convert_type(row[key], typ)
                except (ValueError, CypherRuntimeError):
                    errors.append(
                        f"row {i}: {key!r} not coercible to {typ}")
        return {"valid": not errors, "errors": errors}

    register(im + "validateSchema", _validate_schema)
    register(im + "url", lambda url: _egress_placeholder("import.url"))

    ld = "apoc.load."
    register(ld + "csv", lambda text, sep=",": [
        dict(rec) for rec in _csv.DictReader(
            _io.StringIO(str(text)), delimiter=str(sep))])
    register(ld + "csvStream", lambda text, sep=",": [
        row for row in _csv.reader(_io.StringIO(str(text)),
                                   delimiter=str(sep))])
    register(ld + "json", lambda text: _json.loads(str(text)))
    register(ld + "jsonArray", lambda text: (
        v if isinstance(v := _json.loads(str(text)), list) else [v]))
    register(ld + "jsonStream", lambda text: [
        _json.loads(line) for line in str(text).splitlines()
        if line.strip()])
    register(ld + "jsonParams", lambda text, params: _json.loads(
        str(text) % (params or {})))

    def _json_schema(value):
        if isinstance(value, dict):
            return {k: _json_schema(v) for k, v in value.items()}
        if isinstance(value, list):
            return [_json_schema(value[0])] if value else []
        return type(value).__name__

    register(ld + "jsonSchema", lambda text: _json_schema(
        _json.loads(str(text)) if isinstance(text, str) else text))

    def _load_xml(text, simple=False):
        from nornicdb_tpu.query.apoc import APOC_FUNCS

        return APOC_FUNCS["apoc.xml.parse"](text)

    register(ld + "xml", _load_xml)
    register(ld + "xmlSimple", lambda text: _load_xml(text, simple=True))

    def _load_html(text):
        """Tag-stripping text extraction + link/title capture (the
        reference parses with a full HTML parser; this covers the
        common scrape fields)."""
        import re as _re

        s = str(text)
        title = _re.search(r"<title[^>]*>(.*?)</title>", s,
                           _re.IGNORECASE | _re.DOTALL)
        links = _re.findall(r'href=["\']([^"\']+)["\']', s)
        body = _re.sub(r"<script.*?</script>|<style.*?</style>", " ", s,
                       flags=_re.DOTALL | _re.IGNORECASE)
        body = _re.sub(r"<[^>]+>", " ", body)
        return {"title": title.group(1).strip() if title else None,
                "links": links,
                "text": " ".join(body.split())}

    register(ld + "html", _load_html)

    def _load_directory(path, pattern="*"):
        import fnmatch

        out = []
        for name in sorted(os.listdir(str(path))):
            if fnmatch.fnmatch(name, str(pattern)):
                full = os.path.join(str(path), name)
                out.append({"name": name, "path": full,
                            "isDirectory": os.path.isdir(full),
                            "size": os.path.getsize(full)
                            if os.path.isfile(full) else 0})
        return out

    register(ld + "directory", _load_directory)

    def _load_tree(path, max_depth=5):
        out = []

        def walk(p, depth):
            if depth > int(max_depth):
                return
            for name in sorted(os.listdir(p)):
                full = os.path.join(p, name)
                out.append({"path": full, "depth": depth,
                            "isDirectory": os.path.isdir(full)})
                if os.path.isdir(full):
                    walk(full, depth + 1)

        walk(str(path), 0)
        return out

    register(ld + "directoryTree", _load_tree)
    register(ld + "stream", lambda path: open(
        str(path), "r", encoding="utf-8").read())
    register(ld + "binary", lambda path: list(
        open(str(path), "rb").read()))

    # external systems: acknowledged placeholders, the reference's own
    # behavior (apoc/load/load.go "Placeholder - would ...")
    for external in ("kafka", "redis", "elasticsearch", "jdbc",
                     "jdbcUpdate", "s3", "gcs", "azure", "rest",
                     "graphql", "ldap", "arrow", "avro", "parquet",
                     "driver"):
        register(ld + external,
                 (lambda name: lambda *args: _egress_placeholder(name))
                 (external))


def _egress_placeholder(name: str) -> List[Any]:
    """Reference parity: external-system loaders return empty result
    sets (no egress in this environment either way)."""
    return []


# -- apoc.graph (virtual graphs) ------------------------------------------


def _vgraph(nodes, rels, name="virtual") -> Dict[str, Any]:
    return {"name": name,
            "nodes": [x for x in (nodes or []) if isinstance(x, Node)],
            "relationships": [e for e in (rels or [])
                              if isinstance(e, Edge)]}


def _install_graph() -> None:
    g = "apoc.graph."
    register(g + "from", lambda nodes, rels, name="virtual": _vgraph(
        nodes, rels, name))
    register(g + "fromData", lambda nodes, rels, name="virtual": _vgraph(
        nodes, rels, name))

    def _from_paths(paths, name="virtual"):
        from nornicdb_tpu.query.functions import PathValue

        nodes: Dict[str, Node] = {}
        rels: Dict[str, Edge] = {}
        for p in paths if isinstance(paths, list) else [paths]:
            if isinstance(p, PathValue):
                for n in p.nodes:
                    nodes[n.id] = n
                for e in p.rels:
                    rels[e.id] = e
        return _vgraph(list(nodes.values()), list(rels.values()), name)

    register(g + "fromPath", _from_paths)
    register(g + "fromPaths", _from_paths)

    def _from_document(doc, name="virtual"):
        """JSON document -> virtual graph: maps become nodes, nested
        maps/lists become CONTAINS relationships."""
        import uuid as _uuid

        doc = _json.loads(doc) if isinstance(doc, str) else doc
        nodes: List[Node] = []
        rels: List[Edge] = []

        def visit(value, label) -> Optional[Node]:
            if not isinstance(value, dict):
                return None
            scalars = {k: v for k, v in value.items()
                       if not isinstance(v, (dict, list))}
            node = Node(id=f"vnode-{_uuid.uuid4()}",
                        labels=[str(label)], properties=scalars)
            nodes.append(node)
            for k, v in value.items():
                children = v if isinstance(v, list) else [v]
                for child in children:
                    sub = visit(child, k)
                    if sub is not None:
                        rels.append(Edge(
                            id=f"vrel-{_uuid.uuid4()}", type=k.upper(),
                            start_node=node.id, end_node=sub.id,
                            properties={}))
            return node

        visit(doc, (doc or {}).get("type", "Document")
              if isinstance(doc, dict) else "Document")
        return _vgraph(nodes, rels, name)

    register(g + "fromDocument", _from_document)
    register(g + "fromMap", _from_document)

    def _from_cypher(ctx, stmt, params=None, name="virtual"):
        recs = _sub(ctx, stmt, params).records()  # executed ONCE
        return _vgraph(
            [v for rec in recs for v in rec.values()
             if isinstance(v, Node)],
            [v for rec in recs for v in rec.values()
             if isinstance(v, Edge)],
            name)

    register_ctx(g + "fromCypher", _from_cypher)

    register(g + "nodes", lambda graph: list((graph or {}).get(
        "nodes", [])))
    register(g + "relationships", lambda graph: list((graph or {}).get(
        "relationships", [])))
    register(g + "stats", lambda graph: {
        "nodeCount": len((graph or {}).get("nodes", [])),
        "relCount": len((graph or {}).get("relationships", [])),
        "labels": sorted({l for n in (graph or {}).get("nodes", [])
                          for l in n.labels})})
    register(g + "toMap", lambda graph: {
        "name": (graph or {}).get("name"),
        "nodes": [_jsonable(n) for n in (graph or {}).get("nodes", [])],
        "relationships": [_jsonable(e) for e in (graph or {}).get(
            "relationships", [])]})

    def _validate_graph(graph):
        ids = {n.id for n in (graph or {}).get("nodes", [])}
        dangling = [e.id for e in (graph or {}).get("relationships", [])
                    if e.start_node not in ids or e.end_node not in ids]
        return {"valid": not dangling, "danglingRelationships": dangling}

    register(g + "validate", _validate_graph)
    register(g + "clone", lambda graph: {
        "name": (graph or {}).get("name"),
        "nodes": list((graph or {}).get("nodes", [])),
        "relationships": list((graph or {}).get("relationships", []))})

    def _merge_graphs(a, b):
        nodes = {n.id: n for n in list((a or {}).get("nodes", []))
                 + list((b or {}).get("nodes", []))}
        rels = {e.id: e for e in list((a or {}).get("relationships", []))
                + list((b or {}).get("relationships", []))}
        return _vgraph(list(nodes.values()), list(rels.values()),
                       (a or {}).get("name", "virtual"))

    register(g + "merge", _merge_graphs)

    def _subgraph(graph, node_ids):
        keep = {str(i) for i in (node_ids or [])}
        nodes = [n for n in (graph or {}).get("nodes", [])
                 if n.id in keep]
        ids = {n.id for n in nodes}
        rels = [e for e in (graph or {}).get("relationships", [])
                if e.start_node in ids and e.end_node in ids]
        return _vgraph(nodes, rels, (graph or {}).get("name", "virtual"))

    register(g + "subgraph", _subgraph)

    def _graph_clone_ctx(ctx):
        nodes, rels = _all_graph(ctx)
        return _vgraph(nodes, rels, "snapshot")

    register_ctx(g + "fromStore", _graph_clone_ctx)
    register_ctx(g + "snapshot", _graph_clone_ctx)


# -- triggers, periodic, leftovers ----------------------------------------


class _JobRegistry:
    """apoc.periodic.* background jobs (submit/repeat/countdown)."""

    def __init__(self):
        self.jobs: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()

    def submit(self, name: str, kind: str, meta: Dict[str, Any]):
        with self._lock:
            self.jobs[name] = {"name": name, "kind": kind,
                               "submitted": _time.time(),
                               "cancelled": False, **meta}
            return dict(self.jobs[name])

    def cancel(self, name: str) -> bool:
        with self._lock:
            job = self.jobs.get(name)
            if job is None:
                return False
            job["cancelled"] = True
            timer = job.get("_timer")
        if timer is not None:
            timer.cancel()
        return True

    def list(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{k: v for k, v in j.items()
                     if not k.startswith("_")}
                    for j in self.jobs.values()]


JOBS = _JobRegistry()


def _install_trigger_periodic() -> None:
    tr = "apoc.trigger."

    def _registry(ctx):
        return ctx.ex.triggers

    def _add(ctx, name, statement, selector=None, phase="after"):
        t = _registry(ctx).add(str(name), str(statement), selector)
        t["phase"] = phase
        return dict(t)

    register_ctx(tr + "add", _add)
    register_ctx(tr + "install", _add)
    register_ctx(tr + "after", lambda ctx, name, stmt, sel=None: _add(
        ctx, name, stmt, sel, "after"))
    register_ctx(tr + "afterAsync", lambda ctx, name, stmt, sel=None:
                 _add(ctx, name, stmt, sel, "after"))
    register_ctx(tr + "before", lambda ctx, name, stmt, sel=None: _add(
        ctx, name, stmt, sel, "before"))
    register_ctx(tr + "onCreate", lambda ctx, name, stmt: _add(
        ctx, name, stmt, {"event": "create"}))
    register_ctx(tr + "onDelete", lambda ctx, name, stmt: _add(
        ctx, name, stmt, {"event": "delete"}))
    register_ctx(tr + "onUpdate", lambda ctx, name, stmt: _add(
        ctx, name, stmt, {"event": "update"}))
    register_ctx(tr + "remove", lambda ctx, name: _registry(ctx).remove(
        str(name)) is not None)
    register_ctx(tr + "drop", lambda ctx, name: _registry(ctx).remove(
        str(name)) is not None)
    register_ctx(tr + "removeAll", lambda ctx: _registry(
        ctx).remove_all())
    register_ctx(tr + "list", lambda ctx: [
        dict(t) for t in _registry(ctx).triggers.values()])
    register_ctx(tr + "show", lambda ctx: [
        dict(t) for t in _registry(ctx).triggers.values()])
    register_ctx(tr + "count", lambda ctx: len(_registry(ctx).triggers))
    register_ctx(tr + "pause", lambda ctx, name: dict(
        _registry(ctx).set_paused(str(name), True) or {}))
    register_ctx(tr + "resume", lambda ctx, name: dict(
        _registry(ctx).set_paused(str(name), False) or {}))
    register_ctx(tr + "disable", lambda ctx, name: dict(
        _registry(ctx).set_paused(str(name), True) or {}))
    register_ctx(tr + "enable", lambda ctx, name: dict(
        _registry(ctx).set_paused(str(name), False) or {}))
    register_ctx(tr + "isEnabled", lambda ctx, name: (
        (t := _registry(ctx).triggers.get(str(name))) is not None
        and not t["paused"]))
    register_ctx(tr + "stats", lambda ctx: {
        "count": len(_registry(ctx).triggers),
        "paused": sum(1 for t in _registry(ctx).triggers.values()
                      if t["paused"])})
    register_ctx(tr + "export", lambda ctx: [
        dict(t) for t in _registry(ctx).triggers.values()])

    def _import_triggers(ctx, data):
        n = 0
        for t in data or []:
            _registry(ctx).add(t["name"], t["statement"],
                               t.get("selector"))
            n += 1
        return n

    register_ctx(tr + "import", _import_triggers)
    register_ctx(tr + "nodeByLabel", lambda ctx, name, label, stmt: _add(
        ctx, name, stmt, {"label": label}))
    register_ctx(tr + "relationshipByType", lambda ctx, name, etype,
                 stmt: _add(ctx, name, stmt, {"relType": etype}))

    pd = "apoc.periodic."
    register(pd + "list", lambda: JOBS.list())
    register(pd + "cancel", lambda name: JOBS.cancel(str(name)))
    register(pd + "submit", lambda name, statement: JOBS.submit(
        str(name), "submit", {"statement": str(statement),
                              "state": "registered"}))
    register(pd + "repeat", lambda name, statement, interval_s: JOBS.
             submit(str(name), "repeat", {
                 "statement": str(statement),
                 "intervalSeconds": float(interval_s),
                 "state": "registered"}))
    register(pd + "schedule", lambda name, statement, delay_s: JOBS.
             submit(str(name), "schedule", {
                 "statement": str(statement),
                 "delaySeconds": float(delay_s), "state": "registered"}))
    register(pd + "countdown", lambda name, statement, count: JOBS.
             submit(str(name), "countdown", {
                 "statement": str(statement), "remaining": int(count),
                 "state": "registered"}))
    register(pd + "rock", lambda: {"rocked": True})  # reference easter egg

    def _truncate(ctx, batch_size=1000):
        deleted = 0
        while True:
            batch = []
            for i, node in enumerate(ctx.storage.all_nodes()):
                if i >= int(batch_size):
                    break
                batch.append(node.id)
            if not batch:
                break
            for nid in batch:
                ctx.storage.delete_node(nid)
                deleted += 1
        ctx.stats.nodes_deleted += deleted
        ctx.non_create_writes = True
        return {"deleted": deleted}

    register_ctx(pd + "truncate", _truncate)


def _install_leftovers() -> None:
    # map leftovers
    mp = "apoc.map."
    register(mp + "get", lambda m, key, default=None: (
        (m or {}).get(key, default)))
    register(mp + "dropNullValues",
             lambda m: {k: v for k, v in (m or {}).items()
                        if v is not None})
    register(mp + "removeKeys", lambda m, keys: {
        k: v for k, v in (m or {}).items() if k not in (keys or [])})
    register(mp + "mergeList", lambda maps: {
        k: v for m in (maps or []) for k, v in (m or {}).items()})
    register(mp + "setLists", lambda keys, values: {
        str(k): v for k, v in zip(keys or [], values or [])})
    register(mp + "setPairs", lambda pairs: {
        str(p[0]): (p[1] if len(p) > 1 else None)
        for p in (pairs or [])})
    register(mp + "setValues", lambda m, pairs: {
        **(m or {}), **{str(p[0]): (p[1] if len(p) > 1 else None)
                        for p in (pairs or [])}})

    def _unflatten_map(flat, sep="."):
        out: Dict[str, Any] = {}
        for key, value in (flat or {}).items():
            cur = out
            parts = str(key).split(str(sep))
            for part in parts[:-1]:
                nxt = cur.get(part)
                if not isinstance(nxt, dict):
                    nxt = {}
                    cur[part] = nxt
                cur = nxt
            cur[parts[-1]] = value
        return out

    register(mp + "unflatten", _unflatten_map)

    def _update_tree(tree, key, value):
        import copy

        out = copy.deepcopy(tree or {})

        def walk(m):
            if isinstance(m, dict):
                if key in m:
                    m[key] = value
                for v in m.values():
                    walk(v)
            elif isinstance(m, list):
                for v in m:
                    walk(v)

        walk(out)
        return out

    register(mp + "updateTree", _update_tree)

    # node/rel write forms (delegate to the admin impls' semantics)
    from nornicdb_tpu.query.apoc import APOC_CTX_FUNCS

    nd = "apoc.node."
    register_ctx(nd + "addLabel", lambda ctx, x, l: APOC_CTX_FUNCS[
        "apoc.create.addlabels"](ctx, x, [l]))
    register_ctx(nd + "addLabels", lambda ctx, x, ls: APOC_CTX_FUNCS[
        "apoc.create.addlabels"](ctx, x, ls))
    register_ctx(nd + "removeLabel", lambda ctx, x, l: APOC_CTX_FUNCS[
        "apoc.create.removelabels"](ctx, x, [l]))
    register_ctx(nd + "removeLabels", lambda ctx, x, ls: APOC_CTX_FUNCS[
        "apoc.create.removelabels"](ctx, x, ls))
    register_ctx(nd + "setProperty", lambda ctx, x, k, v: APOC_CTX_FUNCS[
        "apoc.create.setproperty"](ctx, x, k, v))
    register_ctx(nd + "setProperties", lambda ctx, x, m: APOC_CTX_FUNCS[
        "apoc.create.setproperties"](ctx, x, m))
    register_ctx(nd + "removeProperty", lambda ctx, x, k: APOC_CTX_FUNCS[
        "apoc.create.removeproperties"](ctx, x, [k]))
    register_ctx(nd + "removeProperties", lambda ctx, x, ks:
                 APOC_CTX_FUNCS["apoc.create.removeproperties"](
                     ctx, x, ks))

    def _node_clone(ctx, x):
        return APOC_CTX_FUNCS["apoc.create.clone"](ctx, x)

    register_ctx(nd + "clone", _node_clone)

    def _node_from_map(ctx, m):
        from nornicdb_tpu.query.apoc_admin import _fresh_node

        m = dict(m or {})
        labels = m.pop("_labels", m.pop("labels", []))
        props = m.get("properties", m)
        if "properties" in m:
            props = m["properties"]
        return _fresh_node(ctx, labels, props)

    register_ctx(nd + "fromMap", _node_from_map)

    rl = "apoc.rel."
    register_ctx(rl + "setProperty", lambda ctx, x, k, v: APOC_CTX_FUNCS[
        "apoc.create.setproperty"](ctx, x, k, v))
    register_ctx(rl + "setProperties", lambda ctx, x, m: APOC_CTX_FUNCS[
        "apoc.create.setproperties"](ctx, x, m))
    register_ctx(rl + "removeProperty", lambda ctx, x, k: APOC_CTX_FUNCS[
        "apoc.create.removeproperties"](ctx, x, [k]))
    register_ctx(rl + "removeProperties", lambda ctx, x, ks:
                 APOC_CTX_FUNCS["apoc.create.removeproperties"](
                     ctx, x, ks))

    def _rel_clone(ctx, e):
        from nornicdb_tpu.query.apoc_admin import _fresh_edge

        if not isinstance(e, Edge):
            raise CypherRuntimeError("apoc.rel.clone expects a rel")
        return _fresh_edge(ctx, e.type, e.start_node, e.end_node,
                           e.properties)

    register_ctx(rl + "clone", _rel_clone)

    def _rel_delete(ctx, e):
        if not isinstance(e, Edge):
            raise CypherRuntimeError("apoc.rel.delete expects a rel")
        ctx.storage.delete_edge(e.id)
        ctx.stats.relationships_deleted += 1
        ctx.non_create_writes = True
        return True

    register_ctx(rl + "delete", _rel_delete)

    def _rel_from_map(ctx, m):
        from nornicdb_tpu.query.apoc_admin import _fresh_edge

        m = dict(m or {})
        return _fresh_edge(ctx, m.get("type", "RELATED"),
                           str(m.get("start")), str(m.get("end")),
                           m.get("properties") or {})

    register_ctx(rl + "fromMap", _rel_from_map)

    # label write forms
    lb = "apoc.label."
    register_ctx(lb + "add", lambda ctx, x, l: APOC_CTX_FUNCS[
        "apoc.create.addlabels"](ctx, x, [l]))
    register_ctx(lb + "remove", lambda ctx, x, l: APOC_CTX_FUNCS[
        "apoc.create.removelabels"](ctx, x, [l]))
    register_ctx(lb + "set", lambda ctx, x, ls: _label_set(ctx, x, ls))
    register_ctx(lb + "clear", lambda ctx, x: _label_set(ctx, x, []))
    register_ctx(lb + "replace", lambda ctx, x, old, new: (
        _label_set(ctx, x, [new if l == old else l for l in x.labels])))
    register_ctx(lb + "merge", lambda ctx, x, ls: APOC_CTX_FUNCS[
        "apoc.create.addlabels"](ctx, x, ls))

    def _label_set(ctx, x, labels):
        if not isinstance(x, Node):
            raise CypherRuntimeError("apoc.label.set expects a node")
        before = set(x.labels)
        after = list(dict.fromkeys(labels or []))
        x.labels = after
        ctx.storage.update_node(x)
        ctx.stats.labels_added += len(set(after) - before)
        ctx.stats.labels_removed += len(before - set(after))
        ctx.non_create_writes = True
        return x

    # nodes leftovers
    ns = "apoc.nodes."

    def _nodes_delete(ctx, nodes):
        n = 0
        for x in nodes or []:
            if isinstance(x, Node):
                ctx.storage.delete_node(x.id)
                n += 1
        ctx.stats.nodes_deleted += n
        ctx.non_create_writes = True
        return n

    register_ctx(ns + "delete", _nodes_delete)

    def _nodes_link(ctx, nodes, etype):
        from nornicdb_tpu.query.apoc_admin import _fresh_edge

        made = []
        chain = [x for x in (nodes or []) if isinstance(x, Node)]
        for a, b in zip(chain, chain[1:]):
            made.append(_fresh_edge(ctx, str(etype), a.id, b.id, {}))
        return made

    register_ctx(ns + "link", _nodes_link)
    def _collapse_nodes(ctx, nodes):
        from nornicdb_tpu.query.apoc import APOC_CTX_FUNCS as T

        return T["apoc.refactor.mergenodes"](ctx, nodes)

    register_ctx(ns + "collapse", _collapse_nodes)

    register_ctx(ns + "fromMap", lambda ctx, maps: [
        _node_from_map(ctx, m) for m in (maps or [])])
    register_ctx(ns + "batch", lambda ctx, maps, size=1000: [
        _node_from_map(ctx, m) for m in (maps or [])[: int(size)]])

    # search index management: indexes are synchronous label/property
    # maps + the vector/BM25 services; these acknowledge per reference
    # call_index_mgmt.go semantics
    se = "apoc.search."
    register(se + "index", lambda label=None, props=None: {
        "label": label, "properties": props or [], "state": "ONLINE"})
    register(se + "reindex", lambda label=None: {"state": "ONLINE"})
    register(se + "dropIndex", lambda label=None: True)
    register_ctx(se + "fulltext", lambda ctx, labels, prop, q:
                 APOC_CTX_FUNCS["apoc.search.contains"](
                     ctx, labels, prop, q))
    register_ctx(se + "parallel", lambda ctx, specs, q:
                 APOC_CTX_FUNCS["apoc.search.multisearchany"](
                     ctx, specs, q))

    # meta leftovers
    mt = "apoc.meta."
    register(mt + "version",
             lambda: {"version": "2.0", "edition": "tpu"})
    register(mt + "fromString", lambda s: _json.loads(str(s)))
    register(mt + "toString", lambda m: _json.dumps(_jsonable(m)))
    register(mt + "compare", lambda a, b: {
        "equal": _jsonable(a) == _jsonable(b)})
    register(mt + "diff", lambda a, b: {
        "leftOnly": sorted(set(a or {}) - set(b or {})),
        "rightOnly": sorted(set(b or {}) - set(a or {}))})
    register(mt + "config", lambda: {"sampling": "full"})
    register(mt + "pattern", lambda m: " | ".join(
        f"(:{l})" for l in sorted((m or {}).get("labels", {}))))

    def _meta_ctx(name):
        def get(ctx):
            from nornicdb_tpu.query.apoc import APOC_CTX_FUNCS as T

            return T["apoc.meta.data"](ctx)
        return get

    register_ctx(mt + "analyze", _meta_ctx("analyze"))
    register_ctx(mt + "snapshot", _meta_ctx("snapshot"))
    register_ctx(mt + "export", _meta_ctx("export"))
    register_ctx(mt + "subgraph", lambda ctx, labels: {
        l: len(ctx.storage.get_nodes_by_label(l))
        for l in (labels or [])})

    def _meta_constraints(ctx):
        from nornicdb_tpu.query.apoc import APOC_CTX_FUNCS as T

        return T["apoc.schema.info"](ctx)["constraints"]

    register_ctx(mt + "constraints", _meta_constraints)
    register_ctx(mt + "indexes", lambda ctx: [])
    register_ctx(mt + "validate", lambda ctx: APOC_CTX_FUNCS[
        "apoc.schema.validate"](ctx))
    register_ctx(mt + "import", lambda ctx, data: APOC_CTX_FUNCS[
        "apoc.schema.import"](ctx, data))
    register_ctx(mt + "restore", lambda ctx, data: APOC_CTX_FUNCS[
        "apoc.schema.import"](ctx, data))

    def _meta_functions(ctx):
        from nornicdb_tpu.query.apoc import APOC_CTX_FUNCS, APOC_FUNCS
        from nornicdb_tpu.query.functions import REGISTRY

        return sorted(set(REGISTRY) | set(APOC_FUNCS)
                      | set(APOC_CTX_FUNCS))

    register_ctx(mt + "functions", _meta_functions)
    register_ctx(mt + "procedures", lambda ctx: sorted({
        "apoc.periodic.iterate", "apoc.periodic.commit",
        "apoc.cypher.run", "apoc.path.expand", "apoc.path.spanningTree",
        "apoc.trigger.add", "db.labels", "db.relationshipTypes",
        "db.schema.visualization", "gds.pageRank.stream"}))

    # path leftovers (list-of-node-ids convention shared with
    # apoc.paths.*; the PathValue procedure forms live in apoc_ext)
    pt = "apoc.path."
    register(pt + "combine", lambda a, b: (
        list(a or []) + list(b or [])[1:]
        if a and b and a[-1] == b[0] else list(a or []) + list(b or [])))
    register(pt + "slice", lambda p, start, length=None: list(
        (p or [])[int(start): None if length is None
                  else int(start) + int(length)]))

    def _path_elements(p):
        from nornicdb_tpu.query.functions import PathValue

        if isinstance(p, PathValue):
            out: List[Any] = []
            for i, n in enumerate(p.nodes):
                out.append(n)
                if i < len(p.rels):
                    out.append(p.rels[i])
            return out
        return list(p or [])

    register(pt + "elements", _path_elements)

    # lock.with*: run a statement while holding the named locks
    lk = "apoc.lock."

    def _with_lock(ctx, items, statement, params=None):
        from nornicdb_tpu.query.apoc_admin import LOCKS, _ids_of

        keys = _ids_of(items)
        if not LOCKS.acquire(keys, timeout=10.0):
            raise CypherRuntimeError("could not acquire locks")
        try:
            return [rec for rec in _sub(ctx, statement, params).records()]
        finally:
            LOCKS.release(keys)

    register_ctx(lk + "withLock", _with_lock)
    register_ctx(lk + "withReadLock", _with_lock)

    # hashing leftovers: the reference's simplified formulas
    # (apoc/hashing/hashing.go:302-360; cityHash64 delegates to fnv1a64)
    h = "apoc.hashing."

    def _cat(parts) -> bytes:
        if isinstance(parts, list):
            return "".join(str(p) for p in parts).encode()
        return str(parts).encode()

    def _xxhash32(v, seed=0):
        p1, p2, p3, p5 = 2654435761, 2246822519, 3266489917, 374761393
        data = _cat(v)
        h32 = (int(seed) + p5 + len(data)) & _U32
        for b in data:
            h32 = (h32 + b * p5) & _U32
            h32 = (((h32 << 11) | (h32 >> 21)) & _U32) * p1 & _U32
        h32 ^= h32 >> 15
        h32 = (h32 * p2) & _U32
        h32 ^= h32 >> 13
        h32 = (h32 * p3) & _U32
        h32 ^= h32 >> 16
        return h32

    def _xxhash64(v, seed=0):
        p1 = 11400714785074694791
        p2 = 14029467366897019727
        p3 = 1609587929392839161
        p5 = 2870177450012600261
        data = _cat(v)
        h64 = (int(seed) + p5 + len(data)) & _U64
        for b in data:
            h64 = (h64 + b * p5) & _U64
            h64 = (((h64 << 11) | (h64 >> 53)) & _U64) * p1 & _U64
        h64 ^= h64 >> 33
        h64 = (h64 * p2) & _U64
        h64 ^= h64 >> 29
        h64 = (h64 * p3) & _U64
        h64 ^= h64 >> 32
        return h64 - (1 << 64) if h64 >= (1 << 63) else h64

    register(h + "xxhash32", _xxhash32)
    register(h + "xxhash64", _xxhash64)

    def _cityhash64(v):
        from nornicdb_tpu.query.apoc import APOC_FUNCS

        return APOC_FUNCS["apoc.hashing.fnv1a64"](v)

    register(h + "cityhash64", _cityhash64)

    # merge leftovers: transactional forms are out of scope for a
    # function surface; expose explicit state helpers
    mg = "apoc.merge."
    register(mg + "strategy", lambda name="right": {
        "name": str(name),
        "valid": str(name) in ("left", "right", "deep")})
    register_ctx(mg + "snapshot", lambda ctx, x: (
        {"id": x.id, "properties": dict(x.properties)}
        if isinstance(x, (Node, Edge))
        else _raise_merge("snapshot expects a node or relationship")))

    def _rollback(ctx, x, snapshot):
        ent = x if isinstance(x, (Node, Edge)) else None
        if ent is None or not isinstance(snapshot, dict):
            raise CypherRuntimeError(
                "apoc.merge.rollback(entity, snapshot)")
        ent.properties.clear()
        ent.properties.update(snapshot.get("properties") or {})
        if isinstance(ent, Node):
            ctx.storage.update_node(ent)
        else:
            ctx.storage.update_edge(ent)
        ctx.stats.properties_set += 1
        ctx.non_create_writes = True
        return ent

    register_ctx(mg + "rollback", _rollback)

    def _merge_pattern(ctx, frm_labels, frm_ident, etype, to_labels,
                       to_ident):
        from nornicdb_tpu.query.apoc import APOC_CTX_FUNCS as T

        a = T["apoc.merge.mergenode"](ctx, frm_labels, frm_ident)
        b = T["apoc.merge.mergenode"](ctx, to_labels, to_ident)
        e = T["apoc.merge.mergerelationship"](ctx, a, etype, {}, b)
        return {"from": a, "rel": e, "to": b}

    register_ctx(mg + "pattern", _merge_pattern)

    # create.node/nodes/relationship function forms (procedures exist in
    # apoc_ext; function form returns the entity)
    cr = "apoc.create."

    def _create_node_fn(ctx, labels, props=None):
        from nornicdb_tpu.query.apoc_admin import _fresh_node

        return _fresh_node(ctx, labels or [], props or {})

    register_ctx(cr + "node", _create_node_fn)
    register_ctx(cr + "nodes", lambda ctx, labels, props_list: [
        _create_node_fn(ctx, labels, p) for p in (props_list or [])])

    def _create_rel_fn(ctx, frm, etype, props, to):
        from nornicdb_tpu.query.apoc_admin import _fresh_edge

        start = frm.id if isinstance(frm, Node) else str(frm)
        end = to.id if isinstance(to, Node) else str(to)
        return _fresh_edge(ctx, str(etype), start, end, props or {})

    register_ctx(cr + "relationship", _create_rel_fn)

    # convert leftover
    def _set_json_property(ctx, node, key, value):
        if not isinstance(node, Node):
            raise CypherRuntimeError(
                "apoc.convert.setJsonProperty expects a node")
        node.properties[key] = _json.dumps(_jsonable(value))
        ctx.storage.update_node(node)
        ctx.stats.properties_set += 1
        ctx.non_create_writes = True
        return node

    register_ctx("apoc.convert.setJsonProperty", _set_json_property)


def _raise_merge(msg: str):
    raise CypherRuntimeError(f"apoc.merge.{msg}")


def install() -> None:
    _install_cypher()
    _install_export()
    _install_import_load()
    _install_graph()
    _install_trigger_periodic()
    _install_leftovers()


install()
