"""Streaming fast paths: shape-specialized executors that bypass the
general pipeline.

Reference: the reference's perf story is mostly *avoiding* general
execution — tryFastPathCompoundQuery (executor.go:1421), ExecuteOptimized
(optimized_executors.go:25-282), fast aggregations
(traversal_fast_agg.go:15,57), revenue-by-product
(match_with_rel_fast.go:10), namespace-bypass (storage_fastpaths.go).

Two tiers here:

1. O(1)/indexed count shapes answered straight from engine counters
   (`_try_count_shapes`).
2. A *vectorized chain family* (`_try_vectorized`): single-path MATCH of
   fixed-length relationship chains + simple WHERE + projection or
   grouped aggregation + ORDER BY/SKIP/LIMIT, compiled onto the columnar
   catalog (query/columnar.py) as batched numpy array ops instead of the
   row-at-a-time interpreter. This is the TPU-first redesign of the
   reference's per-shape Go executors: one compiler for the whole LDBC/
   Northwind family (message content lookup, recent messages of friends,
   avg friends per city, tag co-occurrence, supplier/category counts,
   revenue per product) rather than a dozen hand-written shapes.

Any unsupported feature falls through (return None) to the general
executor — parity between paths is enforced by tests/test_fastpath_parity.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.query import ast as A


_AGG_NAMES = {"count", "sum", "avg", "min", "max", "collect"}

# serving-tier mix for the chain family (ISSUE 10): the host fast path
# runs ~50us/query, so the labeled child is cached once at import —
# .inc() is a striped add with no dict probe (the device rung records
# itself, span included, in device_graph.chain_topk)
_CHAIN_HOST_SERVED = _audit.served_counter("graph", "host")


def try_fast_path(executor, q: A.Query, ctx) -> Optional["CypherResult"]:
    if not getattr(executor, "enable_fastpaths", True):
        return None
    r = _try_count_shapes(executor, q, ctx)
    if r is not None:
        return r
    # Vectorized paths read through the executor's columnar catalog, which
    # snapshots executor.storage — bail out when this query runs against a
    # different engine view (PROFILE counting proxy, explicit txn overlay).
    if ctx.storage is not executor.storage:
        return None
    catalog = getattr(executor, "columnar", None)
    if catalog is None:
        return None
    try:
        return _try_vectorized(executor, catalog, q, ctx)
    except _Unsupported:
        return None


# -- tier 1: engine-counter shapes ---------------------------------------


_NO_COUNT = object()  # AST-pinned "not an engine-counter shape" verdict


def _try_count_shapes(executor, q: A.Query, ctx) -> Optional["CypherResult"]:
    # shape analysis is pure AST work and the parsed AST is cached, so
    # the verdict is computed once and pinned to the AST object — every
    # OTHER fast-path query was paying this structural walk per
    # execution (the point/chain shapes run at 20-60k qps; ~3 us of
    # re-analysis per call was 5-12% of the whole query)
    plan = getattr(q, "_count_plan", None)
    if plan is None:
        plan = _analyze_count_shape(q) or _NO_COUNT
        try:
            q._count_plan = plan
        except AttributeError:
            pass
    if plan is _NO_COUNT:
        return None
    return _exec_count_shape(plan, ctx)


def _analyze_count_shape(q: A.Query) -> Optional[Dict[str, Any]]:
    clauses = q.clauses
    if len(clauses) != 2:
        return None
    m, r = clauses[0], clauses[1]
    if not isinstance(m, A.MatchClause) or not isinstance(r, A.ReturnClause):
        return None
    if m.optional or m.where is not None or len(m.paths) != 1:
        return None
    if r.distinct or r.order_by or r.skip or r.limit or r.star:
        return None
    if len(r.items) != 1:
        return None
    item = r.items[0]
    e = item.expr
    if not (isinstance(e, A.FuncCall) and e.name == "count" and not e.distinct):
        return None
    path = m.paths[0]
    col = item.alias or item.text

    # MATCH (n[:Label]) RETURN count(n|*)
    if len(path.nodes) == 1 and not path.rels:
        pn = path.nodes[0]
        if pn.props is not None:
            return None
        if not (
            e.star
            or (len(e.args) == 1 and isinstance(e.args[0], A.Var)
                and e.args[0].name == pn.var)
        ):
            return None
        if not pn.labels:
            return {"col": col, "kind": "nodes"}
        if len(pn.labels) == 1:
            return {"col": col, "kind": "nodes_label",
                    "label": pn.labels[0]}
        return None

    # MATCH ()-[r[:TYPE]]->() RETURN count(r|*)
    if len(path.nodes) == 2 and len(path.rels) == 1:
        pr = path.rels[0]
        n0, n1 = path.nodes
        if (
            n0.labels or n1.labels or n0.props or n1.props or pr.props
            or n0.var or n1.var
        ):
            return None
        if pr.min_hops != 1 or pr.max_hops != 1:
            return None
        if pr.direction == "both":
            return None  # both-direction counts each edge twice; general path
        counts_ok = e.star or (
            len(e.args) == 1 and isinstance(e.args[0], A.Var)
            and e.args[0].name == pr.var
        )
        if not counts_ok:
            return None
        if not pr.types:
            return {"col": col, "kind": "edges"}
        return {"col": col, "kind": "edges_types", "types": list(pr.types)}

    return None


def _exec_count_shape(plan: Dict[str, Any], ctx) -> "CypherResult":
    from nornicdb_tpu.query.executor import CypherResult

    kind = plan["kind"]
    if kind == "nodes":
        n = ctx.storage.count_nodes()  # O(1) engine count
    elif kind == "nodes_label":
        counter = getattr(ctx.storage, "count_nodes_by_label", None)
        if counter is not None:
            n = counter(plan["label"])
        else:
            n = len(ctx.storage.get_nodes_by_label(plan["label"]))
    elif kind == "edges":
        n = ctx.storage.count_edges()
    else:
        n = sum(len(ctx.storage.get_edges_by_type(t))
                for t in plan["types"])
    return CypherResult(columns=[plan["col"]], rows=[[n]])


# -- tier 2: vectorized chain family -------------------------------------


class _Unsupported(Exception):
    """Shape outside the vectorized family — fall back to general path."""


def _bail() -> None:
    raise _Unsupported


def _path_supported(path: A.PatternPath, seen_vars: set) -> bool:
    """Shared shape gate for the vectorized chain family (used by both
    the pure-vectorized path and the MATCH-prefix path — one definition,
    so supported shapes cannot drift apart)."""
    if path.path_var or not path.nodes or len(path.nodes) > 4:
        return False
    for pr in path.rels:
        if pr.min_hops != 1 or pr.max_hops != 1 or pr.props is not None:
            return False
        if pr.direction not in ("out", "in"):
            return False
        if len(pr.types) != 1:
            return False
    for pn in path.nodes:
        if pn.var:
            if pn.var in seen_vars:
                return False
            seen_vars.add(pn.var)
    for pr in path.rels:
        if pr.var:
            if pr.var in seen_vars:
                return False
            seen_vars.add(pr.var)
    return True


class _Bindings:
    """Parallel binding columns over match rows.

    node_cols: var -> int32 global node rows
    edge_cols: var/slot -> (EdgeTable, int32 edge rows)
    """

    def __init__(self):
        self.node_cols: Dict[str, np.ndarray] = {}
        self.edge_cols: Dict[str, Tuple[Any, np.ndarray]] = {}
        self.hop_edges: List[Tuple[str, np.ndarray]] = []  # (etype, edge rows)
        self.n_rows = 0
        # multiplicity weight per binding row (terminal-hop pushdown /
        # co-occurrence: one row stands for `weight` full match rows)
        self.row_weights: Optional[np.ndarray] = None
        # pattern vars folded out of the bindings; referenceable only as
        # non-distinct count(var), which equals the weighted row count
        self.stripped_vars: set = set()
        # var -> (candidate rows, per-row code into candidates): dense
        # group codes already known for these vars (co-occurrence path)
        self.cand_map: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        # stripped vars whose count() uses a DIFFERENT weight channel
        # than row_weights (OPTIONAL MATCH: raw degree vs max(deg, 1))
        self.stripped_var_weights: Dict[str, np.ndarray] = {}
        # folded-out vars carrying a per-row count of DISTINCT original
        # values (strip-view route: nnz per group node). Valid only
        # while no two rows of one output group can share a member —
        # _agg_leaf enforces one-row-per-group before using it.
        self.stripped_distinct_counts: Dict[str, np.ndarray] = {}
        # binding rows are known pairwise-distinct over cand_map codes
        self.rows_are_groups = False

    def take(self, sel: np.ndarray) -> None:
        """Keep only selected row positions (index array or bool mask)."""
        self.node_cols = {k: v[sel] for k, v in self.node_cols.items()}
        self.edge_cols = {k: (t, v[sel]) for k, (t, v) in self.edge_cols.items()}
        self.hop_edges = [(t, v[sel]) for t, v in self.hop_edges]
        if self.row_weights is not None:
            self.row_weights = self.row_weights[sel]
        self.stripped_var_weights = {
            k: v[sel] for k, v in self.stripped_var_weights.items()
        }
        self.stripped_distinct_counts = {
            k: v[sel] for k, v in self.stripped_distinct_counts.items()
        }
        self.cand_map = {
            k: (c, v[sel]) for k, (c, v) in self.cand_map.items()
        }
        some = next(iter(self.node_cols.values()), None)
        if some is None and self.hop_edges:
            some = self.hop_edges[0][1]
        if some is None and self.row_weights is not None:
            some = self.row_weights
        if some is not None:
            self.n_rows = len(some)
        elif sel.dtype == bool:
            self.n_rows = int(sel.sum())
        else:
            self.n_rows = len(sel)


_NO_PLAN = object()


def _try_vectorized(executor, catalog, q: A.Query, ctx) -> Optional["CypherResult"]:
    from nornicdb_tpu.query.executor import CypherResult

    # Vectorized-plan cache (the executable-plan-cache analog, reference
    # executor.go:634 + plan reuse): shape analysis is pure AST work and
    # the parsed AST is itself cached, so the decision — which strategy,
    # which columns, which items aggregate — is computed once and pinned
    # to the AST object. Per-execution work is then only array ops.
    plan = getattr(q, "_vec_plan", _NO_PLAN)
    if plan is _NO_PLAN:
        plan = _analyze_vectorized(q)
        try:
            q._vec_plan = plan
        except AttributeError:
            pass
    if plan is None:
        return None

    # device graph plane (query/device_graph.py): the same shapes,
    # compiled onto versioned device snapshots — env-gated, and every
    # miss/degrade lands back on the host arrays below
    plane = getattr(executor, "device_graph", None)

    point = plan["point"]
    if point is not None:
        r = _exec_point(catalog, point, plan, ctx, CypherResult)
        if r is not None:
            return r

    tk = plan.get("topk")
    if tk is not None:
        r = _exec_topk(catalog, tk, plan, ctx, CypherResult, plane)
        if r is not None:
            return r
        # runtime-unsupported (non-numeric order prop, torn build):
        # fall through to the generic chain machinery below

    strip, cooc = plan["strip"], plan["cooc"]
    if strip is not None:
        b = _exec_strip(catalog, strip, ctx, plan, plane)
    elif cooc is not None:
        b = _exec_cooc(catalog, cooc, ctx, plane)
    else:
        b = _match_chain(catalog, plan["path"], ctx)
    if b is None:
        return None  # over budget / unsupported at runtime

    for conj in plan["where_conjs"]:
        b.take(_vec_predicate(conj, b, catalog, ctx))

    oc = plan.get("optional_count")
    if oc is not None:
        _apply_optional_count(catalog, oc, b)

    if plan.get("pipeline") is not None:
        return _exec_with_pipeline(executor, catalog, plan, ctx, b,
                                   CypherResult)
    return _project(executor, catalog, plan["ret"], b, ctx, CypherResult, plan)


def _ret_col_names(ret: A.ReturnClause) -> List[str]:
    """Output column names: alias > var name > var.prop > raw text."""
    cols: List[str] = []
    for item in ret.items:
        if item.alias:
            cols.append(item.alias)
        elif isinstance(item.expr, A.Var):
            cols.append(item.expr.name)
        elif isinstance(item.expr, A.Prop) and isinstance(
                item.expr.target, A.Var):
            cols.append(f"{item.expr.target.name}.{item.expr.name}")
        else:
            cols.append(item.text)
    return cols


def _analyze_vectorized(q: A.Query) -> Optional[Dict[str, Any]]:
    """One-time AST shape analysis for the vectorized chain family."""
    from nornicdb_tpu.query.executor import _contains_agg

    clauses = q.clauses
    if len(clauses) == 3:
        if not isinstance(clauses[0], A.MatchClause):
            return None
        if isinstance(clauses[1], A.WithClause):
            return _analyze_with_pipeline(q)
        if (isinstance(clauses[1], A.MatchClause)
                and clauses[1].optional):
            return _analyze_optional_count(q)
        return None
    if len(clauses) != 2:
        return None
    m, ret = clauses[0], clauses[1]
    if not isinstance(m, A.MatchClause) or not isinstance(ret, A.ReturnClause):
        return None
    if m.optional or len(m.paths) != 1 or ret.star:
        return None
    path = m.paths[0]
    if not _path_supported(path, set()):
        return None

    cols = _ret_col_names(ret)
    agg_flags = [_contains_agg(i.expr) for i in ret.items]
    has_agg = any(agg_flags)

    strip = _analyze_strip(path, m, ret) if has_agg else None
    cooc = None
    if strip is None and has_agg:
        cooc = _analyze_cooc(path, m, ret)
    return {
        "m": m,
        "ret": ret,
        "path": path,
        "where_conjs": _split_and(m.where) if m.where is not None else [],
        "strip": strip,
        "cooc": cooc,
        "point": _analyze_point(path, m, ret) if not has_agg else None,
        "topk": _analyze_topk(path, m, ret) if not has_agg else None,
        "cols": cols,
        "agg_flags": agg_flags,
        "has_agg": has_agg,
    }


def _analyze_point(path: A.PatternPath, m: A.MatchClause,
                   ret: A.ReturnClause) -> Optional[Dict[str, Any]]:
    """Compiled point lookup: MATCH (x:L {k: $p}) RETURN x.a, x.b — the
    reference's indexed-access hot path (LDBC message content lookup,
    storage_fastpaths.go). Per-execution work reduces to one hash-index
    probe plus per-hit property reads; the generic chain machinery
    (candidate arrays, bindings, projection arrays) is skipped."""
    if len(path.nodes) != 1 or path.rels or m.where is not None:
        return None
    pn = path.nodes[0]
    if not pn.var or len(pn.labels) != 1 or pn.props is None:
        return None
    items = list(pn.props.items)
    if len(items) != 1:
        return None
    key, vexpr = items[0]
    if not isinstance(vexpr, (A.Literal, A.Param)):
        return None
    if ret.distinct or ret.order_by or ret.skip or ret.limit:
        return None
    projections = []  # (kind, prop-or-None) per RETURN item
    for item in ret.items:
        e = item.expr
        if isinstance(e, A.Var) and e.name == pn.var:
            projections.append(("node", None))
        elif (isinstance(e, A.Prop) and isinstance(e.target, A.Var)
                and e.target.name == pn.var):
            projections.append(("prop", e.name))
        else:
            return None
    return {
        "label": pn.labels[0],
        "key": key,
        "vexpr": vexpr,
        "projections": projections,
    }


def _exec_point(catalog, point: Dict[str, Any], plan: Dict[str, Any],
                ctx, CypherResult):
    vexpr = point["vexpr"]
    if isinstance(vexpr, A.Param):
        if vexpr.name not in ctx.params:
            return None  # let the general path raise the proper error
        value = ctx.params[vexpr.name]
    else:
        value = vexpr.value
    if isinstance(value, (list, dict)):
        return None  # unhashable key: general path semantics
    hit = catalog.prop_index(point["label"], point["key"]).get(value)
    if hit is None:
        return CypherResult(columns=plan["cols"], rows=[])
    rows_idx = hit.tolist()
    nodes = catalog.nodes()
    if isinstance(value, bool) or value in (0, 1):
        rows_idx = _rows_matching_bool_type(
            nodes, rows_idx, point["key"], value)
    cols_out = []
    for kind, prop in point["projections"]:
        if kind == "node":
            cols_out.append([nodes[i] for i in rows_idx])
        else:
            cols_out.append(
                [nodes[i].properties.get(prop) for i in rows_idx])
    return CypherResult(columns=plan["cols"], col_data=cols_out)


def _analyze_topk(path: A.PatternPath, m: A.MatchClause,
                  ret: A.ReturnClause) -> Optional[Dict[str, Any]]:
    """Per-friend top-k analysis: MATCH (a:L {key: $p})-[:T1]-(f)-[:T2]-
    (t) RETURN <props of a/f/t> ORDER BY t.<prop> DESC LIMIT k — the
    LDBC "recent messages of friends" family (BASELINE.md row 2).

    Executes over the catalog's segment-sorted adjacency strip: the
    global DESC/LIMIT-k answer is a merge of each friend's pre-sorted
    top-k head, so per-query work is O(#friends * k) gathers + one
    argsort over ≤ #friends*k candidates — no join expansion over every
    terminal node, no full-candidate sort. AST-only; cached on the
    parsed query."""
    if len(path.nodes) != 3 or len(path.rels) != 2:
        return None
    if m.where is not None:
        return None
    anchor, mid, term = path.nodes
    r1, r2 = path.rels
    if r1.var is not None or r2.var is not None:
        return None
    if r1.types[0] == r2.types[0]:
        return None  # relationship uniqueness needs edge identity
    # anchor: single-label single-prop equality (the indexed entry)
    if not anchor.var or len(anchor.labels) != 1 or anchor.props is None:
        return None
    items = list(anchor.props.items)
    if len(items) != 1 or not isinstance(items[0][1], (A.Literal, A.Param)):
        return None
    for pn in (mid, term):
        if not pn.var or pn.props is not None or len(pn.labels) > 1:
            return None
    # RETURN/ORDER/LIMIT shape
    if ret.distinct or ret.limit is None:
        return None
    if not ret.order_by or len(ret.order_by) != 1:
        return None
    oexpr, desc = ret.order_by[0]
    if not desc:
        return None  # strips are sorted DESC; ASC takes the general path
    if not (isinstance(oexpr, A.Prop) and isinstance(oexpr.target, A.Var)
            and oexpr.target.name == term.var):
        return None
    known = {anchor.var, mid.var, term.var}
    projections = []  # (var, prop-or-None) per RETURN item
    for item in ret.items:
        e = item.expr
        if isinstance(e, A.Var) and e.name in known:
            projections.append((e.name, None))
        elif (isinstance(e, A.Prop) and isinstance(e.target, A.Var)
                and e.target.name in known):
            projections.append((e.target.name, e.name))
        else:
            return None
    return {
        "anchor_label": anchor.labels[0],
        "anchor_key": items[0][0],
        "anchor_vexpr": items[0][1],
        "anchor_var": anchor.var,
        "etype1": r1.types[0],
        "dir1": r1.direction,
        "mid_var": mid.var,
        "mid_label": mid.labels[0] if mid.labels else None,
        "etype2": r2.types[0],
        # the mid node's side of T2 edges: (f)<-[:T2]-(t) means edges
        # run t -> f, so f is 'dst'
        "mid_side": "src" if r2.direction == "out" else "dst",
        "term_var": term.var,
        "term_label": term.labels[0] if term.labels else None,
        "order_prop": oexpr.name,
        "projections": projections,
    }


def _exec_topk(catalog, tk: Dict[str, Any], plan: Dict[str, Any],
               ctx, CypherResult, plane=None):
    if plane is None:
        return _exec_topk_impl(catalog, tk, plan, ctx, CypherResult, None)
    # in-flight accounting is the device plane's auto-mode demand
    # signal: overlapping chain reads are coalescible, a lone read
    # is not worth a b=1 dispatch
    plane.chain_enter()
    try:
        return _exec_topk_impl(catalog, tk, plan, ctx, CypherResult,
                               plane)
    finally:
        plane.chain_exit()


def _exec_topk_impl(catalog, tk: Dict[str, Any], plan: Dict[str, Any],
                    ctx, CypherResult, plane):
    ret = plan["ret"]
    limit = int(_const_value(ret.limit, ctx))
    skip = int(_const_value(ret.skip, ctx)) if ret.skip is not None else 0
    if limit < 0 or skip < 0:
        return None  # general path raises the proper error
    vexpr = tk["anchor_vexpr"]
    if isinstance(vexpr, A.Param):
        if vexpr.name not in ctx.params:
            return None  # let the general path raise the proper error
        value = ctx.params[vexpr.name]
    else:
        value = vexpr.value
    if isinstance(value, (list, dict)):
        return None  # unhashable key: general path semantics
    sa = catalog.sorted_adjacency(
        tk["etype2"], tk["mid_side"], tk["order_prop"], tk["term_label"])
    if sa is None:
        return None  # non-numeric order prop / torn build
    hit = catalog.prop_index(tk["anchor_label"], tk["anchor_key"]).get(value)
    nodes = catalog.nodes()
    if hit is None:
        return CypherResult(columns=plan["cols"], rows=[])
    rows_idx = hit
    if isinstance(value, bool) or value in (0, 1):
        rows_idx = np.asarray(
            _rows_matching_bool_type(nodes, hit.tolist(),
                                     tk["anchor_key"], value),
            dtype=np.int32,
        )
    if len(rows_idx) == 0:
        return CypherResult(columns=plan["cols"], rows=[])

    tbl1 = catalog.edge_table(tk["etype1"])
    n = catalog.n_nodes()

    if plane is not None and len(rows_idx) == 1 and plane.maybe_device():
        # device route: the whole merge — friend gather, per-friend
        # strip heads, global top-k — as ONE batched dispatch shared
        # with every coalesced rider. Row-identical by construction
        # (tie-sharing rank keys); None means serve on the host arrays.
        spec = (tk["etype1"], tk["dir1"], tk["mid_label"], tk["etype2"],
                tk["mid_side"], tk["order_prop"], tk["term_label"])
        dev = plane.chain_topk(
            spec, int(rows_idx[0]), skip + limit,
            len(sa.nbr) + len(tbl1))
        if dev is not None:
            sel_f, sel_t = dev[0][skip:skip + limit], dev[1][skip:skip + limit]
            sel_a = np.full(len(sel_f), int(rows_idx[0]), dtype=np.int32)
            return _topk_project(catalog, tk, plan, CypherResult,
                                 sel_a, sel_f, sel_t)

    # every chain query from here serves on the host arrays — counted
    # so the tier mix stays truthful (the device rung counted above)
    _CHAIN_HOST_SERVED.inc()

    if len(rows_idx) == 1:
        # single indexed anchor (the overwhelmingly common call): one
        # CSR slice replaces the general repeat/cumsum hop expansion
        indptr1, order1 = tbl1.csr(tk["dir1"], n)
        a = int(rows_idx[0])
        erows = order1[indptr1[a]:indptr1[a + 1]]
        friends = (tbl1.dst if tk["dir1"] == "out" else tbl1.src)[erows]
        a_rep = None  # anchor column is the constant row `a`
    else:
        from nornicdb_tpu.query.columnar import expand_hop

        a_rep, _edges, friends = expand_hop(
            tbl1, np.asarray(rows_idx, dtype=np.int32), tk["dir1"], n)
    if tk["mid_label"] is not None and len(friends):
        fmask = catalog.label_mask(tk["mid_label"])[friends]
        friends = friends[fmask]
        if a_rep is not None:
            a_rep = a_rep[fmask]
    if len(friends) == 0:
        return CypherResult(columns=plan["cols"], rows=[])

    # per-friend heads: positions of each friend's top (skip+limit)
    # strip entries — candidates beyond that depth cannot reach the
    # global top-k because segments are sorted by the same key
    try:
        k_head = skip + limit
        ip = sa.indptr
        starts = ip[friends]
        counts = np.minimum(ip[friends + 1] - starts, k_head)
        cum = np.cumsum(counts)
        total = int(cum[-1])
        if total == 0:
            return CypherResult(columns=plan["cols"], rows=[])
        f_rep = np.repeat(np.arange(len(friends), dtype=np.int64), counts)
        # pos[j] walks each friend's segment head: segment start rebased
        # by the candidate's offset within the concatenated head list
        pos = np.arange(total, dtype=np.int64) + np.repeat(
            starts - (cum - counts), counts)
        keyv = sa.keys[pos]

        # global DESC merge, stable so tie order matches the general
        # path's (anchor, friend-CSR, segment) candidate order
        order = np.argsort(-keyv, kind="stable")[skip:skip + limit]
        sel_f = friends[f_rep[order]]
        sel_t = sa.nbr[pos[order]]
        if a_rep is None:
            sel_a = np.full(len(order), int(rows_idx[0]), dtype=np.int32)
        else:
            sel_a = np.asarray(
                rows_idx, dtype=np.int32)[a_rep[f_rep[order]]]
    except (IndexError, ValueError):
        # the strip raced a concurrent node+edge create (its indptr can
        # lag the CSR the friends came from); fall back to the general
        # chain machinery like every other torn-build path
        return None

    return _topk_project(catalog, tk, plan, CypherResult, sel_a, sel_f,
                         sel_t)


def _topk_project(catalog, tk, plan, CypherResult, sel_a, sel_f, sel_t):
    """Shared projection tail of the per-friend top-k family — the
    host merge and the device merge both land here with the same
    (anchor, friend, terminal) row selection."""
    nodes = catalog.nodes()
    row_of = {tk["anchor_var"]: sel_a, tk["mid_var"]: sel_f,
              tk["term_var"]: sel_t}
    cols_out: List[List[Any]] = []
    for var, prop in tk["projections"]:
        rows = row_of[var]
        if prop is None:
            cols_out.append([nodes[int(i)] for i in rows.tolist()])
        else:
            cols_out.append(catalog.node_prop_col(prop)[rows].tolist())
    return CypherResult(columns=plan["cols"], col_data=cols_out)


def _analyze_with_pipeline(q: A.Query) -> Optional[Dict[str, Any]]:
    """MATCH chain -> WITH group/aggregate [WHERE] -> RETURN [ORDER BY
    SKIP LIMIT]: the top-N-groups family (reference serves these through
    the same optimized executors; e.g. "top posters", "most-used tags").
    The WITH stage reuses the chain aggregation machinery; the RETURN
    stage projects only WITH outputs, so the whole pipeline stays
    columnar."""
    from nornicdb_tpu.query.executor import _contains_agg

    m, w, ret = q.clauses
    if not (isinstance(m, A.MatchClause) and isinstance(w, A.WithClause)
            and isinstance(ret, A.ReturnClause)):
        return None
    if m.optional or len(m.paths) != 1 or ret.star or w.star:
        return None
    if ret.distinct:
        return None  # post-aggregate dedup: general path
    if w.distinct or w.order_by or w.skip is not None or w.limit is not None:
        return None  # WITH-level ordering/dedup: general path
    path = m.paths[0]
    if not _path_supported(path, set()):
        return None

    w_flags = [_contains_agg(i.expr) for i in w.items]
    if not any(w_flags):
        return None  # pure projection WITH adds nothing here
    w_names: List[str] = []
    for item in w.items:
        if item.alias:
            w_names.append(item.alias)
        elif isinstance(item.expr, A.Var):
            w_names.append(item.expr.name)
        else:
            return None  # non-var WITH items must be aliased to be usable
    if len(set(w_names)) != len(w_names):
        return None

    # RETURN may reference only WITH outputs (Var or Prop-on-node-var);
    # no second aggregation stage
    known = set(w_names)
    ret_cols: List[str] = []
    for item in ret.items:
        e = item.expr
        if _contains_agg(e):
            return None
        if isinstance(e, A.Var) and e.name in known:
            ret_cols.append(item.alias or e.name)
        elif (isinstance(e, A.Prop) and isinstance(e.target, A.Var)
                and e.target.name in known):
            ret_cols.append(item.alias or f"{e.target.name}.{e.name}")
        else:
            return None
    for expr, _desc in ret.order_by or []:
        if not _order_expr_known(expr, known, ret):
            return None

    strip = _analyze_strip(path, m, w)
    cooc = None if strip is not None else _analyze_cooc(path, m, w)
    return {
        "pipeline": {
            "w": w,
            "w_flags": w_flags,
            "w_names": w_names,
            "ret": ret,
            "ret_cols": ret_cols,
        },
        "m": m,
        "ret": ret,
        "path": path,
        "where_conjs": _split_and(m.where) if m.where is not None else [],
        "strip": strip,
        "cooc": cooc,
        "point": None,
        "cols": ret_cols,
        "agg_flags": [False] * len(ret.items),
        "has_agg": True,
    }


def _analyze_optional_count(q: A.Query) -> Optional[Dict[str, Any]]:
    """MATCH chain OPTIONAL MATCH (anchor)-[:T]->(x) RETURN keys,
    count(x): the "counts including zeros" family the inner-join degree
    pushdown cannot express (an unmatched anchor still produces a group
    with count 0 via its null-extended row). Compiles to per-anchor
    filtered degrees: row multiplicity is max(degree, 1) and count(x)
    uses the raw degree."""
    from nornicdb_tpu.query.executor import _contains_agg

    m, om, ret = q.clauses
    if not isinstance(ret, A.ReturnClause) or ret.star or ret.distinct:
        return None
    if m.optional or len(m.paths) != 1 or len(om.paths) != 1:
        return None
    if om.where is not None:
        return None  # WHERE on the optional side: general path
    path = m.paths[0]
    opath = om.paths[0]
    if not _path_supported(path, set()):
        return None
    # optional chain: exactly (anchor)-[:T]->(x), anchor bound by chain1
    if len(opath.nodes) != 2 or len(opath.rels) != 1 or opath.path_var:
        return None
    oa, ox = opath.nodes
    orel = opath.rels[0]
    if (orel.min_hops != 1 or orel.max_hops != 1 or orel.props is not None
            or len(orel.types) != 1 or orel.direction not in ("out", "in")
            or orel.var is not None):
        return None
    chain_vars = {pn.var for pn in path.nodes if pn.var}
    if not oa.var or oa.var not in chain_vars or oa.labels or oa.props:
        return None
    if ox.props is not None or len(ox.labels) > 1:
        return None
    if ox.var and ox.var in chain_vars:
        return None
    agg_flags = [_contains_agg(i.expr) for i in ret.items]
    if not any(agg_flags):
        return None  # non-aggregated optional rows: general path
    if not _count_only_usage(ox.var, m, ret):
        return None

    cols = _ret_col_names(ret)

    return {
        "m": m,
        "ret": ret,
        "path": path,
        "where_conjs": _split_and(m.where) if m.where is not None else [],
        "strip": None,
        "cooc": None,
        "point": None,
        "pipeline": None,
        "optional_count": {
            "anchor": oa.var,
            "etype": orel.types[0],
            "direction": orel.direction,
            "label": ox.labels[0] if ox.labels else None,
            "var": ox.var,
        },
        "cols": cols,
        "agg_flags": agg_flags,
        "has_agg": True,
    }


def _apply_optional_count(catalog, oc: Dict[str, Any], b: _Bindings) -> None:
    """Attach optional-hop multiplicity to computed chain bindings."""
    deg = catalog.filtered_degree(oc["etype"], oc["direction"], oc["label"])
    w = deg[b.node_cols[oc["anchor"]]]
    # a row with no optional match still exists once (null-extended)
    b.row_weights = np.maximum(w, 1)
    if oc["var"]:
        b.stripped_vars.add(oc["var"])
        # count(x) must use the RAW degree (0 for unmatched anchors)
        b.stripped_var_weights[oc["var"]] = w.astype(np.int64)


def _order_expr_known(expr: A.Expr, known: set, ret: A.ReturnClause) -> bool:
    if isinstance(expr, A.Var):
        if expr.name in known:
            return True
        return any(item.alias == expr.name for item in ret.items)
    if isinstance(expr, A.Prop) and isinstance(expr.target, A.Var):
        return expr.target.name in known
    return False


def _exec_with_pipeline(executor, catalog, plan, ctx, b, CypherResult):
    """Stage 2+3 of the WITH pipeline over computed chain bindings."""
    pipe = plan["pipeline"]
    w = pipe["w"]

    with_cols = _aggregate(catalog, w, b, ctx, {"agg_flags": pipe["w_flags"]})
    named = dict(zip(pipe["w_names"], with_cols))

    # WITH ... WHERE over aggregated columns
    if w.where is not None:
        n = len(with_cols[0]) if with_cols else 0
        mask = np.ones(n, dtype=bool)
        for conj in _split_and(w.where):
            mask &= _named_predicate(
                conj, lambda e: _resolve_named(named, catalog, e), ctx)
        named = {k: v[mask] for k, v in named.items()}

    out_cols = [_resolve_named(named, catalog, item.expr)
                for item in pipe["ret"].items]

    ret = pipe["ret"]
    cols = pipe["ret_cols"]
    if ret.order_by:
        keys = []
        for expr, desc in ret.order_by:
            col = _resolve_order(expr, named, catalog, ret, cols, out_cols)
            keys.append((col, desc))
        order = _order_from_keys(keys, len(out_cols[0]) if out_cols else 0)
        out_cols = [c[order] for c in out_cols]
    if ret.skip is not None:
        k = int(_const_value(ret.skip, ctx))
        out_cols = [c[k:] for c in out_cols]
    if ret.limit is not None:
        k = int(_const_value(ret.limit, ctx))
        out_cols = [c[:k] for c in out_cols]

    py_cols: List[List[Any]] = []
    for col in out_cols:
        lst = col.tolist()
        if lst and isinstance(lst[0], _NodeRef):
            nodes = catalog.nodes()
            lst = [nodes[v.row] for v in lst]
        py_cols.append(lst)
    if not py_cols:
        return CypherResult(columns=cols, rows=[])
    return CypherResult(columns=cols, col_data=py_cols)


def _resolve_named(named, catalog, e: A.Expr) -> np.ndarray:
    """Column for an expression over the WITH output table: a named
    column directly, or a property gathered over a NodeRef column via
    the catalog's vectorized property columns."""
    if isinstance(e, A.Var) and e.name in named:
        return named[e.name]
    if (isinstance(e, A.Prop) and isinstance(e.target, A.Var)
            and e.target.name in named):
        col = named[e.target.name]
        if len(col) == 0:
            return np.empty(0, dtype=object)
        if not isinstance(col[0], _NodeRef):
            _bail()
        rows = np.fromiter((ref.row for ref in col.tolist()),
                           dtype=np.int64, count=len(col))
        return catalog.node_prop_col(e.name)[rows]
    _bail()


def _resolve_order(expr, named, catalog, ret, cols, out_cols) -> np.ndarray:
    if isinstance(expr, A.Var) and expr.name in cols:
        return out_cols[cols.index(expr.name)]
    return _resolve_named(named, catalog, expr)


def _order_from_keys(keys, n: int) -> np.ndarray:
    """Row order for (column, desc) sort keys: numeric lexsort lane with
    Neo4j null-last-ASC semantics (null -> +inf BEFORE desc negation),
    falling back to a stable _cypher_cmp python sort for mixed types."""
    float_keys = []
    for col, desc in keys:
        f = _as_float(col) if col.dtype == object else (
            col.astype(np.float64), np.ones(len(col), bool))
        if f is None:
            from nornicdb_tpu.query.executor import _cypher_cmp
            import functools as _ft

            idx = list(range(n))

            def cmp(a, bx):
                for c, d in keys:
                    va, vb = c[a], c[bx]
                    if isinstance(va, _NodeRef) or isinstance(vb, _NodeRef):
                        _bail()
                    r = _cypher_cmp(va, vb)
                    if r != 0:
                        return -r if d else r
                return 0

            idx.sort(key=_ft.cmp_to_key(cmp))
            return np.asarray(idx, dtype=np.int64)
        vals, maskv = f
        vals = np.where(maskv, vals, np.inf)
        float_keys.append(-vals if desc else vals)
    if not float_keys:
        return np.arange(n)
    return np.lexsort(list(reversed(float_keys)))


def _named_predicate(e: A.Expr, resolve, ctx) -> np.ndarray:
    """WHERE conjunct over named aggregate columns."""
    if isinstance(e, A.Binary) and e.op in ("=", "<>", "<", "<=", ">", ">="):
        lconst = _is_const(e.left)
        rconst = _is_const(e.right)
        if lconst and rconst:
            _bail()
        if lconst:
            flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
            return _vec_cmp_const(resolve(e.right),
                                  flip.get(e.op, e.op),
                                  _const_value(e.left, ctx))
        if rconst:
            return _vec_cmp_const(resolve(e.left), e.op,
                                  _const_value(e.right, ctx))
        return _vec_cmp_cols(resolve(e.left), resolve(e.right), e.op)
    if isinstance(e, A.IsNull):
        col = resolve(e.operand)
        isnull = np.array([x is None for x in col.tolist()], dtype=bool)
        return ~isnull if e.negated else isnull
    _bail()


# -- aggregation pushdown shapes ------------------------------------------


def _mentions_var(obj: Any, name: str) -> bool:
    """Conservative AST walk: does ``obj`` reference variable ``name``
    anywhere? (Shadowing by list-comprehension/reduce locals counts as a
    mention — over-reporting only costs the fast path, never
    correctness.)"""
    import dataclasses

    if isinstance(obj, A.Var):
        return obj.name == name
    if isinstance(obj, (A.LabelCheck,)):
        return obj.var == name
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            if _mentions_var(getattr(obj, f.name), name):
                return True
        return False
    if isinstance(obj, (list, tuple)):
        return any(_mentions_var(x, name) for x in obj)
    return False


def _var_only_counted(e: A.Expr, name: str) -> bool:
    """True iff every reference to ``name`` inside ``e`` is exactly the
    argument of a non-distinct count()."""
    import dataclasses

    if (
        isinstance(e, A.FuncCall)
        and e.name == "count"
        and not e.distinct
        and not e.star
        and len(e.args) == 1
        and isinstance(e.args[0], A.Var)
        and e.args[0].name == name
    ):
        return True
    if isinstance(e, A.Var):
        return e.name != name
    if isinstance(e, A.LabelCheck):
        return e.var != name
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        return all(
            _var_only_counted(getattr(e, f.name), name)
            for f in dataclasses.fields(e)
        )
    if isinstance(e, (list, tuple)):
        return all(_var_only_counted(x, name) for x in e)
    return True


def _count_only_usage(var: Optional[str], m: A.MatchClause,
                      ret: A.ReturnClause) -> bool:
    """May ``var`` be folded out of the bindings? Requires it to appear
    (if at all) only as non-distinct count(var) in RETURN, and nowhere in
    WHERE or ORDER BY."""
    if var is None:
        return True
    if m.where is not None and _mentions_var(m.where, var):
        return False
    for item in ret.items:
        if not _var_only_counted(item.expr, var):
            return False
    for expr, _desc in ret.order_by or []:
        if _mentions_var(expr, var):
            return False
    return True


def _analyze_strip(path: A.PatternPath, m: A.MatchClause,
                   ret: A.ReturnClause) -> Optional[Dict[str, Any]]:
    """Terminal-hop aggregation pushdown analysis (reference:
    traversal_fast_agg.go:15,57): when the chain's last node is consumed
    only by non-distinct count(), the final join expansion collapses to a
    per-source filtered-degree lookup and the surviving rows carry
    multiplicity weights. AST-only; cached on the parsed query."""
    if len(path.nodes) < 2 or not path.rels:
        return None
    pn, pr = path.nodes[-1], path.rels[-1]
    if pn.props is not None or len(pn.labels) > 1:
        return None
    if pr.var is not None:
        return None
    # a same-type hop elsewhere in the chain brings relationship
    # uniqueness into play; degrees can't see edge identity
    if any(r.types[0] == pr.types[0] for r in path.rels[:-1]):
        return None
    if not _count_only_usage(pn.var, m, ret):
        return None

    src_node = path.nodes[-2]
    if src_node.var is None:
        if any(n.var == "__strip_src__" for n in path.nodes) or any(
            r.var == "__strip_src__" for r in path.rels
        ):
            return None
        src_node = A.PatternNode(
            var="__strip_src__", labels=src_node.labels, props=src_node.props
        )
    tpath = A.PatternPath(
        nodes=list(path.nodes[:-2]) + [src_node],
        rels=list(path.rels[:-1]),
    )
    return {
        "tpath": tpath,
        "src_var": src_node.var,
        "etype": pr.types[0],
        "direction": pr.direction,
        "label": pn.labels[0] if pn.labels else None,
        "var": pn.var,
    }


def _exec_strip(catalog, strip: Dict[str, Any], ctx,
                plan: Optional[Dict[str, Any]] = None,
                plane=None) -> Optional[_Bindings]:
    if plan is not None:
        spec = _strip_view_spec(plan, strip)
        if spec is not None:
            b = _exec_strip_view(catalog, strip, spec, plane)
            if b is not None:
                return b
    b = _match_chain(catalog, strip["tpath"], ctx)
    if b is None:
        return None
    src_rows = b.node_cols[strip["src_var"]]
    deg = catalog.filtered_degree(
        strip["etype"], strip["direction"], strip["label"]
    )
    w = deg[src_rows]
    keep = w > 0
    b.take(keep)
    b.row_weights = w[keep]
    if strip["var"]:
        b.stripped_vars.add(strip["var"])
    return b


_NO_SPEC = object()


def _strip_view_spec(plan: Dict[str, Any],
                     strip: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Eligibility for the materialized strip view (columnar.strip_view):
    the remaining chain is exactly one hop (g)-[:T1]-(p), there are no
    runtime filters, and every RETURN item is either a g reference or a
    count-family aggregate over {*, f, p, g} — then the whole query
    collapses to per-group reads of maintained arrays. AST-only; cached
    on the (AST-pinned) plan."""
    spec = strip.get("view_spec", _NO_SPEC)
    if spec is not _NO_SPEC:
        return spec
    spec = _analyze_strip_view(plan, strip)
    strip["view_spec"] = spec
    return spec


def _analyze_strip_view(plan: Dict[str, Any],
                        strip: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    if plan.get("pipeline") is not None or plan.get("optional_count"):
        return None
    if plan["where_conjs"]:
        return None
    tpath = strip["tpath"]
    if len(tpath.nodes) != 2 or len(tpath.rels) != 1:
        return None
    g, p = tpath.nodes
    rel = tpath.rels[0]
    if (rel.min_hops != 1 or rel.max_hops != 1 or rel.props is not None
            or len(rel.types) != 1 or rel.var is not None
            or rel.direction not in ("out", "in")):
        return None
    if g.var is None or g.props is not None or len(g.labels) > 1:
        return None
    if p.props is not None or len(p.labels) > 1:
        return None
    if p.var != strip["src_var"]:
        return None
    ret = plan["ret"]
    if ret.distinct:
        return None
    f_var = strip["var"]
    count_vars = {v for v in (f_var, p.var, g.var) if v}
    for item, is_agg in zip(ret.items, plan["agg_flags"]):
        if is_agg:
            if not _view_agg_supported(item.expr, count_vars, f_var):
                return None
        else:
            e = item.expr
            ok = (isinstance(e, A.Var) and e.name == g.var) or (
                isinstance(e, A.Prop) and isinstance(e.target, A.Var)
                and e.target.name == g.var
            )
            if not ok:
                return None
    for expr, _desc in ret.order_by or []:
        if _mentions_var(expr, p.var) or (f_var and _mentions_var(expr, f_var)):
            return None
    return {
        "g_var": g.var,
        "g_label": g.labels[0] if g.labels else None,
        "p_var": p.var,
        "p_label": p.labels[0] if p.labels else None,
        "etype1": rel.types[0],
        "g_side": "src" if rel.direction == "out" else "dst",
    }


def _view_agg_supported(e: A.Expr, count_vars: set,
                        f_var: Optional[str]) -> bool:
    """Mirror of _agg_expr's structure: combinators over count leaves.
    f (the stripped terminal) may only be counted non-distinct; p and g
    may be counted with or without DISTINCT (p's distinct channel is the
    maintained nnz array)."""
    if isinstance(e, A.FuncCall) and e.name in _AGG_NAMES:
        if e.name != "count":
            return False
        if e.star:
            return True
        if len(e.args) != 1 or not isinstance(e.args[0], A.Var):
            return False
        name = e.args[0].name
        if name not in count_vars:
            return False
        if e.distinct and name == f_var:
            return False
        return True
    if isinstance(e, A.Binary) and e.op in ("+", "-", "*", "/", "%"):
        return (_view_agg_supported(e.left, count_vars, f_var)
                and _view_agg_supported(e.right, count_vars, f_var))
    if isinstance(e, (A.Literal, A.Param)):
        return True
    if isinstance(e, A.FuncCall) and e.name in ("tofloat", "tointeger",
                                                "round"):
        return all(_view_agg_supported(a, count_vars, f_var)
                   for a in e.args)
    return False


def _exec_strip_view(catalog, strip: Dict[str, Any],
                     spec: Dict[str, Any],
                     plane=None) -> Optional[_Bindings]:
    sv = None
    if plane is not None:
        # device segment-sum build of the SAME view (verified-exact
        # integer arrays, installed into the catalog); None -> host
        sv = plane.build_strip_view(
            spec["etype1"], spec["g_side"], spec["p_label"],
            strip["etype"], strip["direction"], strip["label"],
        )
    if sv is None:
        sv = catalog.strip_view(
            spec["etype1"], spec["g_side"], spec["p_label"],
            strip["etype"], strip["direction"], strip["label"],
        )
    if sv is None:
        return None
    try:
        if spec["g_label"] is not None:
            g_rows = catalog.label_rows(spec["g_label"])
        else:
            g_rows = np.arange(catalog.n_nodes(), dtype=np.int32)
        sum_g = sv.sum_deg[g_rows]
        keep = sum_g > 0
        g_rows = g_rows[keep]
        nnz_g = sv.nnz[g_rows]
    except (IndexError, ValueError):
        return None  # raced a write; per-query expansion instead
    b = _Bindings()
    g_rows = g_rows.astype(np.int32, copy=False)
    b.node_cols[spec["g_var"]] = g_rows
    b.n_rows = len(g_rows)
    # anchor rows are pairwise-distinct label rows: rows ARE the groups
    # when the keys are injective anchor props, letting _aggregate skip
    # the whole group-coding pass (same identity the cooc route uses)
    b.cand_map[spec["g_var"]] = (
        g_rows, np.arange(len(g_rows), dtype=np.int64))
    b.rows_are_groups = True
    b.row_weights = sum_g[keep]
    if strip["var"]:
        b.stripped_vars.add(strip["var"])
        b.stripped_var_weights[strip["var"]] = b.row_weights
    b.stripped_vars.add(spec["p_var"])
    b.stripped_var_weights[spec["p_var"]] = b.row_weights
    b.stripped_distinct_counts[spec["p_var"]] = nnz_g
    return b


def _analyze_cooc(path: A.PatternPath, m: A.MatchClause,
                  ret: A.ReturnClause) -> Optional[Dict[str, Any]]:
    """Co-occurrence shape analysis for (a)<-[:T]-(mid)-[:T]->(b): the
    per-(a, b) match count is the off-diagonal of an incidence-matrix
    product MaT @ Mb — an MXU-shaped contraction instead of a join
    expansion (reference serves this family through hand-written
    executors, optimized_executors.go:25-282; LDBC "tag co-occurrence",
    BASELINE.md). The middle node may only be count()ed; both hops must
    be the same single type so relationship uniqueness reduces to the
    same-edge diagonal correction. AST-only; cached on the parsed
    query."""
    nodes, rels = path.nodes, path.rels
    if len(nodes) != 3 or len(rels) != 2:
        return None
    r0, r1 = rels
    if r0.types[0] != r1.types[0] or r0.var or r1.var:
        return None
    dirs = (r0.direction, r1.direction)
    if dirs not in (("in", "out"), ("out", "in")):
        return None
    a, mid, bn = nodes
    for pn in nodes:
        if pn.props is not None or len(pn.labels) > 1:
            return None
    if not _count_only_usage(mid.var, m, ret):
        return None
    return {
        "etype": r0.types[0],
        "orientation": "mid_src" if dirs == ("in", "out") else "mid_dst",
        "mid_label": mid.labels[0] if mid.labels else None,
        "a_label": a.labels[0] if a.labels else None,
        "b_label": bn.labels[0] if bn.labels else None,
        "a_var": a.var,
        "b_var": bn.var,
        "mid_var": mid.var,
    }


def _exec_cooc(catalog, cooc: Dict[str, Any], ctx,
               plane=None) -> Optional[_Bindings]:
    etype = cooc["etype"]
    orientation = cooc["orientation"]
    # materialized Gram matrix: O(nnz(C)) per query, maintained across
    # creates (columnar.cooc_gram; the build contraction runs on the
    # device plane when gated on). Falls through to the per-query
    # incidence matmul only on a torn concurrent build.
    gram = catalog.cooc_gram(
        etype, orientation, cooc["mid_label"], cooc["a_label"],
        cooc["b_label"], device_plane=plane,
    )
    if gram is not None:
        ii, jj, w, a_rows, b_rows = gram.coo()
        b_out = _Bindings()
        if cooc["a_var"]:
            b_out.node_cols[cooc["a_var"]] = a_rows
            b_out.cand_map[cooc["a_var"]] = (gram.a_cands, ii)
        if cooc["b_var"]:
            b_out.node_cols[cooc["b_var"]] = b_rows
            b_out.cand_map[cooc["b_var"]] = (gram.b_cands, jj)
        b_out.row_weights = w
        b_out.n_rows = len(ii)
        b_out.rows_are_groups = bool(cooc["a_var"] and cooc["b_var"])
        if cooc["mid_var"]:
            b_out.stripped_vars.add(cooc["mid_var"])
        return b_out

    inc_a = catalog.incidence(
        etype, orientation, cooc["mid_label"], cooc["a_label"]
    )
    inc_b = catalog.incidence(
        etype, orientation, cooc["mid_label"], cooc["b_label"]
    )
    if inc_a is None or inc_b is None:
        return None  # over the dense-matrix budget: join expansion instead
    ma, a_c, ea, a_pos = inc_a
    mb, b_c, eb, b_pos = inc_b
    # the two incidence fetches (and the edge table below) can straddle a
    # concurrent write's cache invalidation; mismatched snapshots must
    # fall back to the general path, not crash the read
    if ma.shape[0] != mb.shape[0] or len(ea) != len(eb):
        return None

    # float32 loses integer exactness past 2^24; a cheap upper bound on
    # any per-pair count is n_mid * max(ma) * max(mb)
    if ma.size and mb.size and (
        float(ma.shape[0]) * float(ma.max()) * float(mb.max()) >= 2.0 ** 24
    ):
        c = ma.astype(np.float64).T @ mb.astype(np.float64)
    else:
        c = ma.T @ mb
    # relationship uniqueness: a match may not use one edge for both
    # hops; such pairs land at (far, far) of each doubly-usable edge
    both = ea & eb
    if both.any():
        tbl = catalog.edge_table(etype)
        far_e = tbl.dst if orientation == "mid_src" else tbl.src
        if len(far_e) != len(both):
            return None  # edge table raced a write; general path instead
        flat = a_pos[far_e[both]] * c.shape[1] + b_pos[far_e[both]]
        c -= np.bincount(flat, minlength=c.size).reshape(c.shape)

    ii, jj = np.nonzero(c >= 0.5)
    b_out = _Bindings()
    if cooc["a_var"]:
        b_out.node_cols[cooc["a_var"]] = a_c[ii].astype(np.int32, copy=False)
        b_out.cand_map[cooc["a_var"]] = (a_c, ii)
    if cooc["b_var"]:
        b_out.node_cols[cooc["b_var"]] = b_c[jj].astype(np.int32, copy=False)
        b_out.cand_map[cooc["b_var"]] = (b_c, jj)
    b_out.row_weights = np.rint(c[ii, jj]).astype(np.int64)
    b_out.n_rows = len(ii)
    # (a, b) pairs are distinct by construction — but only the full pair;
    # with one endpoint anonymous the remaining codes repeat
    b_out.rows_are_groups = bool(cooc["a_var"] and cooc["b_var"])
    if cooc["mid_var"]:
        b_out.stripped_vars.add(cooc["mid_var"])
    return b_out


def _match_chain(catalog, path: A.PatternPath, ctx) -> Optional[_Bindings]:
    from nornicdb_tpu.query.columnar import expand_hop

    nodes, rels = path.nodes, path.rels
    n_nodes_total = catalog.n_nodes()

    # candidate rows for each pattern node (None == unconstrained)
    def candidates(pn: A.PatternNode) -> Optional[np.ndarray]:
        rows: Optional[np.ndarray] = None
        if pn.labels:
            rows = catalog.label_rows(pn.labels[0])
            for lbl in pn.labels[1:]:
                rows = rows[catalog.label_mask(lbl)[rows]]
        if pn.props is not None:
            items = list(pn.props.items)
            if pn.labels and items:
                # point lookup via the hash property index (reference:
                # LDBC message-content-lookup path, storage_fastpaths.go)
                k0, vexpr0 = items[0]
                v0 = _const_value(vexpr0, ctx)
                if isinstance(v0, (list, dict)):
                    _bail()  # unhashable probe: general path semantics
                hit = catalog.prop_index(pn.labels[0], k0).get(v0)
                hit = hit if hit is not None else np.empty(0, np.int32)
                if len(hit) and (isinstance(v0, bool) or v0 in (0, 1)):
                    hit = np.asarray(
                        _rows_matching_bool_type(
                            catalog.nodes(), hit.tolist(), k0, v0),
                        dtype=np.int32)
                mask = catalog.label_mask(pn.labels[0])  # noqa: F841 (built)
                rows = (
                    np.intersect1d(rows, hit).astype(np.int32)
                    if len(pn.labels) > 1
                    else hit
                )
                items = items[1:]
            for k, vexpr in items:
                v = _const_value(vexpr, ctx)
                base = rows if rows is not None else np.arange(
                    n_nodes_total, dtype=np.int32
                )
                rows = base[_vec_eq(catalog.node_prop_col(k)[base], v)]
        return rows

    cand = [candidates(pn) for pn in nodes]
    # membership masks for hop-target filtering: the cached label mask
    # when the candidate set IS a label (no per-query O(n_nodes) scatter
    # build — at 10^5 nodes that build dominated the whole query)
    cand_masks = [
        catalog.label_mask(pn.labels[0])
        if (len(pn.labels) == 1 and pn.props is None) else None
        for pn in nodes
    ]

    def size(i: int) -> int:
        return len(cand[i]) if cand[i] is not None else n_nodes_total

    anchor = min(range(len(nodes)), key=size)
    rows0 = cand[anchor]
    if rows0 is None:
        rows0 = np.arange(n_nodes_total, dtype=np.int32)

    b = _Bindings()
    slot_cols: List[Optional[np.ndarray]] = [None] * len(nodes)
    slot_cols[anchor] = rows0.astype(np.int32, copy=False)
    # anchor group codes ride along through every replication for free,
    # so grouping by the anchor var later skips a dense-coding pass
    anchor_codes = [np.arange(len(rows0), dtype=np.int64)]

    def take_all(sel) -> None:
        for i in range(len(nodes)):
            if slot_cols[i] is not None:
                slot_cols[i] = slot_cols[i][sel]
        b.edge_cols = {k: (t, x[sel]) for k, (t, x) in b.edge_cols.items()}
        b.hop_edges = [(t, x[sel]) for t, x in b.hop_edges]
        anchor_codes[0] = anchor_codes[0][sel]

    def expand(frm: int, to: int, rel_idx: int) -> None:
        pr = rels[rel_idx]
        table = catalog.edge_table(pr.types[0])
        forward = to > frm
        # pr.direction 'out': edge start=nodes[rel_idx], end=nodes[rel_idx+1]
        if pr.direction == "out":
            direction = "out" if forward else "in"
        else:
            direction = "in" if forward else "out"
        rep, edge_rows, targets = expand_hop(
            table, slot_cols[frm], direction, n_nodes_total
        )
        # replicate existing columns to the expanded row set
        for i in range(len(nodes)):
            if slot_cols[i] is not None:
                slot_cols[i] = slot_cols[i][rep]
        b.edge_cols = {k: (t, x[rep]) for k, (t, x) in b.edge_cols.items()}
        b.hop_edges = [(t, x[rep]) for t, x in b.hop_edges]
        anchor_codes[0] = anchor_codes[0][rep]
        slot_cols[to] = targets
        if pr.var:
            b.edge_cols[pr.var] = (table, edge_rows)
        b.hop_edges.append((pr.types[0], edge_rows))
        # constrain targets by the `to` node's label/prop candidate set
        if cand[to] is not None:
            if cand_masks[to] is not None:
                take_all(cand_masks[to][targets])
            else:
                keep = np.zeros(n_nodes_total, dtype=bool)
                keep[cand[to]] = True
                take_all(keep[targets])
        # Cypher relationship uniqueness: a match may not reuse an edge.
        # Only same-type hops can collide (edge rows are per-type).
        latest = len(b.hop_edges) - 1
        for j in range(latest):
            if b.hop_edges[j][0] == pr.types[0]:
                take_all(b.hop_edges[latest][1] != b.hop_edges[j][1])

    for to in range(anchor + 1, len(nodes)):
        expand(to - 1, to, to - 1)
    for to in range(anchor - 1, -1, -1):
        expand(to + 1, to, to)

    for i, pn in enumerate(nodes):
        if pn.var:
            b.node_cols[pn.var] = slot_cols[i]
    if nodes[anchor].var:
        b.cand_map[nodes[anchor].var] = (
            rows0.astype(np.int32, copy=False), anchor_codes[0]
        )
    b.n_rows = len(slot_cols[anchor]) if slot_cols[anchor] is not None else 0
    return b


def _rows_matching_bool_type(nodes, rows_idx, key, value):
    """dict keys conflate True/1 and False/0; Cypher treats bool and int
    as distinct. Filter hash-index hits to rows whose stored value has
    the same bool-ness as the probe value."""
    want_bool = isinstance(value, bool)
    return [i for i in rows_idx
            if isinstance(nodes[i].properties.get(key), bool) == want_bool]


def _const_value(e: A.Expr, ctx) -> Any:
    if isinstance(e, A.Literal):
        return e.value
    if isinstance(e, A.Param):
        if e.name not in ctx.params:
            _bail()
        return ctx.params[e.name]
    _bail()


def _index_key(v: Any) -> Any:
    # the prop_index stores raw property values; ints/floats hash-equal
    return v


def _split_and(e: A.Expr) -> List[A.Expr]:
    if isinstance(e, A.Binary) and e.op == "AND":
        return _split_and(e.left) + _split_and(e.right)
    return [e]


def _vec_eq(col: np.ndarray, v: Any) -> np.ndarray:
    """Null-safe elementwise equality (null -> no match)."""
    if v is None:
        return np.zeros(len(col), dtype=bool)
    out = np.zeros(len(col), dtype=bool)
    for i, x in enumerate(col.tolist()):
        if x is None:
            continue
        if isinstance(x, bool) != isinstance(v, bool):
            continue
        try:
            out[i] = x == v
        except TypeError:
            pass
    return out


def _as_float(col: np.ndarray) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(float64 values, valid mask) if all non-null entries numeric."""
    if col.dtype != object:
        f = col.astype(np.float64, copy=False)
        return f, np.ones(len(col), dtype=bool)
    # all-numeric columns (the ORDER BY hot path) convert in one C pass;
    # astype raises on str/dict — but silently accepts bools AND maps
    # None to nan, so a type-scan preserves the bool-is-not-a-number
    # contract and the nan slots are audited back to a null mask
    # (caught by the differential fuzzer: avg() over a column with
    # nulls summed the nans into nan)
    try:
        f = col.astype(np.float64)
    except (TypeError, ValueError):
        pass
    else:
        types = set(map(type, col.tolist()))  # one C pass, no py frames
        if bool in types or np.bool_ in types:
            return None
        mask = ~np.isnan(f)
        if not mask.all() and type(None) not in types:
            # genuine float('nan') values, not nulls: they count
            mask[:] = True
        elif not mask.all():
            # mixed: nan slots are null UNLESS the object is a float
            lst = col.tolist()
            for i in np.flatnonzero(~mask).tolist():
                if lst[i] is not None:
                    mask[i] = True
        return f, mask
    vals = np.empty(len(col), dtype=np.float64)
    mask = np.zeros(len(col), dtype=bool)
    for i, x in enumerate(col.tolist()):
        if x is None:
            vals[i] = np.nan
            continue
        if isinstance(x, bool) or not isinstance(x, (int, float)):
            return None
        vals[i] = float(x)
        mask[i] = True
    return vals, mask


def _vec_col(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    """Evaluate an expression to a value column over binding rows."""
    if isinstance(e, A.Prop) and isinstance(e.target, A.Var):
        name = e.target.name
        if name in b.node_cols:
            return catalog.node_prop_col(e.name)[b.node_cols[name]]
        if name in b.edge_cols:
            table, rows = b.edge_cols[name]
            return table.prop_col(e.name)[rows]
        _bail()
    if isinstance(e, (A.Literal, A.Param)):
        v = _const_value(e, ctx)
        out = np.empty(b.n_rows, dtype=object)
        out[:] = v
        return out
    if isinstance(e, A.Binary) and e.op in ("+", "-", "*", "/"):
        lcol = _vec_col(e.left, b, catalog, ctx)
        rcol = _vec_col(e.right, b, catalog, ctx)
        lf = _as_float(lcol)
        rf = _as_float(rcol)
        if lf is None or rf is None:
            _bail()
        lv, lm = lf
        rv, rm = rf
        with np.errstate(invalid="ignore", divide="ignore"):
            if e.op == "+":
                out = lv + rv
            elif e.op == "-":
                out = lv - rv
            elif e.op == "*":
                out = lv * rv
            else:
                out = lv / rv
        res = np.empty(b.n_rows, dtype=object)
        valid = lm & rm
        for i in range(b.n_rows):
            res[i] = float(out[i]) if valid[i] else None
        return res
    _bail()


def _vec_predicate(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    """Boolean mask over binding rows for one WHERE conjunct."""
    if isinstance(e, A.Binary):
        op = e.op
        # node-var inequality: t1 <> t2 (tag co-occurrence shape)
        if (
            op in ("<>", "=")
            and isinstance(e.left, A.Var)
            and isinstance(e.right, A.Var)
            and e.left.name in b.node_cols
            and e.right.name in b.node_cols
        ):
            same = b.node_cols[e.left.name] == b.node_cols[e.right.name]
            return same if op == "=" else ~same
        if op in ("=", "<>", "<", "<=", ">", ">="):
            lcol = (
                _vec_col(e.left, b, catalog, ctx)
                if not _is_const(e.left) else None
            )
            rcol = (
                _vec_col(e.right, b, catalog, ctx)
                if not _is_const(e.right) else None
            )
            if lcol is None and rcol is None:
                _bail()
            if lcol is None:
                # const OP col  ->  col (flip) OP const
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                op = flip.get(op, op)
                lcol = rcol
                rcol = None
                const = _const_value(e.left, ctx)
            elif rcol is None:
                const = _const_value(e.right, ctx)
            else:
                return _vec_cmp_cols(lcol, rcol, op)
            return _vec_cmp_const(lcol, op, const)
        if op == "IN":
            lcol = _vec_col(e.left, b, catalog, ctx)
            vals = _const_value(e.right, ctx)
            if not isinstance(vals, list):
                _bail()
            out = np.zeros(b.n_rows, dtype=bool)
            vset = set()
            unhashable = []
            for v in vals:
                try:
                    vset.add(v)
                except TypeError:
                    unhashable.append(v)
            for i, x in enumerate(lcol.tolist()):
                if x is None:
                    continue
                try:
                    out[i] = x in vset or x in unhashable
                except TypeError:
                    pass
            return out
        if op in ("STARTS WITH", "ENDS WITH", "CONTAINS"):
            lcol = _vec_col(e.left, b, catalog, ctx)
            v = _const_value(e.right, ctx)
            if not isinstance(v, str):
                _bail()
            out = np.zeros(b.n_rows, dtype=bool)
            for i, x in enumerate(lcol.tolist()):
                if not isinstance(x, str):
                    continue
                if op == "STARTS WITH":
                    out[i] = x.startswith(v)
                elif op == "ENDS WITH":
                    out[i] = x.endswith(v)
                else:
                    out[i] = v in x
            return out
    if isinstance(e, A.LabelCheck):
        if e.var not in b.node_cols:
            _bail()
        mask = np.ones(b.n_rows, dtype=bool)
        for lbl in e.labels:
            mask &= catalog.label_mask(lbl)[b.node_cols[e.var]]
        return mask
    if isinstance(e, A.IsNull):
        col = _vec_col(e.operand, b, catalog, ctx)
        isnull = np.array([x is None for x in col.tolist()], dtype=bool)
        return ~isnull if e.negated else isnull
    _bail()


def _is_const(e: A.Expr) -> bool:
    return isinstance(e, (A.Literal, A.Param))


def _vec_cmp_const(col: np.ndarray, op: str, v: Any) -> np.ndarray:
    if op == "=":
        return _vec_eq(col, v)
    if op == "<>":
        eq = _vec_eq(col, v)
        nonnull = np.array([x is not None for x in col.tolist()], dtype=bool)
        return nonnull & ~eq
    # ordering comparisons: numeric lane when possible
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        f = _as_float(col)
        if f is not None:
            vals, mask = f
            with np.errstate(invalid="ignore"):
                if op == "<":
                    res = vals < v
                elif op == "<=":
                    res = vals <= v
                elif op == ">":
                    res = vals > v
                else:
                    res = vals >= v
            return res & mask
    out = np.zeros(len(col), dtype=bool)
    for i, x in enumerate(col.tolist()):
        if x is None:
            continue
        try:
            if op == "<":
                out[i] = x < v
            elif op == "<=":
                out[i] = x <= v
            elif op == ">":
                out[i] = x > v
            else:
                out[i] = x >= v
        except TypeError:
            pass
    return out


def _vec_cmp_cols(lcol: np.ndarray, rcol: np.ndarray, op: str) -> np.ndarray:
    out = np.zeros(len(lcol), dtype=bool)
    for i, (x, y) in enumerate(zip(lcol.tolist(), rcol.tolist())):
        if x is None or y is None:
            continue
        try:
            if op == "=":
                out[i] = x == y and isinstance(x, bool) == isinstance(y, bool)
            elif op == "<>":
                out[i] = not (x == y and isinstance(x, bool) == isinstance(y, bool))
            elif op == "<":
                out[i] = x < y
            elif op == "<=":
                out[i] = x <= y
            elif op == ">":
                out[i] = x > y
            else:
                out[i] = x >= y
        except TypeError:
            pass
    return out


# -- vectorized MATCH prefix for the general pipeline --------------------

_MAX_MATERIALIZED_ROWS = 20_000


def try_fast_match_rows(executor, clause: A.MatchClause, ctx):
    """Vectorized binding computation for a leading MATCH clause whose
    remaining query is NOT in the pure-vectorized family (MATCH…CREATE,
    MATCH…SET, multi-clause reads). Returns a list of binding dicts for
    the general pipeline, or None to fall back.

    This is the analog of the reference's compound fast path
    (tryFastPathCompoundQuery executor.go:1421): the expensive part of
    `MATCH (a:P {id: $a}), (b:P {id: $b}) CREATE (a)-[:R]->(b)` is the
    lookup, not the write — resolve it through the hash property indexes
    instead of a per-row Python label scan.

    Supports comma-separated paths when at most one path carries
    relationships and paths share no variables (cartesian join).
    """
    if not getattr(executor, "enable_fastpaths", True):
        return None
    if ctx.storage is not executor.storage:
        return None
    catalog = getattr(executor, "columnar", None)
    if catalog is None or clause.optional:
        return None
    paths = clause.paths
    if not paths or len(paths) > 3:
        return None
    seen_vars: set = set()
    n_rel_paths = 0
    for path in paths:
        if not _path_supported(path, seen_vars):
            return None  # unsupported shape or shared vars: general join
        if path.rels:
            n_rel_paths += 1
    if n_rel_paths > 1:
        return None  # same-type edge uniqueness across paths: general
    try:
        rows = _try_point_lookup_rows(catalog, clause, ctx)
        if rows is not None:
            return rows
        bindings = [_match_chain(catalog, p, ctx) for p in paths]
        combined = _cartesian(bindings)
        if combined is None:
            return None
        if clause.where is not None:
            for conj in _split_and(clause.where):
                combined.take(_vec_predicate(conj, combined, catalog, ctx))
        return _materialize_rows(combined, catalog)
    except _Unsupported:
        return None


def _try_point_lookup_rows(catalog, clause: A.MatchClause, ctx):
    """Short-circuit for the write-side hot shape: every comma path is a
    bare single node `(v:Label {prop: $p})`. The general machinery
    (candidate arrays -> cartesian tile/repeat -> column materialize)
    costs ~100us for what is literally two hash-index gets — and this
    is the MATCH half of the reference's Northwind write bench
    (`MATCH (a:P {id:$a}), (b:P {id:$b}) CREATE (a)-[:R]->(b)`).

    Returns row dicts sharing the catalog's node objects (same contract
    as _materialize_rows), or None when any path needs the full path.
    """
    if clause.where is not None:
        return None
    resolved: List[Tuple[str, List[Any]]] = []
    nodes_list = None
    for path in clause.paths:
        if path.rels or len(path.nodes) != 1 or path.path_var:
            return None
        pn = path.nodes[0]
        if (not pn.var or len(pn.labels) != 1 or pn.props is None
                or len(pn.props.items) != 1):
            return None
        k, vexpr = pn.props.items[0]
        v = _const_value(vexpr, ctx)
        if isinstance(v, (list, dict)) or isinstance(v, bool) or v in (0, 1):
            return None  # bool/int-identity or unhashable: general path
        hit = catalog.prop_index(pn.labels[0], k).get(v)
        if hit is None or len(hit) == 0:
            return []  # no match: zero rows, exact semantics
        if nodes_list is None:
            nodes_list = catalog.nodes()
        resolved.append((pn.var, [nodes_list[i] for i in hit.tolist()]))
    # cross product over paths (usually 1 x 1)
    out: List[Dict[str, Any]] = [{}]
    for var, cands in resolved:
        if len(cands) == 1:
            c = cands[0]
            for row in out:
                row[var] = c
        else:
            out = [dict(row, **{var: c}) for row in out for c in cands]
            if len(out) > _MAX_MATERIALIZED_ROWS:
                return None
    return out


def _cartesian(bindings: List[_Bindings]) -> Optional[_Bindings]:
    """Cross-join independent per-path bindings (no shared vars)."""
    if len(bindings) == 1:
        return bindings[0]
    total = 1
    for b in bindings:
        total *= max(b.n_rows, 0)
        if total > _MAX_MATERIALIZED_ROWS:
            return None
    out = _Bindings()
    out.n_rows = total
    if total == 0:
        for b in bindings:
            for k in b.node_cols:
                out.node_cols[k] = np.empty(0, np.int32)
            for k, (t, _v) in b.edge_cols.items():
                out.edge_cols[k] = (t, np.empty(0, np.int32))
        return out
    # repeat/tile index pattern per path
    reps_after = [1] * len(bindings)
    for i in range(len(bindings) - 2, -1, -1):
        reps_after[i] = reps_after[i + 1] * bindings[i + 1].n_rows
    reps_before = [1] * len(bindings)
    for i in range(1, len(bindings)):
        reps_before[i] = reps_before[i - 1] * bindings[i - 1].n_rows
    for i, b in enumerate(bindings):
        idx = np.tile(
            np.repeat(np.arange(b.n_rows, dtype=np.int64), reps_after[i]),
            reps_before[i],
        )
        for k, v in b.node_cols.items():
            out.node_cols[k] = v[idx]
        for k, (t, v) in b.edge_cols.items():
            out.edge_cols[k] = (t, v[idx])
        out.hop_edges.extend((t, v[idx]) for t, v in b.hop_edges)
    return out


def _materialize_rows(b: _Bindings, catalog) -> Optional[List[Dict[str, Any]]]:
    """Binding columns -> general-pipeline row dicts (Node/Edge values)."""
    if b.n_rows > _MAX_MATERIALIZED_ROWS:
        return None  # let the streaming general path handle huge matches
    nodes = catalog.nodes()
    cols: List[Tuple[str, List[Any]]] = []
    for var, rows in b.node_cols.items():
        cols.append((var, [nodes[i] for i in rows.tolist()]))
    for var, (table, erows) in b.edge_cols.items():
        edges = table.edges
        cols.append((var, [edges[i] for i in erows.tolist()]))
    out: List[Dict[str, Any]] = []
    for i in range(b.n_rows):
        out.append({var: vals[i] for var, vals in cols})
    return out


# -- projection / aggregation --------------------------------------------


def _project(executor, catalog, ret: A.ReturnClause, b: _Bindings, ctx,
             CypherResult, plan: Dict[str, Any]):
    has_agg = plan["has_agg"]
    if b.row_weights is not None and not has_agg:
        _bail()  # multiplicity weights are only meaningful under aggregation
    cols = plan["cols"]

    if has_agg:
        out_cols = _aggregate(catalog, ret, b, ctx, plan)
    else:
        out_cols = []
        for item in ret.items:
            out_cols.append(_out_col(item.expr, b, catalog, ctx))
        if ret.distinct:
            from nornicdb_tpu.query.columnar import group_codes

            codes, _ = group_codes(
                [_codeable(c, b, catalog) for c in out_cols]
            )
            first = _first_occurrence(codes)
            out_cols = [c[first] for c in out_cols]

    n = len(out_cols[0]) if out_cols else 0
    order = np.arange(n)
    if ret.order_by:
        order = _order(ret, cols, out_cols, b, catalog, ctx)
        out_cols = [c[order] for c in out_cols]
    if ret.skip is not None:
        k = int(_const_value(ret.skip, ctx))
        out_cols = [c[k:] for c in out_cols]
    if ret.limit is not None:
        k = int(_const_value(ret.limit, ctx))
        out_cols = [c[:k] for c in out_cols]

    py_cols: List[Any] = []
    for col in out_cols:
        if col.dtype == object and len(col) and isinstance(col[0], _NodeRef):
            nodes = catalog.nodes()
            py_cols.append([nodes[v.row] for v in col.tolist()])
        else:
            # handed to CypherResult as-is; np scalars become natives
            # lazily on first row/column access (benches and servers
            # that stream column-major never pay an eager tolist)
            py_cols.append(col)
    if not py_cols:
        return CypherResult(columns=cols, rows=[])
    return CypherResult(columns=cols, col_data=py_cols)


class _NodeRef:
    """Marker wrapping a global node row so projection can materialize the
    Node object only for rows that survive ORDER BY/LIMIT."""

    __slots__ = ("row",)

    def __init__(self, row: int):
        self.row = row


def _out_col(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    if isinstance(e, A.Var):
        if e.name in b.node_cols:
            rows = b.node_cols[e.name]
            out = np.empty(len(rows), dtype=object)
            for i, r in enumerate(rows.tolist()):
                out[i] = _NodeRef(r)
            return out
        _bail()
    return _vec_col(e, b, catalog, ctx)


def _codeable(col: np.ndarray, b: _Bindings, catalog) -> np.ndarray:
    """Column usable as a grouping key (NodeRefs become row ints)."""
    if len(col) and isinstance(col[0], _NodeRef):
        return np.asarray([v.row for v in col.tolist()], dtype=np.int64)
    return col


def _first_occurrence(codes: np.ndarray) -> np.ndarray:
    """Row index of the first occurrence of each group code, in
    first-encounter order (matches the general path's insertion order).
    np.unique's return_index gives first occurrences (stable sort);
    ufunc.at is an order of magnitude slower here."""
    _, first = np.unique(codes, return_index=True)
    return np.sort(first)


def _dense_codes(rows: np.ndarray, n_max: int) -> Tuple[np.ndarray, np.ndarray]:
    """(uniq_values, inverse_codes) for an int array with values in
    [0, n_max) — lookup-table based, no sort (argsort in np.unique is
    the aggregation hot spot at scale)."""
    flags = np.zeros(n_max, dtype=bool)
    flags[rows] = True
    uniq = np.nonzero(flags)[0]
    lut = np.zeros(n_max, dtype=np.int64)
    lut[uniq] = np.arange(len(uniq), dtype=np.int64)
    return uniq, lut[rows]


def _dense_ok(domain: int, n_rows: int, floor: int = 0) -> bool:
    """Dense lookup-table strategy budget: allocate O(domain) scratch
    only when the domain is comparable to the row count — a 20-row group
    on a 50M-node graph must not allocate graph-sized scratch. Single
    definition so every dense/sparse strategy switch tunes together."""
    return 0 < domain <= max(floor, 4 * n_rows + 4096)


def _int_codes(rows: np.ndarray, n_max: int) -> Tuple[np.ndarray, np.ndarray]:
    """Strategy switch: dense lookup when the value domain is comparable
    to the row count (O(n_max) allocation), else sort-based np.unique."""
    if _dense_ok(n_max, len(rows)):
        return _dense_codes(rows, n_max)
    return np.unique(rows, return_inverse=True)


def _group_code_col(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    """Dense int64 group codes for one grouping-key expression.

    Property keys are routed through the entity *row* column first
    (vectorized int unique), then the small unique-row value table is
    deduplicated by value — Cypher groups by value, and two entities can
    share one — so the Python-level work is O(distinct entities), not
    O(match rows)."""
    from nornicdb_tpu.query.columnar import _unique_inverse

    if isinstance(e, A.Prop) and isinstance(e.target, A.Var):
        name = e.target.name
        cm = b.cand_map.get(name)
        if cm is not None and _dense_ok(len(cm[0]), len(cm[1])):
            uniq_rows, inv = cm
            vals = catalog.node_prop_col(e.name)[uniq_rows]
        elif name in b.node_cols:
            rows = b.node_cols[name]
            uniq_rows, inv = _int_codes(rows, catalog.n_nodes())
            vals = catalog.node_prop_col(e.name)[uniq_rows]
        elif name in b.edge_cols:
            table, erows = b.edge_cols[name]
            uniq_rows, inv = _int_codes(erows, len(table))
            vals = table.prop_col(e.name)[uniq_rows]
        else:
            _bail()
        _, vcodes = _unique_inverse(vals)
        return vcodes[inv]
    if isinstance(e, A.Var):
        cm = b.cand_map.get(e.name)
        if cm is not None and _dense_ok(len(cm[0]), len(cm[1])):
            return cm[1].astype(np.int64, copy=False)
        if e.name in b.node_cols:
            _, inv = _int_codes(b.node_cols[e.name], catalog.n_nodes())
            return inv
        if e.name in b.edge_cols:
            table, erows = b.edge_cols[e.name]
            _, inv = _int_codes(erows, len(table))
            return inv
        _bail()
    # anything else: evaluate the value column and hash it
    col = _vec_col(e, b, catalog, ctx)
    _, codes = _unique_inverse(col)
    return codes


def _combine_codes(code_cols: List[np.ndarray]) -> np.ndarray:
    combined = np.zeros(len(code_cols[0]), dtype=np.int64)
    span = 1
    for c in code_cols:
        width = int(c.max()) + 1 if len(c) else 1
        combined = combined * width + c
        span *= width
    if _dense_ok(span, len(combined)):
        # dense lookup beats the sort inside np.unique
        _, codes = _dense_codes(combined, span)
        return codes
    _, codes = np.unique(combined, return_inverse=True)
    return codes


def _rows_are_value_groups(group_items, b: _Bindings, catalog) -> bool:
    """True when binding rows are already exactly the output groups:
    rows are pairwise-distinct over the cand_map codes (co-occurrence
    guarantees this), every group key is a property of a cand_map var,
    the keys cover all cand_map vars, and each key's values over its
    candidates are non-null and injective — then value-grouping cannot
    merge anything and the whole coding machinery is an identity."""
    if not b.rows_are_groups or not group_items or not b.cand_map:
        return False
    for cands, _codes in b.cand_map.values():
        if not _dense_ok(len(cands), b.n_rows):
            return False  # candidate table much larger than the rows
    vars_used = set()
    for item in group_items:
        e = item.expr
        if not (isinstance(e, A.Prop) and isinstance(e.target, A.Var)
                and e.target.name in b.cand_map):
            return False
        vars_used.add(e.target.name)
        cands, _codes = b.cand_map[e.target.name]
        if not catalog.prop_injective_over(e.name, cands):
            return False
    return vars_used == set(b.cand_map)


def _aggregate(catalog, ret: A.ReturnClause, b: _Bindings, ctx,
               plan: Dict[str, Any]) -> List[np.ndarray]:
    agg_flags = plan["agg_flags"]
    group_items = [i for i, f in zip(ret.items, agg_flags) if not f]
    identity_groups = _rows_are_value_groups(group_items, b, catalog)
    if identity_groups:
        codes = np.arange(b.n_rows, dtype=np.int64)
        first = codes
        n_groups = b.n_rows
    else:
        key_cols = [
            _group_code_col(i.expr, b, catalog, ctx) for i in group_items
        ]
        if key_cols:
            codes = _combine_codes(key_cols)
            first = _first_occurrence(codes)
            # remap codes so group ids follow first-encounter order
            # (matches the general path's insertion-ordered groups);
            # `first` is sorted, so codes[first] lists groups in
            # encounter order.
            rank = np.empty(len(first), dtype=np.int64)
            rank[codes[first]] = np.arange(len(first))
            codes = rank[codes]
            n_groups = len(first)
        else:
            codes = np.zeros(b.n_rows, dtype=np.int64)
            first = (np.zeros(1, dtype=np.int64) if b.n_rows
                     else np.empty(0, np.int64))
            n_groups = 1  # global aggregation has exactly one output row

    out: List[np.ndarray] = []
    for item, is_agg in zip(ret.items, agg_flags):
        if not is_agg:
            full = _out_col(item.expr, b, catalog, ctx)
            out.append(full if identity_groups else full[first])
        else:
            out.append(_agg_expr(item.expr, b, catalog, ctx, codes,
                                 n_groups, identity_groups))
    return out


def _agg_expr(
    e: A.Expr, b: _Bindings, catalog, ctx, codes: np.ndarray, n_groups: int,
    identity: bool = False,
) -> np.ndarray:
    """Per-group value of an aggregate-bearing expression."""
    if isinstance(e, A.FuncCall) and e.name in _AGG_NAMES:
        return _agg_leaf(e, b, catalog, ctx, codes, n_groups, identity)
    if isinstance(e, A.Binary) and e.op in ("+", "-", "*", "/", "%"):
        l = _agg_expr(e.left, b, catalog, ctx, codes, n_groups,
                      identity).tolist()
        r = _agg_expr(e.right, b, catalog, ctx, codes, n_groups,
                      identity).tolist()
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            lv, rv = l[i], r[i]
            if lv is None or rv is None:
                out[i] = None
                continue
            if e.op == "+":
                out[i] = lv + rv
            elif e.op == "-":
                out[i] = lv - rv
            elif e.op == "*":
                out[i] = lv * rv
            elif e.op == "/":
                if rv == 0:
                    _bail()
                if isinstance(lv, int) and isinstance(rv, int):
                    q = lv // rv
                    if q < 0 and lv % rv != 0:
                        q += 1
                    out[i] = q
                else:
                    out[i] = lv / rv
            else:
                if rv == 0:
                    _bail()
                mres = abs(lv) % abs(rv)
                out[i] = mres if lv >= 0 else -mres
        return out
    if isinstance(e, (A.Literal, A.Param)):
        v = _const_value(e, ctx)
        out = np.empty(n_groups, dtype=object)
        out[:] = v
        return out
    if isinstance(e, A.FuncCall) and e.name in ("tofloat", "tointeger"):
        inner = _agg_expr(e.args[0], b, catalog, ctx, codes, n_groups,
                          identity).tolist()
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            v = inner[i]
            if v is None:
                out[i] = None
            elif e.name == "tofloat":
                out[i] = float(v)
            else:
                out[i] = int(v)
        return out
    if isinstance(e, A.FuncCall) and e.name == "round":
        inner = _agg_expr(e.args[0], b, catalog, ctx, codes, n_groups,
                          identity).tolist()
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            v = inner[i]
            out[i] = None if v is None else float(round(v))
        return out
    _bail()


def _agg_leaf(
    e: A.FuncCall, b: _Bindings, catalog, ctx, codes: np.ndarray,
    n_groups: int, identity: bool = False,
) -> np.ndarray:
    name = e.name
    w = b.row_weights

    def _row_count(sel_codes, sel_w):
        if identity and sel_codes is codes:
            # rows ARE the groups (codes == arange): the per-group count
            # is the row weight itself — no bincount pass
            if sel_w is None:
                return np.ones(n_groups, dtype=np.int64)
            return sel_w.astype(np.int64, copy=False)
        if sel_w is None:
            return np.bincount(sel_codes, minlength=n_groups)[:n_groups]
        return np.bincount(
            sel_codes, weights=sel_w, minlength=n_groups
        )[:n_groups].astype(np.int64)

    if name == "count" and e.star:
        return _row_count(codes, w)  # numeric column; lazy-native later
    if not e.args:
        _bail()
    arg = e.args[0]
    if (
        name == "count"
        and e.distinct
        and isinstance(arg, A.Var)
        and arg.name in b.stripped_distinct_counts
    ):
        # per-row counts of DISTINCT folded-out values (strip view nnz).
        # Summing them per group is exact only while no two rows of one
        # group can share a member — rows are distinct group nodes, so
        # any group holding >1 row (duplicate group-key values) may
        # overlap and must fall back to real expansion.
        per_group = np.bincount(codes, minlength=n_groups)[:n_groups]
        if len(per_group) and per_group.max() > 1:
            _bail()
        cnt = np.bincount(
            codes,
            weights=b.stripped_distinct_counts[arg.name].astype(np.float64),
            minlength=n_groups,
        )[:n_groups].astype(np.int64)
        return cnt
    if (
        name == "count"
        and isinstance(arg, A.Var)
        and arg.name in b.stripped_vars
    ):
        # the folded-out hop target: bound (non-null) in every match row
        # a binding row stands for, so count(var) == weighted row count
        # (OPTIONAL MATCH strips carry their own channel: raw degree,
        # which is 0 for null-extended rows)
        if e.distinct:
            _bail()
        vw = b.stripped_var_weights.get(arg.name, w)
        return _row_count(codes, vw)
    if isinstance(arg, A.Var) and arg.name in b.node_cols:
        vals = b.node_cols[arg.name].astype(np.int64)
        nonnull = np.ones(b.n_rows, dtype=bool)
        values_obj = None
    else:
        values_obj = _vec_col(arg, b, catalog, ctx)
        nonnull = np.array([x is not None for x in values_obj.tolist()], dtype=bool)
        vals = None

    if name == "count":
        if e.distinct:
            if vals is not None and len(vals):
                # node rows are already small dense ints: flag-table
                # distinct count, no sort, no re-coding pass
                k = int(vals.max()) + 1
                span = n_groups * k
                if _dense_ok(span, len(vals), floor=1_000_000):
                    flags = np.zeros(span, dtype=bool)
                    flags[codes * k + vals] = True
                    nz = np.flatnonzero(flags)
                    cnt = np.bincount(nz // k, minlength=n_groups)[:n_groups]
                    return cnt
            if vals is None:
                from nornicdb_tpu.query.columnar import group_codes as _gc

                vcodes, _ = _gc([values_obj])
            else:
                _, vcodes = _int_codes(
                    vals, int(vals.max()) + 1 if len(vals) else 1)
            sel = nonnull
            pair = codes[sel] * (int(vcodes.max()) + 1 if len(vcodes) else 1) + vcodes[sel]
            uniq_pairs = np.unique(pair)
            denom = int(vcodes.max()) + 1 if len(vcodes) else 1
            grp = uniq_pairs // denom
            cnt = np.bincount(grp, minlength=n_groups)[:n_groups]
        else:
            cnt = _row_count(codes[nonnull], w[nonnull] if w is not None else None)
        return cnt

    if values_obj is None:
        _bail()

    if name == "collect":
        if w is not None:
            _bail()  # collect is order/multiplicity sensitive
        src = values_obj
        sel = nonnull
        if e.distinct:
            from nornicdb_tpu.query.columnar import group_codes as _gc

            vcodes, _ = _gc([values_obj])
            seen = set()
            keep = np.zeros(b.n_rows, dtype=bool)
            for i in range(b.n_rows):
                if not nonnull[i]:
                    continue
                key = (int(codes[i]), int(vcodes[i]))
                if key in seen:
                    continue
                seen.add(key)
                keep[i] = True
            sel = keep
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            out[i] = []
        idxs = np.nonzero(sel)[0]
        for i in idxs.tolist():
            out[codes[i]].append(values_obj[i])
        return out

    f = _as_float(values_obj)
    if f is None:
        if name in ("min", "max"):
            # non-numeric min/max (e.g. strings): python per-group
            out = np.empty(n_groups, dtype=object)
            out[:] = None
            for i in range(b.n_rows):
                if not nonnull[i]:
                    continue
                g = codes[i]
                v = values_obj[i]
                try:
                    if out[g] is None or (
                        v < out[g] if name == "min" else v > out[g]
                    ):
                        out[g] = v
                except TypeError:
                    _bail()
            return out
        _bail()
    fvals, fmask = f
    if e.distinct:
        _bail()
    safe = np.where(fmask, fvals, 0.0)
    if w is not None:
        safe = safe * w  # multiplicity-weighted sums
    cnt = np.bincount(codes[fmask], minlength=n_groups)[:n_groups]
    if name == "avg" and w is not None:
        cnt = np.bincount(
            codes[fmask], weights=w[fmask], minlength=n_groups
        )[:n_groups]
    if name == "sum":
        s = np.bincount(codes, weights=safe, minlength=n_groups)[:n_groups]
        out = np.empty(n_groups, dtype=object)
        all_int = all(
            isinstance(x, int) and not isinstance(x, bool)
            for x in values_obj.tolist()
            if x is not None
        )
        out[:] = (s.astype(np.int64) if all_int else s).tolist()
        return out
    if name == "avg":
        s = np.bincount(codes, weights=safe, minlength=n_groups)[:n_groups]
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            out[i] = float(s[i] / cnt[i]) if cnt[i] else None
        return out
    if name in ("min", "max"):
        init = np.inf if name == "min" else -np.inf
        acc = np.full(n_groups, init, dtype=np.float64)
        op = np.minimum if name == "min" else np.maximum
        op.at(acc, codes[fmask], fvals[fmask])
        out = np.empty(n_groups, dtype=object)
        all_int = all(
            isinstance(x, int) and not isinstance(x, bool)
            for x in values_obj.tolist()
            if x is not None
        )
        for i in range(n_groups):
            if cnt[i] == 0:
                out[i] = None
            else:
                out[i] = int(acc[i]) if all_int else float(acc[i])
        return out
    _bail()


def _order(ret, cols, out_cols, b, catalog, ctx) -> np.ndarray:
    """Row order for ORDER BY over the projected columns (key-list sort
    shared with the WITH pipeline via _order_from_keys)."""
    n = len(out_cols[0]) if out_cols else 0
    keys: List[Tuple[np.ndarray, bool]] = []
    for expr, desc in ret.order_by:
        col = _order_key(expr, ret, cols, out_cols, b, catalog, ctx)
        keys.append((col, desc))
    return _order_from_keys(keys, n)


def _order_key(expr, ret, cols, out_cols, b, catalog, ctx) -> np.ndarray:
    # 1. ORDER BY <alias or column name>
    if isinstance(expr, A.Var) and expr.name in cols:
        return out_cols[cols.index(expr.name)]
    # 2. ORDER BY <projected expression> (AST equality via dataclass eq)
    for i, item in enumerate(ret.items):
        if item.expr == expr:
            return out_cols[i]
    # 3. non-agg queries: any vectorizable expression over bindings.
    # Not under DISTINCT: the projection was already reduced to first
    # occurrences, while bindings still hold every row — the key column
    # would be the wrong length (and the wrong rows). General path owns
    # order-by-unprojected-expression + DISTINCT semantics.
    from nornicdb_tpu.query.executor import _contains_agg

    if not ret.distinct and not any(
            _contains_agg(i.expr) for i in ret.items):
        return _vec_col(expr, b, catalog, ctx)
    _bail()
