"""Streaming fast paths: shape-specialized executors that bypass the
general pipeline.

Reference: the reference's perf story is mostly *avoiding* general
execution — tryFastPathCompoundQuery (executor.go:1421), ExecuteOptimized
(optimized_executors.go:25-282), fast aggregations
(traversal_fast_agg.go:15,57), revenue-by-product
(match_with_rel_fast.go:10), namespace-bypass (storage_fastpaths.go).

Two tiers here:

1. O(1)/indexed count shapes answered straight from engine counters
   (`_try_count_shapes`).
2. A *vectorized chain family* (`_try_vectorized`): single-path MATCH of
   fixed-length relationship chains + simple WHERE + projection or
   grouped aggregation + ORDER BY/SKIP/LIMIT, compiled onto the columnar
   catalog (query/columnar.py) as batched numpy array ops instead of the
   row-at-a-time interpreter. This is the TPU-first redesign of the
   reference's per-shape Go executors: one compiler for the whole LDBC/
   Northwind family (message content lookup, recent messages of friends,
   avg friends per city, tag co-occurrence, supplier/category counts,
   revenue per product) rather than a dozen hand-written shapes.

Any unsupported feature falls through (return None) to the general
executor — parity between paths is enforced by tests/test_fastpath_parity.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nornicdb_tpu.query import ast as A


_AGG_NAMES = {"count", "sum", "avg", "min", "max", "collect"}


def try_fast_path(executor, q: A.Query, ctx) -> Optional["CypherResult"]:
    if not getattr(executor, "enable_fastpaths", True):
        return None
    r = _try_count_shapes(executor, q, ctx)
    if r is not None:
        return r
    # Vectorized paths read through the executor's columnar catalog, which
    # snapshots executor.storage — bail out when this query runs against a
    # different engine view (PROFILE counting proxy, explicit txn overlay).
    if ctx.storage is not executor.storage:
        return None
    catalog = getattr(executor, "columnar", None)
    if catalog is None:
        return None
    try:
        return _try_vectorized(executor, catalog, q, ctx)
    except _Unsupported:
        return None


# -- tier 1: engine-counter shapes ---------------------------------------


def _try_count_shapes(executor, q: A.Query, ctx) -> Optional["CypherResult"]:
    from nornicdb_tpu.query.executor import CypherResult

    clauses = q.clauses
    if len(clauses) != 2:
        return None
    m, r = clauses[0], clauses[1]
    if not isinstance(m, A.MatchClause) or not isinstance(r, A.ReturnClause):
        return None
    if m.optional or m.where is not None or len(m.paths) != 1:
        return None
    if r.distinct or r.order_by or r.skip or r.limit or r.star:
        return None
    if len(r.items) != 1:
        return None
    item = r.items[0]
    e = item.expr
    if not (isinstance(e, A.FuncCall) and e.name == "count" and not e.distinct):
        return None
    path = m.paths[0]
    col = item.alias or item.text

    # MATCH (n[:Label]) RETURN count(n|*)
    if len(path.nodes) == 1 and not path.rels:
        pn = path.nodes[0]
        if pn.props is not None:
            return None
        if not (
            e.star
            or (len(e.args) == 1 and isinstance(e.args[0], A.Var)
                and e.args[0].name == pn.var)
        ):
            return None
        if not pn.labels:
            # O(1) engine count (reference: count fast path)
            return CypherResult(columns=[col], rows=[[ctx.storage.count_nodes()]])
        if len(pn.labels) == 1:
            counter = getattr(ctx.storage, "count_nodes_by_label", None)
            if counter is not None:
                n = counter(pn.labels[0])
            else:
                n = len(ctx.storage.get_nodes_by_label(pn.labels[0]))
            return CypherResult(columns=[col], rows=[[n]])
        return None

    # MATCH ()-[r[:TYPE]]->() RETURN count(r|*)
    if len(path.nodes) == 2 and len(path.rels) == 1:
        pr = path.rels[0]
        n0, n1 = path.nodes
        if (
            n0.labels or n1.labels or n0.props or n1.props or pr.props
            or n0.var or n1.var
        ):
            return None
        if pr.min_hops != 1 or pr.max_hops != 1:
            return None
        if pr.direction == "both":
            return None  # both-direction counts each edge twice; general path
        counts_ok = e.star or (
            len(e.args) == 1 and isinstance(e.args[0], A.Var)
            and e.args[0].name == pr.var
        )
        if not counts_ok:
            return None
        if not pr.types:
            return CypherResult(columns=[col], rows=[[ctx.storage.count_edges()]])
        total = sum(len(ctx.storage.get_edges_by_type(t)) for t in pr.types)
        return CypherResult(columns=[col], rows=[[total]])

    return None


# -- tier 2: vectorized chain family -------------------------------------


class _Unsupported(Exception):
    """Shape outside the vectorized family — fall back to general path."""


def _bail() -> None:
    raise _Unsupported


def _path_supported(path: A.PatternPath, seen_vars: set) -> bool:
    """Shared shape gate for the vectorized chain family (used by both
    the pure-vectorized path and the MATCH-prefix path — one definition,
    so supported shapes cannot drift apart)."""
    if path.path_var or not path.nodes or len(path.nodes) > 4:
        return False
    for pr in path.rels:
        if pr.min_hops != 1 or pr.max_hops != 1 or pr.props is not None:
            return False
        if pr.direction not in ("out", "in"):
            return False
        if len(pr.types) != 1:
            return False
    for pn in path.nodes:
        if pn.var:
            if pn.var in seen_vars:
                return False
            seen_vars.add(pn.var)
    for pr in path.rels:
        if pr.var:
            if pr.var in seen_vars:
                return False
            seen_vars.add(pr.var)
    return True


class _Bindings:
    """Parallel binding columns over match rows.

    node_cols: var -> int32 global node rows
    edge_cols: var/slot -> (EdgeTable, int32 edge rows)
    """

    def __init__(self):
        self.node_cols: Dict[str, np.ndarray] = {}
        self.edge_cols: Dict[str, Tuple[Any, np.ndarray]] = {}
        self.hop_edges: List[Tuple[str, np.ndarray]] = []  # (etype, edge rows)
        self.n_rows = 0

    def take(self, sel: np.ndarray) -> None:
        """Keep only selected row positions (index array or bool mask)."""
        self.node_cols = {k: v[sel] for k, v in self.node_cols.items()}
        self.edge_cols = {k: (t, v[sel]) for k, (t, v) in self.edge_cols.items()}
        self.hop_edges = [(t, v[sel]) for t, v in self.hop_edges]
        some = next(iter(self.node_cols.values()), None)
        if some is None and self.hop_edges:
            some = self.hop_edges[0][1]
        if some is not None:
            self.n_rows = len(some)
        elif sel.dtype == bool:
            self.n_rows = int(sel.sum())
        else:
            self.n_rows = len(sel)


def _try_vectorized(executor, catalog, q: A.Query, ctx) -> Optional["CypherResult"]:
    from nornicdb_tpu.query.executor import CypherResult

    clauses = q.clauses
    if len(clauses) != 2:
        return None
    m, ret = clauses[0], clauses[1]
    if not isinstance(m, A.MatchClause) or not isinstance(ret, A.ReturnClause):
        return None
    if m.optional or len(m.paths) != 1:
        return None
    path = m.paths[0]
    if ret.star:
        return None
    if not _path_supported(path, set()):
        return None

    b = _match_chain(catalog, path, ctx)
    if b is None:
        return None  # empty graph handled below via n_rows == 0

    # WHERE
    if m.where is not None:
        for conj in _split_and(m.where):
            mask = _vec_predicate(conj, b, catalog, ctx)
            b.take(mask)

    return _project(executor, catalog, ret, b, ctx, CypherResult)


def _match_chain(catalog, path: A.PatternPath, ctx) -> Optional[_Bindings]:
    from nornicdb_tpu.query.columnar import expand_hop

    nodes, rels = path.nodes, path.rels
    n_nodes_total = catalog.n_nodes()

    # candidate rows for each pattern node (None == unconstrained)
    def candidates(pn: A.PatternNode) -> Optional[np.ndarray]:
        rows: Optional[np.ndarray] = None
        if pn.labels:
            rows = catalog.label_rows(pn.labels[0])
            for lbl in pn.labels[1:]:
                rows = rows[catalog.label_mask(lbl)[rows]]
        if pn.props is not None:
            items = list(pn.props.items)
            if pn.labels and items:
                # point lookup via the hash property index (reference:
                # LDBC message-content-lookup path, storage_fastpaths.go)
                k0, vexpr0 = items[0]
                hit = catalog.prop_index(pn.labels[0], k0).get(
                    _const_value(vexpr0, ctx)
                )
                hit = hit if hit is not None else np.empty(0, np.int32)
                mask = catalog.label_mask(pn.labels[0])  # noqa: F841 (built)
                rows = (
                    np.intersect1d(rows, hit).astype(np.int32)
                    if len(pn.labels) > 1
                    else hit
                )
                items = items[1:]
            for k, vexpr in items:
                v = _const_value(vexpr, ctx)
                base = rows if rows is not None else np.arange(
                    n_nodes_total, dtype=np.int32
                )
                rows = base[_vec_eq(catalog.node_prop_col(k)[base], v)]
        return rows

    cand = [candidates(pn) for pn in nodes]

    def size(i: int) -> int:
        return len(cand[i]) if cand[i] is not None else n_nodes_total

    anchor = min(range(len(nodes)), key=size)
    rows0 = cand[anchor]
    if rows0 is None:
        rows0 = np.arange(n_nodes_total, dtype=np.int32)

    b = _Bindings()
    slot_cols: List[Optional[np.ndarray]] = [None] * len(nodes)
    slot_cols[anchor] = rows0.astype(np.int32, copy=False)

    def take_all(sel) -> None:
        for i in range(len(nodes)):
            if slot_cols[i] is not None:
                slot_cols[i] = slot_cols[i][sel]
        b.edge_cols = {k: (t, x[sel]) for k, (t, x) in b.edge_cols.items()}
        b.hop_edges = [(t, x[sel]) for t, x in b.hop_edges]

    def expand(frm: int, to: int, rel_idx: int) -> None:
        pr = rels[rel_idx]
        table = catalog.edge_table(pr.types[0])
        forward = to > frm
        # pr.direction 'out': edge start=nodes[rel_idx], end=nodes[rel_idx+1]
        if pr.direction == "out":
            direction = "out" if forward else "in"
        else:
            direction = "in" if forward else "out"
        rep, edge_rows, targets = expand_hop(
            table, slot_cols[frm], direction, n_nodes_total
        )
        # replicate existing columns to the expanded row set
        for i in range(len(nodes)):
            if slot_cols[i] is not None:
                slot_cols[i] = slot_cols[i][rep]
        b.edge_cols = {k: (t, x[rep]) for k, (t, x) in b.edge_cols.items()}
        b.hop_edges = [(t, x[rep]) for t, x in b.hop_edges]
        slot_cols[to] = targets
        if pr.var:
            b.edge_cols[pr.var] = (table, edge_rows)
        b.hop_edges.append((pr.types[0], edge_rows))
        # constrain targets by the `to` node's label/prop candidate set
        if cand[to] is not None:
            keep = np.zeros(n_nodes_total, dtype=bool)
            keep[cand[to]] = True
            take_all(keep[targets])
        # Cypher relationship uniqueness: a match may not reuse an edge.
        # Only same-type hops can collide (edge rows are per-type).
        latest = len(b.hop_edges) - 1
        for j in range(latest):
            if b.hop_edges[j][0] == pr.types[0]:
                take_all(b.hop_edges[latest][1] != b.hop_edges[j][1])

    for to in range(anchor + 1, len(nodes)):
        expand(to - 1, to, to - 1)
    for to in range(anchor - 1, -1, -1):
        expand(to + 1, to, to)

    for i, pn in enumerate(nodes):
        if pn.var:
            b.node_cols[pn.var] = slot_cols[i]
    b.n_rows = len(slot_cols[anchor]) if slot_cols[anchor] is not None else 0
    return b


def _const_value(e: A.Expr, ctx) -> Any:
    if isinstance(e, A.Literal):
        return e.value
    if isinstance(e, A.Param):
        if e.name not in ctx.params:
            _bail()
        return ctx.params[e.name]
    _bail()


def _index_key(v: Any) -> Any:
    # the prop_index stores raw property values; ints/floats hash-equal
    return v


def _split_and(e: A.Expr) -> List[A.Expr]:
    if isinstance(e, A.Binary) and e.op == "AND":
        return _split_and(e.left) + _split_and(e.right)
    return [e]


def _vec_eq(col: np.ndarray, v: Any) -> np.ndarray:
    """Null-safe elementwise equality (null -> no match)."""
    if v is None:
        return np.zeros(len(col), dtype=bool)
    out = np.zeros(len(col), dtype=bool)
    for i, x in enumerate(col.tolist()):
        if x is None:
            continue
        if isinstance(x, bool) != isinstance(v, bool):
            continue
        try:
            out[i] = x == v
        except TypeError:
            pass
    return out


def _as_float(col: np.ndarray) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(float64 values, valid mask) if all non-null entries numeric."""
    if col.dtype != object:
        f = col.astype(np.float64, copy=False)
        return f, np.ones(len(col), dtype=bool)
    vals = np.empty(len(col), dtype=np.float64)
    mask = np.zeros(len(col), dtype=bool)
    for i, x in enumerate(col.tolist()):
        if x is None:
            vals[i] = np.nan
            continue
        if isinstance(x, bool) or not isinstance(x, (int, float)):
            return None
        vals[i] = float(x)
        mask[i] = True
    return vals, mask


def _vec_col(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    """Evaluate an expression to a value column over binding rows."""
    if isinstance(e, A.Prop) and isinstance(e.target, A.Var):
        name = e.target.name
        if name in b.node_cols:
            return catalog.node_prop_col(e.name)[b.node_cols[name]]
        if name in b.edge_cols:
            table, rows = b.edge_cols[name]
            return table.prop_col(e.name)[rows]
        _bail()
    if isinstance(e, (A.Literal, A.Param)):
        v = _const_value(e, ctx)
        out = np.empty(b.n_rows, dtype=object)
        out[:] = v
        return out
    if isinstance(e, A.Binary) and e.op in ("+", "-", "*", "/"):
        lcol = _vec_col(e.left, b, catalog, ctx)
        rcol = _vec_col(e.right, b, catalog, ctx)
        lf = _as_float(lcol)
        rf = _as_float(rcol)
        if lf is None or rf is None:
            _bail()
        lv, lm = lf
        rv, rm = rf
        with np.errstate(invalid="ignore", divide="ignore"):
            if e.op == "+":
                out = lv + rv
            elif e.op == "-":
                out = lv - rv
            elif e.op == "*":
                out = lv * rv
            else:
                out = lv / rv
        res = np.empty(b.n_rows, dtype=object)
        valid = lm & rm
        for i in range(b.n_rows):
            res[i] = float(out[i]) if valid[i] else None
        return res
    _bail()


def _vec_predicate(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    """Boolean mask over binding rows for one WHERE conjunct."""
    if isinstance(e, A.Binary):
        op = e.op
        # node-var inequality: t1 <> t2 (tag co-occurrence shape)
        if (
            op in ("<>", "=")
            and isinstance(e.left, A.Var)
            and isinstance(e.right, A.Var)
            and e.left.name in b.node_cols
            and e.right.name in b.node_cols
        ):
            same = b.node_cols[e.left.name] == b.node_cols[e.right.name]
            return same if op == "=" else ~same
        if op in ("=", "<>", "<", "<=", ">", ">="):
            lcol = (
                _vec_col(e.left, b, catalog, ctx)
                if not _is_const(e.left) else None
            )
            rcol = (
                _vec_col(e.right, b, catalog, ctx)
                if not _is_const(e.right) else None
            )
            if lcol is None and rcol is None:
                _bail()
            if lcol is None:
                # const OP col  ->  col (flip) OP const
                flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
                op = flip.get(op, op)
                lcol = rcol
                rcol = None
                const = _const_value(e.left, ctx)
            elif rcol is None:
                const = _const_value(e.right, ctx)
            else:
                return _vec_cmp_cols(lcol, rcol, op)
            return _vec_cmp_const(lcol, op, const)
        if op == "IN":
            lcol = _vec_col(e.left, b, catalog, ctx)
            vals = _const_value(e.right, ctx)
            if not isinstance(vals, list):
                _bail()
            out = np.zeros(b.n_rows, dtype=bool)
            vset = set()
            unhashable = []
            for v in vals:
                try:
                    vset.add(v)
                except TypeError:
                    unhashable.append(v)
            for i, x in enumerate(lcol.tolist()):
                if x is None:
                    continue
                try:
                    out[i] = x in vset or x in unhashable
                except TypeError:
                    pass
            return out
        if op in ("STARTS WITH", "ENDS WITH", "CONTAINS"):
            lcol = _vec_col(e.left, b, catalog, ctx)
            v = _const_value(e.right, ctx)
            if not isinstance(v, str):
                _bail()
            out = np.zeros(b.n_rows, dtype=bool)
            for i, x in enumerate(lcol.tolist()):
                if not isinstance(x, str):
                    continue
                if op == "STARTS WITH":
                    out[i] = x.startswith(v)
                elif op == "ENDS WITH":
                    out[i] = x.endswith(v)
                else:
                    out[i] = v in x
            return out
    if isinstance(e, A.LabelCheck):
        if e.var not in b.node_cols:
            _bail()
        mask = np.ones(b.n_rows, dtype=bool)
        for lbl in e.labels:
            mask &= catalog.label_mask(lbl)[b.node_cols[e.var]]
        return mask
    if isinstance(e, A.IsNull):
        col = _vec_col(e.operand, b, catalog, ctx)
        isnull = np.array([x is None for x in col.tolist()], dtype=bool)
        return ~isnull if e.negated else isnull
    _bail()


def _is_const(e: A.Expr) -> bool:
    return isinstance(e, (A.Literal, A.Param))


def _vec_cmp_const(col: np.ndarray, op: str, v: Any) -> np.ndarray:
    if op == "=":
        return _vec_eq(col, v)
    if op == "<>":
        eq = _vec_eq(col, v)
        nonnull = np.array([x is not None for x in col.tolist()], dtype=bool)
        return nonnull & ~eq
    # ordering comparisons: numeric lane when possible
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        f = _as_float(col)
        if f is not None:
            vals, mask = f
            with np.errstate(invalid="ignore"):
                if op == "<":
                    res = vals < v
                elif op == "<=":
                    res = vals <= v
                elif op == ">":
                    res = vals > v
                else:
                    res = vals >= v
            return res & mask
    out = np.zeros(len(col), dtype=bool)
    for i, x in enumerate(col.tolist()):
        if x is None:
            continue
        try:
            if op == "<":
                out[i] = x < v
            elif op == "<=":
                out[i] = x <= v
            elif op == ">":
                out[i] = x > v
            else:
                out[i] = x >= v
        except TypeError:
            pass
    return out


def _vec_cmp_cols(lcol: np.ndarray, rcol: np.ndarray, op: str) -> np.ndarray:
    out = np.zeros(len(lcol), dtype=bool)
    for i, (x, y) in enumerate(zip(lcol.tolist(), rcol.tolist())):
        if x is None or y is None:
            continue
        try:
            if op == "=":
                out[i] = x == y and isinstance(x, bool) == isinstance(y, bool)
            elif op == "<>":
                out[i] = not (x == y and isinstance(x, bool) == isinstance(y, bool))
            elif op == "<":
                out[i] = x < y
            elif op == "<=":
                out[i] = x <= y
            elif op == ">":
                out[i] = x > y
            else:
                out[i] = x >= y
        except TypeError:
            pass
    return out


# -- vectorized MATCH prefix for the general pipeline --------------------

_MAX_MATERIALIZED_ROWS = 20_000


def try_fast_match_rows(executor, clause: A.MatchClause, ctx):
    """Vectorized binding computation for a leading MATCH clause whose
    remaining query is NOT in the pure-vectorized family (MATCH…CREATE,
    MATCH…SET, multi-clause reads). Returns a list of binding dicts for
    the general pipeline, or None to fall back.

    This is the analog of the reference's compound fast path
    (tryFastPathCompoundQuery executor.go:1421): the expensive part of
    `MATCH (a:P {id: $a}), (b:P {id: $b}) CREATE (a)-[:R]->(b)` is the
    lookup, not the write — resolve it through the hash property indexes
    instead of a per-row Python label scan.

    Supports comma-separated paths when at most one path carries
    relationships and paths share no variables (cartesian join).
    """
    if not getattr(executor, "enable_fastpaths", True):
        return None
    if ctx.storage is not executor.storage:
        return None
    catalog = getattr(executor, "columnar", None)
    if catalog is None or clause.optional:
        return None
    paths = clause.paths
    if not paths or len(paths) > 3:
        return None
    seen_vars: set = set()
    n_rel_paths = 0
    for path in paths:
        if not _path_supported(path, seen_vars):
            return None  # unsupported shape or shared vars: general join
        if path.rels:
            n_rel_paths += 1
    if n_rel_paths > 1:
        return None  # same-type edge uniqueness across paths: general
    try:
        bindings = [_match_chain(catalog, p, ctx) for p in paths]
        combined = _cartesian(bindings)
        if combined is None:
            return None
        if clause.where is not None:
            for conj in _split_and(clause.where):
                combined.take(_vec_predicate(conj, combined, catalog, ctx))
        return _materialize_rows(combined, catalog)
    except _Unsupported:
        return None


def _cartesian(bindings: List[_Bindings]) -> Optional[_Bindings]:
    """Cross-join independent per-path bindings (no shared vars)."""
    if len(bindings) == 1:
        return bindings[0]
    total = 1
    for b in bindings:
        total *= max(b.n_rows, 0)
        if total > _MAX_MATERIALIZED_ROWS:
            return None
    out = _Bindings()
    out.n_rows = total
    if total == 0:
        for b in bindings:
            for k in b.node_cols:
                out.node_cols[k] = np.empty(0, np.int32)
            for k, (t, _v) in b.edge_cols.items():
                out.edge_cols[k] = (t, np.empty(0, np.int32))
        return out
    # repeat/tile index pattern per path
    reps_after = [1] * len(bindings)
    for i in range(len(bindings) - 2, -1, -1):
        reps_after[i] = reps_after[i + 1] * bindings[i + 1].n_rows
    reps_before = [1] * len(bindings)
    for i in range(1, len(bindings)):
        reps_before[i] = reps_before[i - 1] * bindings[i - 1].n_rows
    for i, b in enumerate(bindings):
        idx = np.tile(
            np.repeat(np.arange(b.n_rows, dtype=np.int64), reps_after[i]),
            reps_before[i],
        )
        for k, v in b.node_cols.items():
            out.node_cols[k] = v[idx]
        for k, (t, v) in b.edge_cols.items():
            out.edge_cols[k] = (t, v[idx])
        out.hop_edges.extend((t, v[idx]) for t, v in b.hop_edges)
    return out


def _materialize_rows(b: _Bindings, catalog) -> Optional[List[Dict[str, Any]]]:
    """Binding columns -> general-pipeline row dicts (Node/Edge values)."""
    if b.n_rows > _MAX_MATERIALIZED_ROWS:
        return None  # let the streaming general path handle huge matches
    nodes = catalog.nodes()
    cols: List[Tuple[str, List[Any]]] = []
    for var, rows in b.node_cols.items():
        cols.append((var, [nodes[i] for i in rows.tolist()]))
    for var, (table, erows) in b.edge_cols.items():
        edges = table.edges
        cols.append((var, [edges[i] for i in erows.tolist()]))
    out: List[Dict[str, Any]] = []
    for i in range(b.n_rows):
        out.append({var: vals[i] for var, vals in cols})
    return out


# -- projection / aggregation --------------------------------------------


def _project(executor, catalog, ret: A.ReturnClause, b: _Bindings, ctx, CypherResult):
    from nornicdb_tpu.query.executor import _contains_agg

    has_agg = any(_contains_agg(i.expr) for i in ret.items)
    cols = []
    for item in ret.items:
        if item.alias:
            cols.append(item.alias)
        elif isinstance(item.expr, A.Var):
            cols.append(item.expr.name)
        elif isinstance(item.expr, A.Prop) and isinstance(item.expr.target, A.Var):
            cols.append(f"{item.expr.target.name}.{item.expr.name}")
        else:
            cols.append(item.text)

    if has_agg:
        out_cols = _aggregate(catalog, ret, b, ctx)
    else:
        out_cols = []
        for item in ret.items:
            out_cols.append(_out_col(item.expr, b, catalog, ctx))
        if ret.distinct:
            from nornicdb_tpu.query.columnar import group_codes

            codes, _ = group_codes(
                [_codeable(c, b, catalog) for c in out_cols]
            )
            first = _first_occurrence(codes)
            out_cols = [c[first] for c in out_cols]

    n = len(out_cols[0]) if out_cols else 0
    order = np.arange(n)
    if ret.order_by:
        order = _order(ret, cols, out_cols, b, catalog, ctx)
        out_cols = [c[order] for c in out_cols]
    if ret.skip is not None:
        k = int(_const_value(ret.skip, ctx))
        out_cols = [c[k:] for c in out_cols]
    if ret.limit is not None:
        k = int(_const_value(ret.limit, ctx))
        out_cols = [c[:k] for c in out_cols]

    py_cols: List[List[Any]] = []
    for col in out_cols:
        lst = col.tolist()  # np scalars -> python natives in one pass
        if lst and isinstance(lst[0], _NodeRef):
            nodes = catalog.nodes()
            lst = [nodes[v.row] for v in lst]
        py_cols.append(lst)
    rows = [list(t) for t in zip(*py_cols)] if py_cols else []
    return CypherResult(columns=cols, rows=rows)


class _NodeRef:
    """Marker wrapping a global node row so projection can materialize the
    Node object only for rows that survive ORDER BY/LIMIT."""

    __slots__ = ("row",)

    def __init__(self, row: int):
        self.row = row


def _out_col(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    if isinstance(e, A.Var):
        if e.name in b.node_cols:
            rows = b.node_cols[e.name]
            out = np.empty(len(rows), dtype=object)
            for i, r in enumerate(rows.tolist()):
                out[i] = _NodeRef(r)
            return out
        _bail()
    return _vec_col(e, b, catalog, ctx)


def _codeable(col: np.ndarray, b: _Bindings, catalog) -> np.ndarray:
    """Column usable as a grouping key (NodeRefs become row ints)."""
    if len(col) and isinstance(col[0], _NodeRef):
        return np.asarray([v.row for v in col.tolist()], dtype=np.int64)
    return col


def _first_occurrence(codes: np.ndarray) -> np.ndarray:
    """Row index of the first occurrence of each group code, in
    first-encounter order (matches the general path's insertion order)."""
    n_groups = int(codes.max()) + 1 if len(codes) else 0
    first = np.full(n_groups, np.iinfo(np.int64).max, dtype=np.int64)
    np.minimum.at(first, codes, np.arange(len(codes), dtype=np.int64))
    return np.sort(first)


def _dense_codes(rows: np.ndarray, n_max: int) -> Tuple[np.ndarray, np.ndarray]:
    """(uniq_values, inverse_codes) for an int array with values in
    [0, n_max) — lookup-table based, no sort (argsort in np.unique is
    the aggregation hot spot at scale)."""
    flags = np.zeros(n_max, dtype=bool)
    flags[rows] = True
    uniq = np.nonzero(flags)[0]
    lut = np.zeros(n_max, dtype=np.int64)
    lut[uniq] = np.arange(len(uniq), dtype=np.int64)
    return uniq, lut[rows]


def _int_codes(rows: np.ndarray, n_max: int) -> Tuple[np.ndarray, np.ndarray]:
    """Strategy switch: dense lookup when the value domain is comparable
    to the row count (O(n_max) allocation), else sort-based np.unique —
    a 20-row group on a 50M-node graph must not allocate graph-sized
    scratch."""
    if 0 < n_max <= 4 * len(rows) + 4096:
        return _dense_codes(rows, n_max)
    return np.unique(rows, return_inverse=True)


def _group_code_col(e: A.Expr, b: _Bindings, catalog, ctx) -> np.ndarray:
    """Dense int64 group codes for one grouping-key expression.

    Property keys are routed through the entity *row* column first
    (vectorized int unique), then the small unique-row value table is
    deduplicated by value — Cypher groups by value, and two entities can
    share one — so the Python-level work is O(distinct entities), not
    O(match rows)."""
    from nornicdb_tpu.query.columnar import _unique_inverse

    if isinstance(e, A.Prop) and isinstance(e.target, A.Var):
        name = e.target.name
        if name in b.node_cols:
            rows = b.node_cols[name]
            uniq_rows, inv = _int_codes(rows, catalog.n_nodes())
            vals = catalog.node_prop_col(e.name)[uniq_rows]
        elif name in b.edge_cols:
            table, erows = b.edge_cols[name]
            uniq_rows, inv = _int_codes(erows, len(table))
            vals = table.prop_col(e.name)[uniq_rows]
        else:
            _bail()
        _, vcodes = _unique_inverse(vals)
        return vcodes[inv]
    if isinstance(e, A.Var):
        if e.name in b.node_cols:
            _, inv = _int_codes(b.node_cols[e.name], catalog.n_nodes())
            return inv
        if e.name in b.edge_cols:
            table, erows = b.edge_cols[e.name]
            _, inv = _int_codes(erows, len(table))
            return inv
        _bail()
    # anything else: evaluate the value column and hash it
    col = _vec_col(e, b, catalog, ctx)
    _, codes = _unique_inverse(col)
    return codes


def _combine_codes(code_cols: List[np.ndarray]) -> np.ndarray:
    combined = np.zeros(len(code_cols[0]), dtype=np.int64)
    span = 1
    for c in code_cols:
        width = int(c.max()) + 1 if len(c) else 1
        combined = combined * width + c
        span *= width
    if 0 < span <= 4 * len(combined) + 4096:
        # dense lookup beats the sort inside np.unique
        _, codes = _dense_codes(combined, span)
        return codes
    _, codes = np.unique(combined, return_inverse=True)
    return codes


def _aggregate(catalog, ret: A.ReturnClause, b: _Bindings, ctx) -> List[np.ndarray]:
    from nornicdb_tpu.query.executor import _contains_agg

    group_items = [i for i in ret.items if not _contains_agg(i.expr)]
    key_cols = [
        _group_code_col(i.expr, b, catalog, ctx) for i in group_items
    ]
    if key_cols:
        codes = _combine_codes(key_cols)
        first = _first_occurrence(codes)
        # remap codes so group ids follow first-encounter order (matches
        # the general path's insertion-ordered groups); `first` is sorted,
        # so codes[first] lists groups in encounter order.
        rank = np.empty(len(first), dtype=np.int64)
        rank[codes[first]] = np.arange(len(first))
        codes = rank[codes]
        n_groups = len(first)
    else:
        codes = np.zeros(b.n_rows, dtype=np.int64)
        first = np.zeros(1, dtype=np.int64) if b.n_rows else np.empty(0, np.int64)
        n_groups = 1  # global aggregation has exactly one output row

    out: List[np.ndarray] = []
    gi = 0
    for item in ret.items:
        if not _contains_agg(item.expr):
            full = _out_col(item.expr, b, catalog, ctx)
            out.append(full[first])
            gi += 1
        else:
            out.append(_agg_expr(item.expr, b, catalog, ctx, codes, n_groups))
    return out


def _agg_expr(
    e: A.Expr, b: _Bindings, catalog, ctx, codes: np.ndarray, n_groups: int
) -> np.ndarray:
    """Per-group value of an aggregate-bearing expression."""
    if isinstance(e, A.FuncCall) and e.name in _AGG_NAMES:
        return _agg_leaf(e, b, catalog, ctx, codes, n_groups)
    if isinstance(e, A.Binary) and e.op in ("+", "-", "*", "/", "%"):
        l = _agg_expr(e.left, b, catalog, ctx, codes, n_groups)
        r = _agg_expr(e.right, b, catalog, ctx, codes, n_groups)
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            lv, rv = l[i], r[i]
            if lv is None or rv is None:
                out[i] = None
                continue
            if e.op == "+":
                out[i] = lv + rv
            elif e.op == "-":
                out[i] = lv - rv
            elif e.op == "*":
                out[i] = lv * rv
            elif e.op == "/":
                if rv == 0:
                    _bail()
                if isinstance(lv, int) and isinstance(rv, int):
                    q = lv // rv
                    if q < 0 and lv % rv != 0:
                        q += 1
                    out[i] = q
                else:
                    out[i] = lv / rv
            else:
                if rv == 0:
                    _bail()
                mres = abs(lv) % abs(rv)
                out[i] = mres if lv >= 0 else -mres
        return out
    if isinstance(e, (A.Literal, A.Param)):
        v = _const_value(e, ctx)
        out = np.empty(n_groups, dtype=object)
        out[:] = v
        return out
    if isinstance(e, A.FuncCall) and e.name in ("tofloat", "tointeger"):
        inner = _agg_expr(e.args[0], b, catalog, ctx, codes, n_groups)
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            v = inner[i]
            if v is None:
                out[i] = None
            elif e.name == "tofloat":
                out[i] = float(v)
            else:
                out[i] = int(v)
        return out
    if isinstance(e, A.FuncCall) and e.name == "round":
        inner = _agg_expr(e.args[0], b, catalog, ctx, codes, n_groups)
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            v = inner[i]
            out[i] = None if v is None else float(round(v))
        return out
    _bail()


def _agg_leaf(
    e: A.FuncCall, b: _Bindings, catalog, ctx, codes: np.ndarray, n_groups: int
) -> np.ndarray:
    name = e.name
    if name == "count" and e.star:
        cnt = np.bincount(codes, minlength=n_groups)[:n_groups]
        out = np.empty(n_groups, dtype=object)
        out[:] = cnt.tolist()  # C-speed int64 -> python int
        return out
    if not e.args:
        _bail()
    arg = e.args[0]
    if isinstance(arg, A.Var) and arg.name in b.node_cols:
        vals = b.node_cols[arg.name].astype(np.int64)
        nonnull = np.ones(b.n_rows, dtype=bool)
        values_obj = None
    else:
        values_obj = _vec_col(arg, b, catalog, ctx)
        nonnull = np.array([x is not None for x in values_obj.tolist()], dtype=bool)
        vals = None

    if name == "count":
        if e.distinct:
            if vals is None:
                from nornicdb_tpu.query.columnar import group_codes as _gc

                vcodes, _ = _gc([values_obj])
            else:
                _, vcodes = _int_codes(
                    vals, int(vals.max()) + 1 if len(vals) else 1)
            sel = nonnull
            pair = codes[sel] * (int(vcodes.max()) + 1 if len(vcodes) else 1) + vcodes[sel]
            uniq_pairs = np.unique(pair)
            denom = int(vcodes.max()) + 1 if len(vcodes) else 1
            grp = uniq_pairs // denom
            cnt = np.bincount(grp, minlength=n_groups)[:n_groups]
        else:
            cnt = np.bincount(codes[nonnull], minlength=n_groups)[:n_groups]
        out = np.empty(n_groups, dtype=object)
        out[:] = cnt.tolist()
        return out

    if values_obj is None:
        _bail()

    if name == "collect":
        src = values_obj
        sel = nonnull
        if e.distinct:
            from nornicdb_tpu.query.columnar import group_codes as _gc

            vcodes, _ = _gc([values_obj])
            seen = set()
            keep = np.zeros(b.n_rows, dtype=bool)
            for i in range(b.n_rows):
                if not nonnull[i]:
                    continue
                key = (int(codes[i]), int(vcodes[i]))
                if key in seen:
                    continue
                seen.add(key)
                keep[i] = True
            sel = keep
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            out[i] = []
        idxs = np.nonzero(sel)[0]
        for i in idxs.tolist():
            out[codes[i]].append(values_obj[i])
        return out

    f = _as_float(values_obj)
    if f is None:
        if name in ("min", "max"):
            # non-numeric min/max (e.g. strings): python per-group
            out = np.empty(n_groups, dtype=object)
            out[:] = None
            for i in range(b.n_rows):
                if not nonnull[i]:
                    continue
                g = codes[i]
                v = values_obj[i]
                try:
                    if out[g] is None or (
                        v < out[g] if name == "min" else v > out[g]
                    ):
                        out[g] = v
                except TypeError:
                    _bail()
            return out
        _bail()
    fvals, fmask = f
    if e.distinct:
        _bail()
    safe = np.where(fmask, fvals, 0.0)
    cnt = np.bincount(codes[fmask], minlength=n_groups)[:n_groups]
    if name == "sum":
        s = np.bincount(codes, weights=safe, minlength=n_groups)[:n_groups]
        out = np.empty(n_groups, dtype=object)
        all_int = all(
            isinstance(x, int) and not isinstance(x, bool)
            for x in values_obj.tolist()
            if x is not None
        )
        out[:] = (s.astype(np.int64) if all_int else s).tolist()
        return out
    if name == "avg":
        s = np.bincount(codes, weights=safe, minlength=n_groups)[:n_groups]
        out = np.empty(n_groups, dtype=object)
        for i in range(n_groups):
            out[i] = float(s[i] / cnt[i]) if cnt[i] else None
        return out
    if name in ("min", "max"):
        init = np.inf if name == "min" else -np.inf
        acc = np.full(n_groups, init, dtype=np.float64)
        op = np.minimum if name == "min" else np.maximum
        op.at(acc, codes[fmask], fvals[fmask])
        out = np.empty(n_groups, dtype=object)
        all_int = all(
            isinstance(x, int) and not isinstance(x, bool)
            for x in values_obj.tolist()
            if x is not None
        )
        for i in range(n_groups):
            if cnt[i] == 0:
                out[i] = None
            else:
                out[i] = int(acc[i]) if all_int else float(acc[i])
        return out
    _bail()


def _order(ret, cols, out_cols, b, catalog, ctx) -> np.ndarray:
    """Row order for ORDER BY over the projected columns."""
    n = len(out_cols[0]) if out_cols else 0
    keys: List[Tuple[np.ndarray, bool]] = []
    for expr, desc in ret.order_by:
        col = _order_key(expr, ret, cols, out_cols, b, catalog, ctx)
        keys.append((col, desc))
    # numeric lane: all keys float-able -> lexsort
    float_keys = []
    ok = True
    for col, desc in keys:
        f = _as_float(col) if col.dtype == object else (
            col.astype(np.float64), np.ones(len(col), bool)
        )
        if f is None:
            ok = False
            break
        vals, mask = f
        # Neo4j treats null as the largest value: last in ASC, first in
        # DESC (general path _cypher_cmp returns 1 for None) — so map
        # null to +inf BEFORE the DESC negation.
        vals = np.where(mask, vals, np.inf)
        float_keys.append(-vals if desc else vals)
    if ok and float_keys:
        order = np.lexsort(list(reversed(float_keys)))
        return order
    # general: stable python sort
    from nornicdb_tpu.query.executor import _cypher_cmp
    import functools

    idx = list(range(n))

    def cmp(a: int, bidx: int) -> int:
        for col, desc in keys:
            va = col[a]
            vb = col[bidx]
            if isinstance(va, _NodeRef) or isinstance(vb, _NodeRef):
                _bail()
            c = _cypher_cmp(va, vb)
            if c != 0:
                return -c if desc else c
        return 0

    idx.sort(key=functools.cmp_to_key(cmp))
    return np.asarray(idx, dtype=np.int64)


def _order_key(expr, ret, cols, out_cols, b, catalog, ctx) -> np.ndarray:
    # 1. ORDER BY <alias or column name>
    if isinstance(expr, A.Var) and expr.name in cols:
        return out_cols[cols.index(expr.name)]
    # 2. ORDER BY <projected expression> (AST equality via dataclass eq)
    for i, item in enumerate(ret.items):
        if item.expr == expr:
            return out_cols[i]
    # 3. non-agg queries: any vectorizable expression over bindings
    from nornicdb_tpu.query.executor import _contains_agg

    if not any(_contains_agg(i.expr) for i in ret.items):
        return _vec_col(expr, b, catalog, ctx)
    _bail()
