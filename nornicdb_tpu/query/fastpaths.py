"""Streaming fast paths: shape-specialized executors that bypass the
general pipeline.

Reference: the reference's perf story is mostly *avoiding* general
execution — tryFastPathCompoundQuery (executor.go:1421), ExecuteOptimized
(optimized_executors.go:25-282), fast aggregations
(traversal_fast_agg.go:15,57), namespace-bypass (storage_fastpaths.go).
Here the detection works on the parsed AST (cheaper to keep correct than
regex shape-matching) and the counting shapes hit the storage engine's
O(1)/indexed paths directly.
"""

from __future__ import annotations

from typing import Optional

from nornicdb_tpu.query import ast as A


def try_fast_path(executor, q: A.Query, ctx) -> Optional["CypherResult"]:
    from nornicdb_tpu.query.executor import CypherResult

    clauses = q.clauses
    if len(clauses) != 2:
        return None
    m, r = clauses[0], clauses[1]
    if not isinstance(m, A.MatchClause) or not isinstance(r, A.ReturnClause):
        return None
    if m.optional or m.where is not None or len(m.paths) != 1:
        return None
    if r.distinct or r.order_by or r.skip or r.limit or r.star:
        return None
    if len(r.items) != 1:
        return None
    item = r.items[0]
    e = item.expr
    if not (isinstance(e, A.FuncCall) and e.name == "count" and not e.distinct):
        return None
    path = m.paths[0]
    col = item.alias or item.text

    # MATCH (n[:Label]) RETURN count(n|*)
    if len(path.nodes) == 1 and not path.rels:
        pn = path.nodes[0]
        if pn.props is not None:
            return None
        if not (
            e.star
            or (len(e.args) == 1 and isinstance(e.args[0], A.Var)
                and e.args[0].name == pn.var)
        ):
            return None
        if not pn.labels:
            # O(1) engine count (reference: count fast path)
            return CypherResult(columns=[col], rows=[[ctx.storage.count_nodes()]])
        if len(pn.labels) == 1:
            counter = getattr(ctx.storage, "count_nodes_by_label", None)
            if counter is not None:
                n = counter(pn.labels[0])
            else:
                n = len(ctx.storage.get_nodes_by_label(pn.labels[0]))
            return CypherResult(columns=[col], rows=[[n]])
        return None

    # MATCH ()-[r[:TYPE]]->() RETURN count(r|*)
    if len(path.nodes) == 2 and len(path.rels) == 1:
        pr = path.rels[0]
        n0, n1 = path.nodes
        if (
            n0.labels or n1.labels or n0.props or n1.props or pr.props
            or n0.var or n1.var
        ):
            return None
        if pr.min_hops != 1 or pr.max_hops != 1:
            return None
        if pr.direction == "both":
            return None  # both-direction counts each edge twice; general path
        counts_ok = e.star or (
            len(e.args) == 1 and isinstance(e.args[0], A.Var)
            and e.args[0].name == pr.var
        )
        if not counts_ok:
            return None
        if not pr.types:
            return CypherResult(columns=[col], rows=[[ctx.storage.count_edges()]])
        total = sum(len(ctx.storage.get_edges_by_type(t)) for t in pr.types)
        return CypherResult(columns=[col], rows=[[total]])

    return None
