"""Cypher AST node types (expressions, patterns, clauses)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


# -- expressions ---------------------------------------------------------


@dataclass
class Expr:
    pass


@dataclass
class Literal(Expr):
    value: Any


@dataclass
class Param(Expr):
    name: str


@dataclass
class Var(Expr):
    name: str


@dataclass
class Prop(Expr):
    target: Expr
    name: str


@dataclass
class ListExpr(Expr):
    items: List[Expr]


@dataclass
class MapExpr(Expr):
    items: List[Tuple[str, Expr]]


@dataclass
class Unary(Expr):
    op: str  # 'NOT', '-', '+'
    operand: Expr


@dataclass
class Binary(Expr):
    op: str  # '=','<>','<','<=','>','>=','+','-','*','/','%','^','AND','OR',
    # 'XOR','IN','STARTS WITH','ENDS WITH','CONTAINS','=~'
    left: Expr
    right: Expr


@dataclass
class IsNull(Expr):
    operand: Expr
    negated: bool


@dataclass
class FuncCall(Expr):
    name: str  # lowercase, may be dotted (apoc.coll.sum)
    args: List[Expr]
    distinct: bool = False
    star: bool = False  # count(*)


@dataclass
class CaseExpr(Expr):
    subject: Optional[Expr]  # CASE <subject> WHEN ... / CASE WHEN ...
    whens: List[Tuple[Expr, Expr]]
    default: Optional[Expr]


@dataclass
class Index(Expr):
    target: Expr
    index: Expr


@dataclass
class Slice(Expr):
    target: Expr
    start: Optional[Expr]
    end: Optional[Expr]


@dataclass
class ListComp(Expr):
    var: str
    source: Expr
    where: Optional[Expr]
    projection: Optional[Expr]


@dataclass
class ListPredicate(Expr):
    """all/any/none/single(x IN list WHERE pred)."""

    kind: str  # 'all' | 'any' | 'none' | 'single'
    var: str
    source: Expr
    where: Expr


@dataclass
class Reduce(Expr):
    """reduce(acc = init, x IN list | expr)."""

    acc: str
    init: Expr
    var: str
    source: Expr
    expr: Expr


@dataclass
class PatternPredicate(Expr):
    """A bare pattern used as a boolean predicate: WHERE (a)-[:KNOWS]->(b)."""

    pattern: "PatternPath"


@dataclass
class Exists(Expr):
    """EXISTS((a)-[:X]->()) or exists(n.prop)."""

    pattern: Optional["PatternPath"]
    prop: Optional[Expr]


@dataclass
class LabelCheck(Expr):
    """n:Label predicate."""

    var: str
    labels: List[str]


# -- patterns ------------------------------------------------------------


@dataclass
class PatternNode:
    var: Optional[str]
    labels: List[str] = field(default_factory=list)
    props: Optional[MapExpr] = None


@dataclass
class PatternRel:
    var: Optional[str]
    types: List[str] = field(default_factory=list)
    direction: str = "both"  # 'out' | 'in' | 'both'
    min_hops: int = 1
    max_hops: int = 1  # -1 == unbounded
    props: Optional[MapExpr] = None


@dataclass
class PatternPath:
    """Alternating nodes/rels: nodes[0] -rels[0]- nodes[1] ..."""

    nodes: List[PatternNode]
    rels: List[PatternRel]
    path_var: Optional[str] = None  # p = (a)-[]->(b)
    # MATCH p = shortestPath((a)-[*]-(b)): 'single' | 'all' | None
    shortest: Optional[str] = None


# -- clauses -------------------------------------------------------------


@dataclass
class Clause:
    pass


@dataclass
class MatchClause(Clause):
    paths: List[PatternPath]
    optional: bool = False
    where: Optional[Expr] = None


@dataclass
class UnwindClause(Clause):
    expr: Expr
    var: str


@dataclass
class ProjectionItem:
    expr: Expr
    alias: Optional[str]
    text: str  # original text for column naming


@dataclass
class WithClause(Clause):
    items: List[ProjectionItem]
    distinct: bool = False
    star: bool = False  # WITH *
    where: Optional[Expr] = None
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)  # (expr, desc)
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None


@dataclass
class ReturnClause(Clause):
    items: List[ProjectionItem]
    distinct: bool = False
    star: bool = False
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)
    skip: Optional[Expr] = None
    limit: Optional[Expr] = None


@dataclass
class CreateClause(Clause):
    paths: List[PatternPath]


@dataclass
class MergeClause(Clause):
    path: PatternPath
    on_create: List["SetItem"] = field(default_factory=list)
    on_match: List["SetItem"] = field(default_factory=list)


@dataclass
class SetItem:
    target: Optional[Expr]  # Prop target or Var for map-set / labels
    value: Optional[Expr]
    labels: List[str] = field(default_factory=list)  # SET n:Label
    merge_map: bool = False  # SET n += {..}
    replace_map: bool = False  # SET n = {..}


@dataclass
class SetClause(Clause):
    items: List[SetItem]


@dataclass
class RemoveClause(Clause):
    items: List[SetItem]  # prop targets or labels


@dataclass
class DeleteClause(Clause):
    exprs: List[Expr]
    detach: bool = False


@dataclass
class CallClause(Clause):
    proc: str
    args: List[Expr]
    yield_items: List[Tuple[str, Optional[str]]] = field(default_factory=list)
    yield_star: bool = False
    where: Optional[Expr] = None


@dataclass
class Query:
    clauses: List[Clause]
    params_used: List[str] = field(default_factory=list)


@dataclass
class UnionQuery:
    parts: List[Query]
    alls: List[bool] = field(default_factory=list)  # UNION vs UNION ALL
