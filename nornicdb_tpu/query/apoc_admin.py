"""APOC admin + write-path long tail: atomic, create/merge extras,
refactor, schema, lock, log, warmup.

Reference: apoc/atomic, apoc/create, apoc/merge, apoc/refactor,
apoc/schema, apoc/lock, apoc/log, apoc/warmup. Write functions mutate
``ctx.storage`` and bump ``ctx.stats`` so the executor's end-of-query
cache maintenance sees them (the same contract apoc_ext's create/merge
procedures follow). Locks and the log ring are process-wide singletons,
like the reference's global registries (apoc/lock/lock.go,
apoc/log/log.go).
"""

from __future__ import annotations

import logging
import threading
import time as _time
import uuid as _uuid
from typing import Any, Dict, List, Optional, Tuple

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.query.apoc import register, register_ctx
from nornicdb_tpu.storage.types import Edge, Node

_log = logging.getLogger("nornicdb_tpu.apoc")


# -- shared write helpers -------------------------------------------------


def _entity(ctx, x, what: str):
    if isinstance(x, (Node, Edge)):
        return x
    raise CypherRuntimeError(f"{what} expects a node or relationship")


def _refetch(ctx, x, what: str):
    """Fresh read of a query-bound entity: engines return copies on
    read, so a read-modify-write must re-read inside the atomic lock or
    concurrent updates are lost."""
    ent = _entity(ctx, x, what)
    from nornicdb_tpu.errors import NotFoundError

    try:
        if isinstance(ent, Node):
            return ctx.storage.get_node(ent.id)
        return ctx.storage.get_edge(ent.id)
    except NotFoundError:
        raise CypherRuntimeError(f"{what}: entity {ent.id} no longer exists")


def _persist(ctx, ent) -> None:
    if isinstance(ent, Node):
        ctx.storage.update_node(ent)
        ctx.stats.properties_set += 1
    else:
        ctx.storage.update_edge(ent)
        ctx.stats.properties_set += 1
    ctx.non_create_writes = True


def _fresh_node(ctx, labels, props) -> Node:
    node = Node(id=str(_uuid.uuid4()), labels=list(labels or []),
                properties=dict(props or {}))
    ctx.storage.create_node(node)
    ctx.stats.nodes_created += 1
    ctx.stats.labels_added += len(node.labels)
    ctx.stats.properties_set += len(node.properties)
    ctx.created_nodes.append(node)
    return node


def _fresh_edge(ctx, etype, start, end, props) -> Edge:
    edge = Edge(id=str(_uuid.uuid4()), type=etype, start_node=start,
                end_node=end, properties=dict(props or {}))
    ctx.storage.create_edge(edge)
    ctx.stats.relationships_created += 1
    ctx.created_edges.append(edge)
    return edge


# -- apoc.atomic ----------------------------------------------------------

_ATOMIC_LOCK = threading.Lock()


def _install_atomic() -> None:
    at = "apoc.atomic."

    def _update_num(ctx, x, prop, delta):
        with _ATOMIC_LOCK:
            ent = _refetch(ctx, x, "apoc.atomic")
            cur = ent.properties.get(prop, 0)
            if not isinstance(cur, (int, float)) or isinstance(cur, bool):
                raise CypherRuntimeError(
                    f"apoc.atomic: property {prop!r} is not numeric")
            ent.properties[prop] = cur + delta
            _persist(ctx, ent)
            return ent.properties[prop]

    register_ctx(at + "add", lambda ctx, x, prop, v: _update_num(
        ctx, x, prop, v))
    register_ctx(at + "subtract", lambda ctx, x, prop, v: _update_num(
        ctx, x, prop, -v))
    register_ctx(at + "increment", lambda ctx, x, prop: _update_num(
        ctx, x, prop, 1))
    register_ctx(at + "decrement", lambda ctx, x, prop: _update_num(
        ctx, x, prop, -1))

    def _concat(ctx, x, prop, suffix):
        with _ATOMIC_LOCK:
            ent = _refetch(ctx, x, "apoc.atomic.concat")
            ent.properties[prop] = str(ent.properties.get(prop, "")) \
                + str(suffix)
            _persist(ctx, ent)
            return ent.properties[prop]

    register_ctx(at + "concat", _concat)

    def _insert(ctx, x, prop, pos, value):
        with _ATOMIC_LOCK:
            ent = _refetch(ctx, x, "apoc.atomic.insert")
            lst = list(ent.properties.get(prop) or [])
            lst.insert(int(pos), value)
            ent.properties[prop] = lst
            _persist(ctx, ent)
            return lst

    register_ctx(at + "insert", _insert)

    def _remove(ctx, x, prop, pos):
        with _ATOMIC_LOCK:
            ent = _refetch(ctx, x, "apoc.atomic.remove")
            lst = list(ent.properties.get(prop) or [])
            if 0 <= int(pos) < len(lst):
                lst.pop(int(pos))
            ent.properties[prop] = lst
            _persist(ctx, ent)
            return lst

    register_ctx(at + "remove", _remove)

    def _update(ctx, x, prop, value):
        with _ATOMIC_LOCK:
            ent = _refetch(ctx, x, "apoc.atomic.update")
            ent.properties[prop] = value
            _persist(ctx, ent)
            return value

    register_ctx(at + "update", _update)

    def _cas(ctx, x, prop, expected, value):
        with _ATOMIC_LOCK:
            ent = _refetch(ctx, x, "apoc.atomic.compareAndSwap")
            if ent.properties.get(prop) != expected:
                return False
            ent.properties[prop] = value
            _persist(ctx, ent)
            return True

    register_ctx(at + "compareAndSwap", _cas)


# -- apoc.create extras ---------------------------------------------------


def _install_create() -> None:
    cr = "apoc.create."

    def _add_labels(ctx, x, labels):
        node = x if isinstance(x, Node) else None
        if node is None:
            raise CypherRuntimeError("addLabels expects a node")
        for l in labels or []:
            if l not in node.labels:
                node.labels.append(l)
                ctx.stats.labels_added += 1
        ctx.storage.update_node(node)
        ctx.non_create_writes = True
        return node

    register_ctx(cr + "addLabels", _add_labels)

    def _remove_labels(ctx, x, labels):
        node = x if isinstance(x, Node) else None
        if node is None:
            raise CypherRuntimeError("removeLabels expects a node")
        for l in labels or []:
            if l in node.labels:
                node.labels.remove(l)
                ctx.stats.labels_removed += 1
        ctx.storage.update_node(node)
        ctx.non_create_writes = True
        return node

    register_ctx(cr + "removeLabels", _remove_labels)

    def _set_property(ctx, x, key, value):
        ent = _entity(ctx, x, "apoc.create.setProperty")
        ent.properties[key] = value
        _persist(ctx, ent)
        return ent

    register_ctx(cr + "setProperty", _set_property)
    register_ctx(cr + "setRelProperty", _set_property)

    def _set_properties(ctx, x, keys, values=None):
        ent = _entity(ctx, x, "apoc.create.setProperties")
        if isinstance(keys, dict):
            ent.properties.update(keys)
        else:
            for k, v in zip(keys or [], values or []):
                ent.properties[k] = v
        _persist(ctx, ent)
        return ent

    register_ctx(cr + "setProperties", _set_properties)
    register_ctx(cr + "setRelProperties", _set_properties)

    def _remove_properties(ctx, x, keys):
        ent = _entity(ctx, x, "apoc.create.removeProperties")
        for k in keys or []:
            ent.properties.pop(k, None)
        _persist(ctx, ent)
        return ent

    register_ctx(cr + "removeProperties", _remove_properties)
    register_ctx(cr + "removeRelProperties", _remove_properties)

    def _clone(ctx, x):
        node = x if isinstance(x, Node) else None
        if node is None:
            raise CypherRuntimeError("clone expects a node")
        return _fresh_node(ctx, node.labels, node.properties)

    register_ctx(cr + "clone", _clone)

    def _clone_subgraph(ctx, nodes, rels=None):
        mapping: Dict[str, Node] = {}
        out_nodes = []
        for n in nodes or []:
            if isinstance(n, Node):
                clone = _fresh_node(ctx, n.labels, n.properties)
                mapping[n.id] = clone
                out_nodes.append(clone)
        out_rels = []
        for e in rels or []:
            if isinstance(e, Edge) and e.start_node in mapping \
                    and e.end_node in mapping:
                out_rels.append(_fresh_edge(
                    ctx, e.type, mapping[e.start_node].id,
                    mapping[e.end_node].id, e.properties))
        return {"nodes": out_nodes, "relationships": out_rels}

    register_ctx(cr + "cloneSubgraph", _clone_subgraph)
    register(cr + "uuids", lambda n: [str(_uuid.uuid4())
                                      for _ in range(int(n))])

    # virtual entities: returned, never persisted (reference
    # apoc/create vNode family)
    register(cr + "vNode", lambda labels, props=None: Node(
        id=f"vnode-{_uuid.uuid4()}", labels=list(labels or []),
        properties=dict(props or {})))
    register(cr + "vNodes", lambda labels, props_list: [
        Node(id=f"vnode-{_uuid.uuid4()}", labels=list(labels or []),
             properties=dict(p or {})) for p in (props_list or [])])
    register(cr + "vRelationship", lambda frm, etype, props, to: Edge(
        id=f"vrel-{_uuid.uuid4()}", type=etype,
        start_node=frm.id if isinstance(frm, Node) else str(frm),
        end_node=to.id if isinstance(to, Node) else str(to),
        properties=dict(props or {})))

    def _vpattern(frm_map, etype, props, to_map):
        a = Node(id=f"vnode-{_uuid.uuid4()}",
                 labels=list((frm_map or {}).get("_labels", [])),
                 properties={k: v for k, v in (frm_map or {}).items()
                             if k != "_labels"})
        b = Node(id=f"vnode-{_uuid.uuid4()}",
                 labels=list((to_map or {}).get("_labels", [])),
                 properties={k: v for k, v in (to_map or {}).items()
                             if k != "_labels"})
        e = Edge(id=f"vrel-{_uuid.uuid4()}", type=etype, start_node=a.id,
                 end_node=b.id, properties=dict(props or {}))
        return {"from": a, "rel": e, "to": b}

    register(cr + "vPattern", _vpattern)


# -- apoc.merge extras ----------------------------------------------------


def _install_merge() -> None:
    mg = "apoc.merge."

    def _merge_node(ctx, labels, ident_props, on_create=None,
                    on_match=None):
        labels = list(labels or [])
        ident = dict(ident_props or {})
        for node in ctx.storage.get_nodes_by_label(
                labels[0]) if labels else ctx.storage.all_nodes():
            if all(node.properties.get(k) == v for k, v in ident.items()) \
                    and all(l in node.labels for l in labels):
                if on_match:
                    node.properties.update(on_match)
                    _persist(ctx, node)
                return node
        props = {**ident, **(on_create or {})}
        return _fresh_node(ctx, labels, props)

    register_ctx(mg + "mergeNode", _merge_node)
    register_ctx(mg + "nodeEager", _merge_node)
    register_ctx(mg + "nodes", lambda ctx, labels, ident_list: [
        _merge_node(ctx, labels, ident) for ident in (ident_list or [])])

    def _merge_rel(ctx, start, etype, ident_props, to, on_create=None):
        a = start if isinstance(start, Node) else None
        b = to if isinstance(to, Node) else None
        if a is None or b is None:
            raise CypherRuntimeError("mergeRelationship expects nodes")
        ident = dict(ident_props or {})
        for e in ctx.storage.get_node_edges(a.id, direction="out"):
            if (e.type == etype and e.end_node == b.id and all(
                    e.properties.get(k) == v for k, v in ident.items())):
                return e
        return _fresh_edge(ctx, etype, a.id, b.id,
                           {**ident, **(on_create or {})})

    register_ctx(mg + "mergeRelationship", _merge_rel)
    # reference signature: (start, relType, identProps, onCreateProps, end)
    register_ctx(mg + "relationshipEager",
                 lambda ctx, start, etype, ident, on_create, to:
                 _merge_rel(ctx, start, etype, ident, to, on_create))

    def _merge_labels(ctx, x, labels):
        node = x if isinstance(x, Node) else None
        if node is None:
            raise CypherRuntimeError("merge.labels expects a node")
        changed = False
        for l in labels or []:
            if l not in node.labels:
                node.labels.append(l)
                ctx.stats.labels_added += 1
                changed = True
        if changed:
            ctx.storage.update_node(node)
            ctx.non_create_writes = True
        return node

    register_ctx(mg + "labels", _merge_labels)

    def _merge_properties(ctx, x, props, overwrite=False):
        ent = _entity(ctx, x, "apoc.merge.properties")
        for k, v in (props or {}).items():
            if overwrite or k not in ent.properties:
                ent.properties[k] = v
        _persist(ctx, ent)
        return ent

    register_ctx(mg + "properties", _merge_properties)

    def _deep_merge(a, b):
        if isinstance(a, dict) and isinstance(b, dict):
            out = dict(a)
            for k, v in b.items():
                out[k] = _deep_merge(a[k], v) if k in a else v
            return out
        return b

    register(mg + "deepMerge", _deep_merge)
    register(mg + "conflict", lambda a, b, strategy="right": (
        {**(a or {}), **(b or {})} if strategy == "right"
        else {**(b or {}), **(a or {})} if strategy == "left"
        else _deep_merge(a or {}, b or {})))
    register(mg + "preview", lambda existing, incoming: {
        "unchanged": {k: v for k, v in (existing or {}).items()
                      if (incoming or {}).get(k, v) == v},
        "added": {k: v for k, v in (incoming or {}).items()
                  if k not in (existing or {})},
        "overwritten": {k: {"old": (existing or {})[k], "new": v}
                        for k, v in (incoming or {}).items()
                        if k in (existing or {})
                        and (existing or {})[k] != v}})
    register(mg + "validate", lambda ident: (
        bool(ident) and all(v is not None for v in ident.values())))

    def _merge_batch(ctx, labels, ident_list, on_create=None):
        return [{"node": _merge_node(ctx, labels, ident, on_create)}
                for ident in (ident_list or [])]

    register_ctx(mg + "batch", _merge_batch)

    def _conditional(ctx, cond, labels, ident, on_create=None):
        if not cond:
            return None
        return _merge_node(ctx, labels, ident, on_create)

    register_ctx(mg + "conditional", _conditional)


# -- apoc.refactor --------------------------------------------------------


def _install_refactor() -> None:
    rf = "apoc.refactor."

    def _rename_label(ctx, old, new):
        n = 0
        for node in list(ctx.storage.get_nodes_by_label(old)):
            node.labels = [new if l == old else l for l in node.labels]
            ctx.storage.update_node(node)
            n += 1
        if n:
            ctx.stats.labels_added += n
            ctx.stats.labels_removed += n
            ctx.non_create_writes = True
        return n

    register_ctx(rf + "renameLabel", _rename_label)

    def _rename_type(ctx, old, new):
        n = 0
        for e in list(ctx.storage.get_edges_by_type(old)):
            ctx.storage.delete_edge(e.id)
            ctx.storage.create_edge(Edge(
                id=e.id, type=new, start_node=e.start_node,
                end_node=e.end_node, properties=dict(e.properties)))
            n += 1
        if n:
            ctx.stats.relationships_created += n
            ctx.stats.relationships_deleted += n
            ctx.non_create_writes = True
        return n

    register_ctx(rf + "renameType", _rename_type)
    register_ctx(rf + "setType", lambda ctx, e, new: _set_type(ctx, e, new))
    register_ctx(rf + "changeType", lambda ctx, e, new: _set_type(
        ctx, e, new))

    def _set_type(ctx, e, new):
        if not isinstance(e, Edge):
            raise CypherRuntimeError("setType expects a relationship")
        ctx.storage.delete_edge(e.id)
        out = Edge(id=e.id, type=new, start_node=e.start_node,
                   end_node=e.end_node, properties=dict(e.properties))
        ctx.storage.create_edge(out)
        ctx.stats.relationships_created += 1
        ctx.stats.relationships_deleted += 1
        ctx.non_create_writes = True
        return out

    def _rename_property(ctx, old, new, labels=None):
        n = 0
        nodes = (ctx.storage.get_nodes_by_label(labels[0])
                 if labels else ctx.storage.all_nodes())
        for node in list(nodes):
            if old in node.properties:
                node.properties[new] = node.properties.pop(old)
                ctx.storage.update_node(node)
                n += 1
        if n:
            ctx.stats.properties_set += n
            ctx.non_create_writes = True
        return n

    register_ctx(rf + "renameProperty", _rename_property)

    def _merge_nodes(ctx, nodes):
        """Merge all nodes onto the first: union labels/props, re-home
        relationships, delete the rest."""
        nodes = [x for x in (nodes or []) if isinstance(x, Node)]
        if not nodes:
            return None
        target = nodes[0]
        for other in nodes[1:]:
            for l in other.labels:
                if l not in target.labels:
                    target.labels.append(l)
            for k, v in other.properties.items():
                target.properties.setdefault(k, v)
            for e in list(ctx.storage.get_node_edges(other.id)):
                ctx.storage.delete_edge(e.id)
                s = target.id if e.start_node == other.id else e.start_node
                t = target.id if e.end_node == other.id else e.end_node
                if s == t == target.id and e.start_node != e.end_node:
                    continue  # collapse would self-loop a merged pair
                ctx.storage.create_edge(Edge(
                    id=e.id, type=e.type, start_node=s, end_node=t,
                    properties=dict(e.properties)))
            ctx.storage.delete_node(other.id)
            ctx.stats.nodes_deleted += 1
        ctx.storage.update_node(target)
        ctx.non_create_writes = True
        return target

    register_ctx(rf + "mergeNodes", _merge_nodes)

    def _merge_relationships(ctx, rels):
        rels = [e for e in (rels or []) if isinstance(e, Edge)]
        if not rels:
            return None
        target = rels[0]
        for other in rels[1:]:
            for k, v in other.properties.items():
                target.properties.setdefault(k, v)
            ctx.storage.delete_edge(other.id)
            ctx.stats.relationships_deleted += 1
        ctx.storage.update_edge(target)
        ctx.non_create_writes = True
        return target

    register_ctx(rf + "mergeRelationships", _merge_relationships)

    def _redirect(ctx, e, node, end=True):
        if not isinstance(e, Edge) or not isinstance(node, Node):
            raise CypherRuntimeError(
                "redirectRelationship expects (rel, node)")
        ctx.storage.delete_edge(e.id)
        out = Edge(id=e.id, type=e.type,
                   start_node=e.start_node if end else node.id,
                   end_node=node.id if end else e.end_node,
                   properties=dict(e.properties))
        ctx.storage.create_edge(out)
        ctx.stats.relationships_created += 1
        ctx.stats.relationships_deleted += 1
        ctx.non_create_writes = True
        return out

    register_ctx(rf + "redirectRelationship", _redirect)
    register_ctx(rf + "to", lambda ctx, e, node: _redirect(
        ctx, e, node, end=True))
    register_ctx(rf + "from", lambda ctx, e, node: _redirect(
        ctx, e, node, end=False))

    def _invert(ctx, e):
        if not isinstance(e, Edge):
            raise CypherRuntimeError("invertRelationship expects a rel")
        ctx.storage.delete_edge(e.id)
        out = Edge(id=e.id, type=e.type, start_node=e.end_node,
                   end_node=e.start_node, properties=dict(e.properties))
        ctx.storage.create_edge(out)
        ctx.stats.relationships_created += 1
        ctx.stats.relationships_deleted += 1
        ctx.non_create_writes = True
        return out

    register_ctx(rf + "invertRelationship", _invert)

    def _clone_nodes(ctx, nodes, with_rels=False):
        mapping: Dict[str, Node] = {}
        out = []
        src = [x for x in (nodes or []) if isinstance(x, Node)]
        for node in src:
            clone = _fresh_node(ctx, node.labels, node.properties)
            mapping[node.id] = clone
            out.append(clone)
        if with_rels:
            ids = {x.id for x in src}
            seen = set()
            for node in src:
                for e in ctx.storage.get_node_edges(node.id):
                    if e.id in seen or e.start_node not in ids \
                            or e.end_node not in ids:
                        continue
                    seen.add(e.id)
                    _fresh_edge(ctx, e.type, mapping[e.start_node].id,
                                mapping[e.end_node].id, e.properties)
        return out

    register_ctx(rf + "cloneNodes", _clone_nodes)
    register_ctx(rf + "cloneSubgraph", lambda ctx, nodes: _clone_nodes(
        ctx, nodes, with_rels=True))

    def _clone_from_paths(ctx, paths):
        from nornicdb_tpu.query.functions import PathValue
        nodes: Dict[str, Node] = {}
        for p in paths or []:
            if isinstance(p, PathValue):
                for n in p.nodes:
                    nodes[n.id] = n
        return _clone_nodes(ctx, list(nodes.values()), with_rels=True)

    register_ctx(rf + "cloneSubgraphFromPaths", _clone_from_paths)

    def _extract_node(ctx, e, labels):
        """Relationship -> intermediate node (reference extractNode)."""
        if not isinstance(e, Edge):
            raise CypherRuntimeError("extractNode expects a relationship")
        mid = _fresh_node(ctx, labels or [e.type], e.properties)
        _fresh_edge(ctx, e.type + "_FROM", e.start_node, mid.id, {})
        _fresh_edge(ctx, e.type + "_TO", mid.id, e.end_node, {})
        ctx.storage.delete_edge(e.id)
        ctx.stats.relationships_deleted += 1
        ctx.non_create_writes = True
        return mid

    register_ctx(rf + "extractNode", _extract_node)

    def _collapse_node(ctx, node, etype):
        """Node with exactly one in- and one out-edge -> single edge."""
        if not isinstance(node, Node):
            raise CypherRuntimeError("collapseNode expects a node")
        ins = ctx.storage.get_node_edges(node.id, direction="in")
        outs = ctx.storage.get_node_edges(node.id, direction="out")
        if len(ins) != 1 or len(outs) != 1:
            raise CypherRuntimeError(
                "collapseNode requires exactly one incoming and one "
                "outgoing relationship")
        new = _fresh_edge(ctx, etype, ins[0].start_node, outs[0].end_node,
                          node.properties)
        ctx.storage.delete_node(node.id)
        ctx.stats.nodes_deleted += 1
        ctx.non_create_writes = True
        return new

    register_ctx(rf + "collapseNode", _collapse_node)

    def _delete_reconnect(ctx, node, etype=None):
        """Delete a node, reconnecting its in-neighbors to out-neighbors."""
        if not isinstance(node, Node):
            raise CypherRuntimeError("deleteAndReconnect expects a node")
        ins = ctx.storage.get_node_edges(node.id, direction="in")
        outs = ctx.storage.get_node_edges(node.id, direction="out")
        made = []
        for ei in ins:
            for eo in outs:
                if ei.start_node == eo.end_node:
                    continue
                made.append(_fresh_edge(
                    ctx, etype or eo.type, ei.start_node, eo.end_node, {}))
        ctx.storage.delete_node(node.id)
        ctx.stats.nodes_deleted += 1
        ctx.non_create_writes = True
        return made

    register_ctx(rf + "deleteAndReconnect", _delete_reconnect)

    def _normalize_bool(ctx, node, prop, true_values, false_values):
        if not isinstance(node, Node):
            raise CypherRuntimeError("normalizeAsBoolean expects a node")
        v = node.properties.get(prop)
        if v in (true_values or []):
            node.properties[prop] = True
        elif v in (false_values or []):
            node.properties[prop] = False
        else:
            node.properties.pop(prop, None)
        _persist(ctx, node)
        return node

    register_ctx(rf + "normalizeAsBoolean", _normalize_bool)
    register_ctx(rf + "normalize", _normalize_bool)

    def _categorize(ctx, prop, etype, label, out_key="name"):
        """Property value -> category node + relationship
        (reference categorizeProperty)."""
        cats: Dict[Any, Node] = {}
        n = 0
        for node in list(ctx.storage.all_nodes()):
            if label in node.labels:
                continue  # category nodes themselves
            v = node.properties.get(prop)
            if v is None or isinstance(v, (list, dict)):
                continue
            cat = cats.get(v)
            if cat is None:
                for existing in ctx.storage.get_nodes_by_label(label):
                    if existing.properties.get(out_key) == v:
                        cat = existing
                        break
                if cat is None:
                    cat = _fresh_node(ctx, [label], {out_key: v})
                cats[v] = cat
            _fresh_edge(ctx, etype, node.id, cat.id, {})
            node.properties.pop(prop, None)
            ctx.storage.update_node(node)
            n += 1
        if n:
            ctx.non_create_writes = True
        return n

    register_ctx(rf + "categorizeProperty", _categorize)
    register_ctx(rf + "denormalize", lambda ctx, prop, etype, label,
                 out_key="name": _categorize(ctx, prop, etype, label,
                                             out_key))


# -- apoc.schema ----------------------------------------------------------


def _schema_mgr(ctx):
    """Find a SchemaManager on the engine chain, else a per-executor one
    (registry-only until a ConstrainedEngine enforces it)."""
    eng = ctx.storage
    for _ in range(8):
        mgr = getattr(eng, "schema", None)
        if mgr is not None and hasattr(mgr, "add") and hasattr(mgr, "list"):
            return mgr
        eng = getattr(eng, "inner", None)
        if eng is None:
            break
    mgr = getattr(ctx.ex, "_apoc_schema", None)
    if mgr is None:
        from nornicdb_tpu.storage.schema import SchemaManager

        mgr = SchemaManager()
        ctx.ex._apoc_schema = mgr
    return mgr


def _install_schema() -> None:
    from nornicdb_tpu.storage.schema import Constraint

    sc = "apoc.schema."

    def _mk_all(kind, label, props, rel=False) -> List[Constraint]:
        """One Constraint per property (the schema model is
        single-property; composite keys expand)."""
        props = props if isinstance(props, list) else [props]
        out = []
        for p in props:
            out.append(Constraint(
                name=f"{kind}_{label}_{p}", kind=kind,
                label="" if rel else label,
                rel_type=label if rel else "", property=p))
        return out

    def _create(ctx, kind, label, props):
        mgr = _schema_mgr(ctx)
        have = {c.name for c in mgr.list()}
        made = []
        for c in _mk_all(kind, label, props):
            if c.name not in have:  # idempotent re-create
                mgr.add(c)
            made.append(c.to_dict())
        return made

    register_ctx(sc + "createUniqueConstraint", lambda ctx, label, props:
                 _create(ctx, "unique", label, props))
    register_ctx(sc + "createExistsConstraint", lambda ctx, label, props:
                 _create(ctx, "exists", label, props))
    register_ctx(sc + "createNodeKeyConstraint", lambda ctx, label, props:
                 _create(ctx, "unique", label, props)
                 + _create(ctx, "exists", label, props))
    register_ctx(sc + "createConstraint", lambda ctx, label, props,
                 kind="unique": _create(ctx, kind, label, props))
    register_ctx(sc + "dropConstraint", lambda ctx, name: _schema_mgr(
        ctx).drop(name))
    register_ctx(sc + "nodeConstraints", lambda ctx: [
        c.to_dict() for c in _schema_mgr(ctx).list() if c.label])
    register_ctx(sc + "relationshipConstraints", lambda ctx: [
        c.to_dict() for c in _schema_mgr(ctx).list() if c.rel_type])
    register_ctx(sc + "nodeConstraintExists", lambda ctx, label, props:
                 all(any(c.label == label and c.property == p
                         for c in _schema_mgr(ctx).list())
                     for p in (props if isinstance(props, list)
                               else [props])))

    def _assert(ctx, indexes, constraints):
        """Declarative schema: drop anything not listed, create what is
        (reference apoc.schema.assert). constraints: {label: [props]}
        (unique). Indexes are synchronous label/property maps here."""
        mgr = _schema_mgr(ctx)
        wanted: List[Constraint] = []
        for label, props in (constraints or {}).items():
            wanted.extend(_mk_all("unique", label, props))
        keep = {c.name for c in wanted}
        dropped = [c.name for c in mgr.list() if c.name not in keep]
        for name in dropped:
            mgr.drop(name)
        created = []
        have = {c.name for c in mgr.list()}
        for c in wanted:
            if c.name not in have:
                mgr.add(c)
                created.append(c.name)
        return {"created": created, "dropped": dropped,
                "indexes": indexes or {}}

    register_ctx(sc + "assert", _assert)

    def _info(ctx):
        mgr = _schema_mgr(ctx)
        return {"constraints": [c.to_dict() for c in mgr.list()],
                "indexes": []}

    register_ctx(sc + "info", _info)
    register_ctx(sc + "export", _info)
    register_ctx(sc + "snapshot", _info)

    def _import(ctx, data):
        mgr = _schema_mgr(ctx)
        have = {c.name for c in mgr.list()}
        n = 0
        for d in (data or {}).get("constraints", []):
            c = Constraint.from_dict(d)
            if c.name in have:
                continue  # idempotent restore
            mgr.add(c)
            have.add(c.name)
            n += 1
        return n

    register_ctx(sc + "import", _import)
    register_ctx(sc + "restore", _import)

    register_ctx(sc + "labels", lambda ctx: sorted(
        {c.label for c in _schema_mgr(ctx).list() if c.label}))
    register_ctx(sc + "relationships", lambda ctx: sorted(
        {c.rel_type for c in _schema_mgr(ctx).list() if c.rel_type}))
    register_ctx(sc + "properties", lambda ctx: sorted(
        {c.property for c in _schema_mgr(ctx).list() if c.property}))
    register_ctx(sc + "nodes", lambda ctx: [
        c.to_dict() for c in _schema_mgr(ctx).list() if c.label])
    register_ctx(sc + "stats", lambda ctx: {
        "constraintCount": len(_schema_mgr(ctx).list())})

    def _validate(ctx):
        """Check existing data against registered constraints."""
        from nornicdb_tpu.storage.schema import ConstrainedEngine

        eng = ctx.storage
        for _ in range(8):
            if isinstance(eng, ConstrainedEngine):
                return eng.validate_existing()
            nxt = getattr(eng, "inner", None)
            if nxt is None:
                break
            eng = nxt
        # registry-only mode: run the unique/exists checks directly
        mgr = _schema_mgr(ctx)
        violations: List[str] = []
        for c in mgr.list():
            if not c.label or not c.property:
                continue
            if c.kind == "unique":
                seen: Dict[Any, str] = {}
                for node in ctx.storage.get_nodes_by_label(c.label):
                    v = node.properties.get(c.property)
                    if v is None or isinstance(v, (list, dict)):
                        continue
                    if v in seen:
                        violations.append(
                            f"{c.name}: duplicate {v!r} on nodes "
                            f"{seen[v]} and {node.id}")
                    else:
                        seen[v] = node.id
            elif c.kind == "exists":
                for node in ctx.storage.get_nodes_by_label(c.label):
                    if node.properties.get(c.property) is None:
                        violations.append(
                            f"{c.name}: missing {c.property!r} on node "
                            f"{node.id}")
        return violations

    register_ctx(sc + "validate", _validate)
    register_ctx(sc + "analyze", _validate)

    def _compare(ctx, other):
        mine = {c.name for c in _schema_mgr(ctx).list()}
        theirs = {d.get("name") for d in (other or {}).get(
            "constraints", [])}
        return {"onlyLocal": sorted(mine - theirs),
                "onlyOther": sorted(theirs - mine),
                "common": sorted(mine & theirs)}

    register_ctx(sc + "compare", _compare)

    # index management maps onto the synchronous label/property maps
    # (reference call_index_mgmt.go semantics: acknowledged, no async
    # population phase)
    register_ctx(sc + "createIndex", lambda ctx, label, props: {
        "label": label,
        "properties": props if isinstance(props, list) else [props],
        "state": "ONLINE"})
    register_ctx(sc + "dropIndex", lambda ctx, label, props=None: True)
    register_ctx(sc + "nodeIndexes", lambda ctx: [])
    register_ctx(sc + "relationshipIndexes", lambda ctx: [])
    register_ctx(sc + "nodeIndexExists", lambda ctx, label, props: True)
    register_ctx(sc + "optimize", lambda ctx: {"status": "ok"})
    register_ctx(sc + "types", lambda ctx: sorted(
        {c.kind for c in _schema_mgr(ctx).list()}))
    register_ctx(sc + "propertiesDistinct", lambda ctx, label, prop: sorted(
        {v for n in ctx.storage.get_nodes_by_label(label)
         if not isinstance(v := n.properties.get(prop), (list, dict))
         and v is not None},
        key=lambda x: (str(type(x).__name__), str(x))))


# -- apoc.lock ------------------------------------------------------------


class _LockManager:
    """Named re-entrant locks over node/rel ids plus one global lock.
    Process-wide singleton, like the reference's lock registry."""

    def __init__(self):
        self._guard = threading.Lock()
        self._locks: Dict[str, threading.RLock] = {}
        self._held: Dict[str, int] = {}

    def _get(self, key: str) -> threading.RLock:
        with self._guard:
            lock = self._locks.get(key)
            if lock is None:
                lock = threading.RLock()
                self._locks[key] = lock
            return lock

    def acquire(self, keys: List[str], timeout: float = 10.0) -> bool:
        got: List[str] = []
        for key in sorted(keys):  # total order prevents deadlock
            if not self._get(key).acquire(timeout=timeout):
                self.release(got)  # roll back: locks must not leak
                return False
            got.append(key)
            with self._guard:
                self._held[key] = self._held.get(key, 0) + 1
        return True

    def try_acquire(self, keys: List[str]) -> bool:
        got: List[str] = []
        for key in sorted(keys):
            if self._get(key).acquire(blocking=False):
                with self._guard:  # count immediately: release() on
                    self._held[key] = self._held.get(key, 0) + 1
                got.append(key)  # rollback decrements symmetrically
            else:
                self.release(got)
                return False
        return True

    def release(self, keys: List[str]) -> int:
        n = 0
        for key in keys:
            lock = self._locks.get(key)
            if lock is None:
                continue
            try:
                lock.release()
                n += 1
                with self._guard:
                    if self._held.get(key, 0) > 0:
                        self._held[key] -= 1
            except RuntimeError:
                pass  # not held by this thread
        return n

    def release_all(self) -> int:
        return self.release(list(self._locks))

    def is_locked(self, key: str) -> bool:
        with self._guard:
            return self._held.get(key, 0) > 0

    def stats(self) -> Dict[str, Any]:
        with self._guard:
            return {"locks": len(self._locks),
                    "held": sum(1 for v in self._held.values() if v > 0)}


LOCKS = _LockManager()


def _ids_of(items) -> List[str]:
    out = []
    for x in items if isinstance(items, list) else [items]:
        if isinstance(x, (Node, Edge)):
            out.append(x.id)
        elif x is not None:
            out.append(str(x))
    return out


def _install_lock() -> None:
    lk = "apoc.lock."
    register(lk + "nodes", lambda nodes, timeout=10.0: LOCKS.acquire(
        _ids_of(nodes), float(timeout)))
    register(lk + "relationships", lambda rels, timeout=10.0: LOCKS.acquire(
        _ids_of(rels), float(timeout)))
    register(lk + "readNodes", lambda nodes, timeout=10.0: LOCKS.acquire(
        _ids_of(nodes), float(timeout)))
    register(lk + "readRelationships",
             lambda rels, timeout=10.0: LOCKS.acquire(
                 _ids_of(rels), float(timeout)))
    register(lk + "all", lambda timeout=10.0: LOCKS.acquire(
        ["__global__"], float(timeout)))
    register(lk + "tryLock", lambda items: LOCKS.try_acquire(
        _ids_of(items)))
    register(lk + "isLocked", lambda item: LOCKS.is_locked(
        _ids_of(item)[0]) if _ids_of(item) else False)
    register(lk + "unlockNodes", lambda nodes: LOCKS.release(
        _ids_of(nodes)))
    register(lk + "unlockRelationships", lambda rels: LOCKS.release(
        _ids_of(rels)))
    register(lk + "unlockBatch", lambda items: LOCKS.release(
        _ids_of(items)))
    register(lk + "unlockAll", lambda: LOCKS.release_all())
    register(lk + "clear", lambda: LOCKS.release_all())
    register(lk + "batch", lambda items, timeout=10.0: LOCKS.acquire(
        _ids_of(items), float(timeout)))
    register(lk + "stats", lambda: LOCKS.stats())
    register(lk + "detectDeadlock", lambda: {
        "deadlocks": [], "note": "lock keys are acquired in total order; "
        "cycles cannot form"})
    register(lk + "waitFor", lambda item, timeout=10.0: (
        LOCKS.acquire(_ids_of(item), float(timeout))
        and bool(LOCKS.release(_ids_of(item)) or True)))
    register(lk + "priority", lambda level=0: {"priority": int(level)})
    register(lk + "trylock", lambda items: LOCKS.try_acquire(
        _ids_of(items)))


# -- apoc.log -------------------------------------------------------------


class _LogRing:
    """In-memory log ring + timers, served behind apoc.log.* (reference
    apoc/log; tail/search/stream read the ring)."""

    LEVELS = ("trace", "debug", "info", "warn", "error")

    def __init__(self, cap: int = 2048):
        self.cap = cap
        self.entries: List[Dict[str, Any]] = []
        self.level = "info"
        self.timers: Dict[str, float] = {}
        self._lock = threading.Lock()

    def log(self, level: str, message: str, category: str = "general"):
        level = level if level in self.LEVELS else "info"
        if self.LEVELS.index(level) < self.LEVELS.index(self.level):
            return None
        entry = {"ts": _time.time(), "level": level,
                 "message": str(message), "category": category}
        with self._lock:
            self.entries.append(entry)
            if len(self.entries) > self.cap:
                del self.entries[: len(self.entries) - self.cap]
        py_level = {"trace": "debug", "warn": "warning"}.get(level, level)
        getattr(_log, py_level)("%s: %s", category, message)
        return entry["message"]

    def tail(self, n: int = 10) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.entries[-int(n):])

    def search(self, pattern: str) -> List[Dict[str, Any]]:
        import re as _re
        rx = _re.compile(str(pattern))
        with self._lock:
            return [e for e in self.entries if rx.search(e["message"])]

    def clear(self) -> int:
        with self._lock:
            n = len(self.entries)
            self.entries.clear()
            return n


LOG = _LogRing()


def _install_log() -> None:
    lg = "apoc.log."
    for level in ("trace", "debug", "info", "warn", "error"):
        register(lg + level,
                 (lambda lv: lambda message, *args: LOG.log(
                     lv, str(message) % tuple(args) if args else message))
                 (level))
    register(lg + "setLevel", lambda level: (
        setattr(LOG, "level", level) or level
        if level in _LogRing.LEVELS
        else _raise_level(level)))
    register(lg + "getLevel", lambda: LOG.level)
    register(lg + "tail", lambda n=10: LOG.tail(n))
    register(lg + "stream", lambda: LOG.tail(len(LOG.entries)))
    register(lg + "search", lambda pattern: LOG.search(pattern))
    register(lg + "clear", lambda: LOG.clear())
    register(lg + "rotate", lambda: LOG.clear())
    register(lg + "stats", lambda: {
        "entries": len(LOG.entries), "level": LOG.level,
        "byLevel": {lv: sum(1 for e in LOG.entries if e["level"] == lv)
                    for lv in _LogRing.LEVELS}})
    register(lg + "format", lambda fmt, *args: LOG.log(
        "info", str(fmt) % tuple(args)))
    register(lg + "custom", lambda category, message: LOG.log(
        "info", message, category=str(category)))
    register(lg + "audit", lambda message: LOG.log(
        "info", message, category="audit"))
    register(lg + "security", lambda message: LOG.log(
        "warn", message, category="security"))
    register(lg + "query", lambda message: LOG.log(
        "debug", message, category="query"))
    register(lg + "result", lambda message: LOG.log(
        "debug", message, category="result"))
    register(lg + "progress", lambda current, total, message="": LOG.log(
        "info", f"[{current}/{total}] {message}", category="progress"))

    def _timer(name, reset=False):
        now = _time.time()
        if reset or name not in LOG.timers:
            LOG.timers[name] = now
            return 0.0
        return (now - LOG.timers[name]) * 1000.0

    register(lg + "timer", _timer)

    def _memory():
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        return {"maxRssKb": ru.ru_maxrss}

    register(lg + "memory", _memory)
    register(lg + "metrics", lambda: {
        "entries": len(LOG.entries),
        "timers": {k: (_time.time() - v) * 1000.0
                   for k, v in LOG.timers.items()}})
    register(lg + "performance", lambda: {
        "timers": {k: (_time.time() - v) * 1000.0
                   for k, v in LOG.timers.items()}})

    def _to_file(path):
        import json as _json
        with open(str(path), "a", encoding="utf-8") as f:
            for e in LOG.tail(len(LOG.entries)):
                f.write(_json.dumps(e) + "\n")
        return len(LOG.entries)

    register(lg + "toFile", _to_file)


def _raise_level(level):
    raise CypherRuntimeError(
        f"unknown log level {level!r}; expected one of "
        f"{', '.join(_LogRing.LEVELS)}")


# -- apoc.warmup ----------------------------------------------------------


def _install_warmup() -> None:
    wu = "apoc.warmup."

    def _catalog(ctx):
        return getattr(ctx.ex, "columnar", None)

    def _warm_nodes(ctx):
        cat = _catalog(ctx)
        n = len(cat.nodes()) if cat is not None else sum(
            1 for _ in ctx.storage.all_nodes())
        return {"nodesLoaded": n}

    def _warm_rels(ctx):
        cat = _catalog(ctx)
        total = 0
        if cat is not None:
            for t in cat.edge_types():
                total += len(cat.edge_table(t))
        else:
            total = sum(1 for _ in ctx.storage.all_edges())
        return {"relationshipsLoaded": total}

    def _warm_props(ctx):
        cat = _catalog(ctx)
        keys = set()
        for node in ctx.storage.all_nodes():
            keys.update(node.properties)
        if cat is not None:
            for k in keys:
                cat.node_prop_col(k)
        return {"propertyColumns": len(keys)}

    def _warm_indexes(ctx):
        cat = _catalog(ctx)
        built = 0
        if cat is not None:
            labels = {l for n in ctx.storage.all_nodes() for l in n.labels}
            for l in labels:
                cat.label_rows(l)
                built += 1
        return {"labelIndexes": built}

    def _run(ctx):
        out = {}
        out.update(_warm_nodes(ctx))
        out.update(_warm_rels(ctx))
        out.update(_warm_props(ctx))
        out.update(_warm_indexes(ctx))
        out["status"] = "ok"
        return out

    register_ctx(wu + "run", _run)
    register_ctx(wu + "runWithParams", lambda ctx, params=None: _run(ctx))
    register_ctx(wu + "nodes", _warm_nodes)
    register_ctx(wu + "relationships", _warm_rels)
    register_ctx(wu + "properties", _warm_props)
    register_ctx(wu + "indexes", _warm_indexes)
    register_ctx(wu + "cache", _run)
    register_ctx(wu + "clear", lambda ctx: (
        _catalog(ctx).invalidate() if _catalog(ctx) is not None else None,
        {"status": "cleared"})[1])
    register_ctx(wu + "stats", lambda ctx: {
        "nodeCount": ctx.storage.count_nodes(),
        "relCount": ctx.storage.count_edges(),
        "catalogVersion": getattr(_catalog(ctx), "version", None)})
    register_ctx(wu + "status", lambda ctx: {
        "warm": _catalog(ctx) is not None, "status": "ok"})
    register_ctx(wu + "progress", lambda ctx: {"progress": 1.0})
    register_ctx(wu + "optimize", lambda ctx: _run(ctx))
    register_ctx(wu + "subgraph", lambda ctx, label: {
        "nodesLoaded": len(ctx.storage.get_nodes_by_label(label))})
    register_ctx(wu + "path", lambda ctx, label=None: _run(ctx))

    def _schedule(ctx, interval_s=3600):
        return {"scheduled": False,
                "note": "use apoc.periodic.repeat('warmup', "
                        "'CALL apoc.warmup.run()', interval)"}

    register_ctx(wu + "schedule", _schedule)


def install() -> None:
    _install_atomic()
    _install_create()
    _install_merge()
    _install_refactor()
    _install_schema()
    _install_lock()
    _install_log()
    _install_warmup()


install()
