"""APOC value-level long tail: bitwise, number, math, stats, scoring,
temporal, text, util, json, diff, coll, convert, date, xml, agg.

Reference: apoc/apoc.go:222 registerAllFunctions (983 names across ~40
categories). This module covers every category whose functions are pure
value transforms (no storage access); graph-touching categories live in
apoc_graph.py. Registered into the same table as nornicdb_tpu.query.apoc
so the executor's single lookup path serves them.

Aggregates (apoc.agg.*) are special: the executor collects per-row
argument tuples and calls the finalizers in AGG_FINALIZERS.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json as _json
import math
import re
import time as _time
import urllib.parse
import uuid as _uuid
import zlib
from typing import Any, Callable, Dict, List, Optional

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.query.apoc import register
from nornicdb_tpu.storage.types import Edge, Node

_U64 = (1 << 64) - 1
_I64_MIN = -(1 << 63)


def _i64(x: Any) -> int:
    """Coerce to a signed 64-bit integer (two's complement wrap)."""
    v = int(x) & _U64
    return v - (1 << 64) if v >= (1 << 63) else v


def _nums(lst) -> List[float]:
    if lst is None:
        return []
    return [float(x) for x in lst
            if isinstance(x, (int, float)) and not isinstance(x, bool)]


def _install_bitwise() -> None:
    register("apoc.bitwise.and", lambda a, b: _i64(_i64(a) & _i64(b)))
    register("apoc.bitwise.or", lambda a, b: _i64(_i64(a) | _i64(b)))
    register("apoc.bitwise.xor", lambda a, b: _i64(_i64(a) ^ _i64(b)))
    register("apoc.bitwise.not", lambda a: _i64(~_i64(a)))
    register("apoc.bitwise.leftShift", lambda a, n: _i64(_i64(a) << int(n)))
    register("apoc.bitwise.rightShift",
             lambda a, n: _i64(_i64(a) >> int(n)))  # arithmetic shift
    register("apoc.bitwise.rotateLeft", lambda a, n: _i64(
        ((_i64(a) & _U64) << (int(n) % 64) |
         (_i64(a) & _U64) >> (64 - int(n) % 64)) & _U64))
    register("apoc.bitwise.rotateRight", lambda a, n: _i64(
        ((_i64(a) & _U64) >> (int(n) % 64) |
         (_i64(a) & _U64) << (64 - int(n) % 64)) & _U64))
    register("apoc.bitwise.setBit", lambda a, i: _i64(_i64(a) | (1 << int(i))))
    register("apoc.bitwise.clearBit",
             lambda a, i: _i64(_i64(a) & ~(1 << int(i))))
    register("apoc.bitwise.toggleBit",
             lambda a, i: _i64(_i64(a) ^ (1 << int(i))))
    register("apoc.bitwise.testBit",
             lambda a, i: bool((_i64(a) >> int(i)) & 1))
    register("apoc.bitwise.countBits",
             lambda a: bin(_i64(a) & _U64).count("1"))
    register("apoc.bitwise.reverseBits", lambda a: _i64(
        int(format(_i64(a) & _U64, "064b")[::-1], 2)))

    def _bit_op(a, op, b=None):
        ops = {"&": lambda: _i64(a) & _i64(b), "and": lambda: _i64(a) & _i64(b),
               "|": lambda: _i64(a) | _i64(b), "or": lambda: _i64(a) | _i64(b),
               "^": lambda: _i64(a) ^ _i64(b), "xor": lambda: _i64(a) ^ _i64(b),
               "~": lambda: ~_i64(a), "not": lambda: ~_i64(a),
               "<<": lambda: _i64(a) << int(b),
               ">>": lambda: _i64(a) >> int(b)}
        fn = ops.get(str(op).lower())
        if fn is None:
            raise CypherRuntimeError(f"apoc.bitwise.op: unknown op {op!r}")
        return _i64(fn())

    register("apoc.bitwise.op", _bit_op)


_ROMAN = [(1000, "M"), (900, "CM"), (500, "D"), (400, "CD"), (100, "C"),
          (90, "XC"), (50, "L"), (40, "XL"), (10, "X"), (9, "IX"),
          (5, "V"), (4, "IV"), (1, "I")]


def _romanize(n) -> str:
    n = int(n)
    if not 0 < n < 4000:
        raise CypherRuntimeError("romanize expects 1..3999")
    out = []
    for v, sym in _ROMAN:
        while n >= v:
            out.append(sym)
            n -= v
    return "".join(out)


def _arabize(s) -> int:
    vals = {"I": 1, "V": 5, "X": 10, "L": 50, "C": 100, "D": 500, "M": 1000}
    s = str(s).upper()
    total = 0
    prev = 0
    for ch in reversed(s):
        if ch not in vals:
            raise CypherRuntimeError(f"arabize: bad numeral {ch!r}")
        v = vals[ch]
        total += v if v >= prev else -v
        prev = max(prev, v)
    return total


def _is_prime(n) -> bool:
    n = int(n)
    if n < 2:
        return False
    if n < 4:
        return True
    if n % 2 == 0:
        return False
    f = 3
    while f * f <= n:
        if n % f == 0:
            return False
        f += 2
    return True


def _next_prime(n) -> int:
    n = int(n) + 1
    while not _is_prime(n):
        n += 1
    return n


def _fibonacci(n) -> int:
    n = int(n)
    if n < 0:
        raise CypherRuntimeError("fibonacci expects n >= 0")
    a, b = 0, 1
    for _ in range(n):
        a, b = b, a + b
    return a


def _factorial(n) -> int:
    n = int(n)
    if n < 0:
        raise CypherRuntimeError("factorial expects n >= 0")
    if n > 170:
        raise CypherRuntimeError("factorial overflow (n > 170)")
    return math.factorial(n)


def _install_number() -> None:
    import random as _random

    n = "apoc.number."
    register(n + "abs", lambda x: None if x is None else abs(x))
    register(n + "ceil", lambda x: None if x is None else math.ceil(x))
    register(n + "floor", lambda x: None if x is None else math.floor(x))
    # reuse the core builtin's half-away-from-zero rounding (Cypher and
    # the reference round 2.5 -> 3, not banker's 2)
    from nornicdb_tpu.query.functions import REGISTRY as _CORE

    register(n + "round", _CORE["round"])
    register(n + "sign", lambda x: None if x is None else (
        0 if x == 0 else (1 if x > 0 else -1)))
    register(n + "exp", lambda x: None if x is None else math.exp(x))
    register(n + "log", lambda x: None if x is None else math.log(x))
    register(n + "log10", lambda x: None if x is None else math.log10(x))
    register(n + "sqrt", lambda x: None if x is None else math.sqrt(x))
    register(n + "power", lambda x, y: None if x is None else x ** y)
    register(n + "gcd", lambda a, b: math.gcd(int(a), int(b)))
    register(n + "lcm", lambda a, b: (
        0 if int(a) == 0 or int(b) == 0
        else abs(int(a) * int(b)) // math.gcd(int(a), int(b))))
    register(n + "isEven", lambda x: int(x) % 2 == 0)
    register(n + "isOdd", lambda x: int(x) % 2 != 0)
    register(n + "isPrime", _is_prime)
    register(n + "nextPrime", _next_prime)
    register(n + "factorial", _factorial)
    register(n + "fibonacci", _fibonacci)
    register(n + "lerp", lambda a, b, t: float(a) + (float(b) - float(a)) * float(t))
    register(n + "clamp", lambda x, lo, hi: max(float(lo), min(float(hi), float(x))))
    register(n + "normalize", lambda x, lo, hi: (
        0.0 if float(hi) == float(lo)
        else (float(x) - float(lo)) / (float(hi) - float(lo))))
    register(n + "map", lambda x, a, b, c, d: (
        float(c) if float(b) == float(a)
        else float(c) + (float(x) - float(a)) * (float(d) - float(c))
        / (float(b) - float(a))))
    register(n + "random", lambda: _random.random())
    register(n + "randomInt", lambda a, b: _random.randrange(int(a), int(b)))
    register(n + "toBase", lambda x, base: _to_base(int(x), int(base)))
    register(n + "fromBase", lambda s, base: int(str(s), int(base)))
    register(n + "toBinary", lambda x: format(int(x), "b"))
    register(n + "fromBinary", lambda s: int(str(s), 2))
    register(n + "toHex", lambda x: format(int(x), "x"))
    register(n + "fromHex", lambda s: int(str(s).removeprefix("0x"), 16))
    register(n + "toOctal", lambda x: format(int(x), "o"))
    register(n + "fromOctal", lambda s: int(str(s), 8))
    register(n + "romanize", _romanize)
    register(n + "arabize", _arabize)

    def _parse(s, pattern=None):
        s = str(s).strip().replace(",", "")
        try:
            return int(s)
        except ValueError:
            try:
                return float(s)
            except ValueError:
                raise CypherRuntimeError(f"apoc.number.parse: {s!r}")

    register(n + "parse", _parse)

    def _exact(s):
        from decimal import Decimal, InvalidOperation
        try:
            return str(Decimal(str(s)).normalize())
        except InvalidOperation:
            raise CypherRuntimeError(f"apoc.number.exact: {s!r}")

    register(n + "exact", _exact)


def _to_base(x: int, base: int) -> str:
    if not 2 <= base <= 36:
        raise CypherRuntimeError("base must be 2..36")
    if x == 0:
        return "0"
    digits = "0123456789abcdefghijklmnopqrstuvwxyz"
    neg = x < 0
    x = abs(x)
    out = []
    while x:
        out.append(digits[x % base])
        x //= base
    return ("-" if neg else "") + "".join(reversed(out))


def _median(vals: List[float]) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    m = len(s) // 2
    return float(s[m]) if len(s) % 2 else (s[m - 1] + s[m]) / 2.0


def _mode(vals: List[Any]) -> Any:
    if not vals:
        return None
    counts: Dict[Any, int] = {}
    for v in vals:
        counts[v] = counts.get(v, 0) + 1
    return max(counts.items(), key=lambda kv: kv[1])[0]


def _variance(vals: List[float], sample: bool = False) -> Optional[float]:
    if not vals or (sample and len(vals) < 2):
        return None
    mean = sum(vals) / len(vals)
    den = (len(vals) - 1) if sample else len(vals)
    return sum((x - mean) ** 2 for x in vals) / den


def _percentile(vals: List[float], p: float) -> Optional[float]:
    if not vals:
        return None
    s = sorted(vals)
    pos = float(p) * (len(s) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    frac = pos - lo
    return s[lo] * (1 - frac) + s[hi] * frac


def _install_math_stats() -> None:
    import random as _random

    m = "apoc.math."
    for name, fn in [
        ("abs", abs), ("acos", math.acos), ("asin", math.asin),
        ("atan", math.atan), ("ceil", math.ceil), ("cos", math.cos),
        ("cosh", math.cosh), ("exp", math.exp), ("floor", math.floor),
        ("log", math.log), ("log10", math.log10), ("sin", math.sin),
        ("sinh", math.sinh), ("sqrt", math.sqrt), ("tan", math.tan),
    ]:
        register(m + name, (lambda f: lambda x: None if x is None else f(x))(fn))
    register(m + "atan2", lambda y, x: math.atan2(y, x))
    register(m + "pow", lambda x, y: float(x) ** float(y))
    register(m + "clamp",
             lambda x, lo, hi: max(float(lo), min(float(hi), float(x))))
    register(m + "factorial", _factorial)
    register(m + "fibonacci", _fibonacci)
    register(m + "gcd", lambda a, b: math.gcd(int(a), int(b)))
    register(m + "lcm", lambda a, b: (
        0 if int(a) == 0 or int(b) == 0
        else abs(int(a) * int(b)) // math.gcd(int(a), int(b))))
    register(m + "isPrime", _is_prime)
    register(m + "nextPrime", _next_prime)
    register(m + "lerp",
             lambda a, b, t: float(a) + (float(b) - float(a)) * float(t))
    register(m + "logit", lambda p: math.log(float(p) / (1.0 - float(p))))
    register(m + "maxDouble", lambda: 1.7976931348623157e308)
    register(m + "minDouble", lambda: 4.9e-324)
    register(m + "mean", lambda l: (
        sum(_nums(l)) / len(_nums(l))) if _nums(l) else None)
    register(m + "median", lambda l: _median(_nums(l)))
    register(m + "mode", lambda l: _mode(list(l or [])))
    register(m + "normalize", lambda x, lo, hi: (
        0.0 if float(hi) == float(lo)
        else (float(x) - float(lo)) / (float(hi) - float(lo))))
    register(m + "percentile", lambda l, p: _percentile(_nums(l), p))
    register(m + "product", lambda l: math.prod(_nums(l)) if _nums(l) else None)
    register(m + "random", lambda: _random.random())
    register(m + "randomInt", lambda a, b: _random.randrange(int(a), int(b)))
    register(m + "range", lambda l: (
        (max(_nums(l)) - min(_nums(l))) if _nums(l) else None))
    register(m + "stdev", lambda l: (
        math.sqrt(v) if (v := _variance(_nums(l), sample=True)) is not None
        else None))
    register(m + "sum", lambda l: sum(_nums(l)) if l else 0.0)
    register(m + "variance", lambda l: _variance(_nums(l), sample=True))

    s = "apoc.stats."
    register(s + "count", lambda l: len(l or []))
    register(s + "max", lambda l: max(_nums(l)) if _nums(l) else None)
    register(s + "min", lambda l: min(_nums(l)) if _nums(l) else None)
    register(s + "mean", lambda l: (
        sum(_nums(l)) / len(_nums(l))) if _nums(l) else None)
    register(s + "median", lambda l: _median(_nums(l)))
    register(s + "mode", lambda l: _mode(list(l or [])))
    register(s + "sum", lambda l: sum(_nums(l)) if l else 0.0)
    register(s + "range", lambda l: (
        (max(_nums(l)) - min(_nums(l))) if _nums(l) else None))
    register(s + "stddev", lambda l: (
        math.sqrt(v) if (v := _variance(_nums(l), sample=True)) is not None
        else None))
    register(s + "variance", lambda l: _variance(_nums(l), sample=True))
    register(s + "percentile", lambda l, p: _percentile(_nums(l), p))
    register(s + "zscore", lambda l, x: (
        None if not _nums(l) or not _variance(_nums(l))
        else (float(x) - sum(_nums(l)) / len(_nums(l)))
        / math.sqrt(_variance(_nums(l)))))
    register(s + "normalize", lambda l: (
        [(x - min(v)) / (max(v) - min(v)) if max(v) != min(v) else 0.0
         for x in v] if (v := _nums(l)) else []))

    def _quartiles(l):
        v = _nums(l)
        if not v:
            return None
        return {"q1": _percentile(v, 0.25), "q2": _percentile(v, 0.5),
                "q3": _percentile(v, 0.75)}

    register(s + "quartiles", _quartiles)

    def _iqr(l):
        q = _quartiles(l)
        return None if q is None else q["q3"] - q["q1"]

    register(s + "iqr", _iqr)

    def _outliers(l):
        v = _nums(l)
        q = _quartiles(v)
        if q is None:
            return []
        spread = 1.5 * (q["q3"] - q["q1"])
        return [x for x in v
                if x < q["q1"] - spread or x > q["q3"] + spread]

    register(s + "outliers", _outliers)

    def _moment(v, k):
        mean = sum(v) / len(v)
        sd = math.sqrt(_variance(v))
        if sd == 0:
            return 0.0
        return sum(((x - mean) / sd) ** k for x in v) / len(v)

    register(s + "skewness", lambda l: (
        _moment(v, 3) if len(v := _nums(l)) >= 2 and _variance(v) else None))
    register(s + "kurtosis", lambda l: (
        _moment(v, 4) - 3.0
        if len(v := _nums(l)) >= 2 and _variance(v) else None))

    def _correlation(a, b):
        va, vb = _nums(a), _nums(b)
        if len(va) != len(vb) or len(va) < 2:
            return None
        ma = sum(va) / len(va)
        mb = sum(vb) / len(vb)
        cov = sum((x - ma) * (y - mb) for x, y in zip(va, vb))
        da = math.sqrt(sum((x - ma) ** 2 for x in va))
        db = math.sqrt(sum((y - mb) ** 2 for y in vb))
        if da == 0 or db == 0:
            return None
        return cov / (da * db)

    register(s + "correlation", _correlation)

    def _covariance(a, b):
        va, vb = _nums(a), _nums(b)
        if len(va) != len(vb) or len(va) < 2:
            return None
        ma = sum(va) / len(va)
        mb = sum(vb) / len(vb)
        return sum((x - ma) * (y - mb)
                   for x, y in zip(va, vb)) / (len(va) - 1)

    register(s + "covariance", _covariance)

    def _histogram(l, buckets=10):
        v = _nums(l)
        if not v:
            return []
        lo, hi = min(v), max(v)
        nb = max(int(buckets), 1)
        width = (hi - lo) / nb or 1.0
        counts = [0] * nb
        for x in v:
            i = min(int((x - lo) / width), nb - 1)
            counts[i] += 1
        return [{"min": lo + i * width, "max": lo + (i + 1) * width,
                 "count": c} for i, c in enumerate(counts)]

    register(s + "histogram", _histogram)

    def _summary(l):
        v = _nums(l)
        if not v:
            return {"count": 0}
        return {"count": len(v), "min": min(v), "max": max(v),
                "mean": sum(v) / len(v), "median": _median(v),
                "stddev": (math.sqrt(_variance(v, sample=True))
                           if len(v) > 1 else 0.0),
                "sum": sum(v)}

    register(s + "summary", _summary)

    def _degrees(l):
        """Degree distribution summary of an integer degree list."""
        v = _nums(l)
        if not v:
            return {"count": 0}
        return {"count": len(v), "min": min(v), "max": max(v),
                "mean": sum(v) / len(v), "median": _median(v)}

    register(s + "degrees", _degrees)


def _install_scoring() -> None:
    sc = "apoc.scoring."

    def _pairs(a, b):
        va, vb = _nums(a), _nums(b)
        if len(va) != len(vb) or not va:
            return None
        return va, vb

    def _cosine(a, b):
        p = _pairs(a, b)
        if p is None:
            return None
        va, vb = p
        na = math.sqrt(sum(x * x for x in va))
        nb = math.sqrt(sum(y * y for y in vb))
        if na == 0 or nb == 0:
            return 0.0
        return sum(x * y for x, y in zip(va, vb)) / (na * nb)

    register(sc + "cosine", _cosine)
    register(sc + "euclidean", lambda a, b: (
        None if _pairs(a, b) is None
        else math.sqrt(sum((x - y) ** 2 for x, y in zip(*_pairs(a, b))))))
    register(sc + "manhattan", lambda a, b: (
        None if _pairs(a, b) is None
        else sum(abs(x - y) for x, y in zip(*_pairs(a, b)))))

    def _pearson(a, b):
        p = _pairs(a, b)
        if p is None or len(p[0]) < 2:
            return None
        va, vb = p
        ma, mb = sum(va) / len(va), sum(vb) / len(vb)
        num = sum((x - ma) * (y - mb) for x, y in zip(va, vb))
        da = math.sqrt(sum((x - ma) ** 2 for x in va))
        db = math.sqrt(sum((y - mb) ** 2 for y in vb))
        return None if da == 0 or db == 0 else num / (da * db)

    register(sc + "pearson", _pearson)

    def _jaccard(a, b):
        s, t = set(_hashable_list(a)), set(_hashable_list(b))
        return len(s & t) / len(s | t) if s | t else 0.0

    def _dice(a, b):
        s, t = set(_hashable_list(a)), set(_hashable_list(b))
        return 2 * len(s & t) / (len(s) + len(t)) if s or t else 0.0

    def _overlap(a, b):
        s, t = set(_hashable_list(a)), set(_hashable_list(b))
        return len(s & t) / min(len(s), len(t)) if s and t else 0.0

    register(sc + "jaccard", _jaccard)
    register(sc + "dice", _dice)
    register(sc + "overlap", _overlap)
    register(sc + "tf", lambda count, total: (
        0.0 if not total else float(count) / float(total)))
    register(sc + "idf", lambda df, n_docs: (
        0.0 if not df else math.log(float(n_docs) / float(df))))
    register(sc + "tfidf", lambda count, total, df, n_docs: (
        (0.0 if not total else float(count) / float(total))
        * (0.0 if not df else math.log(float(n_docs) / float(df)))))

    def _bm25(tf, df, n_docs, dl, avgdl, k1=1.2, b=0.75):
        tf, df, n_docs = float(tf), float(df), float(n_docs)
        dl, avgdl = float(dl), float(avgdl)
        idf = math.log(1.0 + (n_docs - df + 0.5) / (df + 0.5))
        denom = tf + k1 * (1 - b + b * (dl / avgdl if avgdl else 1.0))
        return idf * (tf * (k1 + 1)) / denom if denom else 0.0

    register(sc + "bm25", _bm25)
    register(sc + "sigmoid", lambda x: 1.0 / (1.0 + math.exp(-float(x))))

    def _softmax(l):
        v = _nums(l)
        if not v:
            return []
        mx = max(v)
        exps = [math.exp(x - mx) for x in v]
        tot = sum(exps)
        return [e / tot for e in exps]

    register(sc + "softmax", _softmax)
    register(sc + "minmax", lambda l: (
        [(x - min(v)) / (max(v) - min(v)) if max(v) != min(v) else 0.0
         for x in v] if (v := _nums(l)) else []))
    register(sc + "normalize", lambda l: (
        [x / n for x in v] if (v := _nums(l)) and
        (n := math.sqrt(sum(x * x for x in v))) else list(v or [])))
    register(sc + "zscore", lambda l: (
        [(x - sum(v) / len(v)) / sd for x in v]
        if (v := _nums(l)) and len(v) > 1 and
        (sd := math.sqrt(_variance(v))) else [0.0] * len(_nums(l))))
    register(sc + "percentile", lambda l, p: _percentile(_nums(l), p))

    def _rank(l, desc=True):
        v = _nums(l)
        order = sorted(range(len(v)), key=lambda i: v[i], reverse=bool(desc))
        ranks = [0] * len(v)
        for r, i in enumerate(order):
            ranks[i] = r + 1
        return ranks

    register(sc + "rank", _rank)
    register(sc + "topK", lambda l, k: sorted(
        _nums(l), reverse=True)[: int(k)])
    register(sc + "pagerank", lambda incoming, damping=0.85: (
        (1.0 - float(damping)) + float(damping) * sum(_nums(incoming))))


def _install_coll_extras() -> None:
    import random as _random

    c = "apoc.coll."
    register(c + "containsDuplicates", lambda l: (
        len(_hashable_list(l)) != len(set(_hashable_list(l)))))
    register(c + "containsSorted", lambda l, v: _binary_contains(l or [], v))
    def _disjunction(a, b):
        a, b = list(a or []), list(b or [])
        ka, kb = set(_hashable_list(a)), set(_hashable_list(b))
        seen = set()
        out = []
        for x, k in zip(a + b, _hashable_list(a) + _hashable_list(b)):
            if k in seen or ((k in ka) == (k in kb)):
                continue
            seen.add(k)
            out.append(x)
        return out

    register(c + "disjunction", _disjunction)
    register(c + "duplicatesWithCount", lambda l: [
        {"item": k, "count": n}
        for k, n in _freq(l).items() if n > 1])
    register(c + "frequenciesAsMap", lambda l: {
        str(k): v for k, v in _freq(l).items()})
    register(c + "insertAll", lambda l, idx, items: (
        list(l or [])[: int(idx)] + list(items or [])
        + list(l or [])[int(idx):]))
    register(c + "isEmpty", lambda l: not l)
    register(c + "isNotEmpty", lambda l: bool(l))
    register(c + "pairsMin", lambda l: [
        [l[i], l[i + 1]] for i in range(len(l or []) - 1)])
    register(c + "randomItems", lambda l, n, allow_repeat=False: (
        [_random.choice(l) for _ in range(int(n))] if allow_repeat and l
        else _random.sample(list(l or []), min(int(n), len(l or [])))))
    register(c + "slice", lambda l, offset, length: list(
        (l or [])[int(offset): int(offset) + int(length)]))


def _freq(l) -> Dict[Any, int]:
    out: Dict[Any, int] = {}
    for x in l or []:
        k = x if not isinstance(x, (list, dict)) else repr(x)
        out[k] = out.get(k, 0) + 1
    return out


def _hashable_list(l) -> List[Any]:
    return [x if not isinstance(x, (list, dict)) else repr(x)
            for x in (l or [])]


def _binary_contains(l: List[Any], v: Any) -> bool:
    lo, hi = 0, len(l) - 1
    while lo <= hi:
        mid = (lo + hi) // 2
        if l[mid] == v:
            return True
        try:
            less = l[mid] < v
        except TypeError:
            return v in l
        if less:
            lo = mid + 1
        else:
            hi = mid - 1
    return False


def _install_text_util() -> None:
    t = "apoc.text."
    register(t + "base64Encode", lambda s: base64.b64encode(
        str(s).encode()).decode())

    def _b64decode(s):
        try:
            return base64.b64decode(str(s)).decode("utf-8", "replace")
        except (binascii.Error, ValueError):
            raise CypherRuntimeError("invalid base64")

    register(t + "base64Decode", _b64decode)
    register(t + "bytes", lambda s: list(str(s).encode()))
    register(t + "bytesToString",
             lambda b: bytes(int(x) & 0xFF for x in (b or [])).decode(
                 "utf-8", "replace"))
    register(t + "capitalizeAll", lambda s: None if s is None else
             re.sub(r"\b\w", lambda m: m.group().upper(), str(s)))
    register(t + "decapitalizeAll", lambda s: None if s is None else
             re.sub(r"\b\w", lambda m: m.group().lower(), str(s)))
    register(t + "compareCleaned", lambda a, b: (
        _clean(a) == _clean(b)))
    register(t + "fromCodePoint", lambda *cps: "".join(
        chr(int(c)) for c in cps))
    register(t + "indexesOf", lambda s, sub: [
        m.start() for m in re.finditer(re.escape(str(sub)), str(s))]
        if s is not None and sub is not None else [])
    register(t + "ltrim", lambda s: None if s is None else str(s).lstrip())
    register(t + "rtrim", lambda s: None if s is None else str(s).rstrip())
    register(t + "trim", lambda s: None if s is None else str(s).strip())
    register(t + "reverse", lambda s: None if s is None else str(s)[::-1])
    register(t + "urlencode", lambda s: urllib.parse.quote(str(s), safe=""))
    register(t + "urldecode", lambda s: urllib.parse.unquote(str(s)))
    register(t + "phonetic", lambda s: _soundex(str(s or "")))
    register(t + "phoneticDelta", lambda a, b: {
        "phonetic1": _soundex(str(a or "")), "phonetic2": _soundex(str(b or "")),
        "delta": sum(x != y for x, y in zip(_soundex(str(a or "")),
                                            _soundex(str(b or ""))))})
    register(t + "doubleMetaphone", lambda s: _metaphone(str(s or "")))

    u = "apoc.util."
    register(u + "coalesce", lambda *args: next(
        (a for a in args if a is not None), None))
    register(u + "when", lambda cond, a, b=None: a if cond else b)

    def _case(pairs, default=None):
        items = list(pairs or [])
        for i in range(0, len(items) - 1, 2):
            if items[i]:
                return items[i + 1]
        return default

    register(u + "case", _case)

    def _validate(cond, message="validation failed", params=None):
        if cond:
            raise CypherRuntimeError(str(message))
        return None

    register(u + "validate", _validate)
    register(u + "validatePredicate",
             lambda cond, message="validation failed", params=None: (
                 _validate(cond, message) or True))

    def _validate_pattern(value, pattern, message=None):
        if value is None or not re.fullmatch(str(pattern), str(value)):
            raise CypherRuntimeError(
                str(message or f"value {value!r} does not match {pattern}"))
        return value

    register(u + "validatePattern", _validate_pattern)
    register(u + "compress", lambda s: list(zlib.compress(str(s).encode())))
    register(u + "decompress", lambda b: zlib.decompress(
        bytes(int(x) & 0xFF for x in (b or []))).decode("utf-8", "replace"))

    def _compress_algo(s, algo="deflate"):
        data = str(s).encode()
        algo = str(algo).lower()
        if algo in ("deflate", "zlib"):
            return list(zlib.compress(data))
        if algo == "gzip":
            import gzip
            return list(gzip.compress(data))
        raise CypherRuntimeError(f"unknown algorithm {algo!r}")

    def _decompress_algo(b, algo="deflate"):
        data = bytes(int(x) & 0xFF for x in (b or []))
        algo = str(algo).lower()
        if algo in ("deflate", "zlib"):
            return zlib.decompress(data).decode("utf-8", "replace")
        if algo == "gzip":
            import gzip
            return gzip.decompress(data).decode("utf-8", "replace")
        raise CypherRuntimeError(f"unknown algorithm {algo!r}")

    register(u + "compressWithAlgorithm", _compress_algo)
    register(u + "decompressWithAlgorithm", _decompress_algo)
    register(u + "encodeBase64", lambda s: base64.b64encode(
        str(s).encode()).decode())
    register(u + "decodeBase64", _b64decode)
    register(u + "encodeUrl", lambda s: urllib.parse.quote(str(s), safe=""))
    register(u + "decodeUrl", lambda s: urllib.parse.unquote(str(s)))
    for algo in ("md5", "sha1", "sha256"):
        register(u + f"{algo}Hex", (lambda a: lambda *parts: getattr(
            hashlib, a)("".join(str(p) for p in parts).encode())
            .hexdigest())(algo))
        register(u + f"{algo}Base64", (lambda a: lambda *parts: base64.
                 b64encode(getattr(hashlib, a)(
                     "".join(str(p) for p in parts).encode())
                     .digest()).decode())(algo))
    register(u + "now", lambda: int(_time.time() * 1000))
    register(u + "nowInSeconds", lambda: int(_time.time()))
    register(u + "timestamp", lambda: int(_time.time() * 1000))
    register(u + "formatTimestamp", lambda ms, fmt="%Y-%m-%dT%H:%M:%SZ": (
        _time.strftime(str(fmt), _time.gmtime(float(ms) / 1000.0))))

    def _parse_ts(s, fmt="%Y-%m-%dT%H:%M:%SZ"):
        import calendar
        return int(calendar.timegm(_time.strptime(str(s), str(fmt))) * 1000)

    register(u + "parseTimestamp", _parse_ts)
    register(u + "isNode", lambda x: isinstance(x, Node))
    register(u + "isRelationship", lambda x: isinstance(x, Edge))

    def _is_path(x):
        from nornicdb_tpu.query.functions import PathValue
        return isinstance(x, PathValue)

    register(u + "isPath", _is_path)

    def _typeof(x):
        from nornicdb_tpu.query.functions import REGISTRY
        return REGISTRY["valuetype"](x)

    register(u + "typeof", _typeof)
    register(u + "merge", lambda a, b: {**(a or {}), **(b or {})})
    def _partition(l, size):
        n = int(size)
        if n <= 0:
            raise CypherRuntimeError("partition size must be positive")
        return [list((l or [])[i: i + n]) for i in range(0, len(l or []), n)]

    register(u + "partition", _partition)
    register(u + "range", lambda a, b, step=1: list(
        range(int(a), int(b) + (1 if int(step) > 0 else -1), int(step))))
    register(u + "repeat", lambda s, n: str(s) * int(n))
    register(u + "uuid", lambda: str(_uuid.uuid4()))
    register(u + "randomUuid", lambda: str(_uuid.uuid4()))

    def _sleep(ms):
        _time.sleep(min(float(ms), 10_000.0) / 1000.0)  # clamp: 10s max
        return None

    register(u + "sleep", _sleep)


def _clean(s) -> str:
    return re.sub(r"[^a-z0-9]", "", str(s or "").lower())


def _soundex(s: str) -> str:
    """Classic Soundex code (the reference's phonetic baseline)."""
    s = re.sub(r"[^A-Za-z]", "", s).upper()
    if not s:
        return ""
    codes = {**dict.fromkeys("BFPV", "1"), **dict.fromkeys("CGJKQSXZ", "2"),
             **dict.fromkeys("DT", "3"), "L": "4",
             **dict.fromkeys("MN", "5"), "R": "6"}
    out = s[0]
    prev = codes.get(s[0], "")
    for ch in s[1:]:
        code = codes.get(ch, "")
        if code and code != prev:
            out += code
        if ch not in "HW":
            prev = code
    return (out + "000")[:4]


def _metaphone(s: str) -> List[str]:
    """Simplified double-metaphone: primary key + soundex alternate."""
    s2 = re.sub(r"[^A-Za-z]", "", s).upper()
    if not s2:
        return ["", ""]
    subs = [("PH", "F"), ("GH", "H"), ("CK", "K"), ("SCH", "SK"),
            ("TH", "0"), ("SH", "X"), ("CH", "X"), ("DG", "J"),
            ("WR", "R"), ("KN", "N"), ("GN", "N")]
    w = s2
    for a, b in subs:
        w = w.replace(a, b)
    # drop vowels after the first letter; dedupe runs
    out = w[0]
    for ch in w[1:]:
        if ch in "AEIOU":
            continue
        if out and out[-1] == ch:
            continue
        out += ch
    return [out[:6], _soundex(s)]


def _install_json_diff() -> None:
    j = "apoc.json."

    def _parse(s):
        try:
            return _json.loads(s) if isinstance(s, str) else s
        except (ValueError, TypeError):
            raise CypherRuntimeError("invalid JSON")

    def _jsonable(v):
        if isinstance(v, (Node, Edge)):
            return dict(v.properties)
        if isinstance(v, list):
            return [_jsonable(x) for x in v]
        if isinstance(v, dict):
            return {k: _jsonable(x) for k, x in v.items()}
        return v

    register(j + "parse", _parse)
    register(j + "validate", lambda s: _try_json(s))
    register(j + "stringify", lambda v: _json.dumps(_jsonable(v)))
    register(j + "pretty", lambda v: _json.dumps(
        _jsonable(_parse(v) if isinstance(v, str) else v), indent=2,
        sort_keys=True))
    register(j + "compact", lambda v: _json.dumps(
        _jsonable(_parse(v) if isinstance(v, str) else v),
        separators=(",", ":")))
    register(j + "keys", lambda v: sorted(
        (_parse(v) if isinstance(v, str) else v or {}).keys()))
    register(j + "values", lambda v: list(
        (_parse(v) if isinstance(v, str) else v or {}).values()))
    register(j + "size", lambda v: len(
        _parse(v) if isinstance(v, str) else (v or {})))
    register(j + "type", lambda v: _json_type(
        _parse(v) if isinstance(v, str) else v))
    register(j + "map", lambda v: dict(
        _parse(v) if isinstance(v, str) else (v or {})))
    register(j + "merge", lambda a, b: {
        **(_parse(a) if isinstance(a, str) else a or {}),
        **(_parse(b) if isinstance(b, str) else b or {})})

    def _path_get(obj, path):
        cur = obj
        for part in _split_json_path(path):
            if isinstance(cur, dict):
                if part not in cur:
                    return None
                cur = cur[part]
            elif isinstance(cur, list):
                try:
                    cur = cur[int(part)]
                except (ValueError, IndexError):
                    return None
            else:
                return None
        return cur

    def _split_json_path(path) -> List[str]:
        p = str(path or "")
        p = p[2:] if p.startswith("$.") else p.lstrip("$")
        parts: List[str] = []
        for seg in p.split("."):
            if not seg:
                continue
            m = re.match(r"([^\[]*)((\[\d+\])*)$", seg)
            if m:
                if m.group(1):
                    parts.append(m.group(1))
                for idx in re.findall(r"\[(\d+)\]", m.group(2)):
                    parts.append(idx)
            else:
                parts.append(seg)
        return parts

    def _path_set(obj, path, value, delete=False):
        obj = _parse(obj) if isinstance(obj, str) else obj
        parts = _split_json_path(path)
        if not parts:
            return value
        import copy
        out = copy.deepcopy(obj)
        cur = out
        for part in parts[:-1]:
            if isinstance(cur, list):
                try:
                    idx = int(part)
                except ValueError:
                    raise CypherRuntimeError(
                        f"list index expected at {part!r}")
                if not 0 <= idx < len(cur):
                    raise CypherRuntimeError(f"index {idx} out of range")
                cur = cur[idx]
                continue
            nxt = cur.get(part) if isinstance(cur, dict) else None
            if not isinstance(nxt, (dict, list)):
                nxt = {}
                cur[part] = nxt
            cur = nxt
        last = parts[-1]
        if isinstance(cur, list):
            try:
                idx = int(last)
            except ValueError:
                raise CypherRuntimeError(f"list index expected at {last!r}")
            if delete:
                if 0 <= idx < len(cur):
                    cur.pop(idx)
            elif 0 <= idx < len(cur):
                cur[idx] = value
            elif idx == len(cur):
                cur.append(value)
            else:
                raise CypherRuntimeError(f"index {idx} out of range")
        elif delete:
            if isinstance(cur, dict):
                cur.pop(last, None)
        else:
            cur[last] = value
        return out

    register(j + "get", _path_get)
    register(j + "set", lambda obj, path, v: _path_set(obj, path, v))
    register(j + "delete", lambda obj, path: _path_set(
        obj, path, None, delete=True))
    register(j + "filter", lambda obj, path: _path_get(
        _parse(obj) if isinstance(obj, str) else obj, path))

    def _flatten_json(v, prefix="", out=None):
        out = {} if out is None else out
        if isinstance(v, dict):
            for k, x in v.items():
                _flatten_json(x, f"{prefix}{k}.", out)
        elif isinstance(v, list):
            for i, x in enumerate(v):
                _flatten_json(x, f"{prefix}{i}.", out)
        else:
            out[prefix[:-1]] = v
        return out

    register(j + "flatten", lambda v: _flatten_json(
        _parse(v) if isinstance(v, str) else (v or {})))

    def _unflatten(flat):
        out: Dict[str, Any] = {}
        for key, value in (flat or {}).items():
            cur = out
            parts = str(key).split(".")
            for part in parts[:-1]:
                cur = cur.setdefault(part, {})
            cur[parts[-1]] = value
        return out

    register(j + "unflatten", _unflatten)

    def _reduce(v):
        """Total count of leaf values."""
        obj = _parse(v) if isinstance(v, str) else v
        if isinstance(obj, dict):
            return sum(_reduce(x) for x in obj.values())
        if isinstance(obj, list):
            return sum(_reduce(x) for x in obj)
        return 1

    register(j + "reduce", _reduce)

    d = "apoc.diff."

    def _diff_maps(a, b):
        a, b = a or {}, b or {}
        left = {k: v for k, v in a.items() if k not in b}
        right = {k: v for k, v in b.items() if k not in a}
        different = {k: {"left": a[k], "right": b[k]}
                     for k in a.keys() & b.keys() if a[k] != b[k]}
        same = {k: a[k] for k in a.keys() & b.keys() if a[k] == b[k]}
        return {"leftOnly": left, "rightOnly": right,
                "inCommon": same, "different": different}

    register(d + "maps", _diff_maps)
    register(d + "nodes", lambda a, b: _diff_maps(
        a.properties if isinstance(a, Node) else a,
        b.properties if isinstance(b, Node) else b))
    register(d + "relationships", lambda a, b: _diff_maps(
        a.properties if isinstance(a, Edge) else a,
        b.properties if isinstance(b, Edge) else b))
    register(d + "lists", lambda a, b: {
        "leftOnly": [x for x in (a or []) if x not in (b or [])],
        "rightOnly": [x for x in (b or []) if x not in (a or [])],
        "inCommon": [x for x in (a or []) if x in (b or [])]})

    def _diff_strings(a, b):
        a, b = str(a or ""), str(b or "")
        prefix = 0
        for x, y in zip(a, b):
            if x != y:
                break
            prefix += 1
        return {"equal": a == b, "commonPrefix": a[:prefix],
                "left": a[prefix:], "right": b[prefix:],
                "distance": _levenshtein(a, b)}

    register(d + "strings", _diff_strings)

    def _deep(a, b, path=""):
        diffs: List[Dict[str, Any]] = []
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) | set(b)):
                p = f"{path}.{k}" if path else str(k)
                if k not in a:
                    diffs.append({"path": p, "kind": "added", "right": b[k]})
                elif k not in b:
                    diffs.append({"path": p, "kind": "removed", "left": a[k]})
                else:
                    diffs.extend(_deep(a[k], b[k], p))
        elif isinstance(a, list) and isinstance(b, list):
            for i in range(max(len(a), len(b))):
                p = f"{path}[{i}]"
                if i >= len(a):
                    diffs.append({"path": p, "kind": "added", "right": b[i]})
                elif i >= len(b):
                    diffs.append({"path": p, "kind": "removed", "left": a[i]})
                else:
                    diffs.extend(_deep(a[i], b[i], p))
        elif a != b:
            diffs.append({"path": path, "kind": "changed",
                          "left": a, "right": b})
        return diffs

    register(d + "deep", _deep)
    register(d + "summary", lambda a, b: {
        "differences": len(_deep(a, b)),
        "equal": not _deep(a, b)})
    register(d + "merge", lambda a, b: _deep_merge(a, b))

    def _patch(a, patches):
        import copy
        out = copy.deepcopy(a) if isinstance(a, (dict, list)) else a
        for p in patches or []:
            kind = p.get("kind")
            path = p.get("path", "")
            parts = re.split(r"\.|\[|\]", path)
            parts = [x for x in parts if x]
            cur = out
            for part in parts[:-1]:
                cur = cur[int(part)] if isinstance(cur, list) else cur[part]
            last = parts[-1] if parts else None
            if last is None:
                continue
            key = int(last) if isinstance(cur, list) else last
            if kind == "removed":
                if isinstance(cur, dict):
                    cur.pop(key, None)
                elif isinstance(cur, list) and int(last) < len(cur):
                    cur.pop(int(last))
            else:
                if isinstance(cur, list) and int(last) >= len(cur):
                    cur.append(p.get("right"))
                else:
                    cur[key] = p.get("right")
        return out

    register(d + "patch", _patch)


def _deep_merge(a, b):
    if isinstance(a, dict) and isinstance(b, dict):
        out = dict(a)
        for k, v in b.items():
            out[k] = _deep_merge(a[k], v) if k in a else v
        return out
    return b


# one edit-distance implementation for both apoc.text.* and apoc.diff.*
from nornicdb_tpu.query.apoc import _levenshtein  # noqa: E402


def _try_json(s) -> bool:
    try:
        _json.loads(s)
        return True
    except (ValueError, TypeError):
        return False


def _json_type(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "BOOLEAN"
    if isinstance(v, int):
        return "INTEGER"
    if isinstance(v, float):
        return "FLOAT"
    if isinstance(v, str):
        return "STRING"
    if isinstance(v, list):
        return "LIST"
    if isinstance(v, dict):
        return "MAP"
    return type(v).__name__.upper()


def _install_temporal_date() -> None:
    import datetime as _dt

    from nornicdb_tpu.query import temporal_types as T

    tp = "apoc.temporal."

    def _as_dt(v) -> _dt.datetime:
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return _dt.datetime.fromtimestamp(float(v) / 1000.0,
                                              tz=_dt.timezone.utc)
        dtv = T.make_datetime(v)
        return dtv._dt

    _UNIT_SECONDS = {"millisecond": 0.001, "second": 1, "minute": 60,
                     "hour": 3600, "day": 86400, "week": 604800}

    def _add(v, amount, unit="day"):
        d = _as_dt(v)
        u = str(unit).lower().rstrip("s")
        if u == "month":
            month = d.month - 1 + int(amount)
            year = d.year + month // 12
            month = month % 12 + 1
            day = min(d.day, _days_in_month(year, month))
            return T.CypherDateTime(d.replace(year=year, month=month,
                                              day=day))
        if u == "year":
            return T.CypherDateTime(d.replace(year=d.year + int(amount)))
        secs = _UNIT_SECONDS.get(u)
        if secs is None:
            raise CypherRuntimeError(f"unknown unit {unit!r}")
        return T.CypherDateTime(d + _dt.timedelta(seconds=secs * float(amount)))

    register(tp + "add", _add)
    register(tp + "subtract", lambda v, amount, unit="day": _add(
        v, -float(amount), unit))
    register(tp + "dayOfWeek", lambda v: _as_dt(v).isoweekday())
    register(tp + "dayOfYear", lambda v: _as_dt(v).timetuple().tm_yday)
    register(tp + "daysInMonth", lambda v: _days_in_month(
        _as_dt(v).year, _as_dt(v).month))
    register(tp + "quarter", lambda v: (_as_dt(v).month - 1) // 3 + 1)
    register(tp + "weekOfYear", lambda v: _as_dt(v).isocalendar()[1])
    register(tp + "isLeapYear", lambda v: _is_leap(
        int(v) if isinstance(v, (int, float)) and float(v) < 10_000
        else _as_dt(v).year))
    register(tp + "isWeekday", lambda v: _as_dt(v).isoweekday() <= 5)
    register(tp + "isWeekend", lambda v: _as_dt(v).isoweekday() > 5)
    register(tp + "toEpochMillis", lambda v: int(
        _as_dt(v).timestamp() * 1000))
    register(tp + "fromEpochMillis", lambda ms: T.CypherDateTime(
        _dt.datetime.fromtimestamp(float(ms) / 1000.0, tz=_dt.timezone.utc)))
    register(tp + "isBetween", lambda v, a, b: (
        _as_dt(a) <= _as_dt(v) <= _as_dt(b)))
    def _difference(a, b, unit="millisecond"):
        u = str(unit).lower().rstrip("s")
        secs = _UNIT_SECONDS.get(u)
        if secs is None:
            raise CypherRuntimeError(f"unknown unit {unit!r}")
        return (_as_dt(b) - _as_dt(a)).total_seconds() / secs

    register(tp + "difference", _difference)
    register(tp + "age", lambda v: T.duration_between(
        T.make_datetime(v), T.make_datetime()))
    register(tp + "timezone", lambda v=None: "UTC")
    register(tp + "toUTC", lambda v: T.CypherDateTime(
        _as_dt(v).astimezone(_dt.timezone.utc)))
    register(tp + "toLocal", lambda v: T.CypherLocalDateTime(
        _as_dt(v).replace(tzinfo=None)))
    register(tp + "truncate", lambda v, unit="day": T.truncate(
        str(unit), T.make_datetime(v), "datetime"))

    def _start_of(v, unit="day"):
        return T.truncate(str(unit), T.make_datetime(v), "datetime")

    def _end_of(v, unit="day"):
        start = _start_of(v, unit)
        nxt = _add(start, 1, str(unit))
        return T.CypherDateTime(nxt._dt - _dt.timedelta(milliseconds=1))

    register(tp + "startOf", _start_of)
    register(tp + "endOf", _end_of)

    def _round(v, unit="day"):
        d = _as_dt(v)
        floor = _start_of(v, unit)._dt
        ceil = _add(floor, 1, str(unit))._dt
        return T.CypherDateTime(
            floor if (d - floor) <= (ceil - d) else ceil)

    register(tp + "round", _round)

    def _fmt_duration(ms):
        ms = int(ms)
        sign = "-" if ms < 0 else ""
        ms = abs(ms)
        s, ms = divmod(ms, 1000)
        m, s = divmod(s, 60)
        h, m = divmod(m, 60)
        d, h = divmod(h, 24)
        parts = []
        if d:
            parts.append(f"{d}d")
        if h:
            parts.append(f"{h}h")
        if m:
            parts.append(f"{m}m")
        if s or not parts:
            parts.append(f"{s}s")
        return sign + " ".join(parts)

    register(tp + "formatDuration", _fmt_duration)
    register(tp + "duration", lambda m: T.parse_duration(m))
    register(tp + "parse", lambda s, fmt=None: (
        T.make_datetime(s) if fmt is None else T.CypherDateTime(
            _strptime_utc(s, fmt))))

    dd = "apoc.date."
    register(dd + "fromUnixtime", lambda secs, fmt="%Y-%m-%d %H:%M:%S": (
        _time.strftime(str(fmt).replace("yyyy", "%Y").replace("MM", "%m")
                       .replace("dd", "%d").replace("HH", "%H")
                       .replace("mm", "%M").replace("ss", "%S"),
                       _time.gmtime(float(secs)))))
    register(dd + "toUnixtime", lambda s, fmt=None: int(
        _as_dt(s).timestamp()))
    register(dd + "toYears", lambda ms: float(ms) / (365.25 * 86400 * 1000))
    register(dd + "systemTimezone", lambda: "UTC")
    register(dd + "fields", lambda v, fmt=None: {
        "years": _as_dt(v).year, "months": _as_dt(v).month,
        "days": _as_dt(v).day, "hours": _as_dt(v).hour,
        "minutes": _as_dt(v).minute, "seconds": _as_dt(v).second,
        "weekdays": _as_dt(v).isoweekday()})
    register(dd + "convertFormat", lambda s, from_fmt, to_fmt: (
        _strptime_utc(s, from_fmt).strftime(_java_fmt(to_fmt))))
    register(dd + "parseAsZonedDateTime", lambda s, fmt=None: (
        T.make_datetime(s) if fmt is None
        else T.CypherDateTime(_strptime_utc(s, fmt))))


def _java_fmt(fmt: str) -> str:
    return (str(fmt).replace("yyyy", "%Y").replace("MM", "%m")
            .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M")
            .replace("ss", "%S"))


def _strptime_utc(s, fmt):
    import datetime as _dt
    return _dt.datetime.strptime(str(s), _java_fmt(fmt)).replace(
        tzinfo=_dt.timezone.utc)


def _days_in_month(year: int, month: int) -> int:
    import calendar
    return calendar.monthrange(year, month)[1]


def _is_leap(year: int) -> bool:
    return year % 4 == 0 and (year % 100 != 0 or year % 400 == 0)


def _install_convert_extras() -> None:
    cv = "apoc.convert."

    def _num_or_none(x, typ):
        try:
            return typ(x)
        except (TypeError, ValueError):
            return None

    register(cv + "toIntList", lambda l: [
        _num_or_none(x, int) for x in (l or [])])
    register(cv + "toFloatList", lambda l: [
        _num_or_none(x, float) for x in (l or [])])
    register(cv + "toStringList", lambda l: [
        None if x is None else str(x) for x in (l or [])])
    register(cv + "toBooleanList", lambda l: [
        None if x is None else bool(x) for x in (l or [])])
    register(cv + "toSet", lambda l: list(dict.fromkeys(
        _hashable_list(l))))
    register(cv + "toMap", lambda v: (
        dict(v.properties) if isinstance(v, (Node, Edge))
        else dict(v or {})))
    register(cv + "toNode", lambda v: v if isinstance(v, Node) else None)
    register(cv + "toRelationship",
             lambda v: v if isinstance(v, Edge) else None)
    register(cv + "toNodeList", lambda l: [
        x for x in (l or []) if isinstance(x, Node)])
    register(cv + "toRelationshipList", lambda l: [
        x for x in (l or []) if isinstance(x, Edge)])
    register(cv + "toSortedJsonMap", lambda v: _json.dumps(
        v or {}, sort_keys=True))
    register(cv + "getJsonProperty", lambda node, key: (
        _json.loads(node.properties.get(key))
        if isinstance(node, Node) and isinstance(
            node.properties.get(key), str) else None))
    register(cv + "getJsonPropertyMap", lambda node, key: (
        m if isinstance(m := (
            _json.loads(node.properties[key])
            if isinstance(node, Node) and isinstance(
                node.properties.get(key), str) else None), dict) else None))
    register(cv + "fromJsonNode", lambda s: (
        _json.loads(s) if isinstance(s, str) else s))

    def _to_tree(paths):
        """List of paths -> nested tree keyed by node id (reference
        apoc.convert.totree)."""
        from nornicdb_tpu.query.functions import PathValue

        roots: Dict[str, Dict[str, Any]] = {}
        nodes_seen: Dict[str, Dict[str, Any]] = {}

        def entry(n: Node) -> Dict[str, Any]:
            if n.id not in nodes_seen:
                nodes_seen[n.id] = {"_id": n.id, "_type": ":".join(n.labels),
                                    **n.properties}
            return nodes_seen[n.id]

        for p in paths or []:
            if not isinstance(p, PathValue) or not p.nodes:
                continue
            root = entry(p.nodes[0])
            roots.setdefault(p.nodes[0].id, root)
            for i, rel in enumerate(p.rels):
                parent = entry(p.nodes[i])
                child = entry(p.nodes[i + 1])
                key = rel.type.lower()
                kids = parent.setdefault(key, [])
                if child not in kids:
                    kids.append(child)
        return list(roots.values())

    register(cv + "toTree", _to_tree)


def _install_xml() -> None:
    import xml.etree.ElementTree as ET
    from xml.sax.saxutils import escape as _xesc, unescape as _xunesc

    x = "apoc.xml."

    def _parse(s) -> ET.Element:
        try:
            return ET.fromstring(str(s))
        except ET.ParseError as exc:
            raise CypherRuntimeError(f"invalid XML: {exc}")

    def _el_to_map(el: ET.Element) -> Dict[str, Any]:
        out: Dict[str, Any] = {"_type": el.tag.split("}")[-1]}
        if el.attrib:
            out.update({k.split("}")[-1]: v for k, v in el.attrib.items()})
        text = (el.text or "").strip()
        if text:
            out["_text"] = text
        children = [_el_to_map(c) for c in el]
        if children:
            out["_children"] = children
        return out

    def _map_to_el(m: Dict[str, Any]) -> ET.Element:
        el = ET.Element(str(m.get("_type", "root")))
        for k, v in m.items():
            if k in ("_type", "_text", "_children"):
                continue
            el.set(k, str(v))
        if m.get("_text") is not None:
            el.text = str(m["_text"])
        for child in m.get("_children", []) or []:
            el.append(_map_to_el(child))
        return el

    register(x + "parse", lambda s: _el_to_map(_parse(s)))
    register(x + "toMap", lambda s: _el_to_map(_parse(s)))
    register(x + "toJson", lambda s: _json.dumps(_el_to_map(_parse(s))))
    register(x + "fromJson", lambda s: ET.tostring(
        _map_to_el(_json.loads(s) if isinstance(s, str) else s),
        encoding="unicode"))
    register(x + "fromMap", lambda m: ET.tostring(
        _map_to_el(m or {}), encoding="unicode"))
    register(x + "toString", lambda m: ET.tostring(
        _map_to_el(m) if isinstance(m, dict) else _parse(m),
        encoding="unicode"))
    register(x + "validate", lambda s: _xml_ok(s))
    register(x + "escape", lambda s: _xesc(str(s or "")))
    register(x + "unescape", lambda s: _xunesc(str(s or "")))
    register(x + "minify", lambda s: re.sub(r">\s+<", "><", str(s).strip()))

    def _prettify(s):
        import xml.dom.minidom
        return xml.dom.minidom.parseString(str(s)).toprettyxml(
            indent="  ").replace('<?xml version="1.0" ?>\n', "")

    register(x + "prettify", _prettify)
    register(x + "getAttribute", lambda s, attr: _parse(s).get(str(attr)))
    register(x + "getText", lambda s: "".join(_parse(s).itertext()))
    register(x + "getNamespace", lambda s: (
        m.group(1) if (m := re.match(r"\{(.+)\}", _parse(s).tag)) else None))
    register(x + "namespace", lambda s: (
        m.group(1) if (m := re.match(r"\{(.+)\}", _parse(s).tag)) else None))

    def _query(s, xpath):
        root = _parse(s)
        return [_el_to_map(el) for el in root.findall(str(xpath))]

    register(x + "query", _query)

    def _set_attribute(s, attr, value):
        el = _parse(s)
        el.set(str(attr), str(value))
        return ET.tostring(el, encoding="unicode")

    register(x + "setAttribute", _set_attribute)

    def _set_text(s, text):
        el = _parse(s)
        el.text = str(text)
        return ET.tostring(el, encoding="unicode")

    register(x + "setText", _set_text)

    def _add_child(s, child):
        el = _parse(s)
        el.append(_parse(child) if isinstance(child, str)
                  else _map_to_el(child))
        return ET.tostring(el, encoding="unicode")

    register(x + "addChild", _add_child)

    def _remove_child(s, tag):
        el = _parse(s)
        for c in list(el):
            if c.tag == str(tag):
                el.remove(c)
        return ET.tostring(el, encoding="unicode")

    register(x + "removeChild", _remove_child)
    register(x + "clone", lambda s: ET.tostring(
        _parse(s), encoding="unicode"))
    register(x + "create", lambda tag, attrs=None, text=None: ET.tostring(
        _map_to_el({"_type": tag, **(attrs or {}),
                    **({"_text": text} if text is not None else {})}),
        encoding="unicode"))

    def _transform(s, mapping):
        """Rename tags via a {old: new} map."""
        el = _parse(s)
        for node in el.iter():
            new = (mapping or {}).get(node.tag)
            if new:
                node.tag = str(new)
        root_new = (mapping or {}).get(el.tag)
        if root_new:
            el.tag = str(root_new)
        return ET.tostring(el, encoding="unicode")

    register(x + "transform", _transform)

    def _xml_ok(s) -> bool:
        try:
            ET.fromstring(str(s))
            return True
        except ET.ParseError:
            return False


def _install_hashing_extras() -> None:
    h = "apoc.hashing."

    def _cat(parts) -> bytes:
        if isinstance(parts, list):
            return "".join(str(p) for p in parts).encode()
        return str(parts).encode()

    for algo in ("md5", "sha1", "sha256", "sha384", "sha512"):
        register(h + algo, (lambda a: lambda v: getattr(hashlib, a)(
            _cat(v)).hexdigest())(algo))

    def _fnv1(v, bits64=True, fnv1a=False):
        data = _cat(v)
        if bits64:
            prime, offset, mask = 0x100000001b3, 0xcbf29ce484222325, _U64
        else:
            prime, offset, mask = 0x01000193, 0x811c9dc5, 0xFFFFFFFF
        acc = offset
        for byte in data:
            if fnv1a:
                acc = ((acc ^ byte) * prime) & mask
            else:
                acc = ((acc * prime) & mask) ^ byte
        return _i64(acc) if bits64 else acc

    register(h + "fnv1", lambda v: _fnv1(v, bits64=False))
    register(h + "fnv164", lambda v: _fnv1(v, bits64=True))
    register(h + "fnv1a", lambda v: _fnv1(v, bits64=False, fnv1a=True))
    register(h + "fnv1a64", lambda v: _fnv1(v, bits64=True, fnv1a=True))

    def _murmur3_32(v, seed=0):
        data = _cat(v)
        c1, c2 = 0xcc9e2d51, 0x1b873593
        h1 = int(seed) & 0xFFFFFFFF
        rounded = len(data) - len(data) % 4
        for i in range(0, rounded, 4):
            k1 = int.from_bytes(data[i:i + 4], "little")
            k1 = (k1 * c1) & 0xFFFFFFFF
            k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
            k1 = (k1 * c2) & 0xFFFFFFFF
            h1 ^= k1
            h1 = ((h1 << 13) | (h1 >> 19)) & 0xFFFFFFFF
            h1 = (h1 * 5 + 0xe6546b64) & 0xFFFFFFFF
        k1 = 0
        tail = data[rounded:]
        if len(tail) >= 3:
            k1 ^= tail[2] << 16
        if len(tail) >= 2:
            k1 ^= tail[1] << 8
        if len(tail) >= 1:
            k1 ^= tail[0]
            k1 = (k1 * c1) & 0xFFFFFFFF
            k1 = ((k1 << 15) | (k1 >> 17)) & 0xFFFFFFFF
            k1 = (k1 * c2) & 0xFFFFFFFF
            h1 ^= k1
        h1 ^= len(data)
        h1 ^= h1 >> 16
        h1 = (h1 * 0x85ebca6b) & 0xFFFFFFFF
        h1 ^= h1 >> 13
        h1 = (h1 * 0xc2b2ae35) & 0xFFFFFFFF
        h1 ^= h1 >> 16
        return h1

    register(h + "murmurhash3", _murmur3_32)

    def _jumphash(key, buckets):
        """Jump consistent hash (Lamping & Veach)."""
        k = int(hashlib.md5(_cat(key)).hexdigest()[:16], 16)
        b, j = -1, 0
        nb = int(buckets)
        while j < nb:
            b = j
            k = (k * 2862933555777941757 + 1) & _U64
            j = int((b + 1) * ((1 << 31) / ((k >> 33) + 1)))
        return b

    register(h + "jumphash", _jumphash)
    register(h + "consistenthash", lambda key, buckets: _jumphash(
        key, buckets))

    def _rendezvous(key, nodes):
        best, best_w = None, -1
        for node in nodes or []:
            w = int(hashlib.md5(
                (str(key) + "|" + str(node)).encode()).hexdigest()[:8], 16)
            if w > best_w:
                best, best_w = node, w
        return best

    register(h + "rendezvoushash", _rendezvous)

    def _fingerprint_graph(nodes, rels=None):
        parts = []
        for n in sorted(nodes or [], key=lambda n: n.id):
            parts.append(n.id + "|" + ":".join(sorted(n.labels)) + "|"
                         + _json.dumps(n.properties, sort_keys=True,
                                       default=str))
        for r in sorted(rels or [], key=lambda r: r.id):
            parts.append(r.id + "|" + r.type + "|" + r.start_node + ">"
                         + r.end_node + "|"
                         + _json.dumps(r.properties, sort_keys=True,
                                       default=str))
        return hashlib.sha256("\n".join(parts).encode()).hexdigest()

    register(h + "fingerprintGraph", _fingerprint_graph)


# -- apoc.agg.* aggregate finalizers --------------------------------------
#
# The executor collects one evaluated-args tuple per row and calls
# these with the full list (nulls preserved in the tuples; each
# finalizer applies its own null policy, matching the reference's
# aggregate behavior).

def _vals(rows: List[tuple]) -> List[Any]:
    return [r[0] for r in rows if r and r[0] is not None]


def _agg_first(rows):
    v = _vals(rows)
    return v[0] if v else None


def _agg_last(rows):
    v = _vals(rows)
    return v[-1] if v else None


def _agg_nth(rows):
    v = [r[0] for r in rows if r]
    if not v:
        return None
    n = rows[0][1] if len(rows[0]) > 1 else 0
    nn = int(n or 0)
    vv = [x for x in v if x is not None]
    return vv[nn] if -len(vv) <= nn < len(vv) else None


def _agg_slice(rows):
    vv = _vals(rows)
    start = int(rows[0][1]) if rows and len(rows[0]) > 1 else 0
    length = int(rows[0][2]) if rows and len(rows[0]) > 2 else len(vv)
    return vv[start:start + length]


def _agg_product(rows):
    out = 1
    for v in _vals(rows):
        out *= v
    return out


def _agg_statistics(rows):
    v = [float(x) for x in _vals(rows)
         if isinstance(x, (int, float)) and not isinstance(x, bool)]
    if not v:
        return {"count": 0}
    return {"count": len(v), "min": min(v), "max": max(v),
            "sum": sum(v), "mean": sum(v) / len(v),
            "stdev": (math.sqrt(_variance(v, sample=True))
                      if len(v) > 1 else 0.0)}


def _agg_items(rows, want_max: bool):
    pairs = [(r[0], r[1]) for r in rows
             if r and len(r) > 1 and r[1] is not None]
    if not pairs:
        return {"items": [], "value": None}
    best = max(p[1] for p in pairs) if want_max else min(
        p[1] for p in pairs)
    return {"value": best, "items": [p[0] for p in pairs if p[1] == best]}


def _agg_histogram(rows):
    counts: Dict[Any, int] = {}
    for v in _vals(rows):
        counts[v] = counts.get(v, 0) + 1
    return [{"value": k, "count": n} for k, n in sorted(
        counts.items(), key=lambda kv: (str(type(kv[0])), str(kv[0])))]


def _agg_graph(rows):
    nodes: Dict[str, Node] = {}
    rels: Dict[str, Edge] = {}

    def visit(v):
        if isinstance(v, Node):
            nodes[v.id] = v
        elif isinstance(v, Edge):
            rels[v.id] = v
        elif isinstance(v, list):
            for x in v:
                visit(x)
        else:
            from nornicdb_tpu.query.functions import PathValue
            if isinstance(v, PathValue):
                for n in v.nodes:
                    nodes[n.id] = n
                for r in v.rels:
                    rels[r.id] = r

    for r in rows:
        for v in r:
            visit(v)
    return {"nodes": list(nodes.values()), "relationships": list(rels.values())}


AGG_FINALIZERS: Dict[str, Callable[[List[tuple]], Any]] = {
    "apoc.agg.first": _agg_first,
    "apoc.agg.last": _agg_last,
    "apoc.agg.nth": _agg_nth,
    "apoc.agg.slice": _agg_slice,
    "apoc.agg.median": lambda rows: _median(
        [float(x) for x in _vals(rows)
         if isinstance(x, (int, float)) and not isinstance(x, bool)]),
    "apoc.agg.mode": lambda rows: _mode(_vals(rows)),
    "apoc.agg.product": _agg_product,
    "apoc.agg.statistics": _agg_statistics,
    "apoc.agg.stdev": lambda rows: (
        math.sqrt(v) if (v := _variance(
            [float(x) for x in _vals(rows)
             if isinstance(x, (int, float)) and not isinstance(x, bool)],
            sample=True)) is not None else None),
    "apoc.agg.percentile": lambda rows: _percentile(
        [float(x) for x in _vals(rows)
         if isinstance(x, (int, float)) and not isinstance(x, bool)],
        float(rows[0][1]) if rows and len(rows[0]) > 1 else 0.5),
    "apoc.agg.maxitems": lambda rows: _agg_items(rows, want_max=True),
    "apoc.agg.minitems": lambda rows: _agg_items(rows, want_max=False),
    "apoc.agg.frequencies": _agg_histogram,
    "apoc.agg.histogram": _agg_histogram,
    "apoc.agg.graph": _agg_graph,
}


def install() -> None:
    _install_bitwise()
    _install_number()
    _install_math_stats()
    _install_scoring()
    _install_coll_extras()
    _install_text_util()
    _install_json_diff()
    _install_temporal_date()
    _install_convert_extras()
    _install_xml()
    _install_hashing_extras()


install()
