"""Cypher executor: streaming clause pipeline over binding rows.

Reference: pkg/cypher/executor.go:517-700 (StorageExecutor.Execute routing),
match/traversal (traversal.go, match_*.go), mutations
(executor_mutations.go), aggregation + projection semantics. Rows stream
through clause operators as dicts {var: value}; aggregation groups on the
non-aggregate projection keys exactly as Cypher defines.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

from nornicdb_tpu.errors import CypherRuntimeError, CypherSyntaxError, NotFoundError
from nornicdb_tpu.query import ast as A
from nornicdb_tpu.query.functions import PathValue, lookup as lookup_fn
from nornicdb_tpu.query.parser import parse
from nornicdb_tpu.storage.types import Direction, Edge, Engine, Node

_AGG_FUNCS = {
    "count", "sum", "avg", "min", "max", "collect", "stdev", "stdevp",
    "percentilecont", "percentiledisc",
}


def _is_agg_name(name: str) -> bool:
    """Builtin aggregates plus the apoc.agg.* family (reference
    apoc/agg: first/last/nth/slice/median/statistics/...)."""
    return name in _AGG_FUNCS or name.startswith("apoc.agg.")


@dataclass
class QueryStats:
    nodes_created: int = 0
    nodes_deleted: int = 0
    relationships_created: int = 0
    relationships_deleted: int = 0
    properties_set: int = 0
    labels_added: int = 0
    labels_removed: int = 0

    @property
    def contains_updates(self) -> bool:
        return any(
            (
                self.nodes_created, self.nodes_deleted,
                self.relationships_created, self.relationships_deleted,
                self.properties_set, self.labels_added, self.labels_removed,
            )
        )

    def to_dict(self) -> Dict[str, int]:
        return {
            "nodes_created": self.nodes_created,
            "nodes_deleted": self.nodes_deleted,
            "relationships_created": self.relationships_created,
            "relationships_deleted": self.relationships_deleted,
            "properties_set": self.properties_set,
            "labels_added": self.labels_added,
            "labels_removed": self.labels_removed,
        }


class CypherResult:
    """Query result. Internally column-major when produced by the
    vectorized fast paths (the reference's executor streams records
    rather than materializing them all up front — bolt PULL semantics,
    pkg/bolt/server.go; this is the columnar analog): ``rows`` is
    materialized lazily on first access, so servers that serialize
    straight from columns and benches that only count results never pay
    the per-row Python list cost."""

    __slots__ = ("columns", "_rows", "_col_data", "stats", "plan")

    def __init__(
        self,
        columns: Optional[List[str]] = None,
        rows: Optional[List[List[Any]]] = None,
        stats: Optional[QueryStats] = None,
        plan: Optional[Dict[str, Any]] = None,
        col_data: Optional[List[List[Any]]] = None,
    ):
        self.columns = columns if columns is not None else []
        if rows is not None:
            self._rows = rows
            self._col_data = None
        elif col_data is not None:
            self._rows = None
            self._col_data = col_data
        else:
            self._rows = []
            self._col_data = None
        self.stats = stats if stats is not None else QueryStats()
        self.plan = plan

    def _pycol(self, i: int) -> List[Any]:
        """Column i as a Python list, converting a lazily-held numpy
        column (np scalars -> natives) exactly once."""
        col = self._col_data[i]
        if not isinstance(col, list):
            col = col.tolist()
            self._col_data[i] = col
        return col

    @property
    def rows(self) -> List[List[Any]]:
        if self._rows is None:
            cols = self._col_data
            if cols and len(cols[0]):
                cols = [self._pycol(i) for i in range(len(cols))]
                self._rows = list(map(list, zip(*cols)))
            else:
                self._rows = []
        # the returned list is mutable (UNION merging extends it in
        # place): drop the column view so there is a single source of
        # truth once rows are exposed
        self._col_data = None
        return self._rows

    @rows.setter
    def rows(self, value: List[List[Any]]) -> None:
        self._rows = value
        self._col_data = None

    @property
    def n_rows(self) -> int:
        if self._rows is not None:
            return len(self._rows)
        return len(self._col_data[0]) if self._col_data else 0

    def col_values(self, i: int) -> List[Any]:
        """Column-major access without forcing row materialization.
        Returns a copy: the underlying columns may be shared with the
        query cache, so handing out the live list would let caller
        mutations poison future cache hits."""
        if self._col_data is not None:
            return list(self._pycol(i))
        return [r[i] for r in self.rows]

    def records(self) -> List[Dict[str, Any]]:
        return [dict(zip(self.columns, r)) for r in self.rows]

    def single(self) -> Optional[Dict[str, Any]]:
        recs = self.records()
        return recs[0] if recs else None

    def value(self, col: int = 0) -> Any:
        if self._rows is None and self._col_data:
            c = self._pycol(col)
            return c[0] if c else None
        return self.rows[0][col] if self.rows else None


class _Ctx:
    def __init__(
        self,
        executor: "CypherExecutor",
        params: Dict[str, Any],
        storage: Optional[Engine] = None,
    ):
        self.ex = executor
        self.storage = storage if storage is not None else executor.storage
        self.params = params
        self.stats = QueryStats()
        # create-delta tracking for granular cache maintenance: pure
        # creations extend the columnar snapshot instead of rebuilding it
        self.created_nodes: List[Node] = []
        self.created_edges: List[Edge] = []
        self.created_props = 0  # properties set BY those creations
        self.non_create_writes = False
        # incrementally-built (label, prop) -> value -> nodes map over
        # created_nodes, so per-row index probes stay O(1) amortized
        self.created_probe_index: Dict[Tuple[str, str], Dict] = {}


class CypherExecutor:
    """Executes Cypher against a storage.Engine
    (reference: cypher.NewStorageExecutor, wired at db.go:974)."""

    def __init__(self, storage: Engine, cache_size: int = 1024,
                 cache_ttl: float = 60.0, parser_mode: Optional[str] = None):
        import os

        self.storage = storage
        # 'fast' (default) or 'strict' (diagnostic validation before
        # execution — reference: NORNICDB_PARSER antlr mode,
        # cypher-parser-modes.md)
        self.parser_mode = (
            parser_mode or os.environ.get("NORNICDB_TPU_PARSER", "fast")
        ).lower()
        self._search = None
        self._lock = threading.Lock()
        self._plugin_functions: Dict[str, Any] = {}
        # Columnar snapshot powering the vectorized fast paths
        # (reference analog: the per-shape optimized executors +
        # parallel.go chunked scans; see query/columnar.py).
        from nornicdb_tpu.query.columnar import ColumnarCatalog

        self.columnar = ColumnarCatalog(storage)
        self.enable_fastpaths = True
        # Device graph plane: the LDBC fast-path shapes compiled onto
        # device snapshots of the catalog (query/device_graph.py).
        # Version-keyed — catalog invalidation implicitly stales it;
        # env-gated NORNICDB_GRAPH_DEVICE, host path otherwise.
        from nornicdb_tpu.query.device_graph import DeviceGraphPlane

        self.device_graph = DeviceGraphPlane(self.columnar)
        from nornicdb_tpu import obs as _obs

        _obs.register_resource(
            "device_graph",
            getattr(storage, "database", None) or "default",
            self.device_graph)
        # Read-query result cache with write invalidation (reference:
        # read-cache probe executor.go:634, pkg/cache/query_cache.go).
        from nornicdb_tpu.cache import LRUCache

        self.query_cache: LRUCache = LRUCache(
            max_size=cache_size, ttl_seconds=cache_ttl
        )
        self.enable_query_cache = True
        # parsed-AST cache keyed by query text (reference: cached
        # QueryAnalyzer.Analyze, executor.go:624) — parsing is ~15% of a
        # fast-path query; ASTs are immutable after parse
        self._parse_cache: LRUCache = LRUCache(max_size=512)
        # apoc.trigger.* registry; statements fire after updating queries
        from nornicdb_tpu.query.apoc_ext import TriggerRegistry

        self.triggers = TriggerRegistry()
        self._in_trigger = False
        self._tls = threading.local()

    def on_external_mutation(self) -> None:
        """Storage-listener entry point (db.py wires this): invalidate for
        writes arriving OUTSIDE this executor's own execution (Store,
        embed queue, replication apply). The executor's own writes fire
        the same listeners mid-query; those are handled at end-of-query
        (delta-extend or full invalidate), so they are skipped here —
        otherwise the listener wipes the catalog before the delta path
        runs and MATCH…CREATE pays a full O(N) rebuild per statement."""
        if getattr(self._tls, "depth", 0) > 0:
            return
        self.invalidate_caches()

    def on_external_node_upsert(self, node) -> None:
        """Upsert-shaped external mutation: when only the embedding (or
        other non-query-visible fields) changed, swap the snapshot's node
        in place instead of invalidating wholesale — the embed queue's
        write-backs would otherwise force a full catalog rebuild per
        probe while a bulk ingest runs concurrently."""
        if getattr(self._tls, "depth", 0) > 0:
            return
        if self.columnar.note_external_upsert(node):
            # projected nodes can carry embeddings: drop only results
            self.query_cache.clear()
            return
        self.invalidate_caches()

    def invalidate_caches(self) -> None:
        """Drop the query-result cache and columnar snapshot. Called after
        any write this executor performs, and wired to storage mutation
        listeners for writes arriving from other paths (db.Store, embed
        queue) — reference: cache_policy.go write invalidation."""
        self.query_cache.clear()
        self.columnar.invalidate()

    def set_search_service(self, svc) -> None:
        """Wire the vector/fulltext procedures
        (reference: SetSearchService, db.go:1086-1093)."""
        self._search = svc

    def register_function(self, name: str, fn) -> None:
        """Plugin functions callable from Cypher
        (reference: PluginFunctionLookup, db.go:992-999)."""
        self._plugin_functions[name.lower()] = fn

    # -- entry -----------------------------------------------------------

    def execute(
        self, query: str, params: Optional[Dict[str, Any]] = None
    ) -> CypherResult:
        stripped = query.lstrip()
        head = stripped[:7].upper()
        rest = stripped[7:]
        boundary = rest[:1] == "" or rest[:1].isspace()
        if head == "EXPLAIN" and boundary:
            return self._execute_explain(rest, params)
        if head == "PROFILE" and boundary:
            return self._execute_profile(rest, params)
        if self.parser_mode == "strict":
            from nornicdb_tpu.query.strict import assert_valid

            assert_valid(query)
        uq = self._parse_cached(query)
        cache_key = None
        if self.enable_query_cache and _is_read_only(uq):
            cache_key = _cache_key(query, params, uq)
            if cache_key is not None:
                hit = self.query_cache.get(cache_key)
                if hit is not None:
                    if hit._col_data is not None:
                        # column-major cached result: hits share the
                        # immutable columns; each hit materializes its
                        # own row lists only if the caller iterates them
                        return CypherResult(
                            columns=list(hit.columns),
                            col_data=hit._col_data,
                            plan=hit.plan,
                        )
                    return CypherResult(
                        columns=list(hit.columns),
                        rows=[list(r) for r in hit.rows],
                        plan=hit.plan,
                    )
        result = self._execute_parsed(uq, params)
        if cache_key is not None and not result.stats.contains_updates:
            if result._col_data is not None:
                # cache a detached wrapper over the shared columns so the
                # caller's row materialization (which drops the column
                # view) and row-list mutations can't reach future hits
                self.query_cache.put(
                    cache_key,
                    CypherResult(
                        columns=list(result.columns),
                        col_data=result._col_data,
                        plan=result.plan,
                    ),
                )
            else:
                self.query_cache.put(cache_key, result)
        return result

    def _execute_parsed(
        self,
        uq: "A.UnionQuery",
        params: Optional[Dict[str, Any]],
        storage: Optional[Engine] = None,
    ) -> CypherResult:
        ctx = _Ctx(self, params or {}, storage=storage)
        self._tls.depth = getattr(self._tls, "depth", 0) + 1
        try:
            return self._execute_parsed_inner(uq, ctx, storage)
        except BaseException:
            # a failing write query may have applied SOME mutations before
            # raising (listener invalidation is suppressed at depth>0, and
            # the end-of-query delta path never runs on this path) —
            # conservatively drop the caches
            if ctx.stats.contains_updates or ctx.created_nodes or (
                ctx.created_edges or ctx.non_create_writes
            ):
                self.invalidate_caches()
            raise
        finally:
            self._tls.depth -= 1

    def _execute_parsed_inner(
        self,
        uq: "A.UnionQuery",
        ctx: "_Ctx",
        storage: Optional[Engine] = None,
    ) -> CypherResult:
        result: Optional[CypherResult] = None
        multi_part = len(uq.parts) > 1
        for i, part in enumerate(uq.parts):
            r = self._run_query(part, ctx)
            if multi_part and ctx.stats.contains_updates:
                # later UNION parts must see this part's writes; the
                # delta path only applies after ALL parts, so multi-part
                # writes invalidate between parts (writes in UNION are
                # rare — correctness over the delta micro-optimization)
                self.invalidate_caches()
                ctx.non_create_writes = True  # disable end-of-query delta
            if result is None:
                result = r
            else:
                if r.columns != result.columns:
                    raise CypherRuntimeError("UNION parts must have same columns")
                result.rows.extend(r.rows)
                if not uq.alls[i - 1]:  # UNION (distinct)
                    seen = set()
                    deduped = []
                    for row in result.rows:
                        key = _hashable(row)
                        if key not in seen:
                            seen.add(key)
                            deduped.append(row)
                    result.rows = deduped
        result = result or CypherResult()
        result.stats = ctx.stats
        if ctx.stats.contains_updates:
            # write invalidation for every execution route (including
            # PROFILE and txn overlays) — reference: cache_policy.go.
            # Pure creations (delta lists match the stats counters and no
            # other write kind ran) extend the columnar snapshot in place
            # instead of forcing an O(N) rebuild per statement.
            pure_creates = (
                not ctx.non_create_writes
                and storage is None
                and ctx.stats.nodes_deleted == 0
                and ctx.stats.relationships_deleted == 0
                and ctx.stats.labels_removed == 0
                and len(ctx.created_nodes) == ctx.stats.nodes_created
                and len(ctx.created_edges) == ctx.stats.relationships_created
                # every counted write must be explained by the deltas —
                # a procedure mutating properties (apoc.create.setProperty)
                # bumps these counters without touching the delta lists
                and ctx.stats.properties_set == ctx.created_props
                and ctx.stats.labels_added == sum(
                    len(n.labels) for n in ctx.created_nodes)
            )
            if pure_creates:
                self.query_cache.clear()
                for n in ctx.created_nodes:
                    self.columnar.apply_node_created(n)
                for e in ctx.created_edges:
                    self.columnar.apply_edge_created(e)
            else:
                self.invalidate_caches()
            # apoc triggers ('after' phase); guarded against recursion
            if self.triggers.triggers and not self._in_trigger:
                self.triggers.fire(self)
        return result

    def _parse_cached(self, query: str) -> "A.UnionQuery":
        uq = self._parse_cache.get(query)
        if uq is None:
            uq = parse(query)
            self._parse_cache.put(query, uq)
        return uq

    def _execute_for_trigger(self, statement: str,
                             params: Optional[Dict[str, Any]] = None
                             ) -> "CypherResult":
        """Nested execution for triggers / apoc.periodic / apoc.cypher.run:
        bypasses the read cache and suppresses re-entrant trigger firing.
        Uses the parse cache — a trigger statement re-fires on every
        updating query with identical text."""
        prev = self._in_trigger
        self._in_trigger = True
        try:
            return self._execute_parsed(self._parse_cached(statement),
                                        params or {})
        finally:
            self._in_trigger = prev

    def _execute_explain(
        self, query: str, params: Optional[Dict[str, Any]]
    ) -> CypherResult:
        """EXPLAIN: build and return the plan without executing
        (reference: executeExplain, explain.go:95)."""
        from nornicdb_tpu.query.explain import build_plan, plan_rows

        uq = self._parse_cached(query)
        plan = build_plan(self.storage, uq)
        cols, rows = plan_rows(plan)
        return CypherResult(columns=cols, rows=rows, plan=plan.to_dict())

    def _execute_profile(
        self, query: str, params: Optional[Dict[str, Any]]
    ) -> CypherResult:
        """PROFILE: execute through a db-hit-counting storage proxy and
        attach actuals to the plan (reference: executeProfile,
        explain.go:110). The actuals also land in the telemetry
        registry (ISSUE 3 satellite): /metrics exposes the db-hit and
        wall-time distributions of profiled queries, so query-layer
        cost is observable fleet-wide, not just per response."""
        import time as _time

        from nornicdb_tpu.obs import REGISTRY
        from nornicdb_tpu.query.explain import CountingEngine, build_plan

        uq = self._parse_cached(query)
        plan = build_plan(self.storage, uq)
        counting = CountingEngine(self.storage)
        t0 = _time.perf_counter()
        result = self._execute_parsed(uq, params, storage=counting)
        elapsed = _time.perf_counter() - t0
        root = plan.children[0] if plan.children else plan
        root.db_hits = counting.hits
        plan.actual_rows = root.actual_rows = len(result.rows)
        REGISTRY.histogram(
            "nornicdb_profile_db_hits",
            "Storage hits per PROFILEd query",
            buckets=(1, 10, 100, 1_000, 10_000, 100_000, 1_000_000),
        ).observe(counting.hits)
        REGISTRY.histogram(
            "nornicdb_profile_query_seconds",
            "Wall time per PROFILEd query").observe(elapsed)
        # Neo4j semantics: PROFILE returns the query's records; the
        # profiled plan rides on the result (summary-equivalent).
        result.plan = plan.to_dict()
        return result

    def _run_query(self, q: A.Query, ctx: _Ctx) -> CypherResult:
        from nornicdb_tpu.query.fastpaths import (
            try_fast_match_rows,
            try_fast_path,
        )

        fast = try_fast_path(self, q, ctx)
        if fast is not None:
            return fast
        rows: Iterable[Dict[str, Any]] = [dict()]
        final: Optional[CypherResult] = None
        clauses = q.clauses
        for idx, clause in enumerate(clauses):
            is_last = idx == len(clauses) - 1
            if isinstance(clause, A.MatchClause):
                if idx == 0:
                    # vectorized binding resolution for the leading MATCH
                    # (compound fast path: MATCH…CREATE/SET/DELETE etc.)
                    fast_rows = try_fast_match_rows(self, clause, ctx)
                    if fast_rows is not None:
                        rows = fast_rows
                        continue
                rows = self._exec_match(clause, rows, ctx)
            elif isinstance(clause, A.UnwindClause):
                rows = self._exec_unwind(clause, rows, ctx)
            elif isinstance(clause, A.CreateClause):
                rows = self._exec_create(clause, rows, ctx)
            elif isinstance(clause, A.MergeClause):
                rows = self._exec_merge(clause, rows, ctx)
            elif isinstance(clause, A.SetClause):
                rows = self._exec_set(clause.items, rows, ctx)
            elif isinstance(clause, A.RemoveClause):
                rows = self._exec_remove(clause, rows, ctx)
            elif isinstance(clause, A.DeleteClause):
                rows = self._exec_delete(clause, rows, ctx)
            elif isinstance(clause, A.WithClause):
                rows = self._exec_projection(clause, rows, ctx)
            elif isinstance(clause, A.ReturnClause):
                final = self._exec_return(clause, rows, ctx)
                rows = []
            elif isinstance(clause, A.CallClause):
                rows = self._exec_call(clause, rows, ctx, standalone=len(clauses) == 1)
                if len(clauses) == 1:
                    # bare CALL: yield columns become the result
                    rows = list(rows)
                    cols = (
                        list(rows[0].keys()) if rows else
                        [a or n for n, a in clause.yield_items]
                    )
                    final = CypherResult(
                        columns=cols,
                        rows=[[r.get(c) for c in cols] for r in rows],
                    )
            else:
                raise CypherRuntimeError(f"unhandled clause {type(clause).__name__}")
            if is_last and final is None:
                # writes without RETURN: drain the stream to apply effects
                for _ in rows:
                    pass
        return final or CypherResult()

    # -- expression evaluation -------------------------------------------

    def _eval(self, e: A.Expr, row: Dict[str, Any], ctx: _Ctx) -> Any:
        if isinstance(e, A.Literal):
            return e.value
        if isinstance(e, A.Param):
            if e.name not in ctx.params:
                raise CypherRuntimeError(f"missing parameter ${e.name}")
            return ctx.params[e.name]
        if isinstance(e, A.Var):
            if e.name not in row:
                raise CypherRuntimeError(f"variable `{e.name}` not defined")
            return row[e.name]
        if isinstance(e, A.Prop):
            target = self._eval(e.target, row, ctx)
            if target is None:
                return None
            if isinstance(target, (Node, Edge)):
                return target.properties.get(e.name)
            if isinstance(target, dict):
                return target.get(e.name)
            # temporal/duration/point component access (d.year, dur.days,
            # p.x — reference: temporal component properties)
            comp = getattr(target, "component", None)
            if comp is not None:
                return comp(e.name)
            raise CypherRuntimeError(f"cannot access property on {type(target).__name__}")
        if isinstance(e, A.ListExpr):
            return [self._eval(x, row, ctx) for x in e.items]
        if isinstance(e, A.MapExpr):
            return {k: self._eval(v, row, ctx) for k, v in e.items}
        if isinstance(e, A.Unary):
            v = self._eval(e.operand, row, ctx)
            if e.op == "NOT":
                return None if v is None else (not _truthy(v))
            if v is None:
                return None
            return -v if e.op == "-" else +v
        if isinstance(e, A.Binary):
            return self._eval_binary(e, row, ctx)
        if isinstance(e, A.IsNull):
            v = self._eval(e.operand, row, ctx)
            return (v is not None) if e.negated else (v is None)
        if isinstance(e, A.CaseExpr):
            if e.subject is not None:
                subject = self._eval(e.subject, row, ctx)
                for cond, val in e.whens:
                    if _cypher_eq(subject, self._eval(cond, row, ctx)):
                        return self._eval(val, row, ctx)
            else:
                for cond, val in e.whens:
                    if _truthy(self._eval(cond, row, ctx)):
                        return self._eval(val, row, ctx)
            return self._eval(e.default, row, ctx) if e.default else None
        if isinstance(e, A.Index):
            target = self._eval(e.target, row, ctx)
            idx = self._eval(e.index, row, ctx)
            if target is None or idx is None:
                return None
            if isinstance(target, dict):
                return target.get(idx)
            if isinstance(target, (Node, Edge)):
                return target.properties.get(idx)
            i = int(idx)
            if -len(target) <= i < len(target):
                return target[i]
            return None
        if isinstance(e, A.Slice):
            target = self._eval(e.target, row, ctx)
            if target is None:
                return None
            s = self._eval(e.start, row, ctx) if e.start else None
            t = self._eval(e.end, row, ctx) if e.end else None
            return target[s if s is None else int(s) : t if t is None else int(t)]
        if isinstance(e, A.ListComp):
            src = self._eval(e.source, row, ctx)
            if src is None:
                return None
            out = []
            for item in src:
                inner = dict(row)
                inner[e.var] = item
                if e.where is not None and not _truthy(self._eval(e.where, inner, ctx)):
                    continue
                out.append(
                    self._eval(e.projection, inner, ctx) if e.projection else item
                )
            return out
        if isinstance(e, A.ListPredicate):
            src = self._eval(e.source, row, ctx)
            if src is None:
                return None
            if not isinstance(src, list):
                raise CypherRuntimeError(
                    f"{e.kind}() expects a list, got {type(src).__name__}"
                )
            n_true = 0
            n_null = 0
            for item in src:
                inner = dict(row)
                inner[e.var] = item
                v = self._eval(e.where, inner, ctx)
                if v is None:
                    n_null += 1
                elif _truthy(v):
                    n_true += 1
            n = len(src)
            # Cypher ternary semantics per predicate kind
            if e.kind == "all":
                if n_true == n:
                    return True
                return None if n_true + n_null == n else False
            if e.kind == "any":
                if n_true > 0:
                    return True
                return None if n_null > 0 else False
            if e.kind == "none":
                if n_true > 0:
                    return False
                return None if n_null > 0 else True
            # single
            if n_null > 0 and n_true <= 1:
                return None
            return n_true == 1
        if isinstance(e, A.Reduce):
            src = self._eval(e.source, row, ctx)
            if src is None:
                return None
            if not isinstance(src, list):
                raise CypherRuntimeError(
                    f"reduce() expects a list, got {type(src).__name__}"
                )
            acc = self._eval(e.init, row, ctx)
            for item in src:
                inner = dict(row)
                inner[e.acc] = acc
                inner[e.var] = item
                acc = self._eval(e.expr, inner, ctx)
            return acc
        if isinstance(e, A.LabelCheck):
            v = row.get(e.var)
            if not isinstance(v, Node):
                return None
            return all(l in v.labels for l in e.labels)
        if isinstance(e, A.Exists):
            if e.prop is not None:
                return self._eval(e.prop, row, ctx) is not None
            return any(True for _ in self._match_path(e.pattern, dict(row), ctx, set()))
        if isinstance(e, A.PatternPredicate):
            return any(True for _ in self._match_path(e.pattern, dict(row), ctx, set()))
        if isinstance(e, A.FuncCall):
            return self._eval_func(e, row, ctx)
        raise CypherRuntimeError(f"unhandled expression {type(e).__name__}")

    def _eval_binary(self, e: A.Binary, row, ctx) -> Any:
        op = e.op
        if op in ("AND", "OR", "XOR"):
            l = self._eval(e.left, row, ctx)
            # Cypher ternary logic
            if op == "AND":
                if l is False:
                    return False
                r = self._eval(e.right, row, ctx)
                if r is False:
                    return False
                if l is None or r is None:
                    return None
                return _truthy(l) and _truthy(r)
            if op == "OR":
                if l is True:
                    return True
                r = self._eval(e.right, row, ctx)
                if r is True:
                    return True
                if l is None or r is None:
                    return None
                return _truthy(l) or _truthy(r)
            r = self._eval(e.right, row, ctx)
            if l is None or r is None:
                return None
            return _truthy(l) != _truthy(r)
        l = self._eval(e.left, row, ctx)
        r = self._eval(e.right, row, ctx)
        if op == "=":
            if l is None or r is None:
                return None
            return _cypher_eq(l, r)
        if op == "<>":
            if l is None or r is None:
                return None
            return not _cypher_eq(l, r)
        if op in ("<", "<=", ">", ">="):
            if l is None or r is None:
                return None
            try:
                if op == "<":
                    return l < r
                if op == "<=":
                    return l <= r
                if op == ">":
                    return l > r
                return l >= r
            except TypeError:
                return None
        if op == "+":
            if l is None or r is None:
                return None
            if isinstance(l, list):
                return l + (r if isinstance(r, list) else [r])
            if isinstance(r, list):
                return [l] + r
            if isinstance(l, str) or isinstance(r, str):
                if isinstance(l, str) and isinstance(r, str):
                    return l + r
                return _to_str(l) + _to_str(r)
            try:
                return l + r
            except TypeError:
                raise CypherRuntimeError(
                    f"cannot apply + to {type(l).__name__} and "
                    f"{type(r).__name__}"
                )
        if op in ("-", "*", "/", "%", "^"):
            if l is None or r is None:
                return None
            try:
                return self._arith(op, l, r)
            except TypeError:
                raise CypherRuntimeError(
                    f"cannot apply {op} to {type(l).__name__} and "
                    f"{type(r).__name__}"
                )
        if op == "IN":
            if r is None:
                return None
            if l is None:
                return None
            return any(_cypher_eq(l, x) for x in r)
        if op == "STARTS WITH":
            if l is None or r is None:
                return None
            return isinstance(l, str) and l.startswith(r)
        if op == "ENDS WITH":
            if l is None or r is None:
                return None
            return isinstance(l, str) and l.endswith(r)
        if op == "CONTAINS":
            if l is None or r is None:
                return None
            return isinstance(l, str) and r in l
        if op == "=~":
            if l is None or r is None:
                return None
            import re as _re

            return bool(_re.fullmatch(r, l))
        raise CypherRuntimeError(f"unhandled operator {op}")

    def _arith(self, op: str, l: Any, r: Any) -> Any:
        if op == "-":
            return l - r
        if op == "*":
            return l * r
        if op == "/":
            is_num = isinstance(l, (int, float)) and isinstance(r, (int, float))
            if is_num and r == 0:
                if isinstance(l, float) or isinstance(r, float):
                    # IEEE float semantics (Neo4j returns Infinity/NaN)
                    if l == 0:
                        return float("nan")
                    return float("inf") if l > 0 else float("-inf")
                raise CypherRuntimeError("division by zero")
            if isinstance(l, int) and isinstance(r, int):
                q = l // r
                if q < 0 and l % r != 0:
                    q += 1  # Cypher truncates toward zero
                return q
            return l / r
        if op == "%":
            if r == 0:
                raise CypherRuntimeError("modulo by zero")
            m = abs(l) % abs(r)
            return m if l >= 0 else -m
        return float(l) ** float(r)

    def _eval_func(self, e: A.FuncCall, row, ctx) -> Any:
        name = e.name
        if _is_agg_name(name):
            raise CypherRuntimeError(
                f"aggregate function {name}() not allowed here"
            )
        if name == "__pattern_count__":
            pat = e.args[0]
            assert isinstance(pat, A.PatternPredicate)
            return sum(1 for _ in self._match_path(pat.pattern, dict(row), ctx, set()))
        if name == "exists":
            return self._eval(e.args[0], row, ctx) is not None
        if name in ("shortestpath", "allshortestpaths"):
            pat = e.args[0]
            if not isinstance(pat, A.PatternPredicate):
                raise CypherRuntimeError("shortestPath expects a pattern")
            return self._shortest_path(
                pat.pattern, row, ctx, all_paths=name == "allshortestpaths"
            )
        if name in ("degree", "indegree", "outdegree"):
            # storage-backed degree functions (reference
            # functions_eval_functions.go:534-560): 0 for non-nodes
            v = self._eval(e.args[0], row, ctx) if e.args else None
            if not isinstance(v, Node):
                return 0
            direction = {"degree": Direction.BOTH,
                         "indegree": Direction.INCOMING,
                         "outdegree": Direction.OUTGOING}[name]
            return ctx.storage.degree(v.id, direction)
        args = [self._eval(a, row, ctx) for a in e.args]
        fn = self._plugin_functions.get(name) or lookup_fn(name)
        if fn is None:
            from nornicdb_tpu.query.apoc import lookup_apoc, lookup_apoc_ctx

            cfn = lookup_apoc_ctx(name)
            if cfn is not None:
                return cfn(ctx, *args)
            fn = lookup_apoc(name)
        if fn is None:
            raise CypherRuntimeError(f"unknown function {name}()")
        return fn(*args)

    # -- MATCH ------------------------------------------------------------

    def _exec_match(self, clause: A.MatchClause, rows, ctx) -> Iterator[Dict]:
        for row in rows:
            matched = False
            for out in self._match_paths(clause.paths, row, ctx):
                if clause.where is not None and not _truthy(
                    self._eval(clause.where, out, ctx)
                ):
                    continue
                matched = True
                yield out
            if clause.optional and not matched:
                out = dict(row)
                for p in clause.paths:
                    for n in p.nodes:
                        if n.var and n.var not in out:
                            out[n.var] = None
                    for r in p.rels:
                        if r.var and r.var not in out:
                            out[r.var] = None
                    if p.path_var and p.path_var not in out:
                        out[p.path_var] = None
                yield out  # null-extended row (Neo4j OPTIONAL MATCH semantics)

    def _match_paths(self, paths: List[A.PatternPath], row, ctx) -> Iterator[Dict]:
        """Match all comma-separated paths (cartesian, shared vars join).
        Relationship uniqueness is enforced across the whole MATCH: edges
        bound by path i are excluded from path i+1's search."""

        def rec(i: int, cur: Dict, used: frozenset) -> Iterator[Dict]:
            if i >= len(paths):
                yield cur
                return
            for out, used_out in self._match_path_used(paths[i], cur, ctx, used):
                yield from rec(i + 1, out, used_out)

        yield from rec(0, dict(row), frozenset())

    def _node_candidates(self, pn: A.PatternNode, row, ctx) -> Iterable[Node]:
        if pn.var and pn.var in row and row[pn.var] is not None:
            v = row[pn.var]
            if not isinstance(v, Node):
                raise CypherRuntimeError(f"`{pn.var}` is not a node")
            return [v]
        if pn.labels:
            if (pn.props is not None and pn.props.items
                    and getattr(self, "enable_fastpaths", True)):
                hit = self._indexed_candidates(pn, row, ctx)
                if hit is not None:
                    return hit
            # smallest label set first
            best: Optional[List[Node]] = None
            for lbl in pn.labels:
                cand = ctx.storage.get_nodes_by_label(lbl)
                if best is None or len(cand) < len(best):
                    best = cand
            return best or []
        return ctx.storage.all_nodes()

    def _indexed_candidates(self, pn: A.PatternNode, row,
                            ctx) -> Optional[List[Node]]:
        """Hash-index candidate narrowing for (:Label {k: <expr>}) in the
        ROW interpreter — the difference between O(1) and a label scan
        per row in UNWIND/loop-shaped ingest (reference resolves the same
        shape through indexed access, storage_fastpaths.go). Candidates
        are verified by _node_ok afterward, so the probe only needs to be
        a superset of the true matches; returns None to fall back to the
        label scan whenever the columnar snapshot cannot be trusted."""
        if ctx.storage is not self.storage:
            return None  # txn overlay / PROFILE proxy: snapshot mismatch
        if (ctx.non_create_writes or ctx.stats.nodes_deleted
                or ctx.stats.labels_removed):
            # updates/deletes earlier in this statement are not yet in
            # the snapshot (deltas apply at end of query)
            return None
        k, vexpr = pn.props.items[0]
        try:
            v = self._eval(vexpr, row, ctx)
        except CypherRuntimeError:
            return None
        if v is None or isinstance(v, (list, dict, Node, Edge)):
            return None
        try:
            hit = self.columnar.prop_index(pn.labels[0], k).get(v)
        except TypeError:
            return None  # unhashable probe value
        snapshot = self.columnar.nodes()
        out = [snapshot[i].copy()
               for i in (hit.tolist() if hit is not None else [])]
        # nodes created earlier in THIS statement are visible to MATCH;
        # append only the ones the snapshot does NOT already contain (a
        # lazy snapshot built after the CREATE has already read them
        # from storage — appending again would double the match). The
        # created list is consulted through an incrementally-extended
        # (label, key) -> value map so a 10k-row UNWIND MERGE stays
        # O(rows), not O(rows^2).
        label = pn.labels[0]
        cache = ctx.created_probe_index
        entry = cache.get((label, k))
        if entry is None:
            entry = {"pos": 0, "map": {}}
            cache[(label, k)] = entry
        mp = entry["map"]
        created = ctx.created_nodes
        for n in created[entry["pos"]:]:
            if label in n.labels:
                key_val = n.properties.get(k)
                if key_val is None:
                    continue  # probe values are never None (guard above)
                try:
                    mp.setdefault(key_val, []).append(n)
                except TypeError:
                    pass  # unhashable stored value can't equal probe v
        entry["pos"] = len(created)
        for n in mp.get(v, []):
            if self.columnar.node_row(n.id) is None:
                out.append(n)
        return out

    def _node_ok(self, pn: A.PatternNode, node: Node, row, ctx) -> bool:
        if any(l not in node.labels for l in pn.labels):
            return False
        if pn.props is not None:
            for k, vexpr in pn.props.items:
                if not _cypher_eq(node.properties.get(k), self._eval(vexpr, row, ctx)):
                    return False
        return True

    def _rel_ok(self, pr: A.PatternRel, edge: Edge, row, ctx) -> bool:
        if pr.types and edge.type not in pr.types:
            return False
        if pr.props is not None:
            for k, vexpr in pr.props.items:
                if not _cypher_eq(edge.properties.get(k), self._eval(vexpr, row, ctx)):
                    return False
        return True

    def _match_path(
        self, path: A.PatternPath, row: Dict, ctx, used_edges: set
    ) -> Iterator[Dict]:
        for out, _used in self._match_path_used(path, row, ctx, used_edges):
            yield out

    def _match_path_used(
        self, path: A.PatternPath, row: Dict, ctx, used_edges
    ) -> Iterator[Tuple[Dict, frozenset]]:
        """Like _match_path but also yields the edge-id set consumed by the
        match, so callers can enforce uniqueness across multiple paths."""
        if path.shortest:
            yield from self._match_shortest(path, row, ctx, used_edges)
            return
        nodes, rels = path.nodes, path.rels

        def expand(i: int, cur: Dict, cur_node: Node,
                   acc_nodes: List[Node], acc_rels: List[Edge],
                   used: set) -> Iterator[Tuple[Dict, frozenset]]:
            if i >= len(rels):
                out = dict(cur)
                if path.path_var:
                    out[path.path_var] = PathValue(list(acc_nodes), list(acc_rels))
                yield out, frozenset(used)
                return
            pr = rels[i]
            pn = nodes[i + 1]
            for hop_edges, end_node in self._expand_rel(pr, cur_node, cur, ctx, used):
                if not self._node_ok(pn, end_node, cur, ctx):
                    continue
                nxt = dict(cur)
                if pn.var:
                    if pn.var in nxt and nxt[pn.var] is not None:
                        if not isinstance(nxt[pn.var], Node) or nxt[pn.var].id != end_node.id:
                            continue
                    nxt[pn.var] = end_node
                if pr.var:
                    if pr.max_hops == 1 and pr.min_hops == 1:
                        nxt[pr.var] = hop_edges[0]
                    else:
                        nxt[pr.var] = list(hop_edges)
                new_used = used | {e.id for e in hop_edges}
                yield from expand(
                    i + 1, nxt, end_node,
                    acc_nodes + [end_node], acc_rels + list(hop_edges),
                    new_used,
                )

        first = nodes[0]
        for start in self._node_candidates(first, row, ctx):
            if not self._node_ok(first, start, row, ctx):
                continue
            cur = dict(row)
            if first.var:
                cur[first.var] = start
            yield from expand(0, cur, start, [start], [], set(used_edges))

    def _expand_rel(
        self, pr: A.PatternRel, start: Node, row, ctx, used: set
    ) -> Iterator[Tuple[List[Edge], Node]]:
        """Yield (edges_along_hop(s), end_node) for one pattern relationship,
        honoring variable-length ranges and edge uniqueness."""
        # bound rel var: single edge already fixed
        if pr.var and pr.var in row and row[pr.var] is not None and pr.max_hops == 1:
            e = row[pr.var]
            if isinstance(e, Edge):
                ends = []
                if pr.direction in ("out", "both") and e.start_node == start.id:
                    ends.append(e.end_node)
                if pr.direction in ("in", "both") and e.end_node == start.id:
                    ends.append(e.start_node)
                for other in ends:
                    try:
                        yield [e], ctx.storage.get_node(other)
                    except KeyError:
                        pass
                return

        def neighbors(node: Node) -> Iterator[Tuple[Edge, Node]]:
            direction = {
                "out": Direction.OUTGOING,
                "in": Direction.INCOMING,
                "both": Direction.BOTH,
            }[pr.direction]
            for e in ctx.storage.get_node_edges(node.id, direction):
                if not self._rel_ok(pr, e, row, ctx):
                    continue
                if pr.direction == "out" and e.start_node != node.id:
                    continue
                if pr.direction == "in" and e.end_node != node.id:
                    continue
                other_id = e.end_node if e.start_node == node.id else e.start_node
                if pr.direction == "both" and e.start_node == e.end_node:
                    other_id = node.id  # self-loop
                try:
                    yield e, ctx.storage.get_node(other_id)
                except KeyError:
                    continue

        max_hops = pr.max_hops if pr.max_hops >= 0 else 15  # sane cap
        min_hops = pr.min_hops

        if min_hops == 0:
            yield [], start

        # DFS up to max_hops with edge uniqueness
        stack: List[Tuple[Node, List[Edge], set]] = [(start, [], set(used))]
        while stack:
            node, edges_so_far, local_used = stack.pop()
            depth = len(edges_so_far)
            if depth >= max_hops:
                continue
            for e, other in neighbors(node):
                if e.id in local_used:
                    continue
                new_edges = edges_so_far + [e]
                if len(new_edges) >= min_hops:
                    yield new_edges, other
                stack.append((other, new_edges, local_used | {e.id}))

    def _match_shortest(
        self, path: A.PatternPath, row: Dict, ctx, used_edges
    ) -> Iterator[Tuple[Dict, frozenset]]:
        """MATCH-position shortestPath/allShortestPaths with possibly
        UNBOUND endpoints (the form LDBC/neo4j docs use:
        ``MATCH p = shortestPath((a:X)-[*]-(b:Y)) ...``). Endpoint
        patterns scan candidates like ordinary node patterns; BFS runs
        per (src, dst) pair. Reference: shortest_path.go served through
        its MATCH planner."""
        if len(path.nodes) != 2 or len(path.rels) != 1:
            raise CypherRuntimeError("shortestPath expects a 2-node pattern")
        src_pat, dst_pat, pr = path.nodes[0], path.nodes[1], path.rels[0]
        for a in self._node_candidates(src_pat, row, ctx):
            if not self._node_ok(src_pat, a, row, ctx):
                continue
            row_a = dict(row)
            if src_pat.var:
                row_a[src_pat.var] = a
            for b in self._node_candidates(dst_pat, row_a, ctx):
                if not self._node_ok(dst_pat, b, row_a, ctx):
                    continue
                if a.id == b.id and not (
                    src_pat.var and src_pat.var == dst_pat.var
                ):
                    # neo4j: same-node endpoints only match when both
                    # patterns name the same variable
                    continue
                res = self._bfs_shortest(
                    a, b, pr, ctx, all_paths=path.shortest == "all")
                paths = (res if isinstance(res, list)
                         else [res] if res is not None else [])
                for pv in paths:
                    out = dict(row_a)
                    if dst_pat.var:
                        out[dst_pat.var] = b
                    if pr.var:
                        out[pr.var] = list(pv.rels)
                    if path.path_var:
                        out[path.path_var] = pv
                    yield out, frozenset(used_edges)

    # -- shortest path ----------------------------------------------------

    def _shortest_path(self, path: A.PatternPath, row, ctx, all_paths=False):
        """BFS shortest path(s) (reference: shortest_path.go) —
        expression position: both endpoints must already be bound."""
        if len(path.nodes) != 2 or len(path.rels) != 1:
            raise CypherRuntimeError("shortestPath expects a 2-node pattern")
        src_pat, dst_pat, pr = path.nodes[0], path.nodes[1], path.rels[0]
        src = row.get(src_pat.var) if src_pat.var else None
        dst = row.get(dst_pat.var) if dst_pat.var else None
        if not isinstance(src, Node) or not isinstance(dst, Node):
            raise CypherRuntimeError("shortestPath endpoints must be bound nodes")
        return self._bfs_shortest(src, dst, pr, ctx, all_paths)

    def _bfs_shortest(self, src: Node, dst: Node, pr: A.PatternRel,
                      ctx, all_paths: bool = False):
        if src.id == dst.id:
            return PathValue([src], [])
        max_hops = pr.max_hops if pr.max_hops >= 0 else 25
        from collections import deque

        q = deque([(src.id, [], [src])])
        seen = {src.id: 0}
        found: List[PathValue] = []
        best_len = None
        while q:
            nid, redges, rnodes = q.popleft()
            depth = len(redges)
            if best_len is not None and depth >= best_len:
                continue
            if depth >= max_hops:
                continue
            direction = {
                "out": Direction.OUTGOING,
                "in": Direction.INCOMING,
                "both": Direction.BOTH,
            }[pr.direction]
            for e in ctx.storage.get_node_edges(nid, direction):
                if pr.types and e.type not in pr.types:
                    continue
                if pr.direction == "out" and e.start_node != nid:
                    continue
                if pr.direction == "in" and e.end_node != nid:
                    continue
                other = e.end_node if e.start_node == nid else e.start_node
                nd = depth + 1
                if other == dst.id:
                    try:
                        on = ctx.storage.get_node(other)
                    except KeyError:
                        continue
                    pv = PathValue(rnodes + [on], redges + [e])
                    if all_paths:
                        if best_len is None or nd == best_len:
                            best_len = nd
                            found.append(pv)
                    else:
                        return pv
                    continue
                # strict < so allShortestPaths keeps alternate equal-length
                # routes through an already-seen intermediate node
                if other in seen and (
                    seen[other] < nd or (not all_paths and seen[other] <= nd)
                ):
                    continue
                seen[other] = nd
                try:
                    on = ctx.storage.get_node(other)
                except KeyError:
                    continue
                q.append((other, redges + [e], rnodes + [on]))
        if all_paths:
            return found
        return None

    # -- UNWIND -----------------------------------------------------------

    def _exec_unwind(self, clause: A.UnwindClause, rows, ctx) -> Iterator[Dict]:
        for row in rows:
            v = self._eval(clause.expr, row, ctx)
            if v is None:
                continue
            if not isinstance(v, list):
                v = [v]
            for item in v:
                out = dict(row)
                out[clause.var] = item
                yield out

    # -- CREATE / MERGE ---------------------------------------------------

    def _create_node_from_pattern(self, pn: A.PatternNode, row, ctx) -> Node:
        props = {}
        if pn.props is not None:
            props = {k: self._eval(v, row, ctx) for k, v in pn.props.items}
        node = Node(id=str(uuid.uuid4()), labels=list(pn.labels), properties=props)
        emb = props.pop("embedding", None)
        if emb is not None:
            node.embedding = list(emb)
            node.properties = props
        ctx.storage.create_node(node)
        ctx.stats.nodes_created += 1
        ctx.stats.labels_added += len(pn.labels)
        ctx.stats.properties_set += len(props)
        created = ctx.storage.get_node(node.id)
        ctx.created_nodes.append(created)
        ctx.created_props += len(props)
        return created

    def _exec_create(self, clause: A.CreateClause, rows, ctx) -> Iterator[Dict]:
        for row in rows:
            out = dict(row)
            for path in clause.paths:
                prev: Optional[Node] = None
                path_nodes: List[Node] = []
                path_rels: List[Edge] = []
                for i, pn in enumerate(path.nodes):
                    if pn.var and pn.var in out and out[pn.var] is not None:
                        node = out[pn.var]
                        if not isinstance(node, Node):
                            raise CypherRuntimeError(f"`{pn.var}` is not a node")
                    else:
                        node = self._create_node_from_pattern(pn, out, ctx)
                        if pn.var:
                            out[pn.var] = node
                    path_nodes.append(node)
                    if i > 0:
                        pr = path.rels[i - 1]
                        if pr.max_hops != 1 or pr.min_hops != 1:
                            raise CypherRuntimeError("CREATE cannot use var-length rels")
                        if not pr.types:
                            raise CypherRuntimeError("CREATE requires a relationship type")
                        props = {}
                        if pr.props is not None:
                            props = {k: self._eval(v, out, ctx) for k, v in pr.props.items}
                        if pr.direction == "in":
                            start_id, end_id = node.id, prev.id
                        else:
                            start_id, end_id = prev.id, node.id
                        edge = Edge(
                            id=str(uuid.uuid4()), type=pr.types[0],
                            start_node=start_id, end_node=end_id, properties=props,
                        )
                        ctx.storage.create_edge(edge)
                        ctx.stats.relationships_created += 1
                        ctx.stats.properties_set += len(props)
                        edge = ctx.storage.get_edge(edge.id)
                        ctx.created_edges.append(edge)
                        ctx.created_props += len(props)
                        if pr.var:
                            out[pr.var] = edge
                        path_rels.append(edge)
                    prev = node
                if path.path_var:
                    out[path.path_var] = PathValue(path_nodes, path_rels)
            yield out

    def _exec_merge(self, clause: A.MergeClause, rows, ctx) -> Iterator[Dict]:
        for row in rows:
            found = False
            for out in self._match_path(clause.path, dict(row), ctx, set()):
                found = True
                if clause.on_match:
                    out = self._apply_set_items(clause.on_match, out, ctx)
                yield out
            if not found:
                created = list(
                    self._exec_create(
                        A.CreateClause(paths=[clause.path]), [dict(row)], ctx
                    )
                )
                for out in created:
                    if clause.on_create:
                        out = self._apply_set_items(clause.on_create, out, ctx)
                    yield out

    # -- SET / REMOVE / DELETE --------------------------------------------

    def _apply_set_items(self, items: List[A.SetItem], row, ctx) -> Dict:
        ctx.non_create_writes = True
        out = dict(row)
        for item in items:
            if item.labels:
                target = self._eval(item.target, out, ctx)
                if not isinstance(target, Node):
                    raise CypherRuntimeError("SET label target must be a node")
                node = ctx.storage.get_node(target.id)
                for l in item.labels:
                    if l not in node.labels:
                        node.labels.append(l)
                        ctx.stats.labels_added += 1
                ctx.storage.update_node(node)
                out = _refresh(out, ctx, node.id)
                continue
            if item.replace_map or item.merge_map:
                target = self._eval(item.target, out, ctx)
                value = self._eval(item.value, out, ctx)
                if isinstance(value, (Node, Edge)):
                    value = dict(value.properties)
                if not isinstance(value, dict):
                    raise CypherRuntimeError("SET map value must be a map")
                if isinstance(target, Node):
                    node = ctx.storage.get_node(target.id)
                    if item.replace_map:
                        node.properties = dict(value)
                    else:
                        node.properties.update(value)
                    _strip_null_props(node.properties)
                    ctx.storage.update_node(node)
                    ctx.stats.properties_set += len(value)
                    out = _refresh(out, ctx, node.id)
                elif isinstance(target, Edge):
                    edge = ctx.storage.get_edge(target.id)
                    if item.replace_map:
                        edge.properties = dict(value)
                    else:
                        edge.properties.update(value)
                    _strip_null_props(edge.properties)
                    ctx.storage.update_edge(edge)
                    ctx.stats.properties_set += len(value)
                    out = _refresh_edge(out, ctx, edge.id)
                else:
                    raise CypherRuntimeError("SET target must be node or relationship")
                continue
            # property set: target is Prop
            if not isinstance(item.target, A.Prop):
                raise CypherRuntimeError("bad SET target")
            entity = self._eval(item.target.target, out, ctx)
            value = self._eval(item.value, out, ctx)
            if isinstance(entity, Node):
                node = ctx.storage.get_node(entity.id)
                if value is None:
                    node.properties.pop(item.target.name, None)
                else:
                    node.properties[item.target.name] = value
                ctx.storage.update_node(node)
                ctx.stats.properties_set += 1
                out = _refresh(out, ctx, node.id)
            elif isinstance(entity, Edge):
                edge = ctx.storage.get_edge(entity.id)
                if value is None:
                    edge.properties.pop(item.target.name, None)
                else:
                    edge.properties[item.target.name] = value
                ctx.storage.update_edge(edge)
                ctx.stats.properties_set += 1
                out = _refresh_edge(out, ctx, edge.id)
            elif entity is None:
                continue
            else:
                raise CypherRuntimeError("SET target must be node or relationship")
        return out

    def _exec_set(self, items: List[A.SetItem], rows, ctx) -> Iterator[Dict]:
        for row in rows:
            yield self._apply_set_items(items, row, ctx)

    def _exec_remove(self, clause: A.RemoveClause, rows, ctx) -> Iterator[Dict]:
        ctx.non_create_writes = True
        for row in rows:
            out = dict(row)
            for item in clause.items:
                if item.labels:
                    target = self._eval(item.target, out, ctx)
                    if isinstance(target, Node):
                        node = ctx.storage.get_node(target.id)
                        for l in item.labels:
                            if l in node.labels:
                                node.labels.remove(l)
                                ctx.stats.labels_removed += 1
                        ctx.storage.update_node(node)
                        out = _refresh(out, ctx, node.id)
                elif isinstance(item.target, A.Prop):
                    entity = self._eval(item.target.target, out, ctx)
                    if isinstance(entity, Node):
                        node = ctx.storage.get_node(entity.id)
                        if item.target.name in node.properties:
                            del node.properties[item.target.name]
                            ctx.stats.properties_set += 1
                        ctx.storage.update_node(node)
                        out = _refresh(out, ctx, node.id)
                    elif isinstance(entity, Edge):
                        edge = ctx.storage.get_edge(entity.id)
                        if item.target.name in edge.properties:
                            del edge.properties[item.target.name]
                            ctx.stats.properties_set += 1
                        ctx.storage.update_edge(edge)
                        out = _refresh_edge(out, ctx, edge.id)
            yield out

    def _exec_delete(self, clause: A.DeleteClause, rows, ctx) -> Iterator[Dict]:
        ctx.non_create_writes = True
        for row in rows:
            for e in clause.exprs:
                v = self._eval(e, row, ctx)
                if v is None:
                    continue
                targets = v if isinstance(v, list) else [v]
                for t in targets:
                    if isinstance(t, Node):
                        if not clause.detach and ctx.storage.degree(t.id) > 0:
                            raise CypherRuntimeError(
                                f"cannot delete node {t.id} with relationships; "
                                "use DETACH DELETE"
                            )
                        n_edges = ctx.storage.degree(t.id)
                        try:
                            ctx.storage.delete_node(t.id)
                            ctx.stats.nodes_deleted += 1
                            ctx.stats.relationships_deleted += n_edges
                        except NotFoundError:
                            pass
                    elif isinstance(t, Edge):
                        try:
                            ctx.storage.delete_edge(t.id)
                            ctx.stats.relationships_deleted += 1
                        except NotFoundError:
                            pass
            yield row

    # -- WITH / RETURN ----------------------------------------------------

    def _projection_columns(self, clause, rows_sample: Dict) -> List[str]:
        cols = []
        for item in clause.items:
            if item.alias:
                cols.append(item.alias)
            elif isinstance(item.expr, A.Var):
                cols.append(item.expr.name)
            elif isinstance(item.expr, A.Prop) and isinstance(item.expr.target, A.Var):
                cols.append(f"{item.expr.target.name}.{item.expr.name}")
            else:
                cols.append(item.text)
        return cols

    def _exec_projection(self, clause, rows, ctx):
        cols, _vals, dict_rows = self._project(clause, rows, ctx)
        return dict_rows

    def _project(self, clause, rows, ctx):
        """Shared WITH/RETURN projection. Returns (cols, rows_as_value_lists,
        rows_as_dicts) so RETURN keeps duplicate-named columns positional."""
        rows = list(rows)
        has_agg = any(_contains_agg(i.expr) for i in clause.items)
        star_keys: List[str] = []
        if clause.star:
            seen = set()
            for r in rows:
                for k in r:
                    if k not in seen:
                        seen.add(k)
                        star_keys.append(k)
        cols = (star_keys if clause.star else []) + self._projection_columns(
            clause, rows[0] if rows else {}
        )
        if has_agg:
            out_rows = self._aggregate(clause, rows, ctx, star_keys)
            # ORDER BY after aggregation can only see the projected columns
            envs = [dict(zip(cols, r)) for r in out_rows]
        else:
            out_rows = []
            envs = []
            for row in rows:
                vals = [row.get(k) for k in star_keys]
                vals += [self._eval(i.expr, row, ctx) for i in clause.items]
                out_rows.append(vals)
                # ORDER BY may reference pre-projection variables (Cypher
                # allows ORDER BY p.name after RETURN p.name AS x)
                envs.append({**row, **dict(zip(cols, vals))})
        if clause.distinct:
            seen = set()
            dd, de = [], []
            for r, env in zip(out_rows, envs):
                key = _hashable(r)
                if key not in seen:
                    seen.add(key)
                    dd.append(r)
                    de.append(env)
            out_rows, envs = dd, de
        if clause.order_by:
            out_rows, envs = self._order_rows(clause, cols, out_rows, envs, ctx)
        if clause.skip is not None:
            n_skip = int(self._eval(clause.skip, {}, ctx))
            out_rows, envs = out_rows[n_skip:], envs[n_skip:]
        if clause.limit is not None:
            n_lim = int(self._eval(clause.limit, {}, ctx))
            out_rows, envs = out_rows[:n_lim], envs[:n_lim]
        new_rows = [dict(zip(cols, r)) for r in out_rows]
        if isinstance(clause, A.WithClause) and clause.where is not None:
            kept = [
                (v, r)
                for v, r in zip(out_rows, new_rows)
                if _truthy(self._eval(clause.where, r, ctx))
            ]
            out_rows = [v for v, _ in kept]
            new_rows = [r for _, r in kept]
        return cols, out_rows, new_rows

    def _order_rows(self, clause, cols, out_rows, envs, ctx):
        import functools as _ft

        # ORDER BY may reference a projected item by its expression (legal
        # for grouping keys after aggregation: RETURN o.city, count(*) AS n
        # ORDER BY n DESC, o.city) — resolve those to column positions
        # first, because the source variable is out of scope post-projection.
        col_of_expr: List[Optional[int]] = []
        for expr, _desc in clause.order_by:
            pos = None
            for i, item in enumerate(clause.items):
                if item.expr == expr:
                    pos = len(cols) - len(clause.items) + i
                    break
            col_of_expr.append(pos)
        keyed = []
        for vals, env in zip(out_rows, envs):
            keys = []
            for (expr, desc), pos in zip(clause.order_by, col_of_expr):
                if pos is not None:
                    v = vals[pos]
                else:
                    try:
                        v = self._eval(expr, env, ctx)
                    except CypherRuntimeError:
                        v = None
                keys.append((v, desc))
            keyed.append((keys, vals, env))

        def cmp(a, b):
            for (va, desc), (vb, _) in zip(a[0], b[0]):
                c = _cypher_cmp(va, vb)
                if c != 0:
                    return -c if desc else c
            return 0

        keyed.sort(key=_ft.cmp_to_key(cmp))
        return [k[1] for k in keyed], [k[2] for k in keyed]

    def _aggregate(self, clause, rows, ctx, star_keys):
        group_items = [
            (i, item) for i, item in enumerate(clause.items)
            if not _contains_agg(item.expr)
        ]
        agg_items = [
            (i, item) for i, item in enumerate(clause.items)
            if _contains_agg(item.expr)
        ]
        groups: Dict[Any, Dict] = {}
        order: List[Any] = []
        for row in rows:
            gvals = [row.get(k) for k in star_keys]
            gvals += [self._eval(item.expr, row, ctx) for _, item in group_items]
            key = _hashable(gvals)
            if key not in groups:
                groups[key] = {"gvals": gvals, "rows": []}
                order.append(key)
            groups[key]["rows"].append(row)
        if not rows and not group_items and not star_keys:
            groups[()] = {"gvals": [], "rows": []}
            order.append(())
        out = []
        n_cols = len(star_keys) + len(clause.items)
        for key in order:
            g = groups[key]
            vals: List[Any] = [None] * n_cols
            for j in range(len(star_keys)):
                vals[j] = g["gvals"][j]
            for idx, (i, item) in enumerate(group_items):
                vals[len(star_keys) + i] = g["gvals"][len(star_keys) + idx]
            for i, item in agg_items:
                vals[len(star_keys) + i] = self._eval_agg(item.expr, g["rows"], ctx)
            out.append(vals)
        return out

    def _eval_agg(self, e: A.Expr, rows: List[Dict], ctx) -> Any:
        """Evaluate an expression containing aggregate calls over a group."""
        if isinstance(e, A.FuncCall) and _is_agg_name(e.name):
            return self._run_agg(e, rows, ctx)
        if isinstance(e, A.Binary):
            l = self._eval_agg(e.left, rows, ctx)
            r = self._eval_agg(e.right, rows, ctx)
            return self._eval_binary(
                A.Binary(e.op, A.Literal(l), A.Literal(r)), {}, ctx
            )
        if isinstance(e, A.Unary):
            v = self._eval_agg(e.operand, rows, ctx)
            return self._eval(A.Unary(e.op, A.Literal(v)), {}, ctx)
        if isinstance(e, A.FuncCall):
            args = [self._eval_agg(a, rows, ctx) for a in e.args]
            return self._eval_func(
                A.FuncCall(e.name, [A.Literal(a) for a in args]), {}, ctx
            )
        if isinstance(e, A.Prop):
            inner = self._eval_agg(e.target, rows, ctx)
            return self._eval(A.Prop(A.Literal(inner), e.name), {}, ctx)
        if isinstance(e, A.Index):
            target = self._eval_agg(e.target, rows, ctx)
            idx = self._eval_agg(e.index, rows, ctx)
            return self._eval(A.Index(A.Literal(target), A.Literal(idx)), {}, ctx)
        if isinstance(e, A.Slice):
            target = self._eval_agg(e.target, rows, ctx)
            s = A.Literal(self._eval_agg(e.start, rows, ctx)) if e.start else None
            t = A.Literal(self._eval_agg(e.end, rows, ctx)) if e.end else None
            return self._eval(A.Slice(A.Literal(target), s, t), {}, ctx)
        if isinstance(e, A.MapExpr):
            return {k: self._eval_agg(v, rows, ctx) for k, v in e.items}
        if isinstance(e, A.ListExpr):
            return [self._eval_agg(x, rows, ctx) for x in e.items]
        # plain expression in agg context: evaluate on first row (grouping key
        # normally catches this case)
        return self._eval(e, rows[0], ctx) if rows else None

    def _run_agg(self, e: A.FuncCall, rows: List[Dict], ctx) -> Any:
        name = e.name
        if name.startswith("apoc.agg."):
            from nornicdb_tpu.query.apoc_bulk import AGG_FINALIZERS

            fin = AGG_FINALIZERS.get(name)
            if fin is None:
                raise CypherRuntimeError(f"unknown aggregate {name}()")
            arg_rows = [
                tuple(self._eval(a, row, ctx) for a in e.args)
                for row in rows
            ]
            if e.distinct:
                seen = set()
                dd = []
                for t in arg_rows:
                    key = _hashable(list(t))
                    if key not in seen:
                        seen.add(key)
                        dd.append(t)
                arg_rows = dd
            return fin(arg_rows)
        if name == "count" and e.star:
            return len(rows)
        values = []
        for row in rows:
            v = self._eval(e.args[0], row, ctx) if e.args else None
            if v is not None:
                values.append(v)
        if e.distinct:
            seen = set()
            dd = []
            for v in values:
                key = _hashable([v])
                if key not in seen:
                    seen.add(key)
                    dd.append(v)
            values = dd
        if name == "count":
            return len(values)
        if name == "collect":
            return values
        if name == "sum":
            return sum(values) if values else 0
        if name == "avg":
            return (sum(values) / len(values)) if values else None
        if name == "min":
            return min(values, key=_cmp_key) if values else None
        if name == "max":
            return max(values, key=_cmp_key) if values else None
        if name in ("stdev", "stdevp"):
            if len(values) < 2:
                return 0.0
            mean = sum(values) / len(values)
            var = sum((x - mean) ** 2 for x in values)
            var /= (len(values) - 1) if name == "stdev" else len(values)
            return var ** 0.5
        if name in ("percentilecont", "percentiledisc"):
            if not values:
                return None
            pct = self._eval(e.args[1], rows[0], ctx)
            values = sorted(values)
            pos = pct * (len(values) - 1)
            if name == "percentiledisc":
                return values[round(pos)]
            lo, hi = int(pos), min(int(pos) + 1, len(values) - 1)
            frac = pos - int(pos)
            return values[lo] * (1 - frac) + values[hi] * frac
        raise CypherRuntimeError(f"unknown aggregate {name}()")

    def _exec_return(self, clause: A.ReturnClause, rows, ctx) -> CypherResult:
        cols, val_rows, _dicts = self._project(clause, rows, ctx)
        return CypherResult(columns=cols, rows=val_rows)

    # -- CALL procedures --------------------------------------------------

    def _exec_call(self, clause: A.CallClause, rows, ctx, standalone=False):
        from nornicdb_tpu.query.procedures import run_procedure

        for row in rows:
            args = [self._eval(a, row, ctx) for a in clause.args]
            for rec in run_procedure(self, clause.proc, args, ctx):
                out = dict(row)
                if clause.yield_star or not clause.yield_items:
                    out.update(rec)
                else:
                    for name, alias in clause.yield_items:
                        if name not in rec:
                            raise CypherRuntimeError(
                                f"procedure {clause.proc} has no field {name}"
                            )
                        out[alias or name] = rec[name]
                if clause.where is not None and not _truthy(
                    self._eval(clause.where, out, ctx)
                ):
                    continue
                yield out


# -- helpers -------------------------------------------------------------

_WRITE_CLAUSES = (
    A.CreateClause, A.MergeClause, A.SetClause, A.RemoveClause, A.DeleteClause,
)

# Functions whose results must never be served from cache. Clock
# constructors (date/datetime/...) are volatile only when called with no
# argument; their .transaction/.statement/.realtime variants always are.
_VOLATILE_ALWAYS = frozenset({
    "rand", "randomuuid", "timestamp", "apoc.create.uuid",
    "apoc.create.uuidbase64", "apoc.create.uuids", "apoc.util.uuid",
    "apoc.util.randomuuid", "apoc.util.now", "apoc.util.nowinseconds",
    "apoc.util.timestamp", "apoc.util.sleep", "apoc.number.random",
    "apoc.number.randomint", "apoc.math.random", "apoc.math.randomint",
    "apoc.coll.shuffle", "apoc.coll.randomitems",
})
# whole families whose state lives outside storage (schema registry,
# lock table, log ring, trigger/job registries): results must never be
# served from the query-result cache because storage writes are not
# what invalidates them
_VOLATILE_PREFIXES = (
    "apoc.schema.", "apoc.lock.", "apoc.log.", "apoc.trigger.",
    "apoc.periodic.", "apoc.warmup.", "apoc.atomic.", "apoc.merge.",
    "apoc.refactor.", "apoc.create.", "apoc.cypher.", "apoc.import.",
    "apoc.export.", "apoc.load.", "apoc.meta.",
)
_CLOCK_FUNCS = frozenset({
    "date", "datetime", "localdatetime", "time", "localtime",
})
_CLOCK_SUFFIXES = (".transaction", ".statement", ".realtime")


def _is_read_only(uq: "A.UnionQuery") -> bool:
    """Cacheable = no write clauses and no CALL (procedures may write)."""
    for part in uq.parts:
        for clause in part.clauses:
            if isinstance(clause, _WRITE_CLAUSES + (A.CallClause,)):
                return False
    return True


def _has_volatile_call(obj: Any) -> bool:
    """Walk the parsed query's dataclass tree for volatile FuncCalls."""
    if isinstance(obj, A.FuncCall):
        name = obj.name
        if name in _VOLATILE_ALWAYS:
            return True
        if name.startswith(_VOLATILE_PREFIXES):
            return True
        if name in _CLOCK_FUNCS and not obj.args and not obj.star:
            return True
        if name.endswith(_CLOCK_SUFFIXES):
            return True
    import dataclasses

    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return any(
            _has_volatile_call(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        )
    if isinstance(obj, (list, tuple)):
        return any(_has_volatile_call(x) for x in obj)
    return False


def _cache_key(query: str, params: Optional[Dict[str, Any]],
               uq: Optional["A.UnionQuery"] = None):
    if uq is not None and _has_volatile_call(uq):
        return None
    if not params:
        return query
    try:
        import json

        return (query, json.dumps(params, sort_keys=True, default=str))
    except (TypeError, ValueError):
        return None


def _truthy(v: Any) -> bool:
    return bool(v) if v is not None else False


def _to_str(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _cypher_eq(a: Any, b: Any) -> bool:
    if isinstance(a, (Node, Edge)) and isinstance(b, (Node, Edge)):
        return a.id == b.id
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if isinstance(a, list) and isinstance(b, list):
        return len(a) == len(b) and all(_cypher_eq(x, y) for x, y in zip(a, b))
    return a == b


_TYPE_ORDER = {str: 0, bool: 1, int: 2, float: 2, list: 3, dict: 4, type(None): 9}


def _cypher_cmp(a: Any, b: Any) -> int:
    """Total order for ORDER BY: numbers < strings? Neo4j: null sorts last
    ascending; mixed types ordered by type."""
    if a is None and b is None:
        return 0
    if a is None:
        return 1
    if b is None:
        return -1
    ta = _TYPE_ORDER.get(type(a), 5)
    tb = _TYPE_ORDER.get(type(b), 5)
    if ta != tb:
        return -1 if ta < tb else 1
    try:
        if a < b:
            return -1
        if a > b:
            return 1
        return 0
    except TypeError:
        return 0


def _cmp_key(v):
    import functools as _ft

    class K:
        def __init__(self, val):
            self.val = val

        def __lt__(self, other):
            return _cypher_cmp(self.val, other.val) < 0

    return K(v)


def _hashable(vals: Sequence[Any]) -> Any:
    out = []
    for v in vals:
        if isinstance(v, (Node, Edge)):
            out.append(("__ent__", v.id))
        elif isinstance(v, list):
            out.append(("__list__", _hashable(v)))
        elif isinstance(v, dict):
            out.append(("__map__", tuple(sorted(
                (k, _hashable([x])) for k, x in v.items()
            ))))
        elif isinstance(v, PathValue):
            out.append(("__path__", tuple(n.id for n in v.nodes),
                        tuple(r.id for r in v.rels)))
        else:
            out.append(v)
    return tuple(out)


def _strip_null_props(props: Dict[str, Any]) -> None:
    for k in [k for k, v in props.items() if v is None]:
        del props[k]


def _refresh(row: Dict, ctx, node_id: str) -> Dict:
    """Re-fetch a mutated node into every binding that references it."""
    try:
        fresh = ctx.storage.get_node(node_id)
    except KeyError:
        return row
    out = dict(row)
    for k, v in out.items():
        if isinstance(v, Node) and v.id == node_id:
            out[k] = fresh
    return out


def _refresh_edge(row: Dict, ctx, edge_id: str) -> Dict:
    try:
        fresh = ctx.storage.get_edge(edge_id)
    except KeyError:
        return row
    out = dict(row)
    for k, v in out.items():
        if isinstance(v, Edge) and v.id == edge_id:
            out[k] = fresh
    return out


def _contains_agg(e: A.Expr) -> bool:
    if isinstance(e, A.FuncCall):
        if _is_agg_name(e.name):
            return True
        return any(_contains_agg(a) for a in e.args)
    if isinstance(e, A.Binary):
        return _contains_agg(e.left) or _contains_agg(e.right)
    if isinstance(e, A.Unary):
        return _contains_agg(e.operand)
    if isinstance(e, A.Prop):
        return _contains_agg(e.target)
    if isinstance(e, A.ListExpr):
        return any(_contains_agg(x) for x in e.items)
    if isinstance(e, A.MapExpr):
        return any(_contains_agg(v) for _, v in e.items)
    if isinstance(e, A.Index):
        return _contains_agg(e.target) or _contains_agg(e.index)
    if isinstance(e, A.Slice):
        parts = [e.target] + [x for x in (e.start, e.end) if x is not None]
        return any(_contains_agg(p) for p in parts)
    if isinstance(e, A.ListComp):
        parts = [e.source] + [x for x in (e.where, e.projection) if x is not None]
        return any(_contains_agg(p) for p in parts)
    if isinstance(e, A.ListPredicate):
        return _contains_agg(e.source) or _contains_agg(e.where)
    if isinstance(e, A.Reduce):
        return any(_contains_agg(p) for p in (e.init, e.source, e.expr))
    if isinstance(e, A.CaseExpr):
        parts = [e.subject] if e.subject else []
        for c, v in e.whens:
            parts += [c, v]
        if e.default:
            parts.append(e.default)
        return any(_contains_agg(p) for p in parts if p is not None)
    return False
