"""Cypher parser: clauses, patterns, Pratt expression parsing.

Covers the clause surface the reference routes in
pkg/cypher/executor_internal.go: MATCH / OPTIONAL MATCH / WHERE / RETURN /
WITH / CREATE / MERGE / SET / REMOVE / DELETE / DETACH DELETE / UNWIND /
CALL ... YIELD / ORDER BY / SKIP / LIMIT / UNION [ALL].
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from nornicdb_tpu.errors import CypherSyntaxError
from nornicdb_tpu.query.ast import (
    Binary,
    CallClause,
    CaseExpr,
    Clause,
    CreateClause,
    DeleteClause,
    Exists,
    Expr,
    FuncCall,
    Index,
    IsNull,
    LabelCheck,
    ListComp,
    ListPredicate,
    Reduce,
    ListExpr,
    Literal,
    MapExpr,
    MatchClause,
    MergeClause,
    Param,
    PatternNode,
    PatternPath,
    PatternPredicate,
    PatternRel,
    Prop,
    ProjectionItem,
    Query,
    RemoveClause,
    ReturnClause,
    SetClause,
    SetItem,
    Slice,
    UnionQuery,
    Unary,
    UnwindClause,
    Var,
    WithClause,
)
from nornicdb_tpu.query.tokens import (
    EOF,
    IDENT,
    NUMBER,
    OP,
    PARAM,
    PUNCT,
    STRING,
    Token,
    TokenStream,
    tokenize,
)

_CLAUSE_STARTERS = {
    "MATCH", "OPTIONAL", "WHERE", "RETURN", "WITH", "CREATE", "MERGE",
    "SET", "REMOVE", "DELETE", "DETACH", "UNWIND", "CALL", "ORDER",
    "SKIP", "LIMIT", "UNION", "ON", "YIELD", "FOREACH", "USE",
}

_KEYWORD_LITERALS = {"TRUE": True, "FALSE": False, "NULL": None}


def parse(query: str) -> UnionQuery:
    ts = TokenStream(tokenize(query))
    parts: List[Query] = []
    alls: List[bool] = []
    while True:
        parts.append(_parse_single(ts))
        if ts.accept_kw("UNION"):
            alls.append(bool(ts.accept_kw("ALL")))
            continue
        break
    if not ts.at_end():
        t = ts.peek()
        raise CypherSyntaxError(f"unexpected token {t.value!r} at {t.pos}")
    return UnionQuery(parts=parts, alls=alls)


def _parse_single(ts: TokenStream) -> Query:
    clauses: List[Clause] = []
    while not ts.at_end() and not ts.peek_kw("UNION"):
        t = ts.peek()
        if t.kind == PUNCT and t.value == ";":
            ts.next()
            continue
        if t.kind != IDENT:
            raise CypherSyntaxError(f"expected clause, got {t.value!r} at {t.pos}")
        kw = t.upper()
        if kw == "MATCH":
            ts.next()
            clauses.append(_parse_match(ts, optional=False))
        elif kw == "OPTIONAL":
            ts.next()
            ts.expect("MATCH")
            clauses.append(_parse_match(ts, optional=True))
        elif kw == "CREATE":
            ts.next()
            clauses.append(CreateClause(paths=_parse_patterns(ts)))
        elif kw == "MERGE":
            ts.next()
            clauses.append(_parse_merge(ts))
        elif kw == "SET":
            ts.next()
            clauses.append(SetClause(items=_parse_set_items(ts)))
        elif kw == "REMOVE":
            ts.next()
            clauses.append(RemoveClause(items=_parse_remove_items(ts)))
        elif kw == "DELETE":
            ts.next()
            clauses.append(_parse_delete(ts, detach=False))
        elif kw == "DETACH":
            ts.next()
            ts.expect("DELETE")
            clauses.append(_parse_delete(ts, detach=True))
        elif kw == "UNWIND":
            ts.next()
            expr = parse_expression(ts)
            ts.expect("AS")
            var = ts.next().value
            clauses.append(UnwindClause(expr=expr, var=var))
        elif kw == "WITH":
            ts.next()
            clauses.append(_parse_projection(ts, is_return=False))
        elif kw == "RETURN":
            ts.next()
            clauses.append(_parse_projection(ts, is_return=True))
        elif kw == "CALL":
            ts.next()
            clauses.append(_parse_call(ts))
        elif kw == "WHERE":
            # stray WHERE after WITH (Cypher allows WITH ... WHERE ...)
            ts.next()
            cond = parse_expression(ts)
            if clauses and isinstance(clauses[-1], (WithClause, MatchClause)):
                clauses[-1].where = (
                    cond
                    if clauses[-1].where is None
                    else Binary("AND", clauses[-1].where, cond)
                )
            else:
                raise CypherSyntaxError("WHERE without MATCH/WITH")
        else:
            raise CypherSyntaxError(f"unsupported clause {kw!r} at {t.pos}")
    return Query(clauses=clauses)


# -- clause helpers ------------------------------------------------------


def _parse_match(ts: TokenStream, optional: bool) -> MatchClause:
    paths = _parse_patterns(ts)
    where = None
    if ts.accept_kw("WHERE"):
        where = parse_expression(ts)
    return MatchClause(paths=paths, optional=optional, where=where)


def _parse_merge(ts: TokenStream) -> MergeClause:
    paths = _parse_patterns(ts)
    if len(paths) != 1:
        raise CypherSyntaxError("MERGE takes a single pattern")
    clause = MergeClause(path=paths[0])
    while ts.peek_kw("ON"):
        ts.accept_kw("ON")
        if ts.accept_kw("CREATE"):
            ts.expect("SET")
            clause.on_create.extend(_parse_set_items(ts))
        elif ts.accept_kw("MATCH"):
            ts.expect("SET")
            clause.on_match.extend(_parse_set_items(ts))
        else:
            raise CypherSyntaxError("expected ON CREATE / ON MATCH")
    return clause


def _parse_delete(ts: TokenStream, detach: bool) -> DeleteClause:
    exprs = [parse_expression(ts)]
    while ts.accept(",", PUNCT):
        exprs.append(parse_expression(ts))
    return DeleteClause(exprs=exprs, detach=detach)


def _parse_set_items(ts: TokenStream) -> List[SetItem]:
    items: List[SetItem] = []
    while True:
        target = parse_expression(ts, stop_at_eq=True)
        if isinstance(target, LabelCheck):
            items.append(SetItem(target=None, value=None, labels=target.labels,
                                 merge_map=False))
            items[-1].target = Var(target.var)
        elif ts.accept("+=", OP):
            items.append(SetItem(target=target, value=parse_expression(ts),
                                 merge_map=True))
        elif ts.accept("=", OP):
            value = parse_expression(ts)
            if isinstance(target, Var):
                items.append(SetItem(target=target, value=value, replace_map=True))
            else:
                items.append(SetItem(target=target, value=value))
        else:
            raise CypherSyntaxError("expected = or += in SET")
        if not ts.accept(",", PUNCT):
            break
    return items


def _parse_remove_items(ts: TokenStream) -> List[SetItem]:
    items: List[SetItem] = []
    while True:
        target = parse_expression(ts, stop_at_eq=True)
        if isinstance(target, LabelCheck):
            items.append(SetItem(target=Var(target.var), value=None,
                                 labels=target.labels))
        else:
            items.append(SetItem(target=target, value=None))
        if not ts.accept(",", PUNCT):
            break
    return items


def _parse_projection(ts: TokenStream, is_return: bool):
    distinct = bool(ts.accept_kw("DISTINCT"))
    star = False
    items: List[ProjectionItem] = []
    if ts.peek().kind == OP and ts.peek().value == "*":
        ts.next()
        star = True
        if ts.accept(",", PUNCT):
            items.extend(_parse_projection_items(ts))
    else:
        items.extend(_parse_projection_items(ts))
    order_by: List[Tuple[Expr, bool]] = []
    skip = limit = None
    where = None
    if ts.accept_kw("ORDER"):
        ts.expect("BY")
        while True:
            e = parse_expression(ts)
            desc = False
            if ts.accept_kw("DESC") or ts.accept_kw("DESCENDING"):
                desc = True
            elif ts.accept_kw("ASC") or ts.accept_kw("ASCENDING"):
                desc = False
            order_by.append((e, desc))
            if not ts.accept(",", PUNCT):
                break
    if ts.accept_kw("SKIP"):
        skip = parse_expression(ts)
    if ts.accept_kw("LIMIT"):
        limit = parse_expression(ts)
    if not is_return and ts.accept_kw("WHERE"):
        where = parse_expression(ts)
    if is_return:
        return ReturnClause(items=items, distinct=distinct, star=star,
                            order_by=order_by, skip=skip, limit=limit)
    return WithClause(items=items, distinct=distinct, star=star, where=where,
                      order_by=order_by, skip=skip, limit=limit)


def _expr_text(ts: TokenStream, start: int) -> str:
    toks = ts.toks[start : ts.i]
    return " ".join(t.value for t in toks)


def _parse_projection_items(ts: TokenStream) -> List[ProjectionItem]:
    items = []
    while True:
        start = ts.i
        e = parse_expression(ts)
        alias = None
        if ts.accept_kw("AS"):
            alias = ts.next().value
        items.append(ProjectionItem(expr=e, alias=alias, text=_expr_text(ts, start)))
        if not ts.accept(",", PUNCT):
            break
    return items


def _parse_call(ts: TokenStream) -> CallClause:
    # procedure name: dotted identifiers
    name_parts = [ts.next().value]
    while ts.accept(".", PUNCT):
        name_parts.append(ts.next().value)
    proc = ".".join(name_parts)
    args: List[Expr] = []
    if ts.accept("(", PUNCT):
        if not ts.accept(")", PUNCT):
            while True:
                args.append(parse_expression(ts))
                if not ts.accept(",", PUNCT):
                    break
            ts.expect(")")
    clause = CallClause(proc=proc.lower(), args=args)
    if ts.accept_kw("YIELD"):
        if ts.peek().kind == OP and ts.peek().value == "*":
            ts.next()
            clause.yield_star = True
        else:
            while True:
                name = ts.next().value
                alias = None
                if ts.accept_kw("AS"):
                    alias = ts.next().value
                clause.yield_items.append((name, alias))
                if not ts.accept(",", PUNCT):
                    break
        if ts.accept_kw("WHERE"):
            clause.where = parse_expression(ts)
    return clause


# -- patterns ------------------------------------------------------------


def _parse_patterns(ts: TokenStream) -> List[PatternPath]:
    paths = [_parse_path(ts)]
    while ts.accept(",", PUNCT):
        paths.append(_parse_path(ts))
    return paths


def _parse_path(ts: TokenStream) -> PatternPath:
    path_var = None
    # p = (...)
    if (
        ts.peek().kind == IDENT
        and ts.peek().upper() not in _CLAUSE_STARTERS
        and ts.peek(1).kind == OP
        and ts.peek(1).value == "="
        and (
            (ts.peek(2).kind == PUNCT and ts.peek(2).value == "(")
            or (ts.peek(2).kind == IDENT
                and ts.peek(2).upper() in ("SHORTESTPATH",
                                           "ALLSHORTESTPATHS")
                and ts.peek(3).kind == PUNCT and ts.peek(3).value == "(")
        )
    ):
        path_var = ts.next().value
        ts.next()  # =
    # MATCH-position shortestPath((a)-[*]-(b)) — endpoints may be
    # UNBOUND here (the executor scans candidates and runs BFS per
    # pair); the expression-position form still parses as a FuncCall
    if (
        ts.peek().kind == IDENT
        and ts.peek().upper() in ("SHORTESTPATH", "ALLSHORTESTPATHS")
        and ts.peek(1).kind == PUNCT and ts.peek(1).value == "("
    ):
        kind = "single" if ts.peek().upper() == "SHORTESTPATH" else "all"
        ts.next()
        ts.expect("(")
        inner = _parse_path(ts)
        ts.expect(")")
        inner.path_var = path_var
        inner.shortest = kind
        return inner
    nodes = [_parse_pattern_node(ts)]
    rels: List[PatternRel] = []
    while True:
        t = ts.peek()
        if t.kind == OP and t.value in ("-", "<-"):
            rels.append(_parse_pattern_rel(ts))
            nodes.append(_parse_pattern_node(ts))
        elif t.kind == OP and t.value == "<":
            rels.append(_parse_pattern_rel(ts))
            nodes.append(_parse_pattern_node(ts))
        else:
            break
    return PatternPath(nodes=nodes, rels=rels, path_var=path_var)


def _parse_pattern_node(ts: TokenStream) -> PatternNode:
    ts.expect("(")
    var = None
    labels: List[str] = []
    props = None
    if ts.peek().kind == IDENT:
        var = ts.next().value
    while ts.accept(":", PUNCT):
        labels.append(ts.next().value)
    if ts.peek().kind == PUNCT and ts.peek().value == "{":
        props = _parse_map(ts)
    ts.expect(")")
    return PatternNode(var=var, labels=labels, props=props)


def _parse_pattern_rel(ts: TokenStream) -> PatternRel:
    rel = PatternRel(var=None)
    t = ts.next()  # '-', '<-' or '<'
    incoming = False
    if t.value == "<-":
        incoming = True
    elif t.value == "<":
        ts.expect("-", OP)
        incoming = True
    if ts.accept("[", PUNCT):
        if ts.peek().kind == IDENT:
            rel.var = ts.next().value
        if ts.accept(":", PUNCT):
            rel.types.append(ts.next().value)
            while ts.accept("|", PUNCT):
                ts.accept(":", PUNCT)  # allow |:TYPE legacy syntax
                rel.types.append(ts.next().value)
        if ts.peek().kind == OP and ts.peek().value == "*":
            ts.next()
            rel.min_hops, rel.max_hops = 1, -1
            if ts.peek().kind == NUMBER:
                rel.min_hops = int(ts.next().value)
                rel.max_hops = rel.min_hops
                if ts.accept("..", OP):
                    if ts.peek().kind == NUMBER:
                        rel.max_hops = int(ts.next().value)
                    else:
                        rel.max_hops = -1
            elif ts.accept("..", OP):
                rel.min_hops = 1
                if ts.peek().kind == NUMBER:
                    rel.max_hops = int(ts.next().value)
                else:
                    rel.max_hops = -1
        if ts.peek().kind == PUNCT and ts.peek().value == "{":
            rel.props = _parse_map(ts)
        ts.expect("]")
    # closing direction
    if incoming:
        ts.expect("-", OP)
        rel.direction = "in"
    else:
        nxt = ts.next()
        if nxt.kind == OP and nxt.value == "->":
            rel.direction = "out"
        elif nxt.kind == OP and nxt.value == "-":
            rel.direction = "both"
        else:
            raise CypherSyntaxError(f"bad relationship direction at {nxt.pos}")
    return rel


def _parse_map(ts: TokenStream) -> MapExpr:
    ts.expect("{")
    items: List[Tuple[str, Expr]] = []
    if not ts.accept("}", PUNCT):
        while True:
            key_tok = ts.next()
            if key_tok.kind not in (IDENT, STRING):
                raise CypherSyntaxError(f"bad map key at {key_tok.pos}")
            ts.expect(":")
            items.append((key_tok.value, parse_expression(ts)))
            if not ts.accept(",", PUNCT):
                break
        ts.expect("}")
    return MapExpr(items=items)


# -- expressions (Pratt) -------------------------------------------------

_BINARY_PRECEDENCE = {
    "OR": 1,
    "XOR": 2,
    "AND": 3,
    "=": 5, "<>": 5, "<": 5, "<=": 5, ">": 5, ">=": 5, "=~": 5,
    "IN": 5, "STARTS": 5, "ENDS": 5, "CONTAINS": 5,
    "+": 6, "-": 6,
    "*": 7, "/": 7, "%": 7,
    "^": 8,
}


def parse_expression(ts: TokenStream, min_prec: int = 0, stop_at_eq: bool = False) -> Expr:
    left = _parse_unary(ts, stop_at_eq)
    while True:
        t = ts.peek()
        op = None
        if t.kind == OP and t.value in _BINARY_PRECEDENCE:
            if stop_at_eq and t.value == "=":
                break
            op = t.value
        elif t.kind == IDENT:
            kw = t.upper()
            if kw in ("AND", "OR", "XOR", "IN", "CONTAINS"):
                op = kw
            elif kw == "STARTS" and ts.peek(1).kind == IDENT and ts.peek(1).upper() == "WITH":
                op = "STARTS"
            elif kw == "ENDS" and ts.peek(1).kind == IDENT and ts.peek(1).upper() == "WITH":
                op = "ENDS"
            elif kw == "IS":
                # IS NULL / IS NOT NULL
                save = ts.i
                ts.next()
                negated = bool(ts.accept_kw("NOT"))
                if ts.accept_kw("NULL"):
                    left = IsNull(operand=left, negated=negated)
                    continue
                ts.i = save
                break
            else:
                break
        else:
            break
        prec = _BINARY_PRECEDENCE[op]
        if prec < min_prec:
            break
        ts.next()
        if op in ("STARTS", "ENDS"):
            ts.expect("WITH")
            op = op + " WITH"
        right = parse_expression(ts, prec + 1, stop_at_eq)
        left = Binary(op=op, left=left, right=right)
    return left


def _parse_unary(ts: TokenStream, stop_at_eq: bool = False) -> Expr:
    t = ts.peek()
    if t.kind == IDENT and t.upper() == "NOT":
        ts.next()
        return Unary("NOT", _parse_unary(ts, stop_at_eq))
    if t.kind == OP and t.value in ("-", "+"):
        ts.next()
        return Unary(t.value, _parse_unary(ts, stop_at_eq))
    return _parse_postfix(ts, stop_at_eq)


def _parse_postfix(ts: TokenStream, stop_at_eq: bool = False) -> Expr:
    e = _parse_atom(ts, stop_at_eq)
    while True:
        t = ts.peek()
        if t.kind == PUNCT and t.value == ".":
            ts.next()
            name = ts.next().value
            e = Prop(target=e, name=name)
        elif t.kind == PUNCT and t.value == "[":
            ts.next()
            # index or slice
            start = None
            if not (ts.peek().kind == OP and ts.peek().value == ".."):
                start = parse_expression(ts)
            if ts.accept("..", OP):
                end = None
                if not (ts.peek().kind == PUNCT and ts.peek().value == "]"):
                    end = parse_expression(ts)
                e = Slice(target=e, start=start, end=end)
            else:
                e = Index(target=e, index=start)
            ts.expect("]")
        elif (
            t.kind == PUNCT
            and t.value == ":"
            and isinstance(e, Var)
        ):
            # label predicate n:Label[:Label2]
            labels = []
            while ts.accept(":", PUNCT):
                labels.append(ts.next().value)
            e = LabelCheck(var=e.name, labels=labels)
        else:
            break
    return e


def _parse_atom(ts: TokenStream, stop_at_eq: bool = False) -> Expr:
    t = ts.peek()
    if t.kind == STRING:
        ts.next()
        return Literal(t.value)
    if t.kind == NUMBER:
        ts.next()
        v = t.value
        if v.startswith("0x"):
            return Literal(int(v, 16))
        if "." in v or "e" in v or "E" in v:
            return Literal(float(v))
        return Literal(int(v))
    if t.kind == PARAM:
        ts.next()
        return Param(t.value)
    if t.kind == PUNCT and t.value == "(":
        # parenthesized expr OR pattern predicate (a)-[:X]->(b)
        if _looks_like_pattern(ts):
            path = _parse_path(ts)
            return PatternPredicate(pattern=path)
        ts.next()
        e = parse_expression(ts)
        ts.expect(")")
        return e
    if t.kind == PUNCT and t.value == "[":
        # list literal or list comprehension
        ts.next()
        if ts.peek().kind == PUNCT and ts.peek().value == "]":
            ts.next()
            return ListExpr(items=[])
        # try comprehension: IDENT IN expr [WHERE ...] [| expr]
        if (
            ts.peek().kind == IDENT
            and ts.peek(1).kind == IDENT
            and ts.peek(1).upper() == "IN"
        ):
            var = ts.next().value
            ts.next()  # IN
            source = parse_expression(ts)
            where = None
            proj = None
            if ts.accept_kw("WHERE"):
                where = parse_expression(ts)
            if ts.accept("|", PUNCT):
                proj = parse_expression(ts)
            ts.expect("]")
            return ListComp(var=var, source=source, where=where, projection=proj)
        items = [parse_expression(ts)]
        while ts.accept(",", PUNCT):
            items.append(parse_expression(ts))
        ts.expect("]")
        return ListExpr(items=items)
    if t.kind == PUNCT and t.value == "{":
        return _parse_map(ts)
    if t.kind == IDENT:
        kw = t.upper()
        if kw in _KEYWORD_LITERALS:
            ts.next()
            return Literal(_KEYWORD_LITERALS[kw])
        if kw == "CASE":
            return _parse_case(ts)
        if kw == "EXISTS":
            save = ts.i
            ts.next()
            if ts.peek().kind == PUNCT and ts.peek().value == "(":
                ts.next()
                if _looks_like_pattern(ts):
                    path = _parse_path(ts)
                    ts.expect(")")
                    return Exists(pattern=path, prop=None)
                inner = parse_expression(ts)
                ts.expect(")")
                return Exists(pattern=None, prop=inner)
            ts.i = save
        if (
            kw in ("ALL", "ANY", "NONE", "SINGLE")
            and ts.peek(1).kind == PUNCT and ts.peek(1).value == "("
            and ts.peek(2).kind == IDENT
            and ts.peek(3).kind == IDENT and ts.peek(3).upper() == "IN"
        ):
            # all/any/none/single(x IN list WHERE pred)
            ts.next()  # keyword
            ts.expect("(")
            var = ts.next().value
            ts.next()  # IN
            source = parse_expression(ts)
            if not ts.accept_kw("WHERE"):
                raise CypherSyntaxError(f"{kw.lower()}() requires WHERE")
            where = parse_expression(ts)
            ts.expect(")")
            return ListPredicate(kind=kw.lower(), var=var, source=source,
                                 where=where)
        if kw == "REDUCE" and ts.peek(1).kind == PUNCT and ts.peek(1).value == "(":
            # reduce(acc = init, x IN list | expr)
            ts.next()
            ts.expect("(")
            acc = ts.next().value
            if not (ts.peek().kind == OP and ts.peek().value == "="):
                raise CypherSyntaxError("reduce() expects acc = init")
            ts.next()
            init = parse_expression(ts)
            ts.expect(",")
            var = ts.next().value
            if not (ts.peek().kind == IDENT and ts.peek().upper() == "IN"):
                raise CypherSyntaxError("reduce() expects `x IN list`")
            ts.next()
            source = parse_expression(ts)
            if not ts.accept("|", PUNCT):
                raise CypherSyntaxError("reduce() expects `| expr`")
            expr = parse_expression(ts)
            ts.expect(")")
            return Reduce(acc=acc, init=init, var=var, source=source, expr=expr)
        if (
            kw in ("EXTRACT", "FILTER")
            and ts.peek(1).kind == PUNCT and ts.peek(1).value == "("
            and ts.peek(2).kind == IDENT
            and ts.peek(3).kind == IDENT and ts.peek(3).upper() == "IN"
        ):
            # legacy forms (reference functions_eval_math.go:1388):
            # extract(x IN list | expr), filter(x IN list WHERE pred) —
            # sugar for list comprehensions
            ts.next()
            ts.expect("(")
            var = ts.next().value
            ts.next()  # IN
            source = parse_expression(ts)
            where = None
            proj = None
            if kw == "FILTER":
                if not ts.accept_kw("WHERE"):
                    raise CypherSyntaxError("filter() requires WHERE")
                where = parse_expression(ts)
            else:
                if not ts.accept("|", PUNCT):
                    raise CypherSyntaxError("extract() expects `| expr`")
                proj = parse_expression(ts)
            ts.expect(")")
            return ListComp(var=var, source=source, where=where,
                            projection=proj)
        if kw == "COUNT" and ts.peek(1).kind == PUNCT and ts.peek(1).value == "{":
            # COUNT { (n)--() } subquery-count — parse pattern inside
            ts.next()
            ts.expect("{")
            path = _parse_path(ts)
            ts.expect("}")
            return FuncCall(name="__pattern_count__", args=[PatternPredicate(path)])
        # function call: name(...) possibly dotted
        if _is_func_call(ts):
            name_parts = [ts.next().value]
            while ts.accept(".", PUNCT):
                name_parts.append(ts.next().value)
            ts.expect("(")
            distinct = bool(ts.accept_kw("DISTINCT"))
            star = False
            args: List[Expr] = []
            if ts.peek().kind == OP and ts.peek().value == "*":
                ts.next()
                star = True
            elif not (ts.peek().kind == PUNCT and ts.peek().value == ")"):
                args.append(parse_expression(ts))
                while ts.accept(",", PUNCT):
                    args.append(parse_expression(ts))
            ts.expect(")")
            return FuncCall(name=".".join(name_parts).lower(), args=args,
                            distinct=distinct, star=star)
        ts.next()
        return Var(t.value)
    raise CypherSyntaxError(f"unexpected token {t.value!r} at {t.pos}")


def _is_func_call(ts: TokenStream) -> bool:
    """IDENT (.IDENT)* ( — lookahead."""
    j = 0
    if ts.peek(j).kind != IDENT:
        return False
    j += 1
    while ts.peek(j).kind == PUNCT and ts.peek(j).value == ".":
        if ts.peek(j + 1).kind != IDENT:
            return False
        j += 2
    return ts.peek(j).kind == PUNCT and ts.peek(j).value == "("


def _looks_like_pattern(ts: TokenStream) -> bool:
    """At '(' — does this start a NODE pattern followed by a relationship?
    The group's contents must have node-pattern shape ([var][:Label...]
    [{props}]) — '(1+2)-(3)' is arithmetic, not a pattern — and the matching
    ')' must be followed by a rel arrow."""
    if not (ts.peek().kind == PUNCT and ts.peek().value == "("):
        return False
    j = 1
    # optional variable
    if ts.peek(j).kind == IDENT:
        j += 1
    # optional :Label chain
    while ts.peek(j).kind == PUNCT and ts.peek(j).value == ":":
        if ts.peek(j + 1).kind != IDENT:
            return False
        j += 2
    # optional props map — skip balanced braces
    if ts.peek(j).kind == PUNCT and ts.peek(j).value == "{":
        depth = 0
        while True:
            t = ts.peek(j)
            if t.kind == EOF:
                return False
            if t.kind == PUNCT and t.value == "{":
                depth += 1
            elif t.kind == PUNCT and t.value == "}":
                depth -= 1
                if depth == 0:
                    j += 1
                    break
            j += 1
    if not (ts.peek(j).kind == PUNCT and ts.peek(j).value == ")"):
        return False
    nxt = ts.peek(j + 1)
    if nxt.kind != OP:
        return False
    if nxt.value in ("<-", "<"):
        return True
    if nxt.value == "-":
        # '(a)-(b)' is subtraction; a pattern needs '--', '-[' or '-->'
        after = ts.peek(j + 2)
        return (after.kind == OP and after.value in ("-", "->")) or (
            after.kind == PUNCT and after.value == "["
        )
    return False


def _parse_case(ts: TokenStream) -> CaseExpr:
    ts.expect("CASE")
    subject = None
    if not ts.peek_kw("WHEN"):
        subject = parse_expression(ts)
    whens: List[Tuple[Expr, Expr]] = []
    while ts.accept_kw("WHEN"):
        cond = parse_expression(ts)
        ts.expect("THEN")
        val = parse_expression(ts)
        whens.append((cond, val))
    default = None
    if ts.accept_kw("ELSE"):
        default = parse_expression(ts)
    ts.expect("END")
    return CaseExpr(subject=subject, whens=whens, default=default)
