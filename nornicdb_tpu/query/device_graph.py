"""Device-resident columnar graph plane: the LDBC Cypher family compiled
the way search was compiled.

PRs 2/4/6/8 made vector and hybrid search fully device-resident; the
Cypher fast paths that produce the headline ``ldbc_snb_cypher_geomean``
(query/fastpaths.py over query/columnar.py) still ran on host numpy.
This module snapshots the ``ColumnarCatalog``'s hot structures — CSR
adjacency, segment-sorted strips, label masks, incidence matrices —
into device arrays and compiles the LDBC fast-path shapes onto them as
batched gather/segment-sum programs (the CAGRA-style fixed-shape
traversal pattern; ``ops/graph.py`` PageRank already proves the
segment-sum half at ~1 ms / 20 iterations):

- **chain top-k** (``recent_messages_friends``): anchors -> CSR friend
  gather -> per-friend strip heads -> one ``lax.top_k`` merge, B
  anchors per dispatch. Concurrent point lookups coalesce through a
  ``BatchCoalescer`` so they ride ONE dispatch; key order is encoded as
  a dense tie-sharing rank so the device merge is *row-identical* to
  the host's stable ``argsort`` (no float-precision drift: the f64 sort
  keys never leave the host).
- **strip aggregation** (``avg_friends_per_city``): the materialized
  two-hop grouped-degree view (deg/sum_deg/nnz) built as device
  segment-sums + a lexicographic distinct-pair pass, installed back
  into the catalog so every downstream read and the incremental
  maintenance machinery are unchanged — the arrays are verified-exact
  integers, so parity is inherited, not re-proven per query.
- **co-occurrence Gram** (``tag_cooccurrence``): the incidence
  contraction ``Ma^T @ Mb`` as a device matmul under the same 2^24
  exactness bound the host path uses (0/1-integer f32 products are
  exact below it, so host and device produce equal integers).
- **fused traverse-then-rank**: chain expansion feeding the brute
  cosine top-k over the vector index's device matrix in ONE program —
  the service-level graph+vector query (SURVEY §6: no single baseline
  serves it).

Freshness discipline (PR 2/4/6/8): every snapshot is keyed on the
catalog's mutation-generation ``version()``; any write bumps it and the
next read degrades to the host path while the snapshot lazily rebuilds
— never a wrong answer. Guards (int32 rank overflow, 2^24 count
exactness, torn concurrent builds) likewise degrade to host.

Routing: ``NORNICDB_GRAPH_DEVICE`` = ``off`` | ``auto`` (default) |
``on``. ``auto`` keeps small catalogs on the host path
(``NORNICDB_GRAPH_DEVICE_MIN_N`` structure entries) and only dispatches
chain lookups on-device when concurrent demand actually coalesces a
batch (``NORNICDB_GRAPH_DEVICE_MIN_B`` riders) — a single-stream read
of a device-eligible catalog stays on the ~50 us host path instead of
paying a ~100 us+ b=1 dispatch. ``on`` forces the device route (tests,
benches, real accelerators at batch).
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from nornicdb_tpu.obs import declare_kind, record_dispatch
from nornicdb_tpu.obs import audit as _audit
from nornicdb_tpu.obs import cost as _cost
from nornicdb_tpu.obs.metrics import REGISTRY
from nornicdb_tpu.search.microbatch import BatchCoalescer, pow2_bucket

_EVENTS_C = REGISTRY.counter(
    "nornicdb_device_graph_events_total",
    "Device graph plane lifecycle/degrade events", labels=("event",))

# dispatch kinds pre-registered so the compile-cache accounting carries
# their series (and the sentinel's growth gate sees them) from start
KIND_CHAIN = "graph_chain_topk"
KIND_AGG = "graph_strip_agg"
KIND_GRAM = "graph_cooc_gram"
KIND_RANK = "graph_traverse_rank"
for _k in (KIND_CHAIN, KIND_AGG, KIND_GRAM, KIND_RANK):
    declare_kind(_k)

# canonical serving-tier names (obs/audit taxonomy) for the plane's
# query-shaped rungs (strip/gram are builds, not per-query serving)
TIER_CHAIN = "graph_chain_device"
TIER_RANK = "graph_traverse_rank_device"


def _ledger(from_tier: str, reason: str,
            versions: "Dict[str, Any] | None" = None) -> None:
    """Structured degrade record for a device-graph -> host step (the
    legacy device_graph_events_total labels stay as aliases)."""
    _audit.record_degrade("graph", from_tier, "host", reason,
                          index="device_graph", versions=versions)

_I32_MAX = 2 ** 31 - 1
_EXACT_F32 = float(2 ** 24)  # integer-exactness bound for f32 sums


def graph_device_mode() -> str:
    mode = os.environ.get("NORNICDB_GRAPH_DEVICE", "auto").lower()
    return mode if mode in ("off", "auto", "on") else "auto"


def graph_device_min_n() -> int:
    try:
        return int(os.environ.get("NORNICDB_GRAPH_DEVICE_MIN_N", "200000"))
    except ValueError:
        return 200_000


def graph_device_min_b() -> int:
    try:
        return int(os.environ.get("NORNICDB_GRAPH_DEVICE_MIN_B", "4"))
    except ValueError:
        return 4


def _event(name: str) -> None:
    _EVENTS_C.labels(name).inc()


@functools.lru_cache(maxsize=1)
def _cpu_backend() -> bool:
    """True on the CPU PJRT fallback. ``auto`` mode only engages the
    device plane on a real accelerator — measured on CPU the host numpy
    paths win every rung (strip build 1.7 ms host vs 78 ms XLA-CPU at
    LDBC scale; coalesced chain dispatch roughly GIL-parity) — the same
    host-path policy as ops/graph.py PageRank and vector_index. ``on``
    forces the device route regardless (tests, benches)."""
    try:
        return _jx().default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — no backend: host paths only
        return True


# -- jitted programs ---------------------------------------------------------
#
# All programs take pow2-padded shapes (static) with dynamic validity
# masks, so the compile universe stays log-sized per kind. int32
# everywhere (x64 is off); every count that could exceed the f32/int32
# exactness bounds is guarded at the call site and degrades to host.


def _jx():
    import jax  # deferred: query/ imports stay light for host-only use

    return jax


@functools.lru_cache(maxsize=None)
def _chain_topk_fn(f: int, kp: int):
    jax = _jx()
    jnp = jax.numpy

    @functools.partial(jax.jit)
    def impl(anchors, kh, indptr1, far1, s_indptr, s_nbr, s_rank, mid_ok):
        b = anchors.shape[0]
        e1 = far1.shape[0]
        s = s_nbr.shape[0]
        a = jnp.maximum(anchors, 0)
        a_valid = anchors >= 0
        start = indptr1[a]
        cnt = indptr1[a + 1] - start
        fi = jnp.arange(f, dtype=jnp.int32)
        fpos = start[:, None] + fi[None, :]
        fvalid = (fi[None, :] < cnt[:, None]) & a_valid[:, None]
        friends = far1[jnp.clip(fpos, 0, max(e1 - 1, 0))]
        fvalid = fvalid & mid_ok[friends]
        sstart = s_indptr[friends]
        scnt = jnp.minimum(s_indptr[friends + 1] - sstart, kh)
        ci = jnp.arange(kp, dtype=jnp.int32)
        cpos = sstart[..., None] + ci[None, None, :]
        cvalid = (ci[None, None, :] < scnt[..., None]) & fvalid[..., None]
        cpos_c = jnp.clip(cpos, 0, max(s - 1, 0))
        width = f * kp
        rank = s_rank[cpos_c].reshape(b, width)
        order_idx = jnp.arange(width, dtype=jnp.int32)
        # composite merge key: dense tie-sharing key rank (primary,
        # ascending == key DESC) then candidate order (friend-major,
        # head-position minor) — exactly the host's stable tie order
        combined = jnp.where(
            cvalid.reshape(b, width),
            rank * width + order_idx[None, :],
            _I32_MAX,
        )
        neg_vals, sel = jax.lax.top_k(-combined, kp)
        sel_valid = (-neg_vals) < _I32_MAX
        sel_f = jnp.take_along_axis(friends, sel // kp, axis=1)
        sel_t = jnp.take_along_axis(
            s_nbr[cpos_c].reshape(b, width), sel, axis=1)
        return sel_f, sel_t, sel_valid

    return impl


@functools.lru_cache(maxsize=None)
def _strip_agg_fn(e1p: int, e2p: int, npad: int):
    jax = _jx()
    jnp = jax.numpy

    @functools.partial(jax.jit)
    def impl(g_e, p_e, pmask_e, keys2, fmask2):
        # terminal-hop filtered degree: one segment-sum over etype2
        deg = jax.ops.segment_sum(
            fmask2.astype(jnp.int32), keys2, num_segments=npad)
        # weighted group sums: f32 (exact while < 2^24; caller-verified)
        w = jnp.where(pmask_e, deg[p_e].astype(jnp.float32), 0.0)
        sum_deg = jax.ops.segment_sum(w, g_e, num_segments=npad)
        # DISTINCT (g, p) pairs with deg[p] > 0: lexicographic sort then
        # first-occurrence flags — no g*n+p composite (overflows int32)
        valid = pmask_e & (deg[p_e] > 0)
        g_s = jnp.where(valid, g_e, npad - 1)
        p_s = jnp.where(valid, p_e, npad - 1)
        g_sorted, p_sorted = jax.lax.sort((g_s, p_s), num_keys=2)
        prev_g = jnp.concatenate([jnp.full((1,), -1, jnp.int32),
                                  g_sorted[:-1]])
        prev_p = jnp.concatenate([jnp.full((1,), -1, jnp.int32),
                                  p_sorted[:-1]])
        first = (g_sorted != prev_g) | (p_sorted != prev_p)
        live = g_sorted < (npad - 1)
        nnz = jax.ops.segment_sum(
            (first & live).astype(jnp.int32), g_sorted, num_segments=npad)
        return deg, sum_deg, nnz, jnp.max(deg), jnp.max(sum_deg)

    return impl


@functools.lru_cache(maxsize=None)
def _gram_fn(mp: int):
    jax = _jx()
    jnp = jax.numpy

    @functools.partial(jax.jit)
    def impl(ma, mb):
        # 0/1-integer f32 contraction: exact below 2^24 (caller-guarded)
        return ma.T @ mb

    return impl


@functools.lru_cache(maxsize=None)
def _traverse_rank_fn(f1: int, f2: int, kp: int):
    jax = _jx()
    jnp = jax.numpy

    @functools.partial(jax.jit)
    def impl(anchors, q, indptr1, far1, indptr2, far2, slot_of_row,
             matrix, valid, n_nodes):
        b = anchors.shape[0]
        e1 = far1.shape[0]
        a = jnp.maximum(anchors, 0)
        a_valid = anchors >= 0
        start = indptr1[a]
        cnt = indptr1[a + 1] - start
        fi = jnp.arange(f1, dtype=jnp.int32)
        fpos = start[:, None] + fi[None, :]
        fvalid = (fi[None, :] < cnt[:, None]) & a_valid[:, None]
        rows = far1[jnp.clip(fpos, 0, max(e1 - 1, 0))]
        if f2 > 0:
            e2 = far2.shape[0]
            s2 = indptr2[rows]
            c2 = indptr2[rows + 1] - s2
            gi = jnp.arange(f2, dtype=jnp.int32)
            gpos = s2[..., None] + gi[None, None, :]
            gvalid = (gi[None, None, :] < c2[..., None]) & fvalid[..., None]
            rows = far2[jnp.clip(gpos, 0, max(e2 - 1, 0))].reshape(b, f1 * f2)
            rvalid = gvalid.reshape(b, f1 * f2)
        else:
            rvalid = fvalid
        # dedup: ascending sort with the invalid sentinel past every row
        rows_s = jnp.sort(jnp.where(rvalid, rows, n_nodes), axis=1)
        prev = jnp.concatenate(
            [jnp.full((b, 1), -1, jnp.int32), rows_s[:, :-1]], axis=1)
        keep = (rows_s != prev) & (rows_s < n_nodes)
        slots = slot_of_row[jnp.clip(rows_s, 0, slot_of_row.shape[0] - 1)]
        ok = keep & (slots >= 0)
        slots_c = jnp.maximum(slots, 0)
        ok = ok & valid[slots_c]
        vecs = matrix[slots_c]  # [b, F, D] frontier gather
        qn = q / jnp.maximum(
            jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        scores = jnp.einsum("bd,bfd->bf", qn, vecs)
        scores = jnp.where(ok, scores, -jnp.inf)
        vals, idx = jax.lax.top_k(scores, kp)
        sel_rows = jnp.take_along_axis(rows_s, idx, axis=1)
        return vals, sel_rows

    return impl


# -- the plane ---------------------------------------------------------------


class DeviceGraphPlane:
    """Versioned device snapshots of one ``ColumnarCatalog`` plus the
    compiled LDBC programs over them. One instance per executor; all
    public entry points return ``None`` to mean "serve on the host
    path" — the caller never distinguishes *why* (gated off, too small,
    stale snapshot, guard tripped): every miss is a correct host
    answer."""

    # refuse device arrays past this many entries per structure (int32
    # indices everywhere)
    MAX_ENTRIES = _I32_MAX - 2

    def __init__(self, catalog):
        self.catalog = catalog
        self._lock = threading.Lock()
        self._snaps: Dict[Any, Dict[str, Any]] = {}
        self._batchers: Dict[Any, BatchCoalescer] = {}
        # demand heuristic for auto mode: live chain reads in flight.
        # Guarded by its own tiny lock — a bare `+=` from concurrent
        # query threads loses updates, and a lost decrement would pin
        # the gate permanently (stuck demand or stuck silence)
        self.inflight = 0
        self._inflight_lock = threading.Lock()
        self.dispatches = 0
        # cached forced-mode flag for the per-query pre-gate (env reads
        # cost ~1 us — 2-8% of a whole host chain query); refreshed
        # every 256 single-stream calls. Staleness is only a routing
        # hint: the batch leader re-reads the env authoritatively, so a
        # stale True costs one wasted coalescer submit, never a wrong
        # answer or a gated-off dispatch.
        self._forced: Optional[bool] = None
        self._gate_tick = 0

    # -- snapshot bookkeeping ---------------------------------------------

    def _get_snap(self, key) -> Optional[Dict[str, Any]]:
        """Fetch a live snapshot. Snapshots carrying per-etype delta
        keys (``etv``/``etypes``, ISSUE 19) stay live across writes to
        UNRELATED edge types; legacy whole-catalog snapshots compare
        the global version as before."""
        with self._lock:
            snap = self._snaps.get(key)
        if snap is None:
            return None
        etypes = snap.get("etypes")
        if etypes is not None:
            if snap.get("etv") == self.catalog.etype_versions(etypes):
                return snap
            return None
        if snap.get("version") == self.catalog.version:
            return snap
        return None

    def _put_snap(self, key, snap: Dict[str, Any]) -> bool:
        """Install ``snap`` iff the catalog hasn't moved past its
        version (a build that raced a write must not resurrect a stale
        snapshot — same rule as the catalog's own caches). Per-etype
        snapshots compare their delta key, so an unrelated-etype write
        landing mid-build does not waste the build."""
        etypes = snap.get("etypes")
        if etypes is not None:
            fresh = self.catalog.etype_versions(etypes) == snap.get("etv")
        else:
            fresh = self.catalog.version == snap.get("version")
        if not fresh:
            _event("snapshot_raced")
            return False
        with self._lock:
            self._snaps[key] = snap
        _event("snapshot_built")
        return True

    def drop_snapshots(self) -> None:
        with self._lock:
            self._snaps.clear()

    # -- resource accounting ----------------------------------------------

    def resource_stats(self) -> Dict[str, float]:
        """Device/host footprint + generation gap for the resource
        gauges (nornicdb_index_device_bytes{family="device_graph",...},
        _rows, _mutation_gap)."""
        v = self.catalog.version
        dev = host = rows = 0
        newest = None
        with self._lock:
            snaps = list(self._snaps.values())
        for snap in snaps:
            dev += int(snap.get("device_bytes", 0))
            host += int(snap.get("host_bytes", 0))
            rows += int(snap.get("rows", 0))
            sv = snap.get("version")
            if sv is not None and (newest is None or sv > newest):
                newest = sv
        return {
            "device_bytes": dev,
            "host_bytes": host,
            "rows": rows,
            "mutation_gap": 0 if newest is None else max(0, v - newest),
        }

    # -- chain top-k (recent_messages_friends family) ---------------------

    def _chain_snapshot(self, spec: Tuple) -> Optional[Dict[str, Any]]:
        key = ("chain",) + spec
        snap = self._get_snap(key)
        if snap is not None:
            return snap if snap.get("ok") else None
        (etype1, dir1, mid_label, etype2, mid_side, order_prop,
         term_label) = spec
        cat = self.catalog
        v0 = cat.version
        # per-etype delta key (ISSUE 19): the program reads only these
        # two etypes' CSRs plus node-axis structures, and every
        # node-axis change moves the structural generation inside the
        # tuple — so writes to OTHER etypes leave this snapshot live
        etypes = (etype1, etype2)
        etv0 = cat.etype_versions(etypes)
        jax = _jx()
        jnp = jax.numpy
        try:
            sa = cat.sorted_adjacency(etype2, mid_side, order_prop,
                                      term_label)
            n = cat.n_nodes()
            tbl1 = cat.edge_table(etype1)
            indptr1, order1 = tbl1.csr(dir1, n)
            far_raw = tbl1.dst if dir1 == "out" else tbl1.src
            if sa is None or len(order1) != len(far_raw):
                # non-numeric order prop / torn build: record the
                # verdict so repeat reads don't re-probe until a write
                self._put_snap(key, {"version": v0, "etypes": etypes,
                                     "etv": etv0, "ok": False})
                return None
            if (len(sa.nbr) > self.MAX_ENTRIES
                    or len(far_raw) > self.MAX_ENTRIES
                    or len(sa.nbr) == 0 or len(far_raw) == 0
                    or np.isnan(sa.keys).any()):
                # empty structures answer trivially on the host path
                self._put_snap(key, {"version": v0, "etypes": etypes,
                                     "etv": etv0, "ok": False})
                return None
            far1 = far_raw[order1]
            # dense DESC rank with ties SHARING a rank: the device merge
            # key must order exactly like -keys under stable argsort
            uniq = np.unique(sa.keys)
            rank = (len(uniq) - 1) - np.searchsorted(uniq, sa.keys)
            if mid_label is not None:
                mid_ok = cat.label_mask(mid_label)
            else:
                mid_ok = np.ones(n, dtype=bool)
            if len(mid_ok) < n or len(indptr1) != n + 1 \
                    or len(sa.indptr) != n + 1:
                return None  # raced a node create; next read rebuilds
            snap = {
                "version": v0,
                "etypes": etypes,
                "etv": etv0,
                "ok": True,
                "n": n,
                "s": len(sa.nbr),
                "max_deg": int((indptr1[1:] - indptr1[:-1]).max())
                if n else 0,
                "indptr1": jnp.asarray(indptr1, jnp.int32),
                "far1": jnp.asarray(far1, jnp.int32),
                "s_indptr": jnp.asarray(sa.indptr, jnp.int32),
                "s_nbr": jnp.asarray(sa.nbr, jnp.int32),
                "s_rank": jnp.asarray(rank, jnp.int32),
                "mid_ok": jnp.asarray(mid_ok),
                "device_bytes": 4 * (2 * (n + 1) + 2 * len(far1)
                                     + 2 * len(sa.nbr)) + n,
                "host_bytes": rank.nbytes,
                "rows": len(sa.nbr) + len(far1),
            }
        except (IndexError, ValueError):
            return None  # torn under a concurrent write: host path
        if not self._put_snap(key, snap):
            return None
        return snap

    def chain_enter(self) -> None:
        with self._inflight_lock:
            self.inflight += 1

    def chain_exit(self) -> None:
        with self._inflight_lock:
            self.inflight -= 1

    def maybe_device(self) -> bool:
        """Allocation-free pre-gate for the per-query hot path: False
        when the device route cannot possibly engage — not forced on,
        and no coalescible demand (another chain read in flight). The
        host chain path runs ~50 us per query, so this avoids even the
        env read in the single-stream steady state (see ``_forced``)."""
        if self.inflight > 1:
            return True  # demand exists; the batcher decides the rest
        tick = self._gate_tick = (self._gate_tick + 1) & 0xFF
        if tick == 0 or self._forced is None:
            # THE amortized read the env-knob lint's hot-path rule
            # points at: refreshed every 256 calls, staleness is a
            # routing hint only (see _forced above)
            self._forced = os.environ.get(  # lint: env-ok
                "NORNICDB_GRAPH_DEVICE", "auto") == "on"
        return self._forced

    def chain_topk(
        self,
        spec: Tuple,
        anchor: int,
        k_head: int,
        size_hint: int,
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Device merge for ONE anchor of the per-friend top-k family:
        returns (friend_rows, term_rows) — globally ordered, already
        trimmed to ≤ k_head — or None for the host path. Concurrent
        calls sharing ``spec`` coalesce into one batched dispatch."""
        mode = graph_device_mode()
        if mode == "off" or k_head <= 0:
            return None
        if mode == "auto":
            if _cpu_backend() or size_hint < graph_device_min_n():
                return None
            # demand gate: a single-stream read never pays the b=1
            # dispatch; only coalescible concurrency routes on-device
            if self.inflight <= 1:
                return None
        hold = None
        if not _audit.tier_allowed(TIER_CHAIN):
            # shadow-parity quarantine: the chain rung steps down to
            # the host executor until the breach clears
            hold = "quarantine"
        elif not _audit.admission_allows(TIER_CHAIN):
            # admission posture (ISSUE 15): overload forces the chain
            # rung to the host executor to shrink device pressure
            hold = "admission"
        if hold is not None:
            _event("degrade_quarantine")
            _ledger(TIER_CHAIN, hold,
                    {"catalog_version": self.catalog.version})
            return None
        batcher = self._chain_batcher(spec)
        import time as _time

        t0 = _time.time()
        out = batcher.submit((int(anchor), int(k_head)))
        if out is not None:
            # rider-accurate attribution: this rider was answered by
            # the device chain rung (a None falls to the host path,
            # counted at the fast-path call site)
            _audit.record_served("graph", TIER_CHAIN,
                                 seconds=_time.time() - t0)
        return out

    def _chain_batcher(self, spec: Tuple) -> BatchCoalescer:
        key = ("chainb",) + spec
        with self._lock:
            b = self._batchers.get(key)
            if b is None:
                b = BatchCoalescer(
                    functools.partial(self._chain_batch, spec),
                    max_batch=64, surface="service:graph")
                self._batchers[key] = b
            return b

    def _chain_batch(self, spec: Tuple, items: List[Tuple[int, int]]):
        mode = graph_device_mode()
        none_all = [None] * len(items)
        if mode == "off":
            return none_all
        if mode == "auto" and len(items) < graph_device_min_b():
            _event("batch_below_min_b")
            _ledger(TIER_CHAIN, "min_batch")
            return none_all
        snap = self._chain_snapshot(spec)
        if snap is None:
            _event("degrade_stale")
            _ledger(TIER_CHAIN, "stale_snapshot",
                    {"catalog_version": self.catalog.version})
            return none_all
        import time as _time

        kh = max(k for _a, k in items)
        kp = pow2_bucket(kh)
        # frontier bucket: the snapshot-wide max degree, pow2-padded —
        # stable per snapshot, so batch composition can't churn compiles
        f = pow2_bucket(max(1, snap["max_deg"]))
        width = f * kp
        if snap["s"] * width >= _I32_MAX or width > 1 << 20:
            _event("degrade_rank_overflow")
            _ledger(TIER_CHAIN, "rank_overflow",
                    {"snapshot_version": snap["version"]})
            return none_all
        bsz = pow2_bucket(len(items))
        anchors = np.full(bsz, -1, dtype=np.int32)
        for i, (a, _k) in enumerate(items):
            anchors[i] = a
        jax = _jx()
        jnp = jax.numpy
        t0 = _time.perf_counter()
        try:
            fn = _chain_topk_fn(f, kp)
            sel_f, sel_t, sel_valid = fn(
                jnp.asarray(anchors), jnp.int32(kh),
                snap["indptr1"], snap["far1"], snap["s_indptr"],
                snap["s_nbr"], snap["s_rank"], snap["mid_ok"])
            sel_f = np.asarray(sel_f)
            sel_t = np.asarray(sel_t)
            sel_valid = np.asarray(sel_valid)
        except Exception:  # noqa: BLE001 — degrade, never fail the read
            _event("degrade_error")
            _ledger(TIER_CHAIN, "error",
                    {"snapshot_version": snap["version"]})
            return none_all
        dt = _time.perf_counter() - t0
        record_dispatch(KIND_CHAIN, bsz, f * 100_000 + kp, dt)
        if _cost.pricing_enabled():
            flops, byts = _cost.price_chain_topk(bsz, f, kp)
            _cost.record_query_cost(
                KIND_CHAIN, _cost.cost_name(self), len(items), flops, byts)
        self.dispatches += 1
        # freshness: a write that landed during the dispatch window
        # invalidated the snapshot under us — the host path must serve.
        # Per-etype delta key: only writes touching THIS program's
        # etypes (or the node axis) stale it; unrelated edge appends
        # during the dispatch window are fine (ISSUE 19).
        if self.catalog.etype_versions(snap["etypes"]) != snap["etv"]:
            _event("degrade_stale")
            _ledger(TIER_CHAIN, "stale_snapshot",
                    {"snapshot_etv": snap["etv"],
                     "catalog_version": self.catalog.version})
            return none_all
        out = []
        for i, (_a, k) in enumerate(items):
            nv = int(sel_valid[i].sum())
            take = min(k, nv)
            out.append((sel_f[i, :take].copy(), sel_t[i, :take].copy()))
        return out

    # -- strip aggregation (avg_friends_per_city family) ------------------

    def build_strip_view(
        self,
        etype1: str,
        g_side: str,
        p_label: Optional[str],
        etype2: str,
        dir2: str,
        f_label: Optional[str],
    ):
        """Device-built materialized strip view, installed into the
        catalog (which then serves reads and incremental maintenance
        exactly as if the host had built it). Returns the view or None
        (host builds instead). Exactness: all three arrays are integer
        counts computed as int32/f32 segment-sums with the 2^24 bound
        verified post-dispatch — equal to the host build bit-for-bit."""
        mode = graph_device_mode()
        if mode == "off" or etype1 == etype2:
            return None
        if mode == "auto" and _cpu_backend():
            return None  # host numpy wins the build on CPU (measured)
        cat = self.catalog
        key = (etype1, g_side, p_label, etype2, dir2, f_label)
        sv = cat.peek_strip_view(key)
        if sv is not None:
            return sv
        v0 = cat.version
        try:
            tbl1 = cat.edge_table(etype1)
            tbl2 = cat.edge_table(etype2)
            n = cat.n_nodes()
            e1, e2 = len(tbl1.src), len(tbl2.src)
            if mode == "auto" and (e1 + e2) < graph_device_min_n():
                return None
            if max(e1, e2, n) > self.MAX_ENTRIES or min(e1, e2) == 0:
                return None
            if e1 >= _EXACT_F32 or e2 >= _EXACT_F32:
                _event("degrade_exactness")
                return None
            g_e = tbl1.src if g_side == "src" else tbl1.dst
            p_e = tbl1.dst if g_side == "src" else tbl1.src
            keys2 = tbl2.src if dir2 == "out" else tbl2.dst
            far2 = tbl2.dst if dir2 == "out" else tbl2.src
            pmask_e = (cat.label_mask(p_label)[p_e] if p_label is not None
                       else np.ones(e1, dtype=bool))
            fmask2 = (cat.label_mask(f_label)[far2] if f_label is not None
                      else np.ones(e2, dtype=bool))
        except (IndexError, ValueError):
            return None
        import time as _time

        jax = _jx()
        jnp = jax.numpy
        e1p, e2p, npad = pow2_bucket(e1), pow2_bucket(e2), pow2_bucket(n + 2)
        # pad: sentinel rows land on npad-1 (sliced away on decode)
        g_pad = np.full(e1p, npad - 1, np.int32)
        g_pad[:e1] = g_e
        p_pad = np.full(e1p, npad - 1, np.int32)
        p_pad[:e1] = p_e
        pm_pad = np.zeros(e1p, bool)
        pm_pad[:e1] = pmask_e
        k2_pad = np.full(e2p, npad - 1, np.int32)
        k2_pad[:e2] = keys2
        fm_pad = np.zeros(e2p, bool)
        fm_pad[:e2] = fmask2
        t0 = _time.perf_counter()
        try:
            fn = _strip_agg_fn(e1p, e2p, npad)
            deg_d, sum_d, nnz_d, deg_max, sum_max = fn(
                jnp.asarray(g_pad), jnp.asarray(p_pad), jnp.asarray(pm_pad),
                jnp.asarray(k2_pad), jnp.asarray(fm_pad))
            deg_max = float(deg_max)
            sum_max = float(sum_max)
            deg = np.asarray(deg_d)[:n].astype(np.int64)
            sum_deg = np.asarray(sum_d)[:n]
            nnz = np.asarray(nnz_d)[:n].astype(np.int64)
        except Exception:  # noqa: BLE001
            _event("degrade_error")
            return None
        dt = _time.perf_counter() - t0
        record_dispatch(KIND_AGG, max(e1p, e2p), npad, dt)
        if _cost.pricing_enabled():
            flops, byts = _cost.price_graph_agg(e1p, e2p, npad)
            _cost.record_query_cost(
                KIND_AGG, _cost.cost_name(self), 1, flops, byts)
        if deg_max >= _EXACT_F32 or sum_max >= _EXACT_F32:
            _event("degrade_exactness")
            return None
        from nornicdb_tpu.query.columnar import _StripView

        sv = _StripView(deg, np.rint(sum_deg).astype(np.int64), nnz)
        if not cat.install_strip_view(key, sv, v0):
            _event("degrade_stale")
            return None
        _event("strip_view_device_built")
        return sv

    # -- co-occurrence Gram (tag_cooccurrence family) ---------------------

    def gram_matmul(
        self, ma: np.ndarray, mb: np.ndarray
    ) -> Optional[np.ndarray]:
        """Device contraction ``Ma^T @ Mb`` for the co-occurrence
        family. Caller (columnar.cooc_gram) already holds the 2^24
        exactness bound, under which f32 0/1-integer matmuls are exact
        on host AND device — equal integers, no parity caveat. Returns
        the f32 product or None (host matmul instead)."""
        mode = graph_device_mode()
        if mode == "off":
            return None
        nmid = ma.shape[0]
        if mode == "auto" and (_cpu_backend()
                               or nmid < graph_device_min_n()):
            return None
        if ma.size == 0 or mb.size == 0:
            return None
        import time as _time

        jax = _jx()
        jnp = jax.numpy
        # pad BOTH axes to pow2 (zero rows/columns cannot change the
        # live region of Ma^T @ Mb) so a growing label axis re-uses the
        # bucketed program instead of retracing per distinct width
        mp = pow2_bucket(nmid)
        ac, bc = pow2_bucket(ma.shape[1]), pow2_bucket(mb.shape[1])
        ma_p = np.zeros((mp, ac), np.float32)
        ma_p[:nmid, :ma.shape[1]] = ma
        if mb is ma and bc == ac:
            mb_p = ma_p
        else:
            mb_p = np.zeros((mp, bc), np.float32)
            mb_p[:nmid, :mb.shape[1]] = mb
        t0 = _time.perf_counter()
        try:
            c = np.asarray(_gram_fn(mp)(jnp.asarray(ma_p),
                                        jnp.asarray(mb_p)))
            c = c[:ma.shape[1], :mb.shape[1]]
        except Exception:  # noqa: BLE001
            _event("degrade_error")
            return None
        dt = _time.perf_counter() - t0
        record_dispatch(KIND_GRAM, mp,
                        pow2_bucket(max(ma.shape[1], mb.shape[1], 1)), dt)
        if _cost.pricing_enabled():
            flops, byts = _cost.price_cooc_gram(
                mp, ma.shape[1], mb.shape[1])
            _cost.record_query_cost(
                KIND_GRAM, _cost.cost_name(self), 1, flops, byts)
        return c

    # -- fused traverse-then-rank (graph+vector) --------------------------

    def _rank_snapshot(self, hops: Tuple[Tuple[str, str], ...],
                       index) -> Optional[Dict[str, Any]]:
        meta = index.view_meta()
        if meta is None:
            return None
        mutations, _compactions = meta
        key = ("rank", hops, id(index))
        snap = self._get_snap(key)
        if snap is not None:
            if snap.get("mutations") == mutations:
                return snap
            snap = None  # index moved: rebuild the row->slot join
        cat = self.catalog
        v0 = cat.version
        # per-etype delta key (ISSUE 19): the fused program touches
        # only the hop etypes' CSRs and the node axis
        etypes = tuple(et for et, _d in hops)
        etv0 = cat.etype_versions(etypes)
        jax = _jx()
        jnp = jax.numpy
        try:
            n = cat.n_nodes()
            nodes = cat.nodes()
            per_hop = []
            for etype, direction in hops:
                tbl = cat.edge_table(etype)
                indptr, order = tbl.csr(direction, n)
                far = (tbl.dst if direction == "out" else tbl.src)[order]
                if len(far) > self.MAX_ENTRIES or len(indptr) != n + 1:
                    return None
                per_hop.append((indptr, far))
            slots = index.slots_of([nd.id for nd in nodes],
                                   expect_mutations=mutations)
            if slots is None:
                return None
        except (IndexError, ValueError):
            return None
        snap = {
            "version": v0,
            "etypes": etypes,
            "etv": etv0,
            "mutations": mutations,
            "n": n,
            "hops": [
                (jnp.asarray(ip, jnp.int32), jnp.asarray(fr, jnp.int32),
                 int((ip[1:] - ip[:-1]).max()) if n else 0)
                for ip, fr in per_hop
            ],
            "slot_of_row": jnp.asarray(
                np.asarray(slots, dtype=np.int32)),
            "device_bytes": 4 * sum(len(ip) + len(fr)
                                    for ip, fr in per_hop) + 4 * n,
            "host_bytes": 0,
            "rows": sum(len(fr) for _ip, fr in per_hop),
        }
        if not self._put_snap(key, snap):
            return None
        return snap

    def traverse_rank(
        self,
        anchors: Sequence[int],
        hops: Sequence[Tuple[str, str]],
        queries: np.ndarray,
        k: int,
        index,
    ) -> Optional[List[List[Tuple[int, float]]]]:
        """ONE fused program: chain expansion from ``anchors`` along
        ``hops`` (1 or 2 (etype, direction) stages), frontier dedup,
        cosine scoring against the vector index's device matrix, top-k.
        Returns per-anchor [(catalog_node_row, score)] or None (host
        fallback). The workload no single baseline serves: graph
        traversal and vector ranking in one dispatch."""
        mode = graph_device_mode()
        if mode == "off" or not hops or len(hops) > 2 or k <= 0:
            return None
        if mode == "auto" and _cpu_backend() \
                and len(anchors) < graph_device_min_b():
            # measured on CPU: the fused dispatch beats the host
            # fallback ~2x at b=16 but loses ~4x at b=1
            return None
        hold = None
        if not _audit.tier_allowed(TIER_RANK):
            hold = "quarantine"
        elif not _audit.admission_allows(TIER_RANK):
            hold = "admission"
        if hold is not None:
            _event("degrade_quarantine")
            _ledger(TIER_RANK, hold,
                    {"catalog_version": self.catalog.version})
            return None
        hops_t = tuple((str(e), str(d)) for e, d in hops)
        snap = self._rank_snapshot(hops_t, index)
        if snap is None:
            _event("degrade_stale")
            _ledger(TIER_RANK, "stale_snapshot",
                    {"catalog_version": self.catalog.version})
            return None
        dv = index.device_view()
        if dv is None:
            return None
        matrix, valid, _ext_ids, mutations, _comp = dv
        if mutations != snap["mutations"]:
            _event("degrade_stale")
            _ledger(TIER_RANK, "stale_snapshot",
                    {"snapshot_mutations": snap["mutations"],
                     "index_mutations": mutations})
            return None
        import time as _time

        jax = _jx()
        jnp = jax.numpy
        f1 = pow2_bucket(max(1, snap["hops"][0][2]))
        f2 = pow2_bucket(max(1, snap["hops"][1][2])) if len(hops_t) == 2 \
            else 0
        frontier = f1 * max(f2, 1)
        if frontier > 1 << 18:
            _event("degrade_rank_overflow")
            _ledger(TIER_RANK, "rank_overflow",
                    {"snapshot_version": snap["version"]})
            return None
        kp = pow2_bucket(min(k, max(frontier, 1)))
        bsz = pow2_bucket(len(anchors))
        a = np.full(bsz, -1, dtype=np.int32)
        a[:len(anchors)] = np.asarray(anchors, dtype=np.int32)
        q = np.zeros((bsz, queries.shape[1]), np.float32)
        q[:len(anchors)] = queries
        ip1, fr1, _d1 = snap["hops"][0]
        if f2:
            ip2, fr2, _d2 = snap["hops"][1]
        else:
            ip2, fr2 = ip1, fr1  # unused when f2 == 0
        t0 = _time.perf_counter()
        try:
            vals, sel_rows = _traverse_rank_fn(f1, f2, kp)(
                jnp.asarray(a), jnp.asarray(q), ip1, fr1, ip2, fr2,
                snap["slot_of_row"], matrix, valid,
                jnp.int32(snap["n"]))
            vals = np.asarray(vals)
            sel_rows = np.asarray(sel_rows)
        except Exception:  # noqa: BLE001
            _event("degrade_error")
            _ledger(TIER_RANK, "error",
                    {"snapshot_version": snap["version"]})
            return None
        dt = _time.perf_counter() - t0
        record_dispatch(KIND_RANK, bsz, f1 * 100_000 + kp, dt)
        if _cost.pricing_enabled():
            flops, byts = _cost.price_traverse_rank(
                bsz, frontier, int(matrix.shape[1]), kp)
            _cost.record_query_cost(
                KIND_RANK, _cost.cost_name(self), len(anchors), flops,
                byts)
        self.dispatches += 1
        # per-etype recheck (ISSUE 19): only hop-etype writes or
        # node-axis changes during the dispatch window stale this
        if self.catalog.etype_versions(snap["etypes"]) != snap["etv"] \
                or index.view_meta() != (snap["mutations"], _comp):
            _event("degrade_stale")
            _ledger(TIER_RANK, "stale_snapshot",
                    {"snapshot_etv": snap["etv"],
                     "catalog_version": self.catalog.version})
            return None
        out: List[List[Tuple[int, float]]] = []
        for i in range(len(anchors)):
            hits = [(int(r), float(v))
                    for v, r in zip(vals[i], sel_rows[i])
                    if np.isfinite(v)][:k]
            out.append(hits)
        return out

    # -- shared whole-graph CSR snapshot (PageRank / degree counts) -------

    def pagerank_snapshot(self) -> Optional[Dict[str, Any]]:
        """The whole-graph columnar edge snapshot — built EXACTLY like
        ``ops.graph.graph_snapshot`` (same storage iteration order, so
        PageRank stays bit-identical to the uncached implementation) —
        cached per catalog version together with its one-time device
        transfer. Repeat ``apoc.algo.pagerank`` calls stop re-listing
        the store and re-shipping edge arrays per call."""
        key = ("pagerank",)
        snap = self._get_snap(key)
        if snap is not None:
            return snap
        from nornicdb_tpu.ops.graph import graph_snapshot

        cat = self.catalog
        v0 = cat.version
        try:
            src, dst, ids = graph_snapshot(cat.storage)
        except Exception:  # noqa: BLE001 — engines without iteration
            return None
        if len(src) > self.MAX_ENTRIES:
            return None
        jnp = _jx().numpy
        snap = {
            "version": v0,
            "src": src,
            "dst": dst,
            "ids": ids,
            "dev_src": jnp.asarray(src, jnp.int32),
            "dev_dst": jnp.asarray(dst, jnp.int32),
            "device_bytes": 8 * len(src),
            "host_bytes": src.nbytes + dst.nbytes,
            "rows": len(src),
        }
        if not self._put_snap(key, snap):
            return None
        return snap

    def degree_counts(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(out_degree, in_degree) over the shared snapshot — one fused
        device pass, edge arrays shipped once per catalog version."""
        snap = self.pagerank_snapshot()
        if snap is None:
            return None
        from nornicdb_tpu.ops.graph import degree_counts

        out_d, in_d = degree_counts(
            snap["dev_src"], snap["dev_dst"], len(snap["ids"]))
        return np.asarray(out_d), np.asarray(in_d)

    def traverse_rank_host(
        self,
        anchors: Sequence[int],
        hops: Sequence[Tuple[str, str]],
        queries: np.ndarray,
        k: int,
        index,
    ) -> List[List[Tuple[int, float]]]:
        """Host reference/fallback with the same contract: expand,
        dedup (ascending row order), score exactly, stable top-k."""
        from nornicdb_tpu.query.columnar import expand_hop

        cat = self.catalog
        n = cat.n_nodes()
        nodes = cat.nodes()
        out: List[List[Tuple[int, float]]] = []
        for i, anchor in enumerate(anchors):
            frontier = np.asarray([anchor], dtype=np.int32)
            for etype, direction in hops:
                tbl = cat.edge_table(etype)
                _rep, _erows, frontier = expand_hop(
                    tbl, frontier, direction, n)
            rows = np.unique(frontier)
            if len(rows) == 0:
                out.append([])
                continue
            ids = [nodes[int(r)].id for r in rows]
            vecs = []
            keep_rows = []
            for r, eid in zip(rows.tolist(), ids):
                v = index.get(eid)
                if v is not None:
                    vecs.append(v)
                    keep_rows.append(r)
            if not vecs:
                out.append([])
                continue
            m = np.stack(vecs).astype(np.float32)
            qv = queries[i].astype(np.float32)
            qn = qv / max(float(np.linalg.norm(qv)), 1e-12)
            scores = m @ qn
            order = np.argsort(-scores, kind="stable")[:k]
            out.append([(int(keep_rows[j]), float(scores[j]))
                        for j in order])
        return out
