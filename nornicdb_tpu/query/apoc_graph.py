"""APOC graph-level long tail: node, rel, label, nodes, neighbors,
spatial, meta, search.

Reference: apoc/node, apoc/rel, apoc/label, apoc/nodes, apoc/neighbors,
apoc/spatial, apoc/meta, apoc/search (apoc.go:222 registerAllFunctions).
Pure entity accessors register in the plain APOC table; anything that
reads the graph registers in the ctx table (``register_ctx``) and
receives the executor query context so it can reach ``ctx.storage``.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, List, Optional, Set

from nornicdb_tpu.errors import CypherRuntimeError
from nornicdb_tpu.query.apoc import register, register_ctx
from nornicdb_tpu.storage.types import Direction, Edge, Node


def _node(x, what: str) -> Node:
    if not isinstance(x, Node):
        raise CypherRuntimeError(f"{what} expects a node, got "
                                 f"{type(x).__name__}")
    return x


def _rel(x, what: str) -> Edge:
    if not isinstance(x, Edge):
        raise CypherRuntimeError(f"{what} expects a relationship, got "
                                 f"{type(x).__name__}")
    return x


def _rel_matches(e: Edge, spec: Optional[str]) -> bool:
    """APOC relationship spec: 'TYPE', 'TYPE>', '<TYPE', 'A|B', '' = any."""
    if not spec:
        return True
    for part in str(spec).split("|"):
        part = part.strip()
        if part.endswith(">"):
            part = part[:-1]
        if part.startswith("<"):
            part = part[1:]
        if not part or part == e.type:
            return True
    return False


def _spec_direction(spec: Optional[str]) -> str:
    s = str(spec or "")
    if s.endswith(">"):
        return Direction.OUTGOING
    if s.startswith("<"):
        return Direction.INCOMING
    return Direction.BOTH


def _node_rels(ctx, node: Node, spec: Optional[str] = None) -> List[Edge]:
    direction = _spec_direction(spec)
    out = []
    for e in ctx.storage.get_node_edges(node.id, direction=direction):
        if _rel_matches(e, spec):
            out.append(e)
    return out


def _get_node(ctx, node_id: str) -> Optional[Node]:
    from nornicdb_tpu.errors import NotFoundError
    try:
        return ctx.storage.get_node(node_id)
    except NotFoundError:
        return None


def _install_node_rel() -> None:
    n = "apoc.node."
    register(n + "id", lambda x: _node(x, "apoc.node.id").id)
    register(n + "toMap", lambda x: {
        "id": _node(x, "apoc.node.toMap").id,
        "labels": list(x.labels), "properties": dict(x.properties)})
    register(n + "properties",
             lambda x: dict(_node(x, "apoc.node.properties").properties))
    register(n + "property", lambda x, key: _node(
        x, "apoc.node.property").properties.get(key))
    register(n + "hasLabel",
             lambda x, lb: lb in _node(x, "apoc.node.hasLabel").labels)
    register(n + "hasLabels", lambda x, lbs: all(
        lb in _node(x, "apoc.node.hasLabels").labels for lb in (lbs or [])))
    register(n + "equals", lambda a, b: (
        isinstance(a, Node) and isinstance(b, Node) and a.id == b.id))
    register(n + "diff", lambda a, b: _props_diff(
        _node(a, "apoc.node.diff").properties,
        _node(b, "apoc.node.diff").properties))

    register_ctx(n + "degree", lambda ctx, x, spec=None: len(
        _node_rels(ctx, _node(x, "apoc.node.degree"), spec)))
    register_ctx(n + "degreeIn", lambda ctx, x, etype=None: sum(
        1 for e in ctx.storage.get_node_edges(
            _node(x, "apoc.node.degreeIn").id, direction=Direction.INCOMING)
        if etype is None or e.type == etype))
    register_ctx(n + "degreeOut", lambda ctx, x, etype=None: sum(
        1 for e in ctx.storage.get_node_edges(
            _node(x, "apoc.node.degreeOut").id, direction=Direction.OUTGOING)
        if etype is None or e.type == etype))
    register_ctx(n + "isDense", lambda ctx, x, threshold=50: len(
        ctx.storage.get_node_edges(_node(x, "apoc.node.isDense").id))
        >= int(threshold))
    register_ctx(n + "relationships", lambda ctx, x, spec=None: _node_rels(
        ctx, _node(x, "apoc.node.relationships"), spec))
    register_ctx(n + "relationshipsIn", lambda ctx, x, etype=None: [
        e for e in ctx.storage.get_node_edges(
            _node(x, "apoc.node.relationshipsIn").id,
            direction=Direction.INCOMING)
        if etype is None or e.type == etype])
    register_ctx(n + "relationshipsOut", lambda ctx, x, etype=None: [
        e for e in ctx.storage.get_node_edges(
            _node(x, "apoc.node.relationshipsOut").id,
            direction=Direction.OUTGOING)
        if etype is None or e.type == etype])
    register_ctx(n + "relationshipExists", lambda ctx, x, spec=None: any(
        True for _ in _node_rels(
            ctx, _node(x, "apoc.node.relationshipExists"), spec)))
    register_ctx(n + "relationshipTypes", lambda ctx, x, spec=None: sorted(
        {e.type for e in _node_rels(
            ctx, _node(x, "apoc.node.relationshipTypes"), spec)}))
    register_ctx(n + "relationshipTypesIn", lambda ctx, x: sorted(
        {e.type for e in ctx.storage.get_node_edges(
            _node(x, "apoc.node.relationshipTypesIn").id,
            direction=Direction.INCOMING)}))
    register_ctx(n + "relationshipTypesOut", lambda ctx, x: sorted(
        {e.type for e in ctx.storage.get_node_edges(
            _node(x, "apoc.node.relationshipTypesOut").id,
            direction=Direction.OUTGOING)}))

    def _connected(ctx, a, b, spec=None):
        a = _node(a, "apoc.node.connected")
        b = _node(b, "apoc.node.connected")
        return any(e.start_node == b.id or e.end_node == b.id
                   for e in _node_rels(ctx, a, spec))

    register_ctx(n + "connected", _connected)
    register_ctx(n + "neighbors", lambda ctx, x, spec=None: _neighbor_nodes(
        ctx, _node(x, "apoc.node.neighbors"), spec))
    def _neighbors_one_way(ctx, x, direction):
        node = _node(x, "apoc.node.neighbors")
        seen: Set[str] = set()
        out = []
        for e in ctx.storage.get_node_edges(node.id, direction=direction):
            other_id = (e.start_node if direction == Direction.INCOMING
                        else e.end_node)
            if other_id in seen:
                continue
            seen.add(other_id)
            other = _get_node(ctx, other_id)
            if other is not None:
                out.append(other)
        return out

    register_ctx(n + "neighborsIn", lambda ctx, x: _neighbors_one_way(
        ctx, x, Direction.INCOMING))
    register_ctx(n + "neighborsOut", lambda ctx, x: _neighbors_one_way(
        ctx, x, Direction.OUTGOING))

    r = "apoc.rel."
    register(r + "id", lambda x: _rel(x, "apoc.rel.id").id)
    register(r + "properties",
             lambda x: dict(_rel(x, "apoc.rel.properties").properties))
    register(r + "property", lambda x, key: _rel(
        x, "apoc.rel.property").properties.get(key))
    register(r + "hasProperty", lambda x, key: key in _rel(
        x, "apoc.rel.hasProperty").properties)
    register(r + "hasProperties", lambda x, keys: all(
        k in _rel(x, "apoc.rel.hasProperties").properties
        for k in (keys or [])))
    register(r + "isType", lambda x, t: _rel(
        x, "apoc.rel.isType").type == t)
    register(r + "isAnyType", lambda x, types: _rel(
        x, "apoc.rel.isAnyType").type in (types or []))
    register(r + "isLoop", lambda x: (
        _rel(x, "apoc.rel.isLoop").start_node == x.end_node))
    register(r + "equals", lambda a, b: (
        isinstance(a, Edge) and isinstance(b, Edge) and a.id == b.id))
    register(r + "compare", lambda a, b: _props_diff(
        _rel(a, "apoc.rel.compare").properties,
        _rel(b, "apoc.rel.compare").properties))
    register(r + "toMap", lambda x: {
        "id": _rel(x, "apoc.rel.toMap").id, "type": x.type,
        "start": x.start_node, "end": x.end_node,
        "properties": dict(x.properties)})
    register(r + "weight", lambda x, prop="weight", default=1.0: (
        v if isinstance(v := _rel(x, "apoc.rel.weight").properties.get(
            prop, default), (int, float)) else default))
    register(r + "isBetween", lambda x, a, b: (
        {_rel(x, "apoc.rel.isBetween").start_node, x.end_node}
        == {_node(a, "apoc.rel.isBetween").id,
            _node(b, "apoc.rel.isBetween").id}))
    register(r + "isDirectedBetween", lambda x, a, b: (
        _rel(x, "apoc.rel.isDirectedBetween").start_node
        == _node(a, "apoc.rel.isDirectedBetween").id
        and x.end_node == _node(b, "apoc.rel.isDirectedBetween").id))
    register(r + "direction", lambda x, from_node: (
        "OUTGOING" if _rel(x, "apoc.rel.direction").start_node
        == _node(from_node, "apoc.rel.direction").id else "INCOMING"))
    register(r + "reverse", lambda x: {
        "id": _rel(x, "apoc.rel.reverse").id, "type": x.type,
        "start": x.end_node, "end": x.start_node,
        "properties": dict(x.properties)})

    register_ctx(r + "startNode", lambda ctx, x: _get_node(
        ctx, _rel(x, "apoc.rel.startNode").start_node))
    register_ctx(r + "endNode", lambda ctx, x: _get_node(
        ctx, _rel(x, "apoc.rel.endNode").end_node))
    register_ctx(r + "nodes", lambda ctx, x: [
        _get_node(ctx, _rel(x, "apoc.rel.nodes").start_node),
        _get_node(ctx, x.end_node)])
    register_ctx(r + "otherNode", lambda ctx, x, node: _get_node(
        ctx, _rel(x, "apoc.rel.otherNode").end_node
        if x.start_node == _node(node, "apoc.rel.otherNode").id
        else x.start_node))
    register_ctx(r + "exists", lambda ctx, x: (
        isinstance(x, Edge) and ctx.storage.has_edge(x.id)))


def _neighbor_nodes(ctx, node: Node, spec=None) -> List[Node]:
    seen: Set[str] = set()
    out: List[Node] = []
    for e in _node_rels(ctx, node, spec):
        other_id = e.end_node if e.start_node == node.id else e.start_node
        if other_id in seen:
            continue
        seen.add(other_id)
        other = _get_node(ctx, other_id)
        if other is not None:
            out.append(other)
    return out


def _props_diff(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "leftOnly": {k: v for k, v in a.items() if k not in b},
        "rightOnly": {k: v for k, v in b.items() if k not in a},
        "different": {k: {"left": a[k], "right": b[k]}
                      for k in a.keys() & b.keys() if a[k] != b[k]},
        "inCommon": {k: a[k] for k in a.keys() & b.keys() if a[k] == b[k]},
    }


def _install_label() -> None:
    lb = "apoc.label."
    register(lb + "get", lambda x: list(_node(x, "apoc.label.get").labels))
    register(lb + "has", lambda x, l: l in _node(
        x, "apoc.label.has").labels)
    register(lb + "hasAll", lambda x, ls: all(
        l in _node(x, "apoc.label.hasAll").labels for l in (ls or [])))
    register(lb + "hasAny", lambda x, ls: any(
        l in _node(x, "apoc.label.hasAny").labels for l in (ls or [])))
    register(lb + "compare", lambda a, b: sorted(
        _node(a, "apoc.label.compare").labels)
        == sorted(_node(b, "apoc.label.compare").labels))
    register(lb + "diff", lambda a, b: sorted(
        set(_node(a, "apoc.label.diff").labels)
        - set(_node(b, "apoc.label.diff").labels)))
    register(lb + "intersection", lambda a, b: sorted(
        set(_node(a, "apoc.label.intersection").labels)
        & set(_node(b, "apoc.label.intersection").labels)))
    register(lb + "union", lambda a, b: sorted(
        set(_node(a, "apoc.label.union").labels)
        | set(_node(b, "apoc.label.union").labels)))
    register(lb + "format", lambda x: "".join(
        f":{l}" for l in _node(x, "apoc.label.format").labels))
    register(lb + "toString", lambda x: ":".join(
        _node(x, "apoc.label.toString").labels))
    register(lb + "fromString", lambda s: [
        p for p in str(s or "").split(":") if p])
    register(lb + "fromPattern", lambda s: re.findall(
        r":\s*([A-Za-z_][A-Za-z0-9_]*)", str(s or "")))
    register(lb + "pattern", lambda labels: "".join(
        f":{l}" for l in (labels or [])))
    register(lb + "normalize", lambda s: "".join(
        w.capitalize() for w in re.split(r"[\s_\-]+", str(s or ""))))
    register(lb + "validate", lambda s: bool(re.fullmatch(
        r"[A-Za-z_][A-Za-z0-9_]*", str(s or ""))))

    # NOTE: apoc.label.exists(node, label) already exists in the plain
    # table (apoc.py) — do not shadow it with a ctx variant; the
    # label-presence-in-graph check is apoc.label.count(l) > 0
    register_ctx(lb + "count", lambda ctx, l: len(
        ctx.storage.get_nodes_by_label(l)))
    register_ctx(lb + "nodes", lambda ctx, l: list(
        ctx.storage.get_nodes_by_label(l)))
    register_ctx(lb + "list", lambda ctx: sorted(
        {l for node in ctx.storage.all_nodes() for l in node.labels}))
    register_ctx(lb + "stats", lambda ctx: {
        l: len(ctx.storage.get_nodes_by_label(l))
        for l in sorted({l for node in ctx.storage.all_nodes()
                         for l in node.labels})})
    register_ctx(lb + "search", lambda ctx, pattern: [
        l for l in sorted({l for node in ctx.storage.all_nodes()
                           for l in node.labels})
        if re.search(str(pattern), l)])


def _install_nodes() -> None:
    ns = "apoc.nodes."
    register(ns + "toMap", lambda lst: [
        {"id": x.id, "labels": list(x.labels),
         "properties": dict(x.properties)}
        for x in (lst or []) if isinstance(x, Node)])
    register(ns + "map", lambda lst, key: [
        _node(x, "apoc.nodes.map").properties.get(key)
        for x in (lst or [])])
    register(ns + "filter", lambda lst, key, value: [
        x for x in (lst or [])
        if isinstance(x, Node) and x.properties.get(key) == value])
    register(ns + "sort", lambda lst, key: sorted(
        [x for x in (lst or []) if isinstance(x, Node)],
        key=lambda x: (x.properties.get(key) is None,
                       x.properties.get(key))))
    register(ns + "distinct", lambda lst: list(
        {x.id: x for x in (lst or []) if isinstance(x, Node)}.values()))
    register(ns + "union", lambda a, b: list({
        x.id: x for x in list(a or []) + list(b or [])
        if isinstance(x, Node)}.values()))
    register(ns + "intersect", lambda a, b: [
        x for x in (a or []) if isinstance(x, Node)
        and x.id in {y.id for y in (b or []) if isinstance(y, Node)}])
    register(ns + "difference", lambda a, b: [
        x for x in (a or []) if isinstance(x, Node)
        and x.id not in {y.id for y in (b or []) if isinstance(y, Node)}])
    register(ns + "partition", lambda lst, size: [
        list((lst or [])[i:i + int(size)])
        for i in range(0, len(lst or []), max(int(size), 1))])
    register(ns + "group", lambda lst, key: _group_nodes(lst, key))
    register(ns + "reduce", lambda lst, key: sum(
        v for x in (lst or []) if isinstance(x, Node)
        and isinstance(v := x.properties.get(key), (int, float))
        and not isinstance(v, bool)))

    def _group_nodes(lst, key):
        out: Dict[Any, List[Node]] = {}
        for x in lst or []:
            if isinstance(x, Node):
                out.setdefault(x.properties.get(key), []).append(x)
        return [{"value": k, "nodes": v} for k, v in out.items()]

    register_ctx(ns + "get", lambda ctx, ids: [
        node for i in (ids or [])
        if (node := _get_node(ctx, str(i))) is not None])
    register_ctx(ns + "isDense", lambda ctx, lst, threshold=50: [
        {"node": x, "dense": len(ctx.storage.get_node_edges(x.id))
         >= int(threshold)}
        for x in (lst or []) if isinstance(x, Node)])
    register_ctx(ns + "connected", lambda ctx, a, b: _nodes_connected(
        ctx, a, b))
    register_ctx(ns + "relationships", lambda ctx, lst: _rels_between(
        ctx, lst))
    register_ctx(ns + "distinctRels", lambda ctx, lst: sorted(
        {e.type for e in _rels_between(ctx, lst)}))
    register_ctx(ns + "cycles", lambda ctx, lst, spec=None: _find_cycles(
        ctx, lst, spec))


def _nodes_connected(ctx, a, b) -> bool:
    a = _node(a, "apoc.nodes.connected")
    b = _node(b, "apoc.nodes.connected")
    return any(e.start_node == b.id or e.end_node == b.id
               for e in ctx.storage.get_node_edges(a.id))


def _rels_between(ctx, lst) -> List[Edge]:
    ids = {x.id for x in (lst or []) if isinstance(x, Node)}
    seen: Set[str] = set()
    out: List[Edge] = []
    for nid in ids:
        for e in ctx.storage.get_node_edges(nid):
            if e.id in seen:
                continue
            if e.start_node in ids and e.end_node in ids:
                seen.add(e.id)
                out.append(e)
    return out


def _find_cycles(ctx, lst, spec=None) -> List[List[str]]:
    """Simple directed cycles within the given node set (bounded DFS)."""
    ids = {x.id for x in (lst or []) if isinstance(x, Node)}
    cycles: List[List[str]] = []
    for start in sorted(ids):
        stack = [(start, [start])]
        while stack:
            cur, path = stack.pop()
            if len(path) > 10:
                continue
            for e in ctx.storage.get_node_edges(
                    cur, direction=Direction.OUTGOING):
                if not _rel_matches(e, spec) or e.end_node not in ids:
                    continue
                if e.end_node == start and len(path) > 1:
                    if min(path) == start:  # canonical start: dedupe
                        cycles.append(path + [start])
                elif e.end_node not in path:
                    stack.append((e.end_node, path + [e.end_node]))
    return cycles


def _install_neighbors() -> None:
    nb = "apoc.neighbors."

    def _hop_sets(ctx, node, spec, max_hops) -> List[Set[str]]:
        """Frontier node-id set per hop, 1..max_hops."""
        node = _node(node, "apoc.neighbors")
        visited = {node.id}
        frontier = {node.id}
        levels: List[Set[str]] = []
        for _ in range(int(max_hops)):
            nxt: Set[str] = set()
            for nid in frontier:
                for e in ctx.storage.get_node_edges(
                        nid, direction=_spec_direction(spec)):
                    if not _rel_matches(e, spec):
                        continue
                    other = e.end_node if e.start_node == nid else e.start_node
                    if other not in visited:
                        nxt.add(other)
            visited |= nxt
            levels.append(nxt)
            frontier = nxt
            if not nxt:
                break
        return levels

    def _ids_to_nodes(ctx, ids) -> List[Node]:
        return [node for i in sorted(ids)
                if (node := _get_node(ctx, i)) is not None]

    def _at_hop(ctx, x, spec=None, hop=1):
        levels = _hop_sets(ctx, x, spec, hop)
        if len(levels) < int(hop):
            return []
        return _ids_to_nodes(ctx, levels[int(hop) - 1])

    register_ctx(nb + "atHop", _at_hop)
    def _to_hop(ctx, x, spec=None, hop=1):
        levels = _hop_sets(ctx, x, spec, hop)
        return _ids_to_nodes(ctx, set().union(set(), *levels))

    register_ctx(nb + "toHop", _to_hop)
    register_ctx(nb + "count", lambda ctx, x, spec=None, hop=1: sum(
        len(s) for s in _hop_sets(ctx, x, spec, hop)))
    register_ctx(nb + "exists", lambda ctx, x, spec=None, hop=1: any(
        s for s in _hop_sets(ctx, x, spec, hop)))
    register_ctx(nb + "bfs", lambda ctx, x, spec=None, hop=3: _to_hop(
        ctx, x, spec, hop))

    def _dfs(ctx, x, spec=None, max_depth=3):
        node = _node(x, "apoc.neighbors.dfs")
        visited: List[str] = []
        seen = {node.id}
        stack = [(node.id, 0)]
        while stack:
            cur, depth = stack.pop()
            if depth >= int(max_depth):
                continue
            for e in reversed(ctx.storage.get_node_edges(
                    cur, direction=_spec_direction(spec))):
                if not _rel_matches(e, spec):
                    continue
                other = e.end_node if e.start_node == cur else e.start_node
                if other not in seen:
                    seen.add(other)
                    visited.append(other)
                    stack.append((other, depth + 1))
        return [n for i in visited
                if (n := _get_node(ctx, i)) is not None]

    register_ctx(nb + "dfs", _dfs)


_EARTH_R = 6_371_000.0  # meters


def _install_spatial() -> None:
    from nornicdb_tpu.query import temporal_types as T

    sp = "apoc.spatial."

    def _latlon(p) -> tuple:
        if isinstance(p, T.CypherPoint):
            if p.latitude is None:
                return (p.y, p.x)
            return (p.latitude, p.longitude)
        if isinstance(p, dict):
            low = {k.lower(): v for k, v in p.items()}
            if "latitude" in low:
                return (float(low["latitude"]), float(low["longitude"]))
            if "lat" in low:
                return (float(low["lat"]),
                        float(low.get("lon", low.get("lng", 0.0))))
            if "y" in low:
                return (float(low["y"]), float(low["x"]))
        raise CypherRuntimeError("expected a point or lat/lon map")

    def _haversine(a, b):
        la1, lo1 = _latlon(a)
        la2, lo2 = _latlon(b)
        p1, p2 = math.radians(la1), math.radians(la2)
        dp = math.radians(la2 - la1)
        dl = math.radians(lo2 - lo1)
        h = (math.sin(dp / 2) ** 2
             + math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2)
        return 2 * _EARTH_R * math.asin(math.sqrt(h))

    register(sp + "haversineDistance", _haversine)
    register(sp + "distance", _haversine)

    def _vincenty(a, b):
        """Vincenty inverse on the WGS-84 ellipsoid."""
        la1, lo1 = _latlon(a)
        la2, lo2 = _latlon(b)
        a_ax, f = 6378137.0, 1 / 298.257223563
        b_ax = (1 - f) * a_ax
        u1 = math.atan((1 - f) * math.tan(math.radians(la1)))
        u2 = math.atan((1 - f) * math.tan(math.radians(la2)))
        ll = math.radians(lo2 - lo1)
        lam = ll
        for _ in range(100):
            sin_s = math.sqrt(
                (math.cos(u2) * math.sin(lam)) ** 2
                + (math.cos(u1) * math.sin(u2)
                   - math.sin(u1) * math.cos(u2) * math.cos(lam)) ** 2)
            if sin_s == 0:
                return 0.0
            cos_s = (math.sin(u1) * math.sin(u2)
                     + math.cos(u1) * math.cos(u2) * math.cos(lam))
            sig = math.atan2(sin_s, cos_s)
            sin_a = math.cos(u1) * math.cos(u2) * math.sin(lam) / sin_s
            cos2a = 1 - sin_a ** 2
            cos2sm = (cos_s - 2 * math.sin(u1) * math.sin(u2) / cos2a
                      if cos2a else 0.0)
            c = f / 16 * cos2a * (4 + f * (4 - 3 * cos2a))
            lam_prev = lam
            lam = (ll + (1 - c) * f * sin_a
                   * (sig + c * sin_s
                      * (cos2sm + c * cos_s * (-1 + 2 * cos2sm ** 2))))
            if abs(lam - lam_prev) < 1e-12:
                break
        usq = cos2a * (a_ax ** 2 - b_ax ** 2) / b_ax ** 2
        big_a = 1 + usq / 16384 * (4096 + usq * (-768 + usq * (320 - 175 * usq)))
        big_b = usq / 1024 * (256 + usq * (-128 + usq * (74 - 47 * usq)))
        dsig = (big_b * sin_s
                * (cos2sm + big_b / 4
                   * (cos_s * (-1 + 2 * cos2sm ** 2)
                      - big_b / 6 * cos2sm * (-3 + 4 * sin_s ** 2)
                      * (-3 + 4 * cos2sm ** 2))))
        return b_ax * big_a * (sig - dsig)

    register(sp + "vincentyDistance", _vincenty)

    def _bearing(a, b):
        la1, lo1 = _latlon(a)
        la2, lo2 = _latlon(b)
        p1, p2 = math.radians(la1), math.radians(la2)
        dl = math.radians(lo2 - lo1)
        y = math.sin(dl) * math.cos(p2)
        x = (math.cos(p1) * math.sin(p2)
             - math.sin(p1) * math.cos(p2) * math.cos(dl))
        return (math.degrees(math.atan2(y, x)) + 360) % 360

    register(sp + "bearing", _bearing)

    def _destination(p, bearing, dist_m):
        la, lo = _latlon(p)
        p1 = math.radians(la)
        l1 = math.radians(lo)
        br = math.radians(float(bearing))
        dr = float(dist_m) / _EARTH_R
        p2 = math.asin(math.sin(p1) * math.cos(dr)
                       + math.cos(p1) * math.sin(dr) * math.cos(br))
        l2 = l1 + math.atan2(
            math.sin(br) * math.sin(dr) * math.cos(p1),
            math.cos(dr) - math.sin(p1) * math.sin(p2))
        return {"latitude": math.degrees(p2),
                "longitude": (math.degrees(l2) + 540) % 360 - 180}

    register(sp + "destination", _destination)
    register(sp + "midpoint", lambda a, b: _destination(
        a, _bearing(a, b), _haversine(a, b) / 2.0))

    def _centroid(points):
        lls = [_latlon(p) for p in (points or [])]
        if not lls:
            return None
        return {"latitude": sum(x[0] for x in lls) / len(lls),
                "longitude": sum(x[1] for x in lls) / len(lls)}

    register(sp + "centroid", _centroid)

    def _bbox(points):
        lls = [_latlon(p) for p in (points or [])]
        if not lls:
            return None
        return {"minLatitude": min(x[0] for x in lls),
                "maxLatitude": max(x[0] for x in lls),
                "minLongitude": min(x[1] for x in lls),
                "maxLongitude": max(x[1] for x in lls)}

    register(sp + "boundingBox", _bbox)

    def _within(p, bbox):
        la, lo = _latlon(p)
        return (bbox["minLatitude"] <= la <= bbox["maxLatitude"]
                and bbox["minLongitude"] <= lo <= bbox["maxLongitude"])

    register(sp + "within", _within)
    register(sp + "withinDistance", lambda p, center, m: (
        _haversine(p, center) <= float(m)))

    def _area(points):
        """Planar shoelace area of a lat/lon polygon, in m^2 (small
        polygons; equirectangular projection about the centroid)."""
        lls = [_latlon(p) for p in (points or [])]
        if len(lls) < 3:
            return 0.0
        lat0 = sum(x[0] for x in lls) / len(lls)
        scale = math.cos(math.radians(lat0))
        xy = [(math.radians(lo) * _EARTH_R * scale,
               math.radians(la) * _EARTH_R) for la, lo in lls]
        s = 0.0
        for i in range(len(xy)):
            x1, y1 = xy[i]
            x2, y2 = xy[(i + 1) % len(xy)]
            s += x1 * y2 - x2 * y1
        return abs(s) / 2.0

    register(sp + "area", _area)

    _B32 = "0123456789bcdefghjkmnpqrstuvwxyz"

    def _encode_geohash(p, precision=9):
        la, lo = _latlon(p)
        lat_r, lon_r = [-90.0, 90.0], [-180.0, 180.0]
        bits = []
        even = True
        while len(bits) < int(precision) * 5:
            if even:
                mid = (lon_r[0] + lon_r[1]) / 2
                if lo >= mid:
                    bits.append(1)
                    lon_r[0] = mid
                else:
                    bits.append(0)
                    lon_r[1] = mid
            else:
                mid = (lat_r[0] + lat_r[1]) / 2
                if la >= mid:
                    bits.append(1)
                    lat_r[0] = mid
                else:
                    bits.append(0)
                    lat_r[1] = mid
            even = not even
        out = ""
        for i in range(0, len(bits), 5):
            out += _B32[int("".join(map(str, bits[i:i + 5])), 2)]
        return out

    def _decode_geohash(gh):
        lat_r, lon_r = [-90.0, 90.0], [-180.0, 180.0]
        even = True
        for ch in str(gh).lower():
            idx = _B32.index(ch)
            for bit in range(4, -1, -1):
                b = (idx >> bit) & 1
                r = lon_r if even else lat_r
                mid = (r[0] + r[1]) / 2
                if b:
                    r[0] = mid
                else:
                    r[1] = mid
                even = not even
        return {"latitude": (lat_r[0] + lat_r[1]) / 2,
                "longitude": (lon_r[0] + lon_r[1]) / 2}

    register(sp + "encodeGeohash", _encode_geohash)
    register(sp + "decodeGeohash", _decode_geohash)

    def _nearest(p, candidates):
        best, best_d = None, None
        for c in candidates or []:
            d = _haversine(p, c)
            if best_d is None or d < best_d:
                best, best_d = c, d
        return best

    register(sp + "nearest", _nearest)
    register(sp + "kNearest", lambda p, candidates, k: [
        c for c in sorted(candidates or [],
                          key=lambda c: _haversine(p, c))][: int(k)])

    def _to_geojson(p):
        la, lo = _latlon(p)
        return {"type": "Point", "coordinates": [lo, la]}

    register(sp + "toGeoJson", _to_geojson)
    register(sp + "fromGeoJson", lambda g: {
        "latitude": g["coordinates"][1], "longitude": g["coordinates"][0]}
        if isinstance(g, dict) and g.get("type") == "Point" else None)

    def _poly_contains(points, p):
        lls = [_latlon(q) for q in (points or [])]
        la, lo = _latlon(p)
        inside = False
        j = len(lls) - 1
        for i in range(len(lls)):
            yi, xi = lls[i]
            yj, xj = lls[j]
            if (yi > la) != (yj > la) and (
                    lo < (xj - xi) * (la - yi) / (yj - yi) + xi):
                inside = not inside
            j = i
        return inside

    register(sp + "contains", _poly_contains)
    register(sp + "intersects", lambda a_pts, b_pts: any(
        _poly_contains(a_pts, q) for q in (b_pts or []))
        or any(_poly_contains(b_pts, q) for q in (a_pts or [])))


def _install_meta() -> None:
    mt = "apoc.meta."
    register(mt + "isNode", lambda x: isinstance(x, Node))
    register(mt + "isRelationship", lambda x: isinstance(x, Edge))

    def _is_path(x):
        from nornicdb_tpu.query.functions import PathValue
        return isinstance(x, PathValue)

    register(mt + "isPath", _is_path)

    def _cypher_type(x):
        from nornicdb_tpu.query.functions import REGISTRY
        return REGISTRY["valuetype"](x)

    register(mt + "cypherType", _cypher_type)
    register(mt + "typeOf", _cypher_type)
    register(mt + "types", lambda m: {
        k: _cypher_type(v) for k, v in (m or {}).items()})
    register(mt + "cypherTypes", lambda m: {
        k: _cypher_type(v) for k, v in (m or {}).items()})
    register(mt + "isType", lambda x, t: _cypher_type(x) == str(t).upper())

    def _scan(ctx):
        labels: Dict[str, int] = {}
        props: Dict[str, Set[str]] = {}
        for node in ctx.storage.all_nodes():
            for l in node.labels:
                labels[l] = labels.get(l, 0) + 1
                props.setdefault(l, set()).update(node.properties)
        rels: Dict[str, int] = {}
        rprops: Dict[str, Set[str]] = {}
        for e in ctx.storage.all_edges():
            rels[e.type] = rels.get(e.type, 0) + 1
            rprops.setdefault(e.type, set()).update(e.properties)
        return labels, props, rels, rprops

    def _stats(ctx):
        labels, _props, rels, _rp = _scan(ctx)
        return {"nodeCount": ctx.storage.count_nodes(),
                "relCount": ctx.storage.count_edges(),
                "labels": labels, "relTypes": rels}

    register_ctx(mt + "stats", _stats)
    register_ctx(mt + "nodeLabels", lambda ctx: sorted(_scan(ctx)[0]))
    register_ctx(mt + "relTypes", lambda ctx: sorted(_scan(ctx)[2]))

    def _property_keys(ctx):
        _labels, props, _rels, _rp = _scan(ctx)
        return sorted(set().union(*props.values()) if props else set())

    register_ctx(mt + "propertyKeys", _property_keys)
    register_ctx(mt + "nodeTypeProperties", lambda ctx: [
        {"nodeType": l, "propertyName": p}
        for l, ps in sorted(_scan(ctx)[1].items()) for p in sorted(ps)])
    register_ctx(mt + "relTypeProperties", lambda ctx: [
        {"relType": t, "propertyName": p}
        for t, ps in sorted(_scan(ctx)[3].items()) for p in sorted(ps)])

    def _data(ctx):
        labels, props, rels, rprops = _scan(ctx)
        return {"labels": labels, "relTypes": rels,
                "labelProperties": {l: sorted(ps)
                                    for l, ps in props.items()},
                "relProperties": {t: sorted(ps)
                                  for t, ps in rprops.items()}}

    register_ctx(mt + "data", _data)

    def _schema(ctx):
        labels, props, _rels, _rp = _scan(ctx)
        return {l: {"type": "node", "count": c,
                    "properties": sorted(props.get(l, set()))}
                for l, c in labels.items()}

    register_ctx(mt + "schema", _schema)
    register_ctx(mt + "cardinality", lambda ctx, label: len(
        ctx.storage.get_nodes_by_label(label)))

    def _graph_sample(ctx, limit=100):
        nodes = []
        for i, node in enumerate(ctx.storage.all_nodes()):
            if i >= int(limit):
                break
            nodes.append(node)
        ids = {x.id for x in nodes}
        rels = [e for e in ctx.storage.all_edges()
                if e.start_node in ids and e.end_node in ids]
        return {"nodes": nodes, "relationships": rels}

    register_ctx(mt + "graph", lambda ctx: _graph_sample(ctx, 10 ** 9))
    register_ctx(mt + "graphSample", _graph_sample)


def _install_search() -> None:
    se = "apoc.search."

    def _scan_nodes(ctx, label_or_labels):
        if not label_or_labels:
            yield from ctx.storage.all_nodes()
            return
        labels = (label_or_labels if isinstance(label_or_labels, list)
                  else [label_or_labels])
        seen: Set[str] = set()
        for l in labels:
            for node in ctx.storage.get_nodes_by_label(l):
                if node.id not in seen:
                    seen.add(node.id)
                    yield node

    def _match(value, op, query) -> bool:
        if op == "contains":
            return isinstance(value, str) and str(query) in value
        if op == "starts":
            return isinstance(value, str) and value.startswith(str(query))
        if op == "ends":
            return isinstance(value, str) and value.endswith(str(query))
        if op == "regex":
            return isinstance(value, str) and bool(
                re.search(str(query), value))
        if op == "exact":
            return value == query
        if op == "fuzzy":
            from nornicdb_tpu.query.apoc import _levenshtein
            return (isinstance(value, str)
                    and _levenshtein(value.lower(), str(query).lower())
                    <= max(1, len(str(query)) // 4))
        raise CypherRuntimeError(f"unknown search op {op!r}")

    def _search(ctx, labels, prop, op, query):
        out = []
        for node in _scan_nodes(ctx, labels):
            if _match(node.properties.get(prop), op, query):
                out.append(node)
        return out

    register_ctx(se + "node", lambda ctx, labels, prop, query: _search(
        ctx, labels, prop, "contains", query))
    register_ctx(se + "nodeAll", lambda ctx, spec, op, query: [
        node for label, props in (spec or {}).items()
        for node in _scan_nodes(ctx, label)
        if all(_match(node.properties.get(p), op, query)
               for p in (props if isinstance(props, list) else [props]))])
    register_ctx(se + "nodeAny", lambda ctx, spec, op, query: list({
        node.id: node for label, props in (spec or {}).items()
        for node in _scan_nodes(ctx, label)
        if any(_match(node.properties.get(p), op, query)
               for p in (props if isinstance(props, list) else [props]))
    }.values()))
    register_ctx(se + "nodeReduced", lambda ctx, spec, op, query: [
        {"id": node.id, "labels": list(node.labels)}
        for label, props in (spec or {}).items()
        for node in _scan_nodes(ctx, label)
        if any(_match(node.properties.get(p), op, query)
               for p in (props if isinstance(props, list) else [props]))])
    register_ctx(se + "contains", lambda ctx, labels, prop, q: _search(
        ctx, labels, prop, "contains", q))
    register_ctx(se + "prefix", lambda ctx, labels, prop, q: _search(
        ctx, labels, prop, "starts", q))
    register_ctx(se + "suffix", lambda ctx, labels, prop, q: _search(
        ctx, labels, prop, "ends", q))
    register_ctx(se + "regex", lambda ctx, labels, prop, q: _search(
        ctx, labels, prop, "regex", q))
    register_ctx(se + "exists", lambda ctx, labels, prop: [
        node for node in _scan_nodes(ctx, labels)
        if prop in node.properties])
    register_ctx(se + "missing", lambda ctx, labels, prop: [
        node for node in _scan_nodes(ctx, labels)
        if prop not in node.properties])
    register_ctx(se + "null", lambda ctx, labels, prop: [
        node for node in _scan_nodes(ctx, labels)
        if prop in node.properties and node.properties[prop] is None])
    register_ctx(se + "notNull", lambda ctx, labels, prop: [
        node for node in _scan_nodes(ctx, labels)
        if node.properties.get(prop) is not None])
    register_ctx(se + "in", lambda ctx, labels, prop, values: [
        node for node in _scan_nodes(ctx, labels)
        if node.properties.get(prop) in (values or [])])
    register_ctx(se + "notIn", lambda ctx, labels, prop, values: [
        node for node in _scan_nodes(ctx, labels)
        if node.properties.get(prop) not in (values or [])])

    def _range(ctx, labels, prop, lo, hi):
        out = []
        for node in _scan_nodes(ctx, labels):
            v = node.properties.get(prop)
            if (isinstance(v, (int, float)) and not isinstance(v, bool)
                    and float(lo) <= v <= float(hi)):
                out.append(node)
        return out

    register_ctx(se + "range", _range)
    register_ctx(se + "fuzzy", lambda ctx, labels, prop, q: _search(
        ctx, labels, prop, "fuzzy", q))
    register_ctx(se + "match", lambda ctx, labels, prop, q: _search(
        ctx, labels, prop, "exact", q))

    def _autocomplete(ctx, labels, prop, prefix, limit=10):
        vals = sorted({
            v for node in _scan_nodes(ctx, labels)
            if isinstance(v := node.properties.get(prop), str)
            and v.lower().startswith(str(prefix).lower())})
        return vals[: int(limit)]

    register_ctx(se + "autocomplete", _autocomplete)

    def _didyoumean(ctx, labels, prop, q, limit=5):
        from nornicdb_tpu.query.apoc import _levenshtein
        scored = []
        for node in _scan_nodes(ctx, labels):
            v = node.properties.get(prop)
            if isinstance(v, str):
                scored.append((_levenshtein(v.lower(), str(q).lower()), v))
        scored.sort()
        out = []
        for _d, v in scored:
            if v not in out:
                out.append(v)
            if len(out) >= int(limit):
                break
        return out

    register_ctx(se + "didYouMean", _didyoumean)
    register_ctx(se + "suggest", _didyoumean)

    def _highlight(ctx, labels, prop, q, pre="<b>", post="</b>"):
        out = []
        for node in _search(ctx, labels, prop, "contains", q):
            v = node.properties[prop]
            out.append({"node": node, "highlighted": v.replace(
                str(q), f"{pre}{q}{post}")})
        return out

    register_ctx(se + "highlight", _highlight)
    register_ctx(se + "multiSearchAll", lambda ctx, specs, q: [
        node for spec in (specs or [])
        for node in _search(ctx, spec.get("label"), spec.get("prop"),
                            spec.get("op", "contains"), q)])
    register_ctx(se + "multiSearchAny", lambda ctx, specs, q: list({
        node.id: node for spec in (specs or [])
        for node in _search(ctx, spec.get("label"), spec.get("prop"),
                            spec.get("op", "contains"), q)}.values()))

    def _score(ctx, labels, prop, q):
        """Occurrence-count scoring for a contains search."""
        out = []
        for node in _scan_nodes(ctx, labels):
            v = node.properties.get(prop)
            if isinstance(v, str) and str(q) in v:
                out.append({"node": node, "score": v.count(str(q))})
        out.sort(key=lambda d: -d["score"])
        return out

    register_ctx(se + "score", _score)


def install() -> None:
    _install_node_rel()
    _install_label()
    _install_nodes()
    _install_neighbors()
    _install_spatial()
    _install_meta()
    _install_search()


install()
