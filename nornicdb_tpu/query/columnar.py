"""Columnar graph snapshot for vectorized query execution.

The reference parallelizes hot query shapes by chunking node/edge slices
across cores (pkg/cypher/parallel.go:99-403) and serves LDBC/Northwind
shapes through specialized executors over indexed storage
(optimized_executors.go:25-282, storage_fastpaths.go:14-54). The
TPU-native redesign replaces both with *columnar* execution: the graph is
snapshotted into flat arrays (a global node table, per-edge-type CSR
adjacency, lazily materialized property columns and hash property
indexes) and query shapes compile to batched array ops — numpy for the
small/latency-bound shapes, with the same layout streaming to the device
data plane (ops/) for large scans. SURVEY §2.8 row 1 maps the
reference's multicore chunk parallelism to exactly this design.

The catalog is invalidated wholesale on updates/deletes via
`invalidate()`, wired to executor write stats and to storage mutation
listeners in db.py. Pure creations are *incremental*: node/edge create
deltas extend the snapshot, the per-(etype, direction, label) degree
arrays, and two families of materialized aggregate views in place —
the count-store analog of the reference's single-hop fast aggregations
(pkg/cypher/traversal_fast_agg.go:15,57) and hand-written co-occurrence
executors (optimized_executors.go:25-282):

- `_StripView`: per-anchor-node sums of terminal-hop filtered degrees,
  grouped by the adjacent node over one relationship type — answers the
  "avg friends per city" family in O(#groups) per query.
- `_GramView`: the co-occurrence Gram matrix C = Ma^T @ Mb with the
  same-edge diagonal correction folded in — answers the "tag
  co-occurrence" family in O(nnz(C)) per query.

Without these, both shapes re-run O(edges) array work per query, which
is fine at 10^3 nodes and hopeless at 10^5 (the scale VERDICT r02
demands).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from nornicdb_tpu.storage.types import Direction, Edge, Engine, Node


class EdgeTable:
    """All edges of one type, as parallel arrays over global node rows."""

    __slots__ = (
        "etype", "src", "dst", "edges",
        "_csr_out", "_csr_in", "_prop_cols", "_edge_ids",
        "_buf_src", "_buf_dst",
    )

    def __init__(self, etype: str, src: np.ndarray, dst: np.ndarray,
                 edges: List[Edge]):
        self.etype = etype
        # src/dst are exact-length views over capacity buffers so appends
        # are amortized O(1) (a write-heavy compound loop would otherwise
        # pay an O(len) array copy per created edge). Readers snapshot
        # the views; the region behind a view is never rewritten.
        self._buf_src = src
        self._buf_dst = dst
        self.src = src  # int32[ne] global node row of start
        self.dst = dst  # int32[ne] global node row of end
        self.edges = edges  # Edge objects aligned with src/dst
        self._csr_out: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._csr_in: Optional[Tuple[np.ndarray, np.ndarray]] = None
        self._prop_cols: Dict[str, np.ndarray] = {}
        self._edge_ids = {e.id for e in edges}

    def __len__(self) -> int:
        return len(self.edges)

    def csr(self, direction: str, n_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
        """(indptr, order): edge rows grouped by src (out) or dst (in).

        ``order`` is a permutation of edge rows; edges with source node g
        occupy order[indptr[g]:indptr[g+1]] (for direction 'out').
        """
        if direction == "out":
            if self._csr_out is None:
                self._csr_out = _build_csr(self.src, n_nodes)
            return self._csr_out
        if direction == "in":
            if self._csr_in is None:
                self._csr_in = _build_csr(self.dst, n_nodes)
            return self._csr_in
        raise ValueError(f"bad direction {direction}")

    def prop_col(self, name: str) -> np.ndarray:
        """Object array of edge property ``name`` aligned with edge rows."""
        col = self._prop_cols.get(name)
        if col is None:
            col = np.empty(len(self.edges), dtype=object)
            for i, e in enumerate(self.edges):
                col[i] = e.properties.get(name)
            self._prop_cols[name] = col
        return col

    def append_edge(self, src_row: int, dst_row: int, edge: Edge) -> None:
        """Create-delta append; drops derived caches (CSR, prop cols).

        Idempotent: a lazy table build that raced the write may have
        already read this edge from storage before the create listener
        fired — appending again would duplicate it in every join and
        degree count."""
        if edge.id in self._edge_ids:
            return
        self._edge_ids.add(edge.id)
        n = len(self.src)
        if n == len(self._buf_src):
            cap = max(16, 2 * n)
            grown = np.empty(cap, dtype=np.int32)
            grown[:n] = self._buf_src
            self._buf_src = grown
            grown = np.empty(cap, dtype=np.int32)
            grown[:n] = self._buf_dst
            self._buf_dst = grown
        self._buf_src[n] = src_row
        self._buf_dst[n] = dst_row
        self.src = self._buf_src[:n + 1]
        self.dst = self._buf_dst[:n + 1]
        self.edges.append(edge)
        self._csr_out = None
        self._csr_in = None
        self._prop_cols.clear()


class _StripView:
    """Materialized two-hop grouped degree aggregation.

    For a chain (g)-[:ETYPE1]-(p:PLabel)-[:ETYPE2]-(f:FLabel) where the
    terminal f is consumed only by count(), the per-g aggregates are
    maintained densely over ALL global node rows (g's label filter is a
    query-time row selection, so it is not part of the key):

    - ``deg[p]``: # ETYPE2 edges of p in dir2 whose far end has FLabel
      (a private copy — updates must read the pre-increment value)
    - ``sum_deg[g]``: sum of deg[p] over ETYPE1 edges (g, p) with p
      carrying PLabel == count(f) per g == count(p) per g (weighted)
    - ``nnz[g]``: # *distinct* p with PLabel, an ETYPE1 edge to g, and
      deg[p] > 0 == count(DISTINCT p) per g

    Incrementally maintained on edge creates of either type; the catalog
    drops the view when it cannot update exactly (unknown node rows,
    missing adjacency, over-budget probes). Updates are in-place single
    int64 stores — aligned and untearable for concurrent readers; node
    creates extend arrays copy-on-write (np.append).
    """

    __slots__ = ("deg", "sum_deg", "nnz")

    def __init__(self, deg: np.ndarray, sum_deg: np.ndarray, nnz: np.ndarray):
        self.deg = deg
        self.sum_deg = sum_deg
        self.nnz = nnz


class _SortedAdjacency:
    """Materialized segment-sorted adjacency strip.

    CSR-like layout over ALL global node rows: the far ends of one edge
    type's edges, grouped by the near-side node, each segment pre-sorted
    by a NUMERIC property of the far node — descending, with nulls
    first (Cypher DESC null semantics, mirroring fastpaths's
    _order_from_keys null -> +inf convention).

    This answers the "recent messages of friends" family in O(friends *
    k): per-friend top-k is a head slice of the friend's segment, and
    the global top-k is a merge of those heads — no per-query expansion
    over every message, no per-query sort of the full candidate set.
    The strip is dropped (lazy rebuild) on any create of its edge type:
    inserting into sorted segments in place would cost O(E) per create,
    which is the wrong trade for a read-hot view.
    """

    __slots__ = ("indptr", "nbr", "keys")

    def __init__(self, indptr: np.ndarray, nbr: np.ndarray,
                 keys: np.ndarray):
        self.indptr = indptr  # int64[n_nodes+1]
        self.nbr = nbr        # int32[n_usable_edges] far rows, seg-sorted
        self.keys = keys      # float64 sort keys aligned with nbr


class _GramView:
    """Materialized co-occurrence Gram matrix for (a)<-[:T]-(mid)-[:T]->(b).

    ``C[i, j]`` = # mids with an edge to a-candidate i and a *different*
    edge to b-candidate j (the same-edge diagonal correction is folded
    in at build). ``far_lists`` maps mid global row -> list of far
    global rows of its existing usable edges, so an edge create updates
    C in O(deg(mid)) with in-place (untearable) int64 stores.

    ``coo()`` is the pre-aggregated sparse decomposition the query path
    consumes (VERDICT r4 #9: pre-aggregation, not per-query nonzero):
    recomputed only when ``gen`` moved, i.e. after a C mutation.
    """

    __slots__ = ("C", "a_cands", "b_cands", "a_pos", "b_pos", "far_lists",
                 "gen", "_coo_gen", "_coo")

    def __init__(self, C, a_cands, b_cands, a_pos, b_pos, far_lists):
        self.C = C
        self.a_cands = a_cands
        self.b_cands = b_cands
        self.a_pos = a_pos
        self.b_pos = b_pos
        self.far_lists = far_lists
        self.gen = 0
        self._coo_gen = -1
        self._coo = None

    def coo(self):
        """(ii, jj, weights, a_rows_i32, b_rows_i32) of positive cells.

        Maintained across the view's in-place updates via ``gen``; a
        torn read (concurrent writer bumping gen mid-extract) yields a
        value consistent with SOME interleaving of single int64 cell
        stores — same guarantee the raw C reads already give — and is
        simply not cached."""
        g0 = self.gen
        cached = self._coo
        if cached is not None and self._coo_gen == g0:
            return cached
        c = self.C
        ii, jj = np.nonzero(c > 0)
        out = (
            ii, jj, c[ii, jj],
            self.a_cands[ii].astype(np.int32, copy=False),
            self.b_cands[jj].astype(np.int32, copy=False),
        )
        if self.gen == g0:
            self._coo = out
            self._coo_gen = g0
        return out


def _build_csr(keys: np.ndarray, n_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
    order = np.argsort(keys, kind="stable").astype(np.int32)
    counts = np.bincount(keys, minlength=n_nodes)
    indptr = np.zeros(n_nodes + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    return indptr, order


class ColumnarCatalog:
    """Versioned columnar snapshot of a storage.Engine.

    Everything is built lazily on first use and discarded wholesale on
    ``invalidate()``. Thread-safe for concurrent readers; builds are
    serialized under a lock.
    """

    def __init__(self, storage: Engine):
        self._storage = storage
        self._lock = threading.Lock()
        self._version = 0
        # Per-etype delta generations (ISSUE 19). `_version` stales on
        # EVERY write; background device jobs that only consume one
        # edge-type's slice key their snapshots on
        # ``(struct_gen, etype_gen[etype])`` instead, so a write to
        # etype A leaves etype B's device snapshot live. `_struct_gen`
        # moves on anything that changes the node axis or is not a pure
        # edge append (invalidate, node creates); `_etype_gen[et]`
        # moves only on edge appends of that type.
        self._struct_gen = 0
        self._etype_gen: Dict[str, int] = {}
        self._reset_locked()

    def _reset_locked(self) -> None:
        self._nodes: Optional[List[Node]] = None
        self._node_pos: Optional[Dict[str, int]] = None
        self._label_rows: Dict[str, np.ndarray] = {}
        self._label_mask: Dict[str, np.ndarray] = {}
        self._node_prop_cols: Dict[str, np.ndarray] = {}
        self._prop_index: Dict[Tuple[str, str], Dict[Any, np.ndarray]] = {}
        self._edge_tables: Dict[str, EdgeTable] = {}
        self._all_edge_types: Optional[List[str]] = None
        self._filtered_deg: Dict[Tuple[str, str, Optional[str]], np.ndarray] = {}
        self._mid_axis: Dict[Tuple[str, str, Optional[str]], Any] = {}
        self._incidence: Dict[Tuple[str, str, Optional[str], Optional[str]], Any] = {}
        # materialized aggregate views (see module docstring)
        self._strip_views: Dict[Tuple, _StripView] = {}
        self._gram_views: Dict[Tuple, Optional[_GramView]] = {}
        # segment-sorted adjacency strips (per-friend top-k family);
        # a cached None records "order prop not numeric here"
        self._sorted_adj: Dict[Tuple, Optional[_SortedAdjacency]] = {}
        # (prop, id(cands)) -> (cands ref, verdict): is prop injective,
        # non-null and scalar over the candidate rows? The ref pins the
        # id; property writes invalidate() the whole catalog, and any
        # candidate-set change allocates a new array -> new id.
        self._injective: Dict[Tuple[str, int], Tuple[np.ndarray, bool]] = {}

    @property
    def version(self) -> int:
        return self._version

    def etype_version(self, etype: str) -> Tuple[int, int]:
        """Delta-snapshot key for one edge type: ``(struct_gen,
        etype_gen)``. Unchanged by writes to OTHER edge types, so a
        consumer keyed on this tuple survives unrelated edge appends
        (the whole-catalog :attr:`version` moves on every write)."""
        with self._lock:
            return (self._struct_gen, self._etype_gen.get(etype, 0))

    def etype_versions(self, etypes) -> Tuple[Tuple[int, int], ...]:
        """One consistent read of several etype keys (single lock
        acquisition — no torn tuple across a racing write)."""
        with self._lock:
            return tuple((self._struct_gen, self._etype_gen.get(et, 0))
                         for et in etypes)

    @property
    def storage(self) -> Engine:
        return self._storage

    # -- device-plane install hooks (query/device_graph.py) --------------
    #
    # The device graph plane builds the SAME materialized views the
    # host builds (verified-exact integer arrays) and installs them
    # here, so downstream reads and the incremental maintenance
    # machinery run unchanged regardless of which backend built them.

    def peek_strip_view(self, key: Tuple) -> Optional[_StripView]:
        with self._lock:
            return self._strip_views.get(key)

    def install_strip_view(self, key: Tuple, sv: _StripView,
                           v0: int) -> bool:
        """Install a view built at version ``v0``; refused when the
        catalog has moved (the build raced a write — installing would
        resurrect a stale snapshot)."""
        with self._lock:
            if self._version != v0:
                return False
            self._strip_views[key] = sv
            return True

    def invalidate(self) -> None:
        with self._lock:
            self._version += 1
            # updates/deletes are not attributable to one etype: every
            # per-etype delta key moves with the structural generation
            self._struct_gen += 1
            self._etype_gen.clear()
            self._reset_locked()

    # -- create deltas ----------------------------------------------------
    #
    # Pure creations extend the snapshot in place instead of discarding
    # it — the write-heavy compound shapes (MATCH…CREATE, reference
    # Northwind write bench) would otherwise rebuild O(N) structures on
    # every statement. Updates/deletes still invalidate wholesale.
    # Appends are O(existing) array copies: fine for the sizes where the
    # catalog wins; gigantic stores amortize via the usual lazy rebuild.

    def apply_node_created(self, node: Node) -> None:
        with self._lock:
            self._version += 1
            # the node axis grew: every etype's CSR indptr length moves,
            # so the structural generation (shared by all etype keys)
            # bumps rather than each per-etype generation
            self._struct_gen += 1
            # mid-axis/incidence candidate sets are label-dependent and
            # cheap to rebuild; the maintained views below extend instead
            self._mid_axis.clear()
            self._incidence.clear()
            if self._nodes is None:
                return  # nothing built yet; lazy build sees the node
            if node.id in self._node_pos:
                return  # lazy build raced the write and already has it
            # a brand-new node has no edges: degree/aggregate arrays gain
            # a zero slot (np.append = copy-on-write for live readers)
            for key, deg in list(self._filtered_deg.items()):
                self._filtered_deg[key] = np.append(deg, np.int64(0))
            for sv in self._strip_views.values():
                sv.deg = np.append(sv.deg, np.int64(0))
                sv.sum_deg = np.append(sv.sum_deg, np.int64(0))
                sv.nnz = np.append(sv.nnz, np.int64(0))
            # an edgeless new node extends each strip's indptr with a
            # repeat of the last offset (same treatment as cached CSRs)
            for sa in self._sorted_adj.values():
                if sa is not None:
                    sa.indptr = np.append(sa.indptr, sa.indptr[-1])
            for key, gv in list(self._gram_views.items()):
                if gv is None:
                    continue  # over budget; creates only grow the graph
                _etype, _orient, _mid_l, a_l, b_l = key
                if (a_l is None or b_l is None
                        or a_l in node.labels or b_l in node.labels):
                    # candidate axes grow: rebuild lazily. The rebuild
                    # allocates fresh candidate arrays, so drop the
                    # injectivity memo too — it's id-keyed and would
                    # otherwise pin the dead arrays forever
                    self._gram_views.pop(key)
                    self._injective.clear()
                else:
                    gv.a_pos = np.append(gv.a_pos, np.int64(-1))
                    gv.b_pos = np.append(gv.b_pos, np.int64(-1))
            i = len(self._nodes)
            self._nodes.append(node)
            self._node_pos[node.id] = i
            for lbl, rows in self._label_rows.items():
                if lbl in node.labels:
                    self._label_rows[lbl] = np.append(rows, np.int32(i))
            for lbl, mask in list(self._label_mask.items()):
                self._label_mask[lbl] = np.append(mask, lbl in node.labels)
            for name, col in list(self._node_prop_cols.items()):
                ext = np.empty(1, dtype=object)
                ext[0] = node.properties.get(name)
                self._node_prop_cols[name] = np.concatenate([col, ext])
            for (lbl, prop), idx in self._prop_index.items():
                if lbl in node.labels:
                    v = node.properties.get(prop)
                    if v is not None and not isinstance(v, (list, dict)):
                        rows = idx.get(v)
                        idx[v] = (np.append(rows, np.int32(i))
                                  if rows is not None
                                  else np.asarray([i], dtype=np.int32))
            # CSR indptr arrays are indexed by node row and sized
            # n_nodes+1: the new (edgeless) node extends each cached
            # indptr with a repeat of its last offset (copy-on-write)
            for tbl in self._edge_tables.values():
                if tbl._csr_out is not None:
                    indptr, order = tbl._csr_out
                    tbl._csr_out = (np.append(indptr, indptr[-1]), order)
                if tbl._csr_in is not None:
                    indptr, order = tbl._csr_in
                    tbl._csr_in = (np.append(indptr, indptr[-1]), order)

    def apply_edge_created(self, edge: Edge) -> None:
        with self._lock:
            self._version += 1
            et = edge.type
            # pure edge append: only THIS etype's delta generation moves
            self._etype_gen[et] = self._etype_gen.get(et, 0) + 1
            # per-etype drop of the (non-maintained) incidence caches
            for key in [k for k in self._mid_axis if k[0] == et]:
                self._mid_axis.pop(key)
            for key in [k for k in self._incidence if k[0] == et]:
                self._incidence.pop(key)
            # sorted strips rebuild lazily: a sorted-segment insert
            # would be O(E) in place, the rebuild is one lexsort on read
            for key in [k for k in self._sorted_adj if k[0] == et]:
                self._sorted_adj.pop(key)

            tbl = self._edge_tables.get(et)
            s = d = None
            if self._node_pos is not None:
                s = self._node_pos.get(edge.start_node)
                d = self._node_pos.get(edge.end_node)
            if s is None or d is None:
                # endpoints unseen by the snapshot: every structure
                # derived from this etype is unmaintainable — drop them
                self._edge_tables.pop(et, None)
                self._drop_etype_aggregates_locked(et)
            else:
                # Freshness gate: every maintained structure (degree
                # arrays, strip/gram views) is built FROM the edge table,
                # whose appends dedupe by edge id. A lazy build that
                # raced this write may already include the edge; in that
                # case incrementing again would double count. The table's
                # id set is the single source of truth.
                fresh = tbl is not None and edge.id not in tbl._edge_ids
                if tbl is None:
                    # no table ⇒ no table-derived caches can exist for
                    # this etype (builds force the table; pops drop them)
                    self._drop_etype_aggregates_locked(et)
                elif fresh:
                    # view updates FIRST: they read pre-increment degrees
                    # and the pre-append adjacency of the edge table
                    self._update_strip_views_locked(et, int(s), int(d))
                    self._update_gram_views_locked(et, int(s), int(d))
                    self._update_degrees_locked(et, int(s), int(d))
                if tbl is not None:
                    tbl.append_edge(int(s), int(d), edge)
            if (self._all_edge_types is not None
                    and et not in self._all_edge_types):
                self._all_edge_types.append(et)
                self._all_edge_types.sort()

    # -- incremental maintenance helpers (call with self._lock held) ------

    def _drop_etype_aggregates_locked(self, et: str) -> None:
        for key in [k for k in self._filtered_deg if k[0] == et]:
            self._filtered_deg.pop(key)
        for key in [k for k in self._sorted_adj if k[0] == et]:
            self._sorted_adj.pop(key)
        for key in [k for k in self._strip_views
                    if k[0] == et or k[3] == et]:
            self._strip_views.pop(key)
        for key in [k for k in self._gram_views if k[0] == et]:
            self._gram_views.pop(key)
            self._injective.clear()  # id-keyed on the views' cand arrays

    # a view update without a CSR falls back to one vectorized scan of
    # the etype1 table; past this size, dropping the view (lazy rebuild
    # on next read) is cheaper than scanning per create
    NEIGHBOR_SCAN_MAX_EDGES = 200_000

    def _update_degrees_locked(self, et: str, s: int, d: int) -> None:
        """In-place += on cached (etype, direction, label) degrees.
        Single aligned int64 stores can't tear for concurrent readers;
        cross-array consistency during a write is no weaker than the
        copy-on-write alternative (arrays swap independently either
        way) and this keeps per-create cost O(1) instead of O(n)."""
        for key in [k for k in self._filtered_deg if k[0] == et]:
            _et, kdir, klabel = key
            row, far = (s, d) if kdir == "out" else (d, s)
            if klabel is None or klabel in self._nodes[far].labels:
                self._filtered_deg[key][row] += 1

    def _table_neighbors_locked(
        self, tbl: EdgeTable, probe_side: str, row: int
    ) -> Optional[np.ndarray]:
        """Rows on the OTHER side of ``tbl`` edges whose ``probe_side``
        ('src'|'dst') endpoint is ``row`` — with multiplicity. Uses the
        cached CSR when built, else one vectorized scan of the table;
        None when the table is too big to scan per create (the caller
        drops its view)."""
        if probe_side == "src":
            csr, keys, other = tbl._csr_out, tbl.src, tbl.dst
        else:
            csr, keys, other = tbl._csr_in, tbl.dst, tbl.src
        if csr is not None:
            indptr, order = csr
            return other[order[indptr[row]:indptr[row + 1]]]
        if len(keys) > self.NEIGHBOR_SCAN_MAX_EDGES:
            return None
        return other[keys == row]

    def _update_strip_views_locked(self, et: str, s: int, d: int) -> None:
        for key in list(self._strip_views):
            etype1, g_side, p_label, etype2, dir2, f_label = key
            sv = self._strip_views[key]
            if et == etype1:
                g, p = (s, d) if g_side == "src" else (d, s)
                if p_label is not None and p_label not in self._nodes[p].labels:
                    continue
                dp = int(sv.deg[p])
                if dp == 0:
                    continue  # zero-degree p adds nothing to sum or nnz
                tbl1 = self._edge_tables.get(etype1)
                if tbl1 is None:
                    self._strip_views.pop(key)
                    continue
                # nnz counts DISTINCT p per g: a second parallel edge
                # (g, p) must not re-count p
                p_side = "dst" if g_side == "src" else "src"
                known_gs = self._table_neighbors_locked(tbl1, p_side, p)
                if known_gs is None:
                    self._strip_views.pop(key)  # too big to probe
                    continue
                sv.sum_deg[g] += dp
                if not (known_gs == g).any():
                    sv.nnz[g] += 1
            elif et == etype2:
                p, f = (s, d) if dir2 == "out" else (d, s)
                if f_label is not None and f_label not in self._nodes[f].labels:
                    continue
                old = int(sv.deg[p])
                sv.deg[p] += 1
                if p_label is not None and p_label not in self._nodes[p].labels:
                    continue
                tbl1 = self._edge_tables.get(etype1)
                if tbl1 is None:
                    self._strip_views.pop(key)
                    continue
                p_side = "dst" if g_side == "src" else "src"
                gs = self._table_neighbors_locked(tbl1, p_side, p)
                if gs is None:
                    self._strip_views.pop(key)  # too big to probe
                    continue
                if len(gs) == 0:
                    continue
                np.add.at(sv.sum_deg, gs, 1)
                if old == 0:
                    sv.nnz[np.unique(gs)] += 1

    def _update_gram_views_locked(self, et: str, s: int, d: int) -> None:
        for key in list(self._gram_views):
            etype, orientation, mid_label, _a_l, _b_l = key
            if et != etype:
                continue
            gv = self._gram_views[key]
            if gv is None:
                continue  # over budget; creates only grow the graph
            mid, far = (s, d) if orientation == "mid_src" else (d, s)
            if (mid_label is not None
                    and mid_label not in self._nodes[mid].labels):
                continue
            fa = int(gv.a_pos[far]) >= 0
            fb = int(gv.b_pos[far]) >= 0
            if not (fa or fb):
                continue
            lst = gv.far_lists.get(mid)
            if lst:
                gv.gen += 1  # invalidate coo() BEFORE the cells move
                C = gv.C  # in-place: single int64 cells can't tear
                for f2 in lst:
                    if fb:
                        ap = int(gv.a_pos[f2])
                        if ap >= 0:
                            C[ap, int(gv.b_pos[far])] += 1
                    if fa:
                        bp = int(gv.b_pos[f2])
                        if bp >= 0:
                            C[int(gv.a_pos[far]), bp] += 1
                gv.gen += 1
            if lst is None:
                gv.far_lists[mid] = [far]
            else:
                lst.append(far)

    def note_external_upsert(self, node: Node) -> bool:
        """Absorb an out-of-band node upsert without wholesale
        invalidation when possible. Three cases:

        - known node, query-visible content (labels, properties)
          unchanged — the embed queue's embedding write-backs — swap the
          snapshot's object in place;
        - unseen node (e.g. created by a statement still running, whose
          deltas apply at end-of-query) — append it as a create delta;
        - known node with changed content — return False, the caller
          must invalidate.

        Wholesale invalidation here would force a full snapshot rebuild
        per index probe while bulk ingest races the embed worker."""
        with self._lock:
            if self._nodes is None:
                return True  # nothing built; nothing can be stale
            i = self._node_pos.get(node.id) if self._node_pos else None
            if i is not None:
                cur = self._nodes[i]
                try:
                    same = (cur.labels == node.labels
                            and bool(cur.properties == node.properties))
                except (TypeError, ValueError):
                    same = False  # e.g. numpy-valued property __eq__
                if same:
                    # defensive copy: the listener hands us the writer's
                    # live object; the snapshot must own its nodes
                    self._nodes[i] = node.copy()
                    return True
                return False
            if len(self._nodes) >= self.EXTERNAL_APPEND_MAX_NODES:
                # appending extends every cached O(N) array; past this
                # size one wholesale invalidation + lazy rebuild is
                # cheaper than per-create array copies
                return False
        self.apply_node_created(node.copy())  # idempotent; own lock
        return True

    # -- node table -------------------------------------------------------

    def _ensure_nodes(self) -> List[Node]:
        if self._nodes is None:
            nodes = list(self._storage.all_nodes())
            pos = {n.id: i for i, n in enumerate(nodes)}
            self._nodes = nodes
            self._node_pos = pos
        return self._nodes

    def nodes(self) -> List[Node]:
        with self._lock:
            return self._ensure_nodes()

    def n_nodes(self) -> int:
        with self._lock:
            return len(self._ensure_nodes())

    def node_row(self, node_id: str) -> Optional[int]:
        with self._lock:
            self._ensure_nodes()
            return self._node_pos.get(node_id)

    def label_rows(self, label: str) -> np.ndarray:
        """Global row indices of nodes carrying ``label`` (int32, sorted)."""
        with self._lock:
            rows = self._label_rows.get(label)
            if rows is None:
                nodes = self._ensure_nodes()
                rows = np.asarray(
                    [i for i, n in enumerate(nodes) if label in n.labels],
                    dtype=np.int32,
                )
                self._label_rows[label] = rows
            return rows

    def label_mask(self, label: str) -> np.ndarray:
        """bool[n_nodes] membership mask for ``label``."""
        with self._lock:
            mask = self._label_mask.get(label)
            if mask is None:
                nodes = self._ensure_nodes()
                mask = np.zeros(len(nodes), dtype=bool)
                rows = self._label_rows.get(label)
                if rows is not None:
                    mask[rows] = True
                else:
                    for i, n in enumerate(nodes):
                        if label in n.labels:
                            mask[i] = True
                self._label_mask[label] = mask
            return mask

    def node_prop_col(self, name: str) -> np.ndarray:
        """Object array of node property ``name`` over ALL global rows."""
        with self._lock:
            col = self._node_prop_cols.get(name)
            if col is None:
                nodes = self._ensure_nodes()
                col = np.empty(len(nodes), dtype=object)
                for i, n in enumerate(nodes):
                    col[i] = n.properties.get(name)
                self._node_prop_cols[name] = col
            return col

    def prop_index(self, label: str, prop: str) -> Dict[Any, np.ndarray]:
        """Hash index value -> global rows, over nodes with ``label``.

        The reference reaches point lookups like LDBC "message content
        lookup" through indexed property access (storage_fastpaths.go);
        this is the columnar equivalent.
        """
        with self._lock:
            key = (label, prop)
            idx = self._prop_index.get(key)
            if idx is None:
                nodes = self._ensure_nodes()
                rows = self._label_rows.get(label)
                if rows is None:
                    rows = np.asarray(
                        [i for i, n in enumerate(nodes) if label in n.labels],
                        dtype=np.int32,
                    )
                    self._label_rows[label] = rows
                buckets: Dict[Any, List[int]] = {}
                for i in rows.tolist():
                    v = nodes[i].properties.get(prop)
                    if v is not None and not isinstance(v, (list, dict)):
                        buckets.setdefault(v, []).append(i)
                idx = {
                    v: np.asarray(lst, dtype=np.int32)
                    for v, lst in buckets.items()
                }
                self._prop_index[key] = idx
            return idx

    # -- edge tables ------------------------------------------------------

    def edge_table(self, etype: str) -> EdgeTable:
        with self._lock:
            tbl = self._edge_tables.get(etype)
            if tbl is None:
                self._ensure_nodes()
                pos = self._node_pos
                src: List[int] = []
                dst: List[int] = []
                edges: List[Edge] = []
                for e in self._storage.get_edges_by_type(etype):
                    s = pos.get(e.start_node)
                    d = pos.get(e.end_node)
                    if s is None or d is None:
                        continue  # dangling edge: invisible to matching
                    src.append(s)
                    dst.append(d)
                    edges.append(e)
                tbl = EdgeTable(
                    etype,
                    np.asarray(src, dtype=np.int32),
                    np.asarray(dst, dtype=np.int32),
                    edges,
                )
                self._edge_tables[etype] = tbl
            return tbl

    def filtered_degree(
        self, etype: str, direction: str, label: Optional[str]
    ) -> np.ndarray:
        """int64[n_nodes]: per-node count of ``etype`` edges in
        ``direction`` whose far end carries ``label`` (or any node when
        label is None).

        This is the degree store behind terminal-hop aggregation pushdown
        (reference: degree-based fast aggregations,
        pkg/cypher/traversal_fast_agg.go:15,57): count(f) over a hop that
        is otherwise unused equals a degree sum, so the join expansion
        can be skipped entirely. Cached per (etype, direction, label)
        until any mutation."""
        key = (etype, direction, label)
        with self._lock:
            deg = self._filtered_deg.get(key)
            if deg is not None:
                return deg
            v0 = self._version
        # build outside the (non-reentrant) lock: edge_table/label_mask
        # take it themselves; a racy double-build is harmless, but a
        # build that raced a mutation must not be stored (the mutation
        # already cleared the cache — storing would resurrect a stale
        # snapshot), hence the version check. Ordering matters: src/dst
        # are snapshotted under the lock (no torn pair), and the label
        # mask is fetched AFTER the snapshot — cached masks are extended
        # on node create, so a mask taken after the snapshot always
        # covers every row the snapshot references.
        tbl = self.edge_table(etype)
        with self._lock:
            if direction == "out":
                keys, far = tbl.src, tbl.dst
            else:
                keys, far = tbl.dst, tbl.src
        n = self.n_nodes()
        if label is not None:
            keys = keys[self.label_mask(label)[far]]
        deg = np.bincount(keys, minlength=n).astype(np.int64)
        with self._lock:
            if self._version == v0:
                self._filtered_deg[key] = deg
        return deg

    # dense-matrix budget for one cached incidence matrix (float32 cells;
    # 32 MB at the cap). Bigger label/edge combinations return None and
    # the query falls back to join expansion. Sized so LDBC-scale
    # co-occurrence (100k messages x 40 tags) stays comfortably inside —
    # the incidence matrix is a build-time input to the maintained Gram
    # view, so the cost is one-time, not per-query.
    INCIDENCE_MAX_CELLS = 8_000_000
    # above this snapshot size, external unseen-node upserts invalidate
    # wholesale instead of create-delta appending (each append copies
    # every cached O(N) array)
    EXTERNAL_APPEND_MAX_NODES = 20_000

    def incidence(
        self,
        etype: str,
        orientation: str,
        mid_label: Optional[str],
        far_label: Optional[str],
    ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
        """Dense incidence matrix for co-occurrence matmuls.

        orientation 'mid_src': edges run middle -> far (middle is tbl.src);
        'mid_dst': far -> middle. Returns (M, far_cands, usable, far_pos):

        - M: float32[n_mid, n_far], M[mc, fc] = #edges between middle
          ``mc`` and far candidate ``fc`` (middle filtered by mid_label,
          far end by far_label)
        - far_cands: int32 global rows of far candidates (column order)
        - usable: bool[n_edges] — edge contributes to M
        - far_pos: int64[n_nodes] — global row -> column (or -1)

        The *middle axis* (row order) depends only on (etype, orientation,
        mid_label), so two incidence matrices with different far labels
        share rows and can be contracted against each other — the tag
        co-occurrence family is ``Ma.T @ Mb`` (BASELINE.md row 4; the
        reference hand-writes this family in optimized_executors.go).
        Cached until any mutation; returns None over the size budget."""
        key = (etype, orientation, mid_label, far_label)
        with self._lock:
            if key in self._incidence:
                return self._incidence[key]
            v0 = self._version
        # Ordering vs concurrent writers: snapshot src/dst under the lock
        # (no torn pair), derive every length from the snapshot itself,
        # and fetch masks/candidate rows AFTER the snapshot — those
        # caches are extended on node create, so post-snapshot fetches
        # always cover every row the snapshot references.
        tbl = self.edge_table(etype)
        with self._lock:
            if orientation == "mid_src":
                mid_e, far_e = tbl.src, tbl.dst
            else:
                mid_e, far_e = tbl.dst, tbl.src
        ne = len(mid_e)
        n = self.n_nodes()
        # shared middle axis; a cached axis is usable only if it was
        # built from a same-length (hence identical: appends-only +
        # wholesale invalidation) edge snapshot
        axis_key = (etype, orientation, mid_label)
        with self._lock:
            axis = self._mid_axis.get(axis_key)
        if axis is None or len(axis[2]) != ne:
            emask = (self.label_mask(mid_label)[mid_e]
                     if mid_label is not None
                     else np.ones(ne, dtype=bool))
            flags = np.zeros(n, dtype=bool)
            flags[mid_e[emask]] = True
            uniq_mid = np.nonzero(flags)[0]
            mid_lut = np.zeros(n, dtype=np.int64)
            mid_lut[uniq_mid] = np.arange(len(uniq_mid))
            axis = (uniq_mid, mid_lut, emask)
            with self._lock:
                if self._version == v0:
                    self._mid_axis[axis_key] = axis
        uniq_mid, mid_lut, emask = axis
        far_cands = (self.label_rows(far_label) if far_label is not None
                     else np.arange(n, dtype=np.int32))
        result = None
        if len(uniq_mid) * max(len(far_cands), 1) <= self.INCIDENCE_MAX_CELLS:
            far_pos = np.full(n, -1, dtype=np.int64)
            far_pos[far_cands] = np.arange(len(far_cands))
            usable = emask & (far_pos[far_e] >= 0)
            m = np.zeros((len(uniq_mid), len(far_cands)), dtype=np.float32)
            np.add.at(
                m, (mid_lut[mid_e[usable]], far_pos[far_e[usable]]), 1.0
            )
            result = (m, far_cands, usable, far_pos)
        with self._lock:
            if self._version == v0:
                self._incidence[key] = result
        return result

    def strip_view(
        self,
        etype1: str,
        g_side: str,
        p_label: Optional[str],
        etype2: str,
        dir2: str,
        f_label: Optional[str],
    ) -> Optional[_StripView]:
        """Materialized two-hop grouped degree aggregation (see
        _StripView). g_side is the group node's side of ETYPE1 edges
        ('src'|'dst'); dir2 is the terminal hop's direction from p.
        Returns None when a concurrent write tore the build (callers
        fall back to per-query chain expansion)."""
        if etype1 == etype2:
            # relationship uniqueness: the same edge could serve both
            # hops, which degree products cannot see — and the update
            # path's etype dispatch would silently stop maintaining deg.
            # Callers (fastpaths._analyze_strip) reject this shape.
            raise ValueError("strip_view requires distinct edge types")
        key = (etype1, g_side, p_label, etype2, dir2, f_label)
        with self._lock:
            sv = self._strip_views.get(key)
            if sv is not None:
                return sv
            v0 = self._version
        try:
            tbl = self.edge_table(etype1)
            with self._lock:
                g_e = tbl.src if g_side == "src" else tbl.dst
                p_e = tbl.dst if g_side == "src" else tbl.src
            # private copy: incremental updates must read pre-increment
            # values even if the shared degree array advances
            deg = self.filtered_degree(etype2, dir2, f_label).copy()
            n = len(deg)
            if p_label is not None:
                pmask = self.label_mask(p_label)[p_e]
                gm = g_e[pmask].astype(np.int64)
                pm = p_e[pmask].astype(np.int64)
            else:
                gm = g_e.astype(np.int64)
                pm = p_e.astype(np.int64)
            w = deg[pm]
            sum_deg = np.bincount(
                gm, weights=w.astype(np.float64), minlength=n
            ).astype(np.int64)
            act = w > 0
            pairs = np.unique(gm[act] * n + pm[act])  # DISTINCT (g, p)
            nnz = np.bincount(pairs // n, minlength=n).astype(np.int64)
        except (IndexError, ValueError):
            return None  # torn build under a concurrent write
        sv = _StripView(deg, sum_deg, nnz)
        with self._lock:
            if self._version == v0:
                self._strip_views[key] = sv
        return sv

    def sorted_adjacency(
        self,
        etype: str,
        group_side: str,
        order_prop: str,
        far_label: Optional[str],
    ) -> Optional[_SortedAdjacency]:
        """Materialized segment-sorted adjacency (see _SortedAdjacency).

        ``group_side`` is the NEAR node's side of ``etype`` edges
        ('src'|'dst'); segments hold the far rows (optionally filtered
        by ``far_label``) sorted by the far node's ``order_prop``
        descending, nulls first. Returns None — and caches the verdict —
        when any non-null value of the order prop is non-numeric (the
        general comparator lane must order those), or transiently when a
        concurrent write tore the build."""
        key = (etype, group_side, order_prop, far_label)
        with self._lock:
            if key in self._sorted_adj:
                return self._sorted_adj[key]
            v0 = self._version
        # snapshot src/dst under the lock (no torn pair); masks/prop
        # columns are fetched after and are extended on node create, so
        # they always cover every row the snapshot references
        tbl = self.edge_table(etype)
        with self._lock:
            grp = tbl.src if group_side == "src" else tbl.dst
            far = tbl.dst if group_side == "src" else tbl.src
        n = self.n_nodes()
        result: Optional[_SortedAdjacency] = None
        try:
            if far_label is not None:
                fmask = self.label_mask(far_label)[far]
                grp = grp[fmask]
                far = far[fmask]
            vals = self.node_prop_col(order_prop)[far]
            # one C-pass conversion (the _as_float recipe): astype maps
            # None -> nan and raises on strings; the type scan rejects
            # bools (Cypher orders them as a TYPE, not numerically) and
            # the nan audit distinguishes nulls (-> +inf, Cypher DESC
            # null-first) from genuine float('nan') values
            numeric = True
            keys = None
            try:
                keys = vals.astype(np.float64)
            except (TypeError, ValueError):
                numeric = False
            if numeric:
                types = set(map(type, vals.tolist()))
                if bool in types or np.bool_ in types:
                    numeric = False
                elif type(None) in types:
                    nanpos = np.isnan(keys)
                    if nanpos.any():
                        tl = vals.tolist()
                        for i in np.flatnonzero(nanpos).tolist():
                            if tl[i] is None:
                                keys[i] = np.inf
            if numeric:
                # stable grouped desc sort: group is the primary key,
                # negated value secondary; equal keys keep edge-table
                # order — exactly the general path's tie order
                perm = np.lexsort((-keys, grp))
                counts = np.bincount(grp, minlength=n)
                indptr = np.zeros(n + 1, dtype=np.int64)
                np.cumsum(counts, out=indptr[1:])
                result = _SortedAdjacency(
                    indptr,
                    far[perm].astype(np.int32, copy=False),
                    keys[perm],
                )
        except (IndexError, ValueError):
            return None  # torn build under a concurrent write
        with self._lock:
            if self._version == v0:
                self._sorted_adj[key] = result
        return result

    def cooc_gram(
        self,
        etype: str,
        orientation: str,
        mid_label: Optional[str],
        a_label: Optional[str],
        b_label: Optional[str],
        device_plane=None,
    ) -> Optional[_GramView]:
        """Materialized co-occurrence Gram matrix (see _GramView).
        Returns None when the incidence matrices are over the dense
        budget (cached: the verdict can only flip via invalidate()) or
        when a concurrent write tore the build. With ``device_plane``
        the exact-range contraction runs on device (query/device_graph
        — f32 0/1-integer matmuls are exact below 2^24 on both
        backends, so the integers are equal either way)."""
        key = (etype, orientation, mid_label, a_label, b_label)
        with self._lock:
            if key in self._gram_views:
                return self._gram_views[key]
            v0 = self._version
        inc_a = self.incidence(etype, orientation, mid_label, a_label)
        inc_b = (inc_a if b_label == a_label
                 else self.incidence(etype, orientation, mid_label, b_label))
        result = None
        if inc_a is not None and inc_b is not None:
            ma, a_c, ea, a_pos = inc_a
            mb, b_c, eb, b_pos = inc_b
            if ma.shape[0] != mb.shape[0] or len(ea) != len(eb):
                return None  # mismatched snapshots (raced a write)
            # float32 loses integer exactness past 2^24; cheap upper
            # bound on any per-pair count is n_mid * max(ma) * max(mb)
            if ma.size and mb.size and (
                float(ma.shape[0]) * float(ma.max()) * float(mb.max())
                >= 2.0 ** 24
            ):
                c = ma.astype(np.float64).T @ mb.astype(np.float64)
            else:
                c = None
                if device_plane is not None:
                    c_dev = device_plane.gram_matmul(ma, mb)
                    if c_dev is not None:
                        c = c_dev.astype(np.float64)
                if c is None:
                    c = (ma.T @ mb).astype(np.float64)
            tbl = self.edge_table(etype)
            with self._lock:
                if orientation == "mid_src":
                    mid_e, far_e = tbl.src, tbl.dst
                else:
                    mid_e, far_e = tbl.dst, tbl.src
            if len(far_e) != len(ea):
                return None  # edge table raced a write
            # relationship uniqueness: a match may not use one edge for
            # both hops; such pairs land at (far, far) of each
            # doubly-usable edge
            both = ea & eb
            if both.any():
                flat = a_pos[far_e[both]] * c.shape[1] + b_pos[far_e[both]]
                c -= np.bincount(flat, minlength=c.size).reshape(c.shape)
            try:
                usable = (a_pos[far_e] >= 0) | (b_pos[far_e] >= 0)
                if mid_label is not None:
                    usable &= self.label_mask(mid_label)[mid_e]
                far_lists: Dict[int, List[int]] = {}
                for m_row, f_row in zip(
                    mid_e[usable].tolist(), far_e[usable].tolist()
                ):
                    far_lists.setdefault(m_row, []).append(f_row)
            except (IndexError, ValueError):
                return None
            result = _GramView(
                np.rint(c).astype(np.int64), a_c, b_c, a_pos, b_pos,
                far_lists,
            )
        with self._lock:
            if self._version == v0:
                self._gram_views[key] = result
        return result

    def prop_injective_over(self, prop: str, cands: np.ndarray) -> bool:
        """True when ``prop`` is non-null, scalar and pairwise-distinct
        over candidate rows ``cands`` — the check that lets aggregation
        treat co-occurrence rows as ready-made groups. Memoized per
        candidate array (identity-keyed; see ``_injective``)."""
        key = (prop, id(cands))
        with self._lock:
            hit = self._injective.get(key)
        if hit is not None and hit[0] is cands:
            return hit[1]
        vals = self.node_prop_col(prop)[cands].tolist()
        seen = set()
        verdict = True
        for v in vals:
            if v is None or isinstance(v, (list, dict)) or v in seen:
                verdict = False
                break
            seen.add(v)
        with self._lock:
            self._injective[key] = (cands, verdict)
        return verdict

    def edge_types(self) -> List[str]:
        with self._lock:
            if self._all_edge_types is None:
                types = set()
                for e in self._storage.all_edges():
                    types.add(e.type)
                self._all_edge_types = sorted(types)
            return self._all_edge_types


def expand_hop(
    table: EdgeTable,
    frontier: np.ndarray,
    direction: str,
    n_nodes: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Expand one relationship hop for every row of ``frontier``.

    frontier: int array of global node rows (the current binding column).
    direction: 'out' (frontier is edge source) or 'in' (frontier is edge
    target). Returns (row_repeat, edge_rows, targets):

    - row_repeat: for each produced match, the index into ``frontier`` it
      came from (so sibling binding columns can be np.take'd).
    - edge_rows: the edge-table row of the traversed edge.
    - targets: the global node row reached.

    Fully vectorized (no per-row Python loop): the classic
    repeat/cumsum-offset trick over CSR ranges.
    """
    indptr, order = table.csr(direction, n_nodes)
    starts = indptr[frontier]
    counts = indptr[frontier + 1] - starts
    total = int(counts.sum())
    if total == 0:
        empty = np.empty(0, dtype=np.int32)
        return empty, empty, empty
    row_repeat = np.repeat(
        np.arange(len(frontier), dtype=np.int32), counts
    )
    grp_start = np.repeat(starts, counts)
    grp_off = np.repeat(np.cumsum(counts) - counts, counts)
    within = np.arange(total, dtype=np.int64) - grp_off
    edge_rows = order[grp_start + within]
    if direction == "out":
        targets = table.dst[edge_rows]
    else:
        targets = table.src[edge_rows]
    return row_repeat, edge_rows.astype(np.int32), targets


def group_codes(cols: List[np.ndarray]) -> Tuple[np.ndarray, List[np.ndarray]]:
    """Encode rows of ``cols`` (parallel arrays) into dense group codes.

    Returns (codes[int64 per row], uniques-per-col) where equal rows get
    equal codes in [0, n_groups). Mixed-type object columns are handled
    by per-column np.unique on a sort-stable key.
    """
    if not cols:
        return np.zeros(0, dtype=np.int64), []
    inv_total = np.zeros(len(cols[0]), dtype=np.int64)
    uniques: List[np.ndarray] = []
    for col in cols:
        uniq, inv = _unique_inverse(col)
        uniques.append(uniq)
        inv_total = inv_total * max(len(uniq), 1) + inv
    # re-densify combined codes
    _, codes = np.unique(inv_total, return_inverse=True)
    return codes, uniques


def _unique_inverse(col: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    if col.dtype != object:
        return np.unique(col, return_inverse=True)
    # object column: hash via Python dict (stable, handles mixed types)
    table: Dict[Any, int] = {}
    inv = np.empty(len(col), dtype=np.int64)
    uniq: List[Any] = []
    for i, v in enumerate(col.tolist()):
        key = (type(v).__name__, v) if not isinstance(v, (list, dict)) else (
            "repr", repr(v)
        )
        j = table.get(key)
        if j is None:
            j = len(uniq)
            table[key] = j
            uniq.append(v)
        inv[i] = j
    u = np.empty(len(uniq), dtype=object)
    for i, v in enumerate(uniq):
        u[i] = v
    return u, inv
