"""Data retention policies.

Reference: pkg/retention/retention.go — label/age-based retention rules
swept periodically; nodes past their window are archived or deleted.
Also carries the GDPR delete/export helpers the HTTP admin surface uses
(reference: pkg/server GDPR export/delete routes).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from nornicdb_tpu.storage.types import Engine, Node, now_ms


@dataclass
class RetentionPolicy:
    """Delete-or-archive rule for one label (empty label = all nodes)."""

    name: str
    max_age_days: float
    label: str = ""
    action: str = "archive"  # archive | delete
    property_filter: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SweepResult:
    scanned: int = 0
    archived: int = 0
    deleted: int = 0


class RetentionManager:
    def __init__(self, storage: Engine):
        self.storage = storage
        self._policies: Dict[str, RetentionPolicy] = {}
        self._lock = threading.Lock()

    def add_policy(self, policy: RetentionPolicy) -> None:
        if policy.action not in ("archive", "delete"):
            raise ValueError(f"invalid action: {policy.action}")
        with self._lock:
            self._policies[policy.name] = policy

    def remove_policy(self, name: str) -> bool:
        with self._lock:
            return self._policies.pop(name, None) is not None

    def policies(self) -> List[RetentionPolicy]:
        with self._lock:
            return list(self._policies.values())

    def _matches(self, policy: RetentionPolicy, node: Node, now: int) -> bool:
        if policy.label and policy.label not in node.labels:
            return False
        for k, v in policy.property_filter.items():
            if node.properties.get(k) != v:
                return False
        ts = node.updated_at or node.created_at
        if not ts:
            return False
        return (now - ts) > policy.max_age_days * 86_400_000

    def sweep(self, now: Optional[int] = None) -> SweepResult:
        now = now if now is not None else now_ms()
        res = SweepResult()
        with self._lock:
            policies = list(self._policies.values())
        if not policies:
            return res
        for node in list(self.storage.all_nodes()):
            res.scanned += 1
            for p in policies:
                if not self._matches(p, node, now):
                    continue
                if p.action == "delete":
                    try:
                        self.storage.delete_node(node.id)
                        res.deleted += 1
                    except KeyError:
                        pass
                elif not node.properties.get("_archived"):
                    node.properties["_archived"] = True
                    node.properties["_archived_at"] = now
                    try:
                        self.storage.update_node(node)
                        res.archived += 1
                    except KeyError:
                        pass
                break  # first matching policy wins
        return res


# -- GDPR helpers (reference: pkg/server GDPR export/delete) ----------------


def gdpr_export(storage: Engine, match_property: str, match_value: Any) -> Dict[str, Any]:
    """Export every node (and its edges) whose property matches — the
    data-subject access request path."""
    nodes = [n for n in storage.all_nodes()
             if n.properties.get(match_property) == match_value]
    ids = {n.id for n in nodes}
    edges = [e for e in storage.all_edges()
             if e.start_node in ids or e.end_node in ids]
    return {
        "exported_at_ms": int(time.time() * 1000),
        "match": {match_property: match_value},
        "nodes": [n.to_dict() for n in nodes],
        "edges": [e.to_dict() for e in edges],
    }


def gdpr_delete(storage: Engine, match_property: str, match_value: Any) -> int:
    """Hard-delete all matching nodes (edges cascade). Returns count."""
    ids = [n.id for n in storage.all_nodes()
           if n.properties.get(match_property) == match_value]
    deleted = 0
    for nid in ids:
        try:
            storage.delete_node(nid)
            deleted += 1
        except KeyError:
            pass
    return deleted
