"""Data retention policies.

Reference: pkg/retention/retention.go — label/age-based retention rules
swept periodically; nodes past their window are archived or deleted.
Also carries the GDPR delete/export helpers the HTTP admin surface uses
(reference: pkg/server GDPR export/delete routes).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from nornicdb_tpu.storage.types import Engine, Node, now_ms


@dataclass
class RetentionPolicy:
    """Delete-or-archive rule for one label (empty label = all nodes)."""

    name: str
    max_age_days: float
    label: str = ""
    action: str = "archive"  # archive | delete
    property_filter: Dict[str, Any] = field(default_factory=dict)
    # compliance annotations (reference: retention.go package doc —
    # policies cite the framework that mandates them)
    category: str = ""       # pii | audit | financial | health | ""
    framework: str = ""      # e.g. "GDPR Art.5(1)(e)", "SOX", "HIPAA"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name, "max_age_days": self.max_age_days,
            "label": self.label, "action": self.action,
            "property_filter": dict(self.property_filter),
            "category": self.category, "framework": self.framework,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RetentionPolicy":
        return cls(
            name=d["name"], max_age_days=float(d["max_age_days"]),
            label=d.get("label", ""), action=d.get("action", "archive"),
            property_filter=dict(d.get("property_filter", {})),
            category=d.get("category", ""),
            framework=d.get("framework", ""),
        )


def default_policies() -> List[RetentionPolicy]:
    """Compliance-framework defaults (reference: retention.go
    DefaultPolicies — GDPR storage limitation, HIPAA 6y, FISMA audit,
    SOX 7y financial records)."""
    return [
        RetentionPolicy(
            name="gdpr-pii", label="PII", max_age_days=3 * 365,
            action="delete", category="pii",
            framework="GDPR Art.5(1)(e)"),
        RetentionPolicy(
            name="hipaa-health", label="Health", max_age_days=6 * 365,
            action="archive", category="health",
            framework="HIPAA §164.530(j)"),
        RetentionPolicy(
            name="fisma-audit", label="Audit", max_age_days=6 * 365,
            action="archive", category="audit", framework="FISMA AU-11"),
        RetentionPolicy(
            name="sox-financial", label="Financial",
            max_age_days=7 * 365, action="archive", category="financial",
            framework="SOX"),
        RetentionPolicy(
            name="soc2-records", label="Record", max_age_days=7 * 365,
            action="archive", category="audit", framework="SOC2 CC7.4"),
    ]


@dataclass
class SweepResult:
    scanned: int = 0
    archived: int = 0
    deleted: int = 0
    held: int = 0  # deletions blocked by a legal hold


class RetentionManager:
    """Policy registry + sweeper with legal holds and archive-before-
    delete (reference: retention.go — legal hold support 'prevents
    deletion during litigation'; SetArchiveCallback)."""

    def __init__(self, storage: Engine, archive_callback=None):
        self.storage = storage
        self._policies: Dict[str, RetentionPolicy] = {}
        self._lock = threading.Lock()
        # subject property -> held values (legal holds)
        self._holds: Dict[str, set] = {}
        # called with the node dict before a delete-action removal
        self.archive_callback = archive_callback

    # -- legal holds (retention.go: legal hold support) -------------------

    def add_legal_hold(self, match_property: str, match_value: Any) -> None:
        """Nodes whose ``match_property`` equals ``match_value`` are
        exempt from retention deletion and GDPR erasure until the hold
        is released."""
        with self._lock:
            self._holds.setdefault(match_property, set()).add(match_value)

    def release_legal_hold(self, match_property: str, match_value: Any) -> bool:
        with self._lock:
            vals = self._holds.get(match_property)
            if vals and match_value in vals:
                vals.discard(match_value)
                if not vals:
                    self._holds.pop(match_property)
                return True
            return False

    def legal_holds(self) -> Dict[str, List[Any]]:
        with self._lock:
            return {k: sorted(v, key=str) for k, v in self._holds.items()}

    def is_held(self, node: Node) -> bool:
        with self._lock:
            holds = {k: set(v) for k, v in self._holds.items()}
        return any(
            node.properties.get(k) in vals for k, vals in holds.items()
        )

    # -- persistence (retention.go: policy save/load from JSON) -----------

    def save_policies(self, path: str) -> None:
        with self._lock:
            data = [p.to_dict() for p in self._policies.values()]
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"policies": data}, f, indent=1)

    def load_policies(self, path: str) -> int:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        n = 0
        for d in data.get("policies", []):
            self.add_policy(RetentionPolicy.from_dict(d))
            n += 1
        return n

    def add_policy(self, policy: RetentionPolicy) -> None:
        if policy.action not in ("archive", "delete"):
            raise ValueError(f"invalid action: {policy.action}")
        with self._lock:
            self._policies[policy.name] = policy

    def remove_policy(self, name: str) -> bool:
        with self._lock:
            return self._policies.pop(name, None) is not None

    def policies(self) -> List[RetentionPolicy]:
        with self._lock:
            return list(self._policies.values())

    def _matches(self, policy: RetentionPolicy, node: Node, now: int) -> bool:
        if policy.label and policy.label not in node.labels:
            return False
        for k, v in policy.property_filter.items():
            if node.properties.get(k) != v:
                return False
        ts = node.updated_at or node.created_at
        if not ts:
            return False
        return (now - ts) > policy.max_age_days * 86_400_000

    def sweep(self, now: Optional[int] = None) -> SweepResult:
        now = now if now is not None else now_ms()
        res = SweepResult()
        with self._lock:
            policies = list(self._policies.values())
        if not policies:
            return res
        for node in list(self.storage.all_nodes()):
            res.scanned += 1
            for p in policies:
                if not self._matches(p, node, now):
                    continue
                if p.action == "delete":
                    if self.is_held(node):
                        res.held += 1
                        break  # legal hold: no deletion while held
                    if self.archive_callback is not None:
                        # archive-before-delete (retention.go)
                        self.archive_callback(node.to_dict())
                    try:
                        self.storage.delete_node(node.id)
                        res.deleted += 1
                    except KeyError:
                        pass
                elif not node.properties.get("_archived"):
                    node.properties["_archived"] = True
                    node.properties["_archived_at"] = now
                    try:
                        self.storage.update_node(node)
                        res.archived += 1
                    except KeyError:
                        pass
                break  # first matching policy wins
        return res


# -- GDPR helpers (reference: pkg/server GDPR export/delete) ----------------


def gdpr_export(storage: Engine, match_property: str, match_value: Any) -> Dict[str, Any]:
    """Export every node (and its edges) whose property matches — the
    data-subject access request path."""
    nodes = [n for n in storage.all_nodes()
             if n.properties.get(match_property) == match_value]
    ids = {n.id for n in nodes}
    edges = [e for e in storage.all_edges()
             if e.start_node in ids or e.end_node in ids]
    return {
        "exported_at_ms": int(time.time() * 1000),
        "match": {match_property: match_value},
        "nodes": [n.to_dict() for n in nodes],
        "edges": [e.to_dict() for e in edges],
    }


def gdpr_delete(storage: Engine, match_property: str, match_value: Any,
                retention: Optional[RetentionManager] = None) -> int:
    """Hard-delete all matching nodes (edges cascade). Returns count.
    When a RetentionManager is supplied, erasure respects its legal
    holds (reference: ProcessErasure 'respects legal holds')."""
    matches = [n for n in storage.all_nodes()
               if n.properties.get(match_property) == match_value]
    deleted = 0
    for node in matches:
        if retention is not None and retention.is_held(node):
            continue
        try:
            storage.delete_node(node.id)
            deleted += 1
        except KeyError:
            pass
    return deleted
