"""Multi-host distributed execution: DCN x ICI meshes and per-process
data feeding.

Reference: the reference scales across machines with an NCCL/MPI cluster
backend (pkg/cluster). The TPU-native equivalent is JAX multi-process
execution: every host runs this same program, ``init_distributed`` wires
them into one runtime (coordinator handshake), and collectives are
placed by mesh axis so that the slow cross-host hops ride the *leading*
mesh axis (DCN) while bandwidth-hungry tp/sp/ep collectives stay inside
a host's ICI domain — the scaling-book layout rule.

Single-host processes (the common dev case, and this repo's test
environment) degrade gracefully: ``init_distributed`` is a no-op when no
coordinator is configured and ``hybrid_mesh`` collapses to an ordinary
mesh with a singleton dcn axis.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Dict[str, int]:
    """Join the multi-process JAX runtime.

    Arguments default from the standard environment variables
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID, the
    TPU pod launcher contract). With no coordinator configured this is a
    single-process no-op — the same binary runs unchanged on a laptop,
    one TPU host, or a pod slice.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address:
        num_processes = int(num_processes
                            or os.environ.get("JAX_NUM_PROCESSES", "1"))
        process_id = int(process_id
                         or os.environ.get("JAX_PROCESS_ID", "0"))
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def hybrid_mesh(
    ici_axes: Dict[str, int],
    dcn_axis: str = "dcn",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh with a leading cross-host (DCN) axis and intra-host (ICI)
    axes, e.g. hybrid_mesh({"tp": 2, "sp": 2}) on a 2-host x 4-chip
    topology -> Mesh('dcn'=2, 'tp'=2, 'sp'=2).

    The leading axis spans hosts, so only collectives over ``dcn_axis``
    (typically the data-parallel gradient all-reduce) cross the data
    center network; tp/sp/ep traffic stays on ICI. Falls back to a
    singleton dcn axis in single-process runs.
    """
    devices = list(devices if devices is not None else jax.devices())
    n_proc = max(
        len({getattr(d, "process_index", 0) for d in devices}), 1)
    per_host = len(devices) // n_proc
    ici_size = 1
    for v in ici_axes.values():
        ici_size *= v
    if per_host % ici_size != 0:
        raise ValueError(
            f"ici axes {ici_axes} (size {ici_size}) do not divide the "
            f"{per_host} devices per host")
    dcn = len(devices) // ici_size
    shape = (dcn,) + tuple(ici_axes.values())
    if n_proc > 1:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(per_host // ici_size,) + tuple(ici_axes.values()),
            dcn_mesh_shape=(n_proc,) + (1,) * len(ici_axes),
            devices=devices,
        )
        arr = arr.reshape(shape)
    else:
        arr = np.array(devices).reshape(shape)
    return Mesh(arr, axis_names=(dcn_axis,) + tuple(ici_axes.keys()))


def process_local_batch(
    mesh: Mesh,
    local_data: np.ndarray,
    batch_axis: str = "dcn",
) -> jax.Array:
    """Assemble the global batch array from this process's local shard.

    Every host loads only its own slice of the batch (the data-loader
    contract of multi-host training); the returned jax.Array is globally
    sharded over ``batch_axis`` without any host ever materializing the
    full batch.
    """
    sharding = NamedSharding(
        mesh, P(batch_axis, *([None] * (local_data.ndim - 1))))
    if jax.process_count() == 1:
        return jax.device_put(local_data, sharding)
    return jax.make_array_from_process_local_data(sharding, local_data)


def replicate_to_mesh(mesh: Mesh, value: np.ndarray) -> jax.Array:
    """Place ``value`` fully replicated on every mesh device."""
    return jax.device_put(value, NamedSharding(mesh, P()))


def dcn_allreduce_bytes_per_step(
    param_count: int, dtype_bytes: int = 4, dcn_size: int = 2
) -> Tuple[int, str]:
    """Back-of-envelope: bytes each host exchanges over DCN per gradient
    all-reduce (ring: 2 * (n-1)/n * payload). Exposed for capacity
    planning in deployment docs/tests."""
    payload = param_count * dtype_bytes
    per_host = int(2 * (dcn_size - 1) / dcn_size * payload)
    return per_host, (
        f"{per_host / 1e6:.1f} MB/host/step over DCN for "
        f"{param_count / 1e6:.1f}M params at {dtype_bytes}B")
