"""Pipeline (pp) and expert (ep) parallelism over the device mesh.

Completes the five-axis sharding story (dp/tp/sp from models/train.py,
pp/ep here). TPU-first design, not a translation: stages and experts are
laid out with ``shard_map`` over named mesh axes and the collectives are
explicit XLA primitives that ride ICI — ``ppermute`` moves microbatch
activations between pipeline stages (GPipe schedule) and ``all_to_all``
does MoE token dispatch/combine (GShard top-1 gating with capacity).

Both transforms are differentiable end to end (ppermute/all_to_all have
transposes), so ``jax.grad`` through a pp x ep step works — the dryrun
executes exactly that.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from nornicdb_tpu.parallel.mesh import compat_shard_map


# -- pipeline parallelism -------------------------------------------------
#
# Model: a stack of identical MLP blocks, one (or more) per stage. Stage
# parameters live stacked on a leading [pp] axis, sharded so each device
# along 'pp' holds only its own stage weights. The GPipe schedule runs
# n_micro + pp - 1 ticks; on every tick each stage processes the
# activation it holds, then the ring ppermutes activations forward.


def init_pipeline_params(
    rng: jax.Array, n_stages: int, width: int, scale: float = 0.02
) -> Dict[str, jnp.ndarray]:
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (n_stages, width, width)) * scale,
        "b1": jnp.zeros((n_stages, width)),
        "w2": jax.random.normal(k2, (n_stages, width, width)) * scale,
        "b2": jnp.zeros((n_stages, width)),
    }


def _stage_block(params: Dict[str, jnp.ndarray], x: jnp.ndarray) -> jnp.ndarray:
    """One residual MLP stage; params are this stage's [1, ...] slices."""
    h = x @ params["w1"][0] + params["b1"][0]
    h = jax.nn.gelu(h)
    return x + h @ params["w2"][0] + params["b2"][0]


def pipeline_apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    mesh: Mesh,
    n_microbatches: int,
    batch_axis: str = None,
) -> jnp.ndarray:
    """Run x [B, D] through the pp-staged network; B % n_microbatches == 0.

    Inside shard_map each 'pp' device sees its own stage params and the
    full microbatch stream. Tick t: stage s processes the activation
    that entered the pipe at microbatch t - s; a forward ppermute ring
    then advances activations one stage. Output microbatch i leaves the
    last stage at tick i + pp - 1 and is captured there.

    ``batch_axis`` names a second mesh axis to shard the rows of each
    microbatch over (the combined pp x ep step passes "ep") so those
    devices each process their slice instead of replicating the whole
    pipeline compute; rows must divide evenly.
    """
    pp = mesh.shape["pp"]
    batch, width = x.shape
    assert batch % n_microbatches == 0
    micro = batch // n_microbatches
    if batch_axis is not None:
        assert micro % mesh.shape[batch_axis] == 0, (
            f"microbatch rows {micro} not divisible by "
            f"{batch_axis}={mesh.shape[batch_axis]}")
    n_ticks = n_microbatches + pp - 1
    xs = x.reshape(n_microbatches, micro, width)

    def staged(local_params, xs_local):
        idx = jax.lax.axis_index("pp")
        perm = [(i, (i + 1) % pp) for i in range(pp)]
        rows_local = xs_local.shape[1]  # micro / ep when batch_axis set

        def tick(carry, t):
            held, outputs = carry
            # stage 0 ingests microbatch t (zero-padded past the end)
            feed = jnp.where(
                t < n_microbatches,
                xs_local[jnp.minimum(t, n_microbatches - 1)],
                jnp.zeros((rows_local, width), xs_local.dtype),
            )
            held = jnp.where(idx == 0, feed, held)
            out = _stage_block(local_params, held)
            # the last stage emits microbatch t - (pp - 1) at this tick
            emit_slot = t - (pp - 1)
            is_emit = jnp.logical_and(idx == pp - 1, emit_slot >= 0)
            outputs = jax.lax.cond(
                is_emit,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, out, jnp.maximum(emit_slot, 0), axis=0),
                lambda o: o,
                outputs,
            )
            # advance the ring: stage s's output becomes s+1's input
            held = jax.lax.ppermute(out, "pp", perm)
            return (held, outputs), None

        init = (
            jnp.zeros((rows_local, width), xs_local.dtype),
            jnp.zeros((n_microbatches, rows_local, width), xs_local.dtype),
        )
        (held, outputs), _ = jax.lax.scan(
            tick, init, jnp.arange(n_ticks))
        # only the last stage holds real outputs; psum broadcasts them
        outputs = jnp.where(idx == pp - 1, outputs, 0.0)
        return jax.lax.psum(outputs, "pp")

    data_spec = P(None, batch_axis) if batch_axis else P()
    out = compat_shard_map(
        staged,
        mesh=mesh,
        in_specs=(P("pp"), data_spec),
        out_specs=data_spec,
    )(params, xs)
    return out.reshape(batch, width)


# -- expert parallelism (MoE) ---------------------------------------------
#
# GShard-style top-1 routing with a fixed per-expert capacity. Tokens are
# sharded over 'ep' (data-parallel along the same axis the experts live
# on); dispatch/combine are einsums against a one-hot dispatch tensor and
# the cross-device exchange is a single all_to_all each way.


def init_moe_params(
    rng: jax.Array, n_experts: int, width: int, hidden: int,
    scale: float = 0.02,
) -> Dict[str, jnp.ndarray]:
    k1, k2, k3 = jax.random.split(rng, 3)
    return {
        "router": jax.random.normal(k1, (width, n_experts)) * scale,
        "wi": jax.random.normal(k2, (n_experts, width, hidden)) * scale,
        "wo": jax.random.normal(k3, (n_experts, hidden, width)) * scale,
    }


def moe_apply(
    params: Dict[str, jnp.ndarray],
    x: jnp.ndarray,
    mesh: Mesh,
    capacity_factor: float = 1.25,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 MoE layer: x [B, D] -> (y [B, D], aux_loss).

    B is sharded over 'ep'; expert weights are sharded over 'ep' (expert
    e lives on device e * n_local_experts). Router weights are
    replicated. aux_loss is the standard load-balancing term.
    """
    ep = mesh.shape["ep"]
    n_experts = params["wi"].shape[0]
    assert n_experts % ep == 0

    def local(params_local, x_local):
        router, wi, wo = (params_local["router"], params_local["wi"],
                          params_local["wo"])
        b_local, width = x_local.shape
        capacity = max(int(capacity_factor * b_local / n_experts), 1)

        scores = jax.nn.softmax(x_local @ router, axis=-1)  # [b, E]
        expert = jnp.argmax(scores, axis=-1)                # [b]
        gate = jnp.max(scores, axis=-1)                     # [b]
        onehot = jax.nn.one_hot(expert, n_experts, dtype=x_local.dtype)

        # position of each token within its expert's queue
        pos = jnp.cumsum(onehot, axis=0) * onehot  # [b, E]
        rank = (jnp.sum(pos, axis=-1) - 1.0).astype(jnp.int32)
        keep = rank < capacity  # overflow tokens are dropped (std MoE)
        # dispatch tensor [b, E, C]
        dispatch = (onehot[:, :, None]
                    * jax.nn.one_hot(rank, capacity,
                                     dtype=x_local.dtype)[:, None, :])
        dispatch = dispatch * keep[:, None, None].astype(x_local.dtype)

        # load-balancing aux loss (GShard eq. 4)
        density = jnp.mean(onehot, axis=0)
        density_proxy = jnp.mean(scores, axis=0)
        aux = jnp.sum(density * density_proxy) * (n_experts ** 2) / 100.0

        # [E, C, D] expert inputs, exchanged so each device holds the
        # token slots of ITS experts from EVERY device. Expert ids are
        # owner-major: e = owner * n_local + e_local.
        n_local = n_experts // ep
        slots = jnp.einsum("bec,bd->ecd", dispatch, x_local)
        slots = slots.reshape(ep, n_local, capacity, width)
        # split the owner axis; received chunks stack on a new source
        # axis at position 2: [n_local, C, src, D]
        slots = jax.lax.all_to_all(
            slots, "ep", split_axis=0, concat_axis=2, tiled=False)
        slots = jnp.moveaxis(slots, 2, 1).reshape(
            n_local, ep * capacity, width)
        h = jax.nn.gelu(jnp.einsum("ecd,edh->ech", slots, wi))
        y = jnp.einsum("ech,ehd->ecd", h, wo)
        # inverse exchange: split the source axis, stack owners at 0
        y = y.reshape(n_local, ep, capacity, width)
        y = jax.lax.all_to_all(
            y, "ep", split_axis=1, concat_axis=0, tiled=False)
        y = y.reshape(n_experts, capacity, width)
        out = jnp.einsum("bec,ecd->bd", dispatch, y) * gate[:, None]
        return out, jax.lax.pmean(aux, "ep")

    return compat_shard_map(
        local,
        mesh=mesh,
        in_specs=(
            {"router": P(), "wi": P("ep"), "wo": P("ep")},
            P("ep"),
        ),
        out_specs=(P("ep"), P()),
    )(params, x)


# -- combined pp x ep training step ---------------------------------------


def make_pp_ep_train_step(
    mesh: Mesh,
    width: int,
    hidden: int,
    n_microbatches: int,
    learning_rate: float = 1e-3,
):
    """A jitted train step for a pipeline of MLP stages followed by an
    expert-parallel MoE head, over a (pp, ep) mesh. Returns
    (init_params_fn, step_fn); step_fn(params, x, y) -> (params, loss).
    """
    pp = mesh.shape["pp"]
    ep = mesh.shape["ep"]

    def init_params(rng):
        r1, r2 = jax.random.split(rng)
        params = {
            "pipe": init_pipeline_params(r1, pp, width),
            "moe": init_moe_params(r2, ep, width, hidden),
        }
        shardings = {
            "pipe": jax.tree.map(
                lambda _: NamedSharding(mesh, P("pp")), params["pipe"]),
            "moe": {
                "router": NamedSharding(mesh, P()),
                "wi": NamedSharding(mesh, P("ep")),
                "wo": NamedSharding(mesh, P("ep")),
            },
        }
        return jax.device_put(params, shardings), shardings

    def loss_fn(params, x, y):
        h = pipeline_apply(params["pipe"], x, mesh, n_microbatches,
                           batch_axis="ep")
        delta, aux = moe_apply(params["moe"], h, mesh)
        out = h + delta  # residual MoE head
        mse = jnp.mean((out - y) ** 2)
        return mse + 0.01 * aux

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(params, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        params = jax.tree.map(
            lambda p, g: p - learning_rate * g, params, grads)
        return params, loss

    return init_params, step


def make_pp_ep_mesh(n_devices: int, devices=None) -> Mesh:
    """Split devices into (pp, ep): pp gets 2 when possible, ep the rest."""
    pp = 2 if n_devices % 2 == 0 and n_devices >= 4 else 1
    ep = n_devices // pp
    devices = list(devices if devices is not None else jax.devices())
    arr = np.array(devices[:n_devices]).reshape(pp, ep)
    return Mesh(arr, axis_names=("pp", "ep"))
