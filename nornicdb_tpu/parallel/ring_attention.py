"""Ring attention: exact attention over a sequence sharded across devices.

Long-context is first-class in the TPU design (SURVEY.md §2.8): each
device holds S/n query/key/value blocks; K/V blocks rotate around the
``sp`` ring with jax.lax.ppermute (ICI neighbor exchange) while each
device accumulates its queries' attention with the numerically-stable
streaming-softmax (flash/online) update. Compute overlaps the rotation —
no device ever materializes the full [S, S] score matrix or the full K/V.

This is exact (matches dense attention to float tolerance), not an
approximation.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nornicdb_tpu.parallel.mesh import compat_shard_map


def _ring_attention_local(q, k, v, mask, axis_name: str):
    """Per-device body under shard_map.

    q: [B, Sq, H, D] local queries; k/v: [B, Sk, H, D] local K/V block;
    mask: [B, Sk] local key validity. Rotates k/v/mask n-1 times.
    """
    n = jax.lax.psum(1, axis_name)
    scale = q.shape[-1] ** -0.5

    def attend_block(q, k, v, kmask):
        # [B, H, Sq, Sk]
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        s = jnp.where(kmask[:, None, None, :], s, -1e30)
        m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,Sq,1]
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return o, m[..., 0], l[..., 0]  # o:[B,Sq,H,D], m/l:[B,H,Sq]

    def combine(acc, new):
        o_a, m_a, l_a = acc
        o_n, m_n, l_n = new
        m = jnp.maximum(m_a, m_n)
        ca = jnp.exp(m_a - m)
        cn = jnp.exp(m_n - m)
        o = (
            o_a * jnp.transpose(ca, (0, 2, 1))[..., None]
            + o_n * jnp.transpose(cn, (0, 2, 1))[..., None]
        )
        l = l_a * ca + l_n * cn
        return o, m, l

    def step(carry, _):
        (k, v, kmask), acc = carry
        new = attend_block(q, k, v, kmask)
        acc = combine(acc, new)
        # rotate K/V block to the next device on the ring (ICI neighbor)
        perm = [(i, (i + 1) % n) for i in range(n)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        kmask = jax.lax.ppermute(kmask, axis_name, perm)
        return ((k, v, kmask), acc), None

    b, sq, h, d = q.shape
    acc0 = (
        jnp.zeros((b, sq, h, d), jnp.float32),
        jnp.full((b, h, sq), -jnp.inf, jnp.float32),
        jnp.zeros((b, h, sq), jnp.float32),
    )
    ((_, _, _), (o, m, l)), _ = jax.lax.scan(
        step,
        ((k.astype(jnp.float32), v.astype(jnp.float32), mask), acc0),
        None,
        length=n,
    )
    l = jnp.maximum(l, 1e-30)
    return (o / jnp.transpose(l, (0, 2, 1))[..., None]).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, S, H, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,  # [B, S] key validity
    mesh: Optional[Mesh] = None,
    axis_name: str = "sp",
    batch_axis: Optional[str] = None,  # mesh axis carrying the batch (dp)
    head_axis: Optional[str] = None,  # mesh axis carrying the heads (tp)
) -> jnp.ndarray:
    """Exact attention with the sequence dim sharded over ``axis_name``.

    ``batch_axis``/``head_axis`` declare how B and H are already sharded on
    the same mesh so the ring only rotates over the sequence axis (no
    spurious gathers of dp/tp-sharded operands). Outside a mesh (or axis
    size 1) this degrades to dense attention."""
    if mask is None:
        mask = jnp.ones(q.shape[:2], dtype=bool)
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return _dense_attention(q, k, v, mask)

    qkv_spec = P(batch_axis, axis_name, head_axis, None)
    fn = compat_shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(batch_axis, axis_name)),
        out_specs=qkv_spec,
    )
    return fn(q, k, v, mask)


def _dense_attention(q, k, v, mask):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(q.dtype)
