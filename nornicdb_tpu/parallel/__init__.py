"""Parallelism layer: device meshes, sharding rules, collectives.

The reference's distributed story is a CPU RPC mesh (pkg/replication
transport.go) plus single-device GPU kernels; the TPU-native design keeps
a host-side control plane (replication module) and moves the bulk data
plane onto XLA collectives over ICI/DCN (SURVEY.md §2.8, §5).
"""

from nornicdb_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    best_mesh,
    compat_shard_map,
    data_mesh,
    make_mesh,
    sharded_cosine_topk,
)
