"""Device mesh construction + sharded vector-search collectives.

Multi-chip kNN: the embedding matrix is row-sharded across the ``data``
mesh axis (each chip holds C/n rows in its HBM); every chip computes its
local top-k and the results merge with one all-gather over ICI. This is
the TPU-native replacement for the reference's single-GPU search fan-out
(pkg/gpu/accelerator.go GPUEmbeddingIndex.Search) and scales it to slices.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MeshSpec:
    """Named mesh axes used across the framework.

    - ``dp``: data parallel (batch)
    - ``tp``: tensor parallel (hidden/heads)
    - ``sp``: sequence/context parallel (ring attention)
    """

    dp: int = 1
    tp: int = 1
    sp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.tp * self.sp


def best_mesh(n_devices: int) -> MeshSpec:
    """Factor a device count into (dp, tp, sp) favoring dp (batch) first,
    then tp, then sp — the right default for embedding inference."""
    dp, tp, sp = 1, 1, 1
    rem = n_devices
    # give tp the smallest prime factor pack up to 4, sp up to 2, dp the rest
    if rem % 2 == 0 and rem >= 4:
        tp = 2
        rem //= 2
    if rem % 2 == 0 and rem >= 4:
        sp = 2
        rem //= 2
    dp = rem
    return MeshSpec(dp=dp, tp=tp, sp=sp)


def make_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if spec is None:
        spec = MeshSpec(dp=len(devices))
    if spec.size != len(devices):
        raise ValueError(f"mesh spec {spec} does not cover {len(devices)} devices")
    arr = np.array(devices).reshape(spec.dp, spec.tp, spec.sp)
    return Mesh(arr, axis_names=("dp", "tp", "sp"))


def data_mesh(n: Optional[int] = None) -> Mesh:
    """1-D mesh over all (or n) devices for row-sharded vector search."""
    devices = jax.devices()[: n or len(jax.devices())]
    return Mesh(np.array(devices), axis_names=("data",))


def compat_shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions: ``jax.shard_map`` (with
    ``check_vma``) on new releases, ``jax.experimental.shard_map`` (with
    ``check_rep``) on 0.4.x — both replication checks disabled, since
    the local top-k bodies intentionally mix replicated queries with
    sharded rows."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def mesh_context(mesh: Mesh):
    """Trace-time mesh scope across jax versions: ``jax.set_mesh`` on
    new releases; on 0.4.x a Mesh is its own context manager (both make
    raw-PartitionSpec sharding constraints resolvable inside jit)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh


@functools.partial(jax.jit, static_argnames=("k", "mesh_holder"))
def _sharded_topk_impl(queries, matrix, valid, k, mesh_holder):
    mesh = mesh_holder.mesh
    n_shards = mesh.shape["data"]
    shard_rows = matrix.shape[0] // n_shards
    # every member of the global top-k is within the top-min(k, rows) of its
    # own shard, so gathering local_k per shard merges to the EXACT top-k
    local_k = min(k, shard_rows)

    def local_topk(q, m, v):
        # q: [B, D] replicated; m: [rows/n, D]; v: [rows/n]
        scores = q @ m.T
        scores = jnp.where(v[None, :], scores, -1e30)
        s, i = jax.lax.top_k(scores, local_k)
        # local indices -> global row ids
        shard = jax.lax.axis_index("data")
        gi = i + shard * shard_rows
        # merge across shards over ICI
        all_s = jax.lax.all_gather(s, "data", axis=1, tiled=True)  # [B, n*local_k]
        all_i = jax.lax.all_gather(gi, "data", axis=1, tiled=True)
        top_s, pos = jax.lax.top_k(all_s, k)
        top_i = jnp.take_along_axis(all_i, pos, axis=1)
        return top_s, top_i

    return compat_shard_map(
        local_topk,
        mesh=mesh,
        in_specs=(P(), P("data", None), P("data")),
        out_specs=(P(), P()),
    )(queries, matrix, valid)


class _MeshHolder:
    """Hashable wrapper so a Mesh can ride through static_argnames."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    def __hash__(self):
        return hash(
            (tuple(self.mesh.axis_names), tuple(d.id for d in self.mesh.devices.flat))
        )

    def __eq__(self, other):
        return (
            isinstance(other, _MeshHolder)
            and tuple(self.mesh.axis_names) == tuple(other.mesh.axis_names)
            and [d.id for d in self.mesh.devices.flat]
            == [d.id for d in other.mesh.devices.flat]
        )


def sharded_cosine_topk(
    queries: jnp.ndarray,
    matrix: jnp.ndarray,
    valid: jnp.ndarray,
    k: int,
    mesh: Optional[Mesh] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Multi-device exact kNN: row-shard ``matrix`` over the mesh's
    ``data`` axis, local top-k per chip, one all-gather merge.
    ``matrix.shape[0]`` must be divisible by the shard count (use
    ops.similarity.pad_dim capacity + valid mask)."""
    mesh = mesh or data_mesh()
    n = mesh.shape["data"]
    c = matrix.shape[0]
    if c % n != 0:
        raise ValueError(f"capacity {c} not divisible by {n} shards")
    k = min(k, c)
    sharding = NamedSharding(mesh, P("data", None))
    matrix = jax.device_put(matrix, sharding)
    valid = jax.device_put(valid, NamedSharding(mesh, P("data")))
    queries = jax.device_put(queries, NamedSharding(mesh, P()))
    return _sharded_topk_impl(queries, matrix, valid, k, _MeshHolder(mesh))
