"""Replication: control plane for HA standby, Raft, and multi-region.

Reference: pkg/replication — modes standalone/ha_standby/raft/multi_region
(config.go:104-129), sync modes async/quorum (config.go:133-142), Raft
elections (raft.go:14-60), HA standby WAL streaming + heartbeat + fencing
+ auto-failover (ha_standby.go:170-779), ReplicatedEngine
(replicated_engine.go), custom TCP cluster transport (transport.go:53-158).

TPU-native split (SURVEY.md §5 "Distributed communication backend"):
the consensus/metadata control plane stays on the host CPU over this TCP
mesh; bulk vector data movement (index shard rebuilds, replica embedding
sync, multi-chip search fan-out) rides XLA collectives over ICI/DCN —
see nornicdb_tpu.parallel.mesh (sharded kNN psum/all_gather paths).
"""

from nornicdb_tpu.replication.transport import (
    ClusterMessage,
    ClusterTransport,
    DualPlaneTransport,
)
from nornicdb_tpu.replication.replicator import (
    NotPrimaryError,
    ReplicationConfig,
    Replicator,
    Role,
)
from nornicdb_tpu.replication.replicated_engine import ReplicatedEngine
from nornicdb_tpu.replication.ha_standby import HAPrimary, HAStandby
from nornicdb_tpu.replication.raft import RaftNode


def __getattr__(name):
    # read-fleet classes resolve lazily: read_fleet imports the DB
    # facade (and through it the API layers), so an eager import here
    # would cycle db.py -> replication -> read_fleet -> db.py
    if name in ("FleetStandby", "ReadFleet", "ReadReplica"):
        from nornicdb_tpu.replication import read_fleet

        return getattr(read_fleet, name)
    raise AttributeError(name)



from nornicdb_tpu.replication.multi_region import (
    MultiRegionNode,
    NotPrimaryRegionError,
)

__all__ = [
    "ClusterMessage",
    "ClusterTransport",
    "DualPlaneTransport",
    "FleetStandby",
    "HAPrimary",
    "HAStandby",
    "ReadFleet",
    "ReadReplica",
    "MultiRegionNode",
    "NotPrimaryError",
    "NotPrimaryRegionError",
    "RaftNode",
    "ReplicatedEngine",
    "ReplicationConfig",
    "Replicator",
    "Role",
]
