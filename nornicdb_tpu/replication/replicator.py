"""Replicator contract + configuration.

Reference: pkg/replication/replicator.go:53 (Replicator.Apply — every
write on a replicated node routes through the replicator), config.go:
104-142 (modes standalone/ha_standby/raft/multi_region; sync modes
async/quorum).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple


class Role(str, enum.Enum):
    PRIMARY = "primary"
    STANDBY = "standby"
    CANDIDATE = "candidate"  # raft only


class NotPrimaryError(RuntimeError):
    """Raised when a write lands on a non-primary replica; carries the
    current leader hint so API layers can redirect."""

    def __init__(self, leader: Optional[str] = None):
        super().__init__(
            "not primary" + (f" (leader: {leader})" if leader else "")
        )
        self.leader = leader


@dataclass
class ReplicationConfig:
    """Reference: config.go:104-142."""

    mode: str = "standalone"  # standalone | ha_standby | raft | multi_region
    sync: str = "async"  # async | quorum
    node_id: str = "node-0"
    listen: Tuple[str, int] = ("127.0.0.1", 0)
    peers: List[Tuple[str, int]] = field(default_factory=list)
    heartbeat_interval: float = 0.5
    election_timeout: Tuple[float, float] = (1.5, 3.0)  # randomized range
    failover_timeout: float = 3.0  # missed-heartbeat window before takeover
    ha_role: str = "primary"  # primary | standby (ha_standby)
    primary_addr: Optional[Tuple[str, int]] = None  # standby's upstream
    # multi_region (reference: config.go:125-129 MultiRegion section):
    # this node's region, whether that region starts as the primary
    # (write-coordinating) region, the remote regions' node addresses,
    # and the async cross-region streaming tick
    region_id: str = "region-0"
    region_primary: bool = True
    remote_regions: List[Tuple[str, List[Tuple[str, int]]]] = field(
        default_factory=list
    )
    xregion_interval: float = 0.1
    # read-fleet hooks (replication/read_fleet.py): a subclass to stand
    # in for HAStandby/HAPrimary (the fleet's standby tracks replication
    # lag and fans applied records out to the search indexes), and a
    # promotion callback so the fleet router can re-point writes. None
    # keeps the stock classes — existing configs are untouched.
    standby_cls: Optional[Any] = None
    primary_cls: Optional[Any] = None
    on_promote: Optional[Any] = None
    # two-plane transport (ISSUE 16): when set, the node binds a second
    # bulk data-plane endpoint at this address and WAL batches/snapshot
    # ships ride it, keeping heartbeats and fences on the control
    # channel. None keeps the stock single-plane ClusterTransport.
    data_listen: Optional[Tuple[str, int]] = None
    # standby epoch persistence (ISSUE 16): when set, the standby loads
    # its fencing epoch from this file at construction and rewrites it
    # on every epoch change, so a restarted replica resumes at its
    # persisted epoch + local WAL watermark instead of re-bootstrapping
    # at epoch 1 (where a stale primary could feed it a fenced stream).
    epoch_path: Optional[str] = None


class Replicator:
    """Base: applies mutations locally and replicates them. Subclasses:
    HAPrimary/HAStandby (ha_standby.py), RaftNode (raft.py)."""

    def apply(self, op: str, data: Dict[str, Any]) -> None:
        raise NotImplementedError

    @property
    def role(self) -> Role:
        raise NotImplementedError

    def close(self) -> None:  # noqa: B027
        pass


# canonical decode lives next to the op vocabulary in storage/wal_engine.py;
# re-exported here because replication callers address it from this module
from nornicdb_tpu.storage.wal_engine import decode_op_args  # noqa: E402,F401
