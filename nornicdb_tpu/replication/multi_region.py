"""Multi-region replication: Raft per region, async streams between.

Reference: pkg/replication/multi_region.go — each region runs its own
Raft cluster for strong local consistency; the primary region's raft
leader asynchronously streams committed entries to remote region
coordinators; region failover promotes a remote region to primary.

This redesign tightens two things the reference leaves loose:

- **Region fencing.** Every cross-region message carries a region
  epoch. ``promote_region()`` bumps the epoch and broadcasts a fence;
  the deposed primary region demotes itself the moment it sees the
  higher epoch, so two regions can never both accept writes after a
  failover heals (the reference only flips an ``isPrimary`` bool).
- **Exact convergence.** The raft log index doubles as the cross-region
  sequence: receivers apply strictly in order, buffer out-of-order
  batches, and pull gaps via ``xr_sync`` catch-up — the same
  watermark + reorder-buffer discipline the HA standby uses
  (ha_standby.py), so a partitioned region converges exactly once the
  link heals.

All handlers are plain methods over the loopback ClusterTransport, so
multi-region clusters run in one process for tests (SURVEY.md §4
"multi-node without a real cluster").
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.replication.raft import RaftNode
from nornicdb_tpu.replication.replicator import (
    NotPrimaryError,
    ReplicationConfig,
    Replicator,
    Role,
)
from nornicdb_tpu.replication.transport import ClusterMessage, ClusterTransport

Addr = Tuple[str, int]


class NotPrimaryRegionError(NotPrimaryError):
    """Write landed on a region that is not the primary region."""


class MultiRegionNode(Replicator):
    """One node of one region in a multi-region deployment.

    ``config.peers`` are the node's in-region raft peers;
    ``config.remote_regions`` maps remote region ids to their node
    addresses. ``config.region_primary`` marks the initially-primary
    region (reference: first region listed is primary).
    """

    def __init__(
        self,
        transport: ClusterTransport,
        config: ReplicationConfig,
        apply_fn: Callable[[str, Dict[str, Any]], None],
    ):
        self.transport = transport
        self.config = config
        self._apply_fn = apply_fn
        self.region_id = config.region_id
        self.region_epoch = 1
        self._is_primary_region = bool(config.region_primary)
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # streaming state (leader of primary region): per remote region,
        # the highest raft index acked by that region
        self._streamed: Dict[str, int] = {}
        # receiving state: per origin region, applied watermark and the
        # out-of-order buffer
        self._applied_from: Dict[str, int] = {}
        self._reorder: Dict[str, Dict[int, Dict[str, Any]]] = {}

        self._raft = RaftNode(transport, config, self._apply_local)
        transport.register_handler("xr_batch", self.handle_xr_batch)
        transport.register_handler("xr_sync", self.handle_xr_sync)
        transport.register_handler("region_fence", self.handle_region_fence)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._raft.start()
        threading.Thread(
            target=self._stream_loop, daemon=True,
            name=f"xregion-{self.config.node_id}",
        ).start()

    def close(self) -> None:
        self._closed.set()
        self._raft.close()

    # -- replicator ------------------------------------------------------

    def apply(self, op: str, data: Dict[str, Any]) -> None:
        """Client write: only the primary region coordinates writes
        (reference: 'one region is designated as primary for write
        coordination'); within it, only the local raft leader accepts."""
        with self._lock:
            if not self._is_primary_region:
                raise NotPrimaryRegionError(self.primary_region_hint())
        self._raft.apply(op, data)

    def _apply_local(self, op: str, data: Dict[str, Any]) -> None:
        """Raft commit callback — every committed entry (local write or
        cross-region import) lands here on every in-region node."""
        self._apply_fn(op, data)

    @property
    def role(self) -> Role:
        return self._raft.role

    @property
    def is_primary_region(self) -> bool:
        with self._lock:
            return self._is_primary_region

    def primary_region_hint(self) -> Optional[str]:
        return None  # a deposed region learns the new primary by fence

    # -- cross-region streaming (primary-region raft leader only) ---------

    def _stream_loop(self) -> None:
        interval = getattr(self.config, "xregion_interval", 0.1)
        while not self._closed.wait(interval):
            with self._lock:
                if not self._is_primary_region:
                    continue
                epoch = self.region_epoch
            if self._raft.role is not Role.PRIMARY:
                continue
            self._stream_once(epoch)

    def _stream_once(self, epoch: int) -> None:
        for region, addrs in self.config.remote_regions:
            acked = self._streamed.get(region, 0)
            entries = self._raft.committed_entries(acked)
            if not entries:
                continue
            msg: ClusterMessage = {
                "type": "xr_batch",
                "region": self.region_id,
                "epoch": epoch,
                "records": [
                    {"xseq": i, "op": op, "data": data}
                    for i, op, data in entries
                ],
            }
            for addr in addrs:
                try:
                    reply = self.transport.request(tuple(addr), msg)
                except ConnectionError:
                    continue
                if reply.get("ok"):
                    self._streamed[region] = int(
                        reply.get("applied_xseq", acked)
                    )
                    break
                if reply.get("error") == "fenced":
                    # a higher-epoch region exists: demote ourselves
                    self._demote(int(reply.get("epoch", epoch)))
                    return
                # not the remote leader: try the next address

    # -- receiving side ---------------------------------------------------

    def handle_xr_batch(self, msg: ClusterMessage) -> ClusterMessage:
        origin = msg.get("region", "?")
        epoch = int(msg.get("epoch", 0))
        with self._lock:
            if epoch < self.region_epoch:
                return {"ok": False, "error": "fenced",
                        "epoch": self.region_epoch}
            if epoch > self.region_epoch:
                # a newer primary region is streaming: adopt its epoch
                # and drop any stale primary claim of our own
                self.region_epoch = epoch
                self._is_primary_region = False
        if self._raft.role is not Role.PRIMARY:
            return {"ok": False, "error": "not_leader",
                    "leader": self._raft.leader_id}
        try:
            applied = self._apply_batch(origin, msg.get("records", []))
            if not applied:
                # a gap precedes the buffered records: pull the missing
                # range from the origin region
                self._catch_up(origin, msg)
        except NotPrimaryError:
            # lost in-region leadership mid-batch; the streamer retries
            # against the new leader next tick
            return {"ok": False, "error": "not_leader",
                    "leader": self._raft.leader_id}
        with self._lock:
            return {
                "ok": True,
                "applied_xseq": self._applied_from.get(origin, 0),
            }

    def _apply_batch(
        self, origin: str, records: List[Dict[str, Any]]
    ) -> bool:
        """Apply in xseq order through the LOCAL raft so the whole
        region converges; buffer out-of-order. Returns False when a gap
        blocked progress."""
        progressed = True
        for rec in sorted(records, key=lambda r: r.get("xseq", 0)):
            xseq = int(rec.get("xseq", 0))
            with self._lock:
                watermark = self._applied_from.get(origin, 0)
                if xseq <= watermark:
                    continue  # duplicate (re-stream after failover)
                if xseq > watermark + 1:
                    self._reorder.setdefault(origin, {})[xseq] = rec
                    progressed = False
                    continue
            self._raft.apply(rec["op"], rec["data"])
            with self._lock:
                self._applied_from[origin] = xseq
                buf = self._reorder.get(origin, {})
            # drain any directly-following buffered records
            while True:
                with self._lock:
                    nxt = buf.pop(self._applied_from.get(origin, 0) + 1,
                                  None)
                if nxt is None:
                    break
                self._raft.apply(nxt["op"], nxt["data"])
                with self._lock:
                    self._applied_from[origin] += 1
        return progressed

    def _catch_up(self, origin: str, msg: ClusterMessage) -> None:
        addrs = dict(self.config.remote_regions).get(origin)
        if not addrs:
            return
        with self._lock:
            from_xseq = self._applied_from.get(origin, 0)
        req = {"type": "xr_sync", "region": self.region_id,
               "from_xseq": from_xseq}
        for addr in addrs:
            try:
                reply = self.transport.request(tuple(addr), req)
            except ConnectionError:
                continue
            if reply.get("ok"):
                self._apply_batch(origin, reply.get("records", []))
                return

    def handle_xr_sync(self, msg: ClusterMessage) -> ClusterMessage:
        """Serve a catch-up request from a remote region: committed raft
        entries after its watermark."""
        from_xseq = int(msg.get("from_xseq", 0))
        entries = self._raft.committed_entries(from_xseq)
        return {
            "ok": True,
            "records": [
                {"xseq": i, "op": op, "data": data}
                for i, op, data in entries
            ],
        }

    # -- failover ---------------------------------------------------------

    def promote_region(self) -> None:
        """Promote this region to primary (reference: RegionFailover).
        Must run on the region's raft leader. Bumps the region epoch and
        fences every remote region — the deposed primary demotes on
        sight of the higher epoch."""
        if self._raft.role is not Role.PRIMARY:
            raise NotPrimaryError(self._raft.leader_id)
        with self._lock:
            self.region_epoch += 1
            self._is_primary_region = True
            epoch = self.region_epoch
            # everything committed here so far was imported from (or
            # already shared with) the other regions — streaming it back
            # would re-append the whole history to their logs on every
            # failover. Start the outbound stream at the promotion point.
            start = self._raft.commit_index
            for region, _addrs in self.config.remote_regions:
                self._streamed.setdefault(region, 0)
                self._streamed[region] = max(self._streamed[region], start)
        fence: ClusterMessage = {
            "type": "region_fence",
            "region": self.region_id,
            "epoch": epoch,
        }
        for _region, addrs in self.config.remote_regions:
            for addr in addrs:
                try:
                    self.transport.request(tuple(addr), fence)
                    break
                except ConnectionError:
                    continue

    def handle_region_fence(self, msg: ClusterMessage) -> ClusterMessage:
        epoch = int(msg.get("epoch", 0))
        with self._lock:
            if epoch > self.region_epoch:
                self.region_epoch = epoch
                self._is_primary_region = False
                return {"ok": True}
            return {"ok": False, "error": "stale fence epoch",
                    "epoch": self.region_epoch}

    def _demote(self, epoch: int) -> None:
        with self._lock:
            if epoch > self.region_epoch:
                self.region_epoch = epoch
            self._is_primary_region = False

    # -- introspection ----------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """Reference: Health() — mode, region, role, epoch, stream state."""
        with self._lock:
            return {
                "mode": "multi_region",
                "node_id": self.config.node_id,
                "region": self.region_id,
                "region_epoch": self.region_epoch,
                "is_primary_region": self._is_primary_region,
                "raft_role": self._raft.role.value,
                "raft_leader": self._raft.leader_id,
                "streamed": dict(self._streamed),
                "applied_from": dict(self._applied_from),
            }
