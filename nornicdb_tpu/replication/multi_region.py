"""Multi-region replication: Raft per region, async streams between.

Reference: pkg/replication/multi_region.go — each region runs its own
Raft cluster for strong local consistency; the primary region's raft
leader asynchronously streams committed entries to remote region
coordinators; region failover promotes a remote region to primary.

This redesign tightens two things the reference leaves loose:

- **Region fencing.** Every cross-region message carries a region
  epoch. ``promote_region()`` bumps the epoch and broadcasts a fence;
  the deposed primary region demotes itself the moment it sees the
  higher epoch (the reference only flips an ``isPrimary`` bool).
  There IS a divergence window: writes the old primary accepted
  between the promotion and its demotion were committed to its
  regional raft but never streamed. On demotion they are detected
  (entries past the new primary's acked watermark) and surfaced via
  ``diverged_entries()`` / ``health()['diverged']`` for
  reconciliation — they are never silently dropped, and never
  silently merged either (the new primary's history wins).
- **Exact convergence.** The raft log index doubles as the cross-region
  sequence: receivers apply strictly in order, buffer out-of-order
  batches, and pull gaps via ``xr_sync`` catch-up — the same
  watermark + reorder-buffer discipline the HA standby uses
  (ha_standby.py), so a partitioned region converges exactly once the
  link heals. A promoted region streams from its promotion point and
  stamps that base on every fence/batch, so receivers fast-forward
  their watermark instead of re-pulling the shared history from
  xseq 0 on every failover.

All handlers are plain methods over the loopback ClusterTransport, so
multi-region clusters run in one process for tests (SURVEY.md §4
"multi-node without a real cluster").
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from nornicdb_tpu.replication.raft import RaftNode
from nornicdb_tpu.replication.replicator import (
    NotPrimaryError,
    ReplicationConfig,
    Replicator,
    Role,
)
from nornicdb_tpu.replication.transport import ClusterMessage, ClusterTransport

Addr = Tuple[str, int]


class NotPrimaryRegionError(NotPrimaryError):
    """Write landed on a region that is not the primary region."""


class MultiRegionNode(Replicator):
    """One node of one region in a multi-region deployment.

    ``config.peers`` are the node's in-region raft peers;
    ``config.remote_regions`` maps remote region ids to their node
    addresses. ``config.region_primary`` marks the initially-primary
    region (reference: first region listed is primary).
    """

    def __init__(
        self,
        transport: ClusterTransport,
        config: ReplicationConfig,
        apply_fn: Callable[[str, Dict[str, Any]], None],
    ):
        self.transport = transport
        self.config = config
        self._apply_fn = apply_fn
        self.region_id = config.region_id
        self.region_epoch = 1
        self._is_primary_region = bool(config.region_primary)
        self._primary_region: Optional[str] = (
            self.region_id if self._is_primary_region else None
        )
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # streaming state (leader of primary region): per remote region,
        # the highest raft index acked by that region
        self._streamed: Dict[str, int] = {}
        # outbound stream base: raft index at which this region became
        # primary (0 for the initial primary). Stamped on fences and
        # batches so receivers fast-forward instead of catching up from 0.
        self._xr_base = 0
        # receiving state: per origin region, applied watermark and the
        # out-of-order buffer
        self._applied_from: Dict[str, int] = {}
        self._reorder: Dict[str, Dict[int, Dict[str, Any]]] = {}
        # entries committed while primary that the NEW primary never
        # acked, captured at demotion for reconciliation
        self._diverged: List[Dict[str, Any]] = []

        self._raft = RaftNode(transport, config, self._apply_local)
        transport.register_handler("xr_batch", self.handle_xr_batch)
        transport.register_handler("xr_sync", self.handle_xr_sync)
        transport.register_handler("region_fence", self.handle_region_fence)

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        self._raft.start()
        threading.Thread(
            target=self._stream_loop, daemon=True,
            name=f"xregion-{self.config.node_id}",
        ).start()

    def close(self) -> None:
        self._closed.set()
        self._raft.close()

    # -- replicator ------------------------------------------------------

    def apply(self, op: str, data: Dict[str, Any]) -> None:
        """Client write: only the primary region coordinates writes
        (reference: 'one region is designated as primary for write
        coordination'); within it, only the local raft leader accepts."""
        with self._lock:
            if not self._is_primary_region:
                # read the hint inline: primary_region_hint() takes the
                # same non-reentrant lock
                raise NotPrimaryRegionError(self._primary_region)
        self._raft.apply(op, data)

    def _apply_local(self, op: str, data: Dict[str, Any]) -> None:
        """Raft commit callback — every committed entry (local write or
        cross-region import) lands here on every in-region node."""
        self._apply_fn(op, data)

    @property
    def role(self) -> Role:
        return self._raft.role

    @property
    def is_primary_region(self) -> bool:
        with self._lock:
            return self._is_primary_region

    def primary_region_hint(self) -> Optional[str]:
        with self._lock:
            return self._primary_region

    # -- cross-region streaming (primary-region raft leader only) ---------

    def _stream_loop(self) -> None:
        interval = getattr(self.config, "xregion_interval", 0.1)
        while not self._closed.wait(interval):
            with self._lock:
                if not self._is_primary_region:
                    continue
                epoch = self.region_epoch
            if self._raft.role is not Role.PRIMARY:
                continue
            self._stream_once(epoch)

    def _stream_once(self, epoch: int) -> None:
        for region, addrs in self.config.remote_regions:
            with self._lock:
                acked = max(self._streamed.get(region, 0), self._xr_base)
                base = self._xr_base
            entries = self._raft.committed_entries(acked)
            if not entries:
                continue
            msg: ClusterMessage = {
                "type": "xr_batch",
                "region": self.region_id,
                "epoch": epoch,
                "base": base,
                "records": [
                    {"xseq": i, "op": op, "data": data}
                    for i, op, data in entries
                ],
            }
            for addr in addrs:
                try:
                    reply = self.transport.request(tuple(addr), msg)
                except ConnectionError:
                    continue
                if reply.get("ok"):
                    with self._lock:
                        self._streamed[region] = int(
                            reply.get("applied_xseq", acked)
                        )
                    break
                if reply.get("error") == "fenced":
                    # a higher-epoch region exists: demote ourselves
                    self._demote(
                        int(reply.get("epoch", epoch)),
                        new_primary=reply.get("primary_region"),
                    )
                    return
                # not the remote leader: try the next address

    # -- receiving side ---------------------------------------------------

    def handle_xr_batch(self, msg: ClusterMessage) -> ClusterMessage:
        origin = msg.get("region", "?")
        epoch = int(msg.get("epoch", 0))
        was_primary = False
        with self._lock:
            if epoch < self.region_epoch:
                return {"ok": False, "error": "fenced",
                        "epoch": self.region_epoch,
                        "primary_region": self._primary_region}
            if epoch > self.region_epoch:
                # a newer primary region is streaming: adopt its epoch
                # and drop any stale primary claim of our own
                self.region_epoch = epoch
                was_primary = self._is_primary_region
                self._is_primary_region = False
            # only the primary region streams batches, so origin IS it
            self._primary_region = origin
            # fast-forward the origin watermark to the stream base (the
            # origin's promotion point): everything at or below it is the
            # shared pre-failover history, already applied via the OLD
            # origin's stream — re-pulling it would replay O(history)
            base = int(msg.get("base", 0))
            if base > self._applied_from.get(origin, 0):
                self._applied_from[origin] = base
        if was_primary:
            self._capture_divergence(origin)
        if self._raft.role is not Role.PRIMARY:
            return {"ok": False, "error": "not_leader",
                    "leader": self._raft.leader_id}
        try:
            applied = self._apply_batch(origin, msg.get("records", []))
            if not applied:
                # a gap precedes the buffered records: pull the missing
                # range from the origin region
                self._catch_up(origin, msg)
        except NotPrimaryError:
            # lost in-region leadership mid-batch; the streamer retries
            # against the new leader next tick
            return {"ok": False, "error": "not_leader",
                    "leader": self._raft.leader_id}
        with self._lock:
            return {
                "ok": True,
                "applied_xseq": self._applied_from.get(origin, 0),
            }

    def _apply_batch(
        self, origin: str, records: List[Dict[str, Any]]
    ) -> bool:
        """Apply in xseq order through the LOCAL raft so the whole
        region converges; buffer out-of-order. Returns False when a gap
        blocked progress."""
        progressed = True
        for rec in sorted(records, key=lambda r: r.get("xseq", 0)):
            xseq = int(rec.get("xseq", 0))
            with self._lock:
                watermark = self._applied_from.get(origin, 0)
                if xseq <= watermark:
                    continue  # duplicate (re-stream after failover)
                if xseq > watermark + 1:
                    self._reorder.setdefault(origin, {})[xseq] = rec
                    progressed = False
                    continue
            self._raft.apply(rec["op"], rec["data"])
            with self._lock:
                self._applied_from[origin] = xseq
                buf = self._reorder.get(origin, {})
            # drain any directly-following buffered records
            while True:
                with self._lock:
                    nxt = buf.pop(self._applied_from.get(origin, 0) + 1,
                                  None)
                if nxt is None:
                    break
                self._raft.apply(nxt["op"], nxt["data"])
                with self._lock:
                    self._applied_from[origin] += 1
        return progressed

    def _catch_up(self, origin: str, msg: ClusterMessage) -> None:
        addrs = dict(self.config.remote_regions).get(origin)
        if not addrs:
            return
        with self._lock:
            from_xseq = self._applied_from.get(origin, 0)
        req = {"type": "xr_sync", "region": self.region_id,
               "from_xseq": from_xseq}
        for addr in addrs:
            try:
                reply = self.transport.request(tuple(addr), req)
            except ConnectionError:
                continue
            if reply.get("ok"):
                self._apply_batch(origin, reply.get("records", []))
                return

    def handle_xr_sync(self, msg: ClusterMessage) -> ClusterMessage:
        """Serve a catch-up request from a remote region: committed raft
        entries after its watermark."""
        from_xseq = int(msg.get("from_xseq", 0))
        entries = self._raft.committed_entries(from_xseq)
        return {
            "ok": True,
            "records": [
                {"xseq": i, "op": op, "data": data}
                for i, op, data in entries
            ],
        }

    # -- failover ---------------------------------------------------------

    def promote_region(self) -> None:
        """Promote this region to primary (reference: RegionFailover).
        Must run on the region's raft leader. Bumps the region epoch and
        fences every remote region — the deposed primary demotes on
        sight of the higher epoch."""
        if self._raft.role is not Role.PRIMARY:
            raise NotPrimaryError(self._raft.leader_id)
        with self._lock:
            self.region_epoch += 1
            self._is_primary_region = True
            self._primary_region = self.region_id
            epoch = self.region_epoch
            # everything committed here so far was imported from (or
            # already shared with) the other regions — streaming it back
            # would re-append the whole history to their logs on every
            # failover. Start the outbound stream at the promotion point
            # and stamp it on fences/batches so receivers fast-forward.
            start = self._raft.commit_index
            self._xr_base = start
            for region, _addrs in self.config.remote_regions:
                self._streamed.setdefault(region, 0)
                self._streamed[region] = max(self._streamed[region], start)
        fence: ClusterMessage = {
            "type": "region_fence",
            "region": self.region_id,
            "epoch": epoch,
            "base": start,
        }
        # fence EVERY node of every remote region, not first-success:
        # regional roles aren't known here, and a fence that only
        # reaches a follower leaves that region's leader accepting
        # writes until the next stream exchange
        for _region, addrs in self.config.remote_regions:
            for addr in addrs:
                try:
                    self.transport.request(tuple(addr), fence)
                except ConnectionError:
                    continue

    def handle_region_fence(self, msg: ClusterMessage) -> ClusterMessage:
        epoch = int(msg.get("epoch", 0))
        origin = msg.get("region", "?")
        with self._lock:
            if epoch <= self.region_epoch:
                return {"ok": False, "error": "stale fence epoch",
                        "epoch": self.region_epoch,
                        "primary_region": self._primary_region}
            self.region_epoch = epoch
            was_primary = self._is_primary_region
            self._is_primary_region = False
            self._primary_region = origin
            base = int(msg.get("base", 0))
            if base > self._applied_from.get(origin, 0):
                self._applied_from[origin] = base
        if was_primary:
            self._capture_divergence(origin)
        return {"ok": True}

    def _demote(self, epoch: int, new_primary: Optional[str] = None) -> None:
        with self._lock:
            if epoch > self.region_epoch:
                self.region_epoch = epoch
            was_primary = self._is_primary_region
            self._is_primary_region = False
            if new_primary:
                self._primary_region = new_primary
        if was_primary:
            self._capture_divergence(new_primary)

    def _capture_divergence(self, new_primary: Optional[str]) -> None:
        """Record writes this region committed as primary that the new
        primary never acked. They exist because fencing is detection,
        not prevention: between the remote promotion and this demotion,
        local clients could still commit here. The new primary's history
        wins; these entries are surfaced (``diverged_entries()``,
        ``health()['diverged']``) for explicit reconciliation rather
        than silently dropped or silently merged."""
        with self._lock:
            if new_primary is not None and new_primary in self._streamed:
                acked = self._streamed[new_primary]
            elif self._streamed:
                acked = min(self._streamed.values())
            else:
                acked = self._raft.commit_index
        entries = self._raft.committed_entries(acked)
        if entries:
            with self._lock:
                known = {d["xseq"] for d in self._diverged}
                self._diverged.extend(
                    {"xseq": i, "op": op, "data": data}
                    for i, op, data in entries
                    if i not in known
                )

    def diverged_entries(self) -> List[Dict[str, Any]]:
        """Entries committed here as primary that the current primary
        region never received (captured at demotion)."""
        with self._lock:
            return list(self._diverged)

    # -- introspection ----------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """Reference: Health() — mode, region, role, epoch, stream state."""
        with self._lock:
            return {
                "mode": "multi_region",
                "node_id": self.config.node_id,
                "region": self.region_id,
                "region_epoch": self.region_epoch,
                "is_primary_region": self._is_primary_region,
                "raft_role": self._raft.role.value,
                "raft_leader": self._raft.leader_id,
                "primary_region": self._primary_region,
                "streamed": dict(self._streamed),
                "applied_from": dict(self._applied_from),
                "diverged": len(self._diverged),
            }
