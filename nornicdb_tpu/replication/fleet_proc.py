"""Multi-process read fleet: replica DBs as real subprocesses (ISSUE 16).

The in-process :class:`~nornicdb_tpu.replication.read_fleet.ReadFleet`
proved replica correctness (parity, drains, failover) but every replica
shares one interpreter and one GIL — measured fleet read scaling was
~0.5x, i.e. a replica made reads *slower*. This module takes the same
topology across real process boundaries:

- each replica runs ``python -m nornicdb_tpu.replication.fleet_proc
  --replica <json-spec>`` — the api/wire_plane.py spawn discipline: a
  clean interpreter via module entry (never multiprocessing spawn, which
  re-imports the parent's ``__main__``), PYTHONPATH pinned to the
  package parent, stderr to a file (a pipe nobody drains would block the
  child mid-write), an atomically-written ready file the parent polls,
  and a stop-file + parent-pid watch in the child's serve loop so an
  orphaned replica exits instead of eating the test timeout;
- the child is a full :class:`ReadReplica` (WAL streaming over the
  two-plane socket transport, epoch persisted in its data dir) fronted
  by the standard :class:`~nornicdb_tpu.api.http_server.HttpServer` —
  ``/readyz`` carries the replica watermark doc, ``/nornicdb/search``
  serves reads, ``/admin/fleet/state`` feeds the fleet aggregator;
- the parent-side :class:`ReplicaProcess` handle wraps spawn/stop/kill,
  and :class:`ProcessReadFleet` assembles 1 in-parent primary + N
  replica subprocesses behind a :class:`~nornicdb_tpu.api.fleet_router.
  FleetRouter` of :class:`RemoteReplica` node handles, with every
  replica registered as a fleet telemetry source so ``/admin/fleet``
  merges the whole topology.

A killed replica resumes from its persisted epoch + seq-aligned local
WAL: the restart pulls only the tail (``resume_seq`` in the ready file
is the watermark recovered from disk BEFORE any catch-up), never a full
re-bootstrap.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple


# -- child side --------------------------------------------------------------


def _replica_main(spec: Dict[str, Any]) -> None:
    """Subprocess entry: build the replica, attach, serve until the
    parent signals stop or disappears."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    work_dir = spec["work_dir"]
    name = spec["name"]
    stop_paths = (os.path.join(work_dir, "stop"),
                  os.path.join(work_dir, f"stop-{name}"))
    try:
        from nornicdb_tpu.api.http_server import HttpServer
        from nornicdb_tpu.replication.read_fleet import ReadReplica

        replica = ReadReplica(
            name, spec["data_dir"], database=spec.get("database", "neo4j"),
            heartbeat_interval=float(spec.get("heartbeat_interval", 0.25)),
            failover_timeout=float(spec.get("failover_timeout", 30.0)),
        )
        # the watermark/epoch recovered from LOCAL state, before any
        # catch-up traffic: the parent's restart test reads this to
        # prove the rejoin was a tail-pull, not a re-bootstrap
        resume_seq = int(replica.standby.applied_seq)
        resume_epoch = int(replica.standby.epoch)
        replica.attach(tuple(spec["primary_addr"]),
                       [tuple(a) for a in spec.get("peer_addrs", ())])
        http = HttpServer(replica.db, host=spec.get("host", "127.0.0.1"),
                          port=0).start()
        ready_doc = {
            "pid": os.getpid(),
            "transport_addr": list(replica.addr),
            "http_port": http.port,
            "resume_seq": resume_seq,
            "resume_epoch": resume_epoch,
        }
        ready_path = os.path.join(work_dir, f"ready-{name}")
        with open(ready_path + ".tmp", "w", encoding="utf-8") as f:
            json.dump(ready_doc, f)
        os.replace(ready_path + ".tmp", ready_path)
    except Exception:  # noqa: BLE001 — parent's ready-poll times out
        import traceback

        traceback.print_exc()
        os._exit(1)

    ppid = os.getppid()
    while True:
        time.sleep(0.25)
        if any(os.path.exists(p) for p in stop_paths):
            break
        if os.getppid() != ppid:
            break  # orphaned: the parent died without cleanup
    try:
        http.stop()
        replica.close()
    except Exception:  # noqa: BLE001
        pass
    os._exit(0)


# -- parent side -------------------------------------------------------------


class ReplicaProcess:
    """Parent-side handle over one replica subprocess."""

    def __init__(self, name: str, data_dir: str, work_dir: str,
                 primary_addr: Tuple[str, int],
                 peer_addrs: Sequence[Tuple[str, int]] = (),
                 database: str = "neo4j",
                 heartbeat_interval: float = 0.25,
                 failover_timeout: float = 30.0,
                 host: str = "127.0.0.1"):
        self.name = str(name)
        self.data_dir = data_dir
        self.work_dir = work_dir
        self.host = host
        self._spec = {
            "name": self.name,
            "data_dir": data_dir,
            "work_dir": work_dir,
            "primary_addr": list(primary_addr),
            "peer_addrs": [list(a) for a in peer_addrs],
            "database": database,
            "heartbeat_interval": heartbeat_interval,
            "failover_timeout": failover_timeout,
            "host": host,
        }
        self._proc: Optional[Any] = None
        self.ready_doc: Dict[str, Any] = {}

    # -- lifecycle -------------------------------------------------------

    def start(self, ready_timeout_s: float = 90.0) -> "ReplicaProcess":
        import subprocess
        import sys

        import nornicdb_tpu as _pkg

        os.makedirs(self.work_dir, exist_ok=True)
        for stale in (f"ready-{self.name}", f"stop-{self.name}"):
            try:
                os.unlink(os.path.join(self.work_dir, stale))
            except OSError:
                pass
        # the child interpreter must resolve this package regardless of
        # the parent's cwd: prepend the package parent (wire_plane
        # discipline)
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        err_path = os.path.join(self.work_dir, f"{self.name}.err")
        with open(err_path, "wb") as err_f:
            self._proc = subprocess.Popen(
                [sys.executable, "-m",
                 "nornicdb_tpu.replication.fleet_proc", "--replica",
                 json.dumps(self._spec)],
                stdout=subprocess.DEVNULL, stderr=err_f, env=env)
        self._err_path = err_path
        ready_path = os.path.join(self.work_dir, f"ready-{self.name}")
        deadline = time.time() + ready_timeout_s
        while time.time() < deadline:
            if os.path.exists(ready_path):
                with open(ready_path, "r", encoding="utf-8") as f:
                    self.ready_doc = json.load(f)
                return self
            if self._proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.name} died during startup: "
                    f"{self.err_tail()}")
            time.sleep(0.05)
        self.stop()
        raise RuntimeError(
            f"replica {self.name} not ready within {ready_timeout_s:.0f}s")

    def err_tail(self, n: int = 800) -> str:
        try:
            with open(self._err_path, "rb") as f:
                return f.read().decode(errors="replace")[-n:]
        except OSError:
            return ""

    @property
    def addr(self) -> Tuple[str, int]:
        return tuple(self.ready_doc["transport_addr"])

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.ready_doc['http_port']}"

    @property
    def pid(self) -> Optional[int]:
        return None if self._proc is None else self._proc.pid

    def alive(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def remote(self, timeout_s: float = 2.0):
        """The router-facing node handle for this process."""
        from nornicdb_tpu.api.fleet_router import RemoteReplica

        return RemoteReplica(self.name, self.base_url,
                             timeout_s=timeout_s)

    def kill(self) -> None:
        """Hard SIGKILL — failure injection for the drain tests."""
        if self._proc is not None:
            try:
                self._proc.kill()
                self._proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                pass

    def stop(self, timeout_s: float = 5.0) -> None:
        """Graceful stop via the per-replica stop file, escalating to
        terminate/kill — teardown is guaranteed (no orphan may outlive
        the test and eat the tier-1 timeout)."""
        if self._proc is None:
            return
        try:
            with open(os.path.join(self.work_dir, f"stop-{self.name}"),
                      "w") as f:
                f.write("1")
        except OSError:
            pass
        try:
            self._proc.wait(timeout=timeout_s)
        except Exception:  # noqa: BLE001
            self._proc.terminate()
            try:
                self._proc.wait(timeout=3)
            except Exception:  # noqa: BLE001
                self._proc.kill()
                try:
                    self._proc.wait(timeout=3)
                except Exception:  # noqa: BLE001
                    pass
        self._proc = None


class ProcessReadFleet:
    """1 in-parent primary + N replica subprocesses behind the router.

    Construction order (inverse of the in-process ReadFleet, because a
    child cannot exist before it can be told the primary's address):
    primary DB first with an empty peer set, then the replica processes
    — each attaches to the primary over the two-plane transport and
    pulls history — then the collected child transport addresses become
    the primary's streaming peer set, and each child's RemoteReplica
    handle joins the router. Every replica also registers as a fleet
    telemetry source (obs/fleet.py http_state_source) so
    ``/admin/fleet`` merges the whole topology."""

    def __init__(
        self,
        base_dir: str,
        n_replicas: int = 2,
        database: str = "neo4j",
        sync: str = "async",
        heartbeat_interval: float = 0.1,
        failover_timeout: float = 30.0,
        auto_embed: bool = False,
        ready_timeout_s: float = 90.0,
        http_timeout_s: float = 5.0,
    ):
        from nornicdb_tpu import obs
        from nornicdb_tpu.api.fleet_router import FleetRouter
        from nornicdb_tpu.api.http_server import HttpServer
        from nornicdb_tpu.db import DB
        from nornicdb_tpu.replication.replicator import ReplicationConfig

        self.base_dir = base_dir
        self.work_dir = os.path.join(base_dir, "fleet-proc")
        self._http_timeout_s = http_timeout_s
        self.procs: List[ReplicaProcess] = []
        self.remotes: List[Any] = []
        self.primary_db = None
        self.primary_http = None
        self._fleet_sources: List[str] = []
        try:
            cfg = ReplicationConfig(
                mode="ha_standby", ha_role="primary", node_id="primary",
                sync=sync, peers=[],
                heartbeat_interval=heartbeat_interval,
                failover_timeout=failover_timeout,
                data_listen=("127.0.0.1", 0),
            )
            self.primary_db = DB(
                os.path.join(base_dir, "primary"), engine="python",
                auto_embed=auto_embed, database=database,
                replication=cfg)
            primary_addr = self.primary_db._cluster_transport.addr
            # the primary's own HTTP surface: the single-process bench
            # baseline, and the fallback read target
            self.primary_http = HttpServer(self.primary_db, port=0).start()
            for i in range(n_replicas):
                proc = ReplicaProcess(
                    f"replica-{i}",
                    os.path.join(base_dir, f"replica-{i}"),
                    self.work_dir, primary_addr,
                    database=database,
                    heartbeat_interval=heartbeat_interval,
                    failover_timeout=failover_timeout,
                )
                self.procs.append(proc)
                proc.start(ready_timeout_s=ready_timeout_s)
            # children are attached and caught up: their transport
            # addresses become the primary's streaming peer set (list
            # swap is atomic; the stream/heartbeat loops read it fresh
            # each round)
            self.primary_db.replicator.config.peers = [
                tuple(p.addr) for p in self.procs]
            self.router = FleetRouter(self.primary_db)
            for proc in self.procs:
                remote = proc.remote(timeout_s=http_timeout_s)
                self.remotes.append(remote)
                self.router.add_replica(remote)
                obs.register_fleet_source(
                    proc.name, obs.http_state_source(proc.base_url))
                self._fleet_sources.append(proc.name)
            # cross-NODE admission posture (ISSUE 16): the replicas'
            # posture gauges ride the telemetry feeds just registered;
            # the aggregator sweep becomes a posture source for the
            # primary's controller
            from nornicdb_tpu import admission as _adm
            from nornicdb_tpu.obs import fleet as _obs_fleet

            self._posture_source = _obs_fleet.posture_source()
            _adm.CONTROLLER.add_posture_source(self._posture_source)
        except BaseException:
            self.close()
            raise

    @property
    def primary_url(self) -> str:
        return f"http://127.0.0.1:{self.primary_http.port}"

    def restart(self, index: int,
                ready_timeout_s: float = 90.0) -> ReplicaProcess:
        """Restart replica ``index`` in place. The child resumes from
        its persisted standby epoch + local WAL watermark (no full
        re-bootstrap — the ready doc's ``resume_seq``/``resume_epoch``
        prove it), comes back on fresh ephemeral ports, and the
        primary's streaming peer set plus the router's node handle are
        re-pointed at them. The replica rejoins UNADMITTED — callers
        re-admit once it converges, mirroring first boot."""
        from nornicdb_tpu import obs

        proc = self.procs[index]
        proc.stop()  # no-op when the child is already dead (kill())
        proc.start(ready_timeout_s=ready_timeout_s)
        self.primary_db.replicator.config.peers = [
            tuple(p.addr) for p in self.procs]
        remote = proc.remote(timeout_s=self._http_timeout_s)
        self.router.remove_replica(proc.name)
        self.router.add_replica(remote)
        self.remotes[index] = remote
        try:
            obs.unregister_fleet_source(proc.name)
        except Exception:  # noqa: BLE001
            pass
        obs.register_fleet_source(
            proc.name, obs.http_state_source(proc.base_url))
        if proc.name not in self._fleet_sources:
            self._fleet_sources.append(proc.name)
        return proc

    def admit_all_unchecked(self) -> None:
        """Admit every replica without the in-process parity probe —
        remote handles are parity-verified out of band against their
        own HTTP surface (bench/tests), per the RemoteReplica
        contract."""
        for proc in self.procs:
            self.router.admit_unchecked(proc.name)

    def wait_converged(self, timeout_s: float = 30.0) -> bool:
        """Block until every live replica's applied watermark reaches
        the primary's current last_seq (observed over each replica's
        /readyz watermark doc)."""
        self.primary_db._base.wal.flush()
        target = self.primary_db._base.wal.last_seq
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            done = True
            for remote in self.remotes:
                remote.ready_reasons()  # refreshes the watermark doc
                seq = remote.applied_seq()
                if seq is None or seq < target:
                    done = False
            if done:
                return True
            time.sleep(0.1)
        return False

    def close(self) -> None:
        from nornicdb_tpu import obs

        if getattr(self, "_posture_source", None) is not None:
            from nornicdb_tpu import admission as _adm

            _adm.CONTROLLER.remove_posture_source(self._posture_source)
            self._posture_source = None
        for name in self._fleet_sources:
            try:
                obs.unregister_fleet_source(name)
            except Exception:  # noqa: BLE001
                pass
        self._fleet_sources = []
        # broadcast stop to all children first so they exit in parallel
        try:
            os.makedirs(self.work_dir, exist_ok=True)
            with open(os.path.join(self.work_dir, "stop"), "w") as f:
                f.write("1")
        except OSError:
            pass
        for proc in self.procs:
            try:
                proc.stop()
            except Exception:  # noqa: BLE001
                pass
        if self.primary_http is not None:
            try:
                self.primary_http.stop()
            except Exception:  # noqa: BLE001
                pass
        if self.primary_db is not None:
            try:
                self.primary_db.close()
            except Exception:  # noqa: BLE001
                pass


if __name__ == "__main__":  # replica process entry
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--replica", required=True,
                    help="JSON replica spec from ReplicaProcess")
    _args = ap.parse_args()
    _replica_main(json.loads(_args.replica))
