"""Read fleet: WAL-shipping replicas that rebuild their device indexes.

ROADMAP item 1 / ISSUE 12 — the read half of multi-host scale-out. The
host-side HA port (ha_standby.py) streams WAL batches, heartbeats and
fencing epochs between engines, but a standby's copy of the data was
invisible to every serving surface: streamed records land at the BASE
``WALEngine`` — below the Namespaced/Listenable layers — so the
mutation listeners that feed the search indexes, the qdrant
per-collection caches and the executor's columnar snapshots never fire.
A standby could vote in a quorum but could not answer a query.

This module closes that gap and stands the standby up as a *read
replica*:

- :class:`FleetStandby` extends ``HAStandby`` with replication-lag
  truth: the primary's ``last_seq`` (carried by every heartbeat) and
  the max streamed seq are tracked next to ``applied_seq``, so
  ``lag_ops()`` is exact in WAL operations, and ``catching_up`` is
  observable while a gap repair / rejoin sync is in flight;
- :class:`ReadReplica` owns a full DB facade over the standby engine
  and installs the ``WALEngine.on_applied`` replay hook: every applied
  record is translated back to its LOGICAL shape (namespace prefix
  stripped) and fanned out through the replica's own mutation
  listeners and ``SearchService.index_node``/``remove_node`` — the
  exact add/update/delete paths a local write takes, so changelogs,
  freshness ladders and background device rebuilds (PRs 2/4/6/8) work
  unchanged on replayed traffic. Bulk ``delete_by_prefix`` records
  reconcile via ``SearchService.prune_missing``;
- readiness: ``ready_reasons()`` yields ``replica_lag:<node>`` when
  ``lag_ops()`` exceeds ``NORNICDB_READY_MAX_LAG_OPS`` and
  ``catching_up:<node>`` during a sync — surfaced by the replica's own
  ``/readyz`` (api/http_server.py reads ``db.fleet_node``) and by the
  fleet router's drain decision (api/fleet_router.py);
- :class:`ReadFleet` builds the in-process 1-primary/N-replica
  topology (real loopback ``ClusterTransport`` sockets, directly
  callable handlers for fencing tests — the ha_standby.py discipline)
  and wires the router.

Failover: ``FleetStandby.promote`` rides the stock fencing path (epoch
bump + best-effort fence); the promotion callback re-points the fleet
router's write target and re-registers the node's observability
resources exactly once (obs/resources.register is a no-op for the same
object, ISSUE 11).

Observability (docs/observability.md catalog): scrape-time collector
gauges ``nornicdb_replica_lag_ops``/``_applied_seq``/``_catching_up``
per node plus the ``nornicdb_fleet_failover_total`` event counter;
per-read routing counters live in api/fleet_router.py.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from nornicdb_tpu.obs import events as _events
from nornicdb_tpu.obs.metrics import LATENCY_BUCKETS, REGISTRY
from nornicdb_tpu.replication.ha_standby import HAStandby
from nornicdb_tpu.replication.replicator import ReplicationConfig
from nornicdb_tpu.storage.types import Edge, Node

_LAG_G = REGISTRY.gauge(
    "nornicdb_replica_lag_ops",
    "WAL operations between the primary's last_seq and this replica's "
    "applied watermark", labels=("node",))
# replication latency in SECONDS, not ops (ISSUE 13): every streamed
# record carries the primary's append timestamp; the replica observes
# append->apply delay per record — "lag 400 ops" becomes "p99 replay
# delay 38 ms". Catch-up replays of old history are excluded (a
# mid-history joiner's day-old records are bootstrap, not steady-state
# replication latency).
_APPLY_DELAY_H = REGISTRY.histogram(
    "nornicdb_replication_apply_delay_seconds",
    "Per-record delay between primary WAL append and replica apply "
    "(streamed records; catch-up bootstrap excluded)",
    labels=("node",), buckets=LATENCY_BUCKETS)
# where replica-side replay time goes, per record: the listener
# fan-out (cache invalidation, columnar catalog) vs the search-index
# apply (brute changelog, BM25, CAGRA triggers)
_REPLAY_H = REGISTRY.histogram(
    "nornicdb_replica_replay_seconds",
    "Replica replay fan-out time per applied record, by stage",
    labels=("node", "stage"), buckets=LATENCY_BUCKETS)
_APPLIED_G = REGISTRY.gauge(
    "nornicdb_replica_applied_seq",
    "Last WAL seq this replica has applied", labels=("node",))
_CATCH_G = REGISTRY.gauge(
    "nornicdb_replica_catching_up",
    "1 while a catch-up sync (rejoin / gap repair) is in flight",
    labels=("node",))
_FAILOVER_C = REGISTRY.counter(
    "nornicdb_fleet_failover_total",
    "Fleet failover events (promote, fence_rejected, step_down)",
    labels=("event",))

# live replicas for the scrape-time gauge collector (weak — a closed
# fleet's series disappear instead of freezing at their last value)
_lock = threading.Lock()
_replicas: Dict[str, "weakref.ref[ReadReplica]"] = {}


def _track(replica: "ReadReplica") -> None:
    with _lock:
        _replicas[replica.name] = weakref.ref(replica)


def update_fleet_gauges(registry=None) -> None:
    """Collector body: per-node replication gauges derived from the
    live :class:`ReadReplica` objects on every scrape."""
    reg = registry if registry is not None else REGISTRY
    dead: List[str] = []
    with _lock:
        items = list(_replicas.items())
    for name, ref in items:
        r = ref()
        if r is None or r.closed:
            dead.append(name)
            continue
        st = r.standby
        if st is None:
            continue
        lag = reg.gauge(_LAG_G.name, _LAG_G.help, labels=("node",))
        lag.labels(name).set(float(st.lag_ops()))
        reg.gauge(_APPLIED_G.name, _APPLIED_G.help,
                  labels=("node",)).labels(name).set(float(st.applied_seq))
        reg.gauge(_CATCH_G.name, _CATCH_G.help,
                  labels=("node",)).labels(name).set(
            1.0 if st.catching_up else 0.0)
    if dead and reg is REGISTRY:
        with _lock:
            for name in dead:
                _replicas.pop(name, None)
        for g in (_LAG_G, _APPLIED_G, _CATCH_G):
            for name in dead:
                g.remove((name,))


REGISTRY.add_collector(update_fleet_gauges)


class FleetStandby(HAStandby):
    """HAStandby + replication-lag truth.

    ``primary_last_seq`` advances from every accepted heartbeat (the
    primary stamps ``last_seq``) and every accepted WAL batch (max
    record seq), never from fenced messages — a deposed primary's
    inflated watermark must not make a healthy replica look behind."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.primary_last_seq = 0
        self._catching = 0
        self._catch_lock = threading.Lock()

    def _apply_record(self, op, data, seq: int = 0, ts: float = 0.0):
        # apply AND log UNDER THE PRIMARY'S SEQ (WALEngine.apply_and_log
        # with seq pinned): the replica's own WAL mirrors the primary's
        # numbering record-for-record even when this replica joined
        # mid-history (the primary's pre-snapshot segments are pruned,
        # so the first shipped record may be seq 50001 — logging it at
        # local seq 1 would skew the watermark by the whole pruned
        # prefix). Promotion then CONTINUES the numbering — surviving
        # peers at watermark N accept the new primary's N+1 instead of
        # silently dropping a restarted stream — restarts resume from
        # the true watermark, and this node can serve wal_sync
        # catch-ups itself once promoted.
        self.engine.apply_and_log(op, data, seq=seq if seq > 0 else None)
        if ts and not self.catching_up:
            # per-record replication latency (ISSUE 13): primary
            # append -> replica apply, streamed records only — catch-up
            # bootstrap replays old history whose age is join depth,
            # not replication health
            _APPLY_DELAY_H.labels(self.config.node_id).observe(
                max(0.0, time.time() - ts))

    def _apply_snapshot(self, state, snap_seq: int) -> int:
        # base impl applies through apply_record, so the replica's
        # on_applied index fan-out fires per entry; afterwards pin the
        # local WAL counter at the snapshot seq and persist the
        # bootstrapped state as a LOCAL snapshot — the streamed tail
        # then appends under the primary's numbering with no gap and a
        # restart resumes from the true watermark
        n = super()._apply_snapshot(state, snap_seq)
        self.engine.wal.advance_seq(snap_seq)
        try:
            self.engine.snapshot()
        except Exception:  # noqa: BLE001 — bootstrap still succeeded
            pass
        return n

    # -- handlers --------------------------------------------------------

    def handle_heartbeat(self, msg):
        r = super().handle_heartbeat(msg)
        if r.get("ok"):
            with self._lock:
                self.primary_last_seq = max(
                    self.primary_last_seq, int(msg.get("last_seq", 0) or 0))
        return r

    def handle_wal_batch(self, msg):
        r = super().handle_wal_batch(msg)
        if "error" not in r:
            seqs = [int(rec.get("seq", 0) or 0)
                    for rec in msg.get("records", [])]
            if seqs:
                with self._lock:
                    self.primary_last_seq = max(self.primary_last_seq,
                                                max(seqs))
        else:
            _FAILOVER_C.labels("fence_rejected").inc()
            _events.record_event(
                "fence_rejected", node=self.config.node_id,
                surface="fleet",
                reason=f"stale_epoch:{msg.get('epoch', 0)}")
        return r

    # -- lag truth -------------------------------------------------------

    def catch_up(self, addr=None) -> int:
        with self._catch_lock:
            self._catching += 1
        try:
            n = super().catch_up(addr)
        finally:
            with self._catch_lock:
                self._catching -= 1
        if n:
            with self._lock:
                self.primary_last_seq = max(self.primary_last_seq,
                                            self.applied_seq)
        return n

    @property
    def catching_up(self) -> bool:
        with self._catch_lock:
            return self._catching > 0

    def lag_ops(self) -> int:
        with self._lock:
            return max(0, self.primary_last_seq - self.applied_seq)


class ReadReplica:
    """One read replica: a DB facade whose base engine applies the
    primary's WAL stream, with every applied record fanned out into the
    replica's own listeners and search indexes.

    The DB chain is the standard standby chain (writes raise
    ``NotPrimaryError`` through the ReplicatedEngine until promotion);
    reads — vector, hybrid, qdrant, Cypher — serve from local state.
    ``auto_embed`` stays off: embeddings are computed once, on the
    primary, and arrive in the replicated ``update_node`` records."""

    def __init__(
        self,
        name: str,
        data_dir: str,
        database: str = "neo4j",
        heartbeat_interval: float = 0.25,
        failover_timeout: float = 30.0,
        listen: Tuple[str, int] = ("127.0.0.1", 0),
        on_promote: Optional[Callable[["ReadReplica"], None]] = None,
    ):
        import os

        from nornicdb_tpu.db import DB

        self.name = str(name)
        self.database = database
        self.on_promote = on_promote
        self.closed = False
        self._promoted_once = False
        self._prefix = database + ":"
        cfg = ReplicationConfig(
            mode="ha_standby", ha_role="standby", node_id=self.name,
            listen=listen, heartbeat_interval=heartbeat_interval,
            failover_timeout=failover_timeout,
            standby_cls=FleetStandby,
            on_promote=self._on_promoted,
            # two-plane streaming (ISSUE 16): bulk WAL batches/snapshot
            # ships ride a second socket so they never head-of-line
            # block heartbeats or fences on the control channel
            data_listen=("127.0.0.1", 0),
            # fencing epoch survives restarts: together with the
            # seq-aligned local WAL this makes a replica restart a
            # tail-pull, not a re-bootstrap
            epoch_path=os.path.join(data_dir, "standby.epoch"),
        )
        self.db = DB(data_dir, engine="python", auto_embed=False,
                     database=database, replication=cfg)
        # per-node resource identity BEFORE the lazy search service
        # builds (service:<db>@<node> — in-process fleets share one obs
        # registry; colliding names would swap each other's gauges)
        self.db._search_resource_name = f"service:{database}@{self.name}"
        self.db.fleet_node = self  # /readyz reads ready_reasons()
        self.standby: FleetStandby = self.db.replicator
        self.transport = self.db._cluster_transport
        # resume the watermark from the local WAL: applied records are
        # logged seq-aligned with the primary (FleetStandby
        # _apply_record), so after a restart the replica pulls only the
        # tail instead of replaying full history
        self.standby.applied_seq = self.db._base.wal.last_seq
        # replay fan-out: every record the standby applies at the base
        # WALEngine re-enters the replica's index/listener paths
        self.db._base.on_applied = self._on_applied
        # build the search service EAGERLY: a serving replica must not
        # pay the full index backfill on its first query (and the lazy
        # publish-before-backfill window would let a racing read see a
        # half-built index); from here on the replay fan-out keeps the
        # indexes current incrementally
        self.db.search
        _track(self)

    # -- topology --------------------------------------------------------

    @property
    def addr(self) -> Tuple[str, int]:
        return self.transport.addr

    def attach(self, primary_addr: Tuple[str, int],
               peer_addrs: Sequence[Tuple[str, int]] = ()) -> None:
        """Point this replica at its primary (and the sibling replicas
        it would stream to after a promotion), then pull history."""
        self.standby.primary_addr = tuple(primary_addr)
        self.standby.config.peers = [tuple(a) for a in peer_addrs]
        self.catch_up()

    def catch_up(self) -> int:
        return self.standby.catch_up()

    # -- replay fan-out --------------------------------------------------

    def _logical_node(self, data: Dict[str, Any]) -> Optional[Node]:
        node = Node.from_dict(data)
        if not node.id.startswith(self._prefix):
            return None  # another logical database on the same store
        node.id = node.id[len(self._prefix):]
        return node

    def _logical_edge(self, data: Dict[str, Any]) -> Optional[Edge]:
        edge = Edge.from_dict(data)
        if not edge.id.startswith(self._prefix):
            return None
        edge.id = edge.id[len(self._prefix):]
        if edge.start_node.startswith(self._prefix):
            edge.start_node = edge.start_node[len(self._prefix):]
        if edge.end_node.startswith(self._prefix):
            edge.end_node = edge.end_node[len(self._prefix):]
        return edge

    def _on_applied(self, op: str, data: Dict[str, Any]) -> None:
        """Replay fan-out: one applied WAL record -> the same listener
        events and index mutations the write produced on the primary.
        Replicated embeddings ride the node dict, so ``index_node``
        lands them straight in the device indexes (brute changelog,
        BM25, CAGRA rebuild triggers — the standard freshness paths).
        Per-stage replay timing (ISSUE 13) splits each record's cost
        into the listener fan-out vs the search-index apply
        (nornicdb_replica_replay_seconds{node,stage}) — the seconds
        behind the apply-delay histogram's tail. The whole fan-out
        rides the REPLAY admission lane (ISSUE 15): index work it
        triggers seals behind interactive reads on this replica."""
        from nornicdb_tpu import admission as _adm

        with _adm.lane_scope(_adm.LANE_REPLAY):
            self._on_applied_replay(op, data)

    def _on_applied_replay(self, op: str, data: Dict[str, Any]) -> None:
        listeners = self.db._listenable._each()
        svc = self.db._search
        if op in ("create_node", "update_node"):
            node = self._logical_node(data)
            if node is None:
                return
            t0 = time.perf_counter()
            for listener in listeners:
                try:
                    listener.on_node_upsert(node)
                except Exception:  # noqa: BLE001 — listener isolation
                    pass
            t1 = time.perf_counter()
            if svc is not None:
                svc.index_node(node)
                _REPLAY_H.labels(self.name, "index").observe(
                    time.perf_counter() - t1)
            _REPLAY_H.labels(self.name, "listeners").observe(t1 - t0)
        elif op == "delete_node":
            nid = str(data.get("id", ""))
            if not nid.startswith(self._prefix):
                return
            nid = nid[len(self._prefix):]
            t0 = time.perf_counter()
            for listener in listeners:
                try:
                    listener.on_node_delete(nid)
                except Exception:  # noqa: BLE001
                    pass
            t1 = time.perf_counter()
            if svc is not None:
                svc.remove_node(nid)
                _REPLAY_H.labels(self.name, "index").observe(
                    time.perf_counter() - t1)
            _REPLAY_H.labels(self.name, "listeners").observe(t1 - t0)
        elif op in ("create_edge", "update_edge"):
            edge = self._logical_edge(data)
            if edge is None:
                return
            for listener in listeners:
                try:
                    listener.on_edge_upsert(edge)
                except Exception:  # noqa: BLE001
                    pass
        elif op == "delete_edge":
            eid = str(data.get("id", ""))
            if not eid.startswith(self._prefix):
                return
            eid = eid[len(self._prefix):]
            for listener in listeners:
                try:
                    listener.on_edge_delete(eid)
                except Exception:  # noqa: BLE001
                    pass
        elif op == "delete_by_prefix":
            # bulk record: no per-node events exist, reconcile instead
            for listener in listeners:
                try:
                    listener.on_bulk_change()
                except Exception:  # noqa: BLE001
                    pass
            if svc is not None:
                svc.prune_missing()

    # -- readiness -------------------------------------------------------

    def ready_reasons(self, max_lag_ops: Optional[int] = None) -> List[str]:
        """Reasons this replica must drain instead of serving reads:
        ``replica_lag:<node>(lag/max)`` past the env-tunable
        ``NORNICDB_READY_MAX_LAG_OPS`` threshold, ``catching_up:<node>``
        while a rejoin/gap sync runs. Empty list = ready."""
        from nornicdb_tpu.config import env_int

        if max_lag_ops is None:
            max_lag_ops = env_int("READY_MAX_LAG_OPS", 512)
        reasons: List[str] = []
        st = self.standby
        if st is None or self.closed:
            return [f"replica_closed:{self.name}"]
        if st.catching_up:
            reasons.append(f"catching_up:{self.name}")
        lag = st.lag_ops()
        if lag > max_lag_ops:
            reasons.append(f"replica_lag:{self.name}({lag}/{max_lag_ops})")
        return reasons

    def rebuild_in_flight(self) -> bool:
        """True while any of this replica's own index structures runs a
        background rebuild — the router drains a mid-rebuild replica
        (same signal the node's /readyz index_rebuild reasons carry)."""
        svc = self.db._search
        if svc is None:
            return False
        for obj in (svc.vectors, svc.bm25, svc.cagra):
            if obj is None:
                continue
            try:
                if obj.resource_stats().get("rebuild_in_flight"):
                    return True
            except Exception:  # noqa: BLE001
                continue
        return False

    def is_replica(self) -> bool:
        from nornicdb_tpu.replication.replicator import Role

        st = self.standby
        return st is not None and st.role is Role.STANDBY

    # -- read dispatch (router entry points) -----------------------------

    def vec_dispatch(self, key: str, queries, k: int):
        """The WirePlane vec-dispatch contract served from THIS
        replica's device indexes — the SAME key vocabulary the plane's
        local dispatch resolves (api/wire_plane.resolve_vec_dispatch),
        so plane and replica can never drift apart."""
        from nornicdb_tpu.api.wire_plane import resolve_vec_dispatch

        return resolve_vec_dispatch(self.db, key, queries, k)

    # -- failover --------------------------------------------------------

    def promote(self) -> None:
        self.standby.promote()

    def _on_promoted(self, standby) -> None:
        """Promotion side effects, exactly once: the failover counter
        ticks on the transition only, and the node's obs resources
        re-register idempotently (register() is a no-op for the same
        object — a double promote cannot churn a weakref or drop a
        series mid-scrape)."""
        if not self._promoted_once:
            self._promoted_once = True
            _FAILOVER_C.labels("promote").inc()
            _events.record_event("failover", node=self.name,
                                 surface="fleet", reason="promote")
        self._register_resources()
        if self.on_promote is not None:
            try:
                self.on_promote(self)
            except Exception:  # noqa: BLE001 — router hook isolation
                pass

    def _register_resources(self) -> None:
        from nornicdb_tpu.obs import register_resource

        svc = self.db._search
        if svc is None:
            return
        register_resource("bm25", svc.resource_name, svc.bm25)
        register_resource("brute", svc.resource_name, svc.vectors)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self.db._base.on_applied = None
        except Exception:  # noqa: BLE001
            pass
        self.db.close()


class ReadFleet:
    """In-process 1-primary/N-replica topology over real loopback
    transports — the testable fleet (SURVEY §4 "multi-node without a
    real cluster"; handlers stay directly callable for fencing tests).

    Construction order matters: replicas first (their transport
    addresses become the primary's peer set), then the primary, then
    each replica attaches (primary addr + sibling peers) and pulls
    history. ``router`` is a :class:`~nornicdb_tpu.api.fleet_router.
    FleetRouter` over the topology; admission stays parity-gated —
    call ``admit_all`` with probe vectors once the corpus is loaded."""

    def __init__(
        self,
        base_dir: str,
        n_replicas: int = 2,
        database: str = "neo4j",
        sync: str = "async",
        heartbeat_interval: float = 0.1,
        failover_timeout: float = 30.0,
        auto_embed: bool = False,
    ):
        import os

        from nornicdb_tpu.api.fleet_router import FleetRouter
        from nornicdb_tpu.db import DB

        self.replicas: List[ReadReplica] = []
        self.primary_db = None
        try:
            for i in range(n_replicas):
                self.replicas.append(ReadReplica(
                    f"replica-{i}",
                    os.path.join(base_dir, f"replica-{i}"),
                    database=database,
                    heartbeat_interval=heartbeat_interval,
                    failover_timeout=failover_timeout,
                ))
            cfg = ReplicationConfig(
                mode="ha_standby", ha_role="primary", node_id="primary",
                sync=sync, peers=[r.addr for r in self.replicas],
                heartbeat_interval=heartbeat_interval,
                failover_timeout=failover_timeout,
                # two-plane: wal_sync catch-up pulls (potentially a full
                # snapshot ship) land on the bulk endpoint, away from
                # the fence/heartbeat channel
                data_listen=("127.0.0.1", 0),
            )
            self.primary_db = DB(
                os.path.join(base_dir, "primary"), engine="python",
                auto_embed=auto_embed, database=database,
                replication=cfg)
            primary_addr = self.primary_db._cluster_transport.addr
            for r in self.replicas:
                peers = [o.addr for o in self.replicas if o is not r]
                r.attach(primary_addr, peers)
            self.router = FleetRouter(self.primary_db)
            for r in self.replicas:
                r.on_promote = self._promoted
                self.router.add_replica(r)
        except BaseException:
            self.close()
            raise

    def _promoted(self, replica: ReadReplica) -> None:
        self.router.on_promote(replica)

    def wait_converged(self, timeout_s: float = 10.0) -> bool:
        """Block until every replica's applied watermark reaches the
        primary's current last_seq (bounded)."""
        self.primary_db._base.wal.flush()
        target = self.primary_db._base.wal.last_seq
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if all(r.standby.applied_seq >= target for r in self.replicas):
                return True
            for r in self.replicas:
                if r.standby.applied_seq < target:
                    r.catch_up()
            time.sleep(0.02)
        return False

    def admit_all(self, probes, k: int = 10) -> Dict[str, float]:
        """Parity-gated admission of every replica (router.admit):
        probe vectors answered by the replica's device path are scored
        against the primary's exact host reference at the PR 10 floors
        (exact 1.0 / statistical 0.95)."""
        return {r.name: self.router.admit(r.name, probes, k=k)
                for r in self.replicas}

    def close(self) -> None:
        for r in self.replicas:
            try:
                r.close()
            except Exception:  # noqa: BLE001
                pass
        if self.primary_db is not None:
            try:
                self.primary_db.close()
            except Exception:  # noqa: BLE001
                pass
